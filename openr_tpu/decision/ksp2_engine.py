"""Incremental KSP2_ED_ECMP engine: persist paths across churn, re-solve
only affected destinations.

The per-build cost of the original device prefetch
(spf_solver._prefetch_ksp2_paths) is O(D) host work per churn event —
first-path traces, mask building, masked-row tracing and route assembly
for EVERY KSP2 destination — even though one adjacency change leaves
almost every destination's paths untouched. At fabric scale that host
work dominates the rebuild (reference convergence goal is <100 ms,
openr/docs/Introduction/Overview.md:28; the per-destination semantics
being preserved are LinkState.cpp:763 getKthPaths and Decision.cpp:908
selectBestPathsKsp2).

This engine caches, per destination: the traced first/second paths, the
first-path link (exclusion) set, and the masked-SPF distance row. On a
topology change it determines the exact set of destinations whose paths
may differ — everything else is primed straight from the cache — using
a sound distance-algebra test:

  For a changed directed edge C = (u, v) with weight w, C lies on some
  shortest path src -> dst iff

      d(src, u) + w + d(v, dst) == d(src, dst)

  If no changed edge lies on dst's shortest-path DAG under EITHER the
  old or the new distances, the DAG restricted to dst's explored region
  is unchanged, so the (canonically ordered) first-path trace output is
  unchanged. The same test bounds the MASKED graph of the second-path
  solve: masking only removes edges, so base distances lower-bound
  masked distances, giving a conservative (never unsound) filter.

  Soundness sketch for multiple simultaneous changes {C_i}: if a
  distance d(x, y) differs between the old and new graphs, some C_i
  lies on an old or new shortest x->y path (otherwise both old and new
  optima would be achievable in the other graph). Applying this to the
  endpoints of any DAG(dst) link whose membership flips places some
  C_i on DAG_old(dst) or DAG_new(dst) — exactly what the test checks.

The distances come from a device-resident all-pairs matrix over the
sliced-ELL bands (ops/spf_sparse.py): at KSP2 scale (n_pad <= 4096, the
engine's activation bound) a full all-sources solve is ONE source block
(~1-2 ms on-device), so every churn event recomputes it, swaps it with
the previous event's matrix (kept resident — no transfer), and reads
back one fused packet: the SPF view batch (served to SpfView, saving
its separate dispatch) plus old/new distance rows for the changed-edge
endpoints. Steady-state churn that touches no cached path costs ONE
device round trip and O(changed) host work.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from openr_tpu.analysis.annotations import (
    mirrored_by,
    resident_buffers,
    thread_confined,
)
from openr_tpu.graph.linkstate import Link, LinkState
from openr_tpu.ops import dispatch_accounting as _da
from openr_tpu.ops.spf import INF

# Engine activation bound: the event loop keeps TWO device-resident
# [n_pad, n_pad] int32 matrices (current + previous all-pairs) — at the
# 12k bound that is ~1.2 GB, comfortably inside a single chip's HBM,
# and the per-event fused dispatch is one all-sources ELL solve. Past
# this, the all-pairs residency must shard over a device mesh (the ELL
# machinery already shards source rows — sharded_ell_all_sources); the
# bound is where single-chip residency stops, not where the algorithm
# does.
ENGINE_MAX_NODES = 12288

# Optional device mesh for the engine's all-pairs residency: when set
# (set_engine_mesh), the all-pairs fixed point and the masked batches
# run SHARDED over the mesh — per-device footprint n^2/ndev — and the
# activation bound scales with sqrt(ndev) (~100k on a 64-way mesh).
# The speculative resident-masks fast path runs mesh-wide too: the
# destination batch pads to a mesh multiple and the mask stack / dm
# residents stripe over the batch axis (ShardingPlan.batch3/rows).
# When the fast path CANNOT engage on-mesh (mask budget, empty batch)
# the drop is typed — decision.ksp2.spec_mesh_fallbacks plus a trace
# stamp — never silent.
_ENGINE_MESH = None


def set_engine_mesh(mesh) -> None:
    """Install (or clear, with None) the mesh the KSP2 engines shard
    their resident all-pairs state over. Takes effect on the next
    engine cold build."""
    global _ENGINE_MESH
    _ENGINE_MESH = mesh


def get_engine_mesh():
    return _ENGINE_MESH


def engine_max_nodes() -> int:
    """The activation bound under the current mesh setting: the two
    resident [n, n] matrices shard over rows, so the single-chip
    residency bound scales with sqrt(ndev)."""
    if _ENGINE_MESH is None:
        return ENGINE_MAX_NODES
    import math

    return int(ENGINE_MAX_NODES * math.sqrt(_ENGINE_MESH.devices.size))


# churn larger than this falls back to a full (cold) rebuild
ENGINE_MAX_CHANGED_PAIRS = 64
ENGINE_MAX_ENDPOINTS = 32
# if more than this fraction of destinations is affected, a cold
# rebuild is cheaper than the incremental machinery
ENGINE_FULL_REBUILD_FRACTION = 3  # affected * N > dsts  -> cold
# fast path: how many changed masked rows the fused dispatch reads back
# inline; more than this forces one extra full-matrix readback
ENGINE_ROW_BUDGET = 64


def _fast_path_enabled() -> bool:
    """The resident-mask speculative solve trades extra device compute
    (a masked re-solve of EVERY destination per event — single-digit ms
    on an accelerator) for one fewer host<->device round trip (~70-200ms
    on a relay-backed chip). On the CPU backend round trips are free
    and the speculation is pure overhead (measured 8x slower at
    fabric-1008), so it only engages on real accelerators.
    OPENR_KSP2_FAST=1/0 overrides (tests force it on under the CPU
    mesh)."""
    import os

    override = os.environ.get("OPENR_KSP2_FAST")
    if override is not None:
        return override == "1"
    import jax

    return jax.devices()[0].platform != "cpu"


def _counters():
    from openr_tpu.decision import spf_solver as _ss

    return _ss.SPF_COUNTERS


def trace_paths_from_row(
    src: str,
    dest: str,
    index: Dict[str, int],
    dlist,
    excluded: Set[Link],
    cands_of,
    transit_blocked: Set[str],
    preds_cache: Optional[Dict[str, list]] = None,
):
    """Enumerate link-disjoint shortest paths src -> dest from a
    distance row — byte-identical to LinkState._trace_one_path over the
    same SPF (both walk predecessor links in canonical sorted order;
    reference: LinkState.cpp:399 traceOnePath).

    ``preds_cache``: predecessor lists depend only on (dlist, excluded,
    transit_blocked) — NOT on the destination — so a caller tracing
    many destinations from the SAME row under the same filters (the
    per-event first-path loops) passes one shared dict and each node's
    predecessor list is computed once per event instead of once per
    destination."""
    inf = int(INF)
    did = index.get(dest)
    if did is None:
        return []
    # numpy rows index/compare element-wise MUCH slower than a plain
    # list in the tight predecessor scans below (np.int32 arithmetic
    # per candidate); one bulk tolist() pays for itself immediately
    if isinstance(dlist, np.ndarray):
        dlist = dlist.tolist()
    if dlist[did] >= inf:
        return []

    visited: Set[Link] = set()
    preds: Dict[str, list] = (
        preds_cache if preds_cache is not None else {}
    )

    # first-path traces run with BOTH filter sets empty (nothing
    # excluded yet): skip the two per-candidate membership tests there
    # — this is the hottest loop of the per-event host work
    plain = not excluded and not transit_blocked

    def preds_of(v: str):
        got = preds.get(v)
        if got is None:
            dv = dlist[index[v]]
            if plain:
                got = preds[v] = [
                    (link, u)
                    for link, u, uid, w in cands_of(v)
                    if uid is not None and dlist[uid] + w == dv
                ]
            else:
                got = preds[v] = [
                    (link, u)
                    for link, u, uid, w in cands_of(v)
                    if uid is not None
                    and link not in excluded
                    and (u == src or u not in transit_blocked)
                    and dlist[uid] < inf
                    and dlist[uid] + w == dv
                ]
        return got

    def trace_one(v: str):
        if v == src:
            return []
        for link, u in preds_of(v):
            if link in visited:
                continue
            visited.add(link)
            sub = trace_one(u)
            if sub is not None:
                sub.append(link)
                return sub
        return None

    paths = []
    path = trace_one(dest)
    while path:
        paths.append(path)
        path = trace_one(dest)
    return paths


def make_cands_of(ls: LinkState, node_index: Dict[str, int]):
    """Per-build candidate list factory shared by the trace calls: up
    links of each node in canonical order with (origin, origin id,
    metric) pre-resolved."""
    in_cands: Dict[str, list] = {}

    def cands_of(v: str):
        got = in_cands.get(v)
        if got is None:
            got = in_cands[v] = [
                (
                    link,
                    link.other_node(v),
                    node_index.get(link.other_node(v)),
                    link.metric_from(link.other_node(v)),
                )
                for link in ls.ordered_links_from_node(v)
                if link.is_up()
            ]
        return got

    return cands_of


class _TraceArrays:
    """Int-encoded view of one build's candidate structure for the
    native batch tracer (native/spfcore.cpp ksp2_trace_batch): a
    candidate CSR in the same canonical order make_cands_of yields,
    a link table for id<->object mapping, and the transit-blocked
    bitmap. Built once per churn event and shared by every trace
    site; the Python tracer remains the fallback and the semantic
    reference."""

    __slots__ = (
        "off", "link", "uid", "w", "links", "lid_of", "blocked",
        "n_pad",
    )

    def __init__(self, graph, cands_of, transit_blocked):
        index = graph.node_index
        names = graph.node_names
        n_pad = graph.n_pad
        off = np.zeros(n_pad + 1, np.int32)
        link_l: List[int] = []
        uid_l: List[int] = []
        w_l: List[int] = []
        links: List[Link] = []
        # keyed by the Link VALUE (its hash is cached), not id(): the
        # Python tracer excludes via `link not in excluded` — a link
        # that flapped down and back up is a fresh-but-EQUAL object,
        # and an identity key would silently drop its exclusion
        lid_of: Dict[Link, int] = {}
        for i, v in enumerate(names):
            for lnk, _u, uuid, w in cands_of(v):
                lid = lid_of.get(lnk)
                if lid is None:
                    lid = lid_of[lnk] = len(links)
                    links.append(lnk)
                link_l.append(lid)
                uid_l.append(-1 if uuid is None else int(uuid))
                w_l.append(int(w))
            off[i + 1] = len(link_l)
        off[len(names) + 1 :] = len(link_l)
        self.off = off
        self.link = np.asarray(link_l, np.int32)
        self.uid = np.asarray(uid_l, np.int32)
        self.w = np.asarray(w_l, np.int32)
        self.links = links
        self.lid_of = lid_of
        blocked = np.zeros(n_pad, np.uint8)
        for nm in transit_blocked:
            bi = index.get(nm)
            if bi is not None:
                blocked[bi] = 1
        self.blocked = blocked
        self.n_pad = n_pad

    def _excl_arrays(self, excls):
        """Per-dst exclusion ranges; a link absent from the current
        candidate table is down, so its exclusion is vacuous."""
        ids: List[int] = []
        off = np.zeros(len(excls) + 1, np.int32)
        for i, excl in enumerate(excls):
            for lnk in excl:
                lid = self.lid_of.get(lnk)
                if lid is not None:
                    ids.append(lid)
            off[i + 1] = len(ids)
        return off, np.asarray(ids, np.int32)

    def trace(self, src_id, dst_ids, rows, shared_row, excls):
        """Batch-enumerate via the native core; None when it is
        unavailable. Paths come back as Link-object lists, identical
        in content and order to trace_paths_from_row."""
        from openr_tpu.graph import native_spf

        excl_off, excl_ids = self._excl_arrays(excls)
        got = native_spf.trace_batch(
            self.n_pad, len(self.links), self.off, self.link,
            self.uid, self.w, src_id, self.blocked,
            np.ascontiguousarray(dst_ids, np.int32),
            np.ascontiguousarray(rows, np.int32),
            shared_row, excl_off, excl_ids,
        )
        if got is None:
            return None
        links = self.links
        return [
            [[links[l] for l in p] for p in paths] for paths in got
        ]


def _path_nodes(src: str, path: List[Link]) -> List[str]:
    """Nodes visited after src along a traced path."""
    out = []
    cur = src
    for link in path:
        cur = link.other_node(cur)
        out.append(cur)
    return out


def _pad_ids(ids: List[int], bucket_min: int = 8) -> np.ndarray:
    """Pad an id list to a power-of-two bucket by repeating the first id
    (inert for row gathers) so jit shapes stay bounded."""
    bucket = bucket_min
    while bucket < len(ids):
        bucket *= 2
    return np.asarray(
        ids + [ids[0]] * (bucket - len(ids)), dtype=np.int32
    )


@mirrored_by(
    d_prev_dev="rebuilt by _cold_build from the resident EllState "
               "distance cache (engine invalidates to valid=False and "
               "re-seeds on the next sync)",
    dm_dev="rebuilt by _cold_build from the traced host-side dm rows",
    masks_t="re-derived by _cold_build from the band tensor shapes",
)
@resident_buffers("d_prev_dev", "dm_dev", "masks_t")
# externally serialized, never internally locked: every engine is
# created and driven by exactly one plane — Decision's under evb, a
# ctrl handler's under SolverCtrlHandler._lock, the twin's on its one
# thread. The shared-state rule merges all instances by class, so
# cross-role access to one instance is impossible by construction —
# hence "owner" confinement (same contract as WorldManager).
@thread_confined(
    "owner",
    "_mesh",
    "_mesh_knob",
    "_slot_maps",
    "_tarrays",
    "attr_sig",
    "aversion",
    "band_shapes",
    "d_base",
    "d_prev_dev",
    "dm",
    "dm_dev",
    "dst_pos",
    "dsts",
    "ecc_hops",
    "eff_w",
    "excl",
    "first_paths",
    "host_dsts",
    "last_affected",
    "masks_t",
    "node_label",
    "node_users",
    "ov",
    "pairs_by_node",
    "second_paths",
    "sid",
    "state",
    "valid",
    "version",
)
class Ksp2Engine:
    """Per-(LinkState, root) incremental KSP2 state. Invalid until the
    first successful cold build."""

    def __init__(self, src_name: str) -> None:
        self.src_name = src_name
        self.valid = False
        self.last_affected: Optional[Set[str]] = None
        # _mesh_knob: the module knob as of the last (re)build — the
        # change-detection identity. _mesh: the mesh the resident
        # arrays are ACTUALLY sharded over (None when the knob is off
        # OR the graph's n_pad does not divide by the mesh size, in
        # which case the single-chip dispatch runs instead).
        self._mesh_knob = _ENGINE_MESH
        self._mesh = None

    # -- public entry ------------------------------------------------------

    def sync(self, ls: LinkState, dsts: List[str]) -> Optional[Set[str]]:
        """Bring the cache to ls.topology_version, prime the LinkState
        kth-path cache for every destination, and return the set of
        destination names whose paths may have changed (for route
        reuse). Returns None when the engine had to cold-rebuild (no
        reuse this build) or cannot run (caller falls back).

        The whole relay round trip runs inside one accounting window:
        every device readback must ride the committed chain
        (``aot_call`` + async kick, reaped via ``reap_read``), and the
        ``ops.host_touches.ksp2_window`` observation is the gate."""
        with _da.event_window("ksp2_window"):
            return self._sync_window(ls, dsts)

    def _sync_window(self, ls: LinkState, dsts: List[str]) -> Optional[Set[str]]:
        self.last_affected = None
        from openr_tpu.decision import spf_solver as _ss

        state = _ss._ELL_RESIDENT.state_for(ls)
        if (
            not self.valid
            or state is not getattr(self, "state", None)
            or dsts != self.dsts
            or self.sid != state.graph.node_index.get(self.src_name)
            # a widened band (ell_patch grew a slot class in place)
            # changed the band tensor shapes the resident masks were
            # built for: the masked fast path would shape-mismatch,
            # so re-seed everything from the new shapes
            or tuple(state.graph.bands) != getattr(
                self, "band_shapes", None
            )
            # the engine-mesh knob changed: resident arrays carry the
            # old sharding — re-seed under the new one
            or self._mesh_knob is not _ENGINE_MESH
        ):
            self._cold_build(ls, state, dsts)
            return None
        if (
            ls.topology_version == self.version
            and ls.attributes_version == self.aversion
        ):
            # nothing changed since the last build; the kth-path cache
            # was not invalidated, so priming is already in place
            self.last_affected = set()
            return set()
        affected_nodes = ls.affected_since(self.version)
        attr_nodes = ls.attr_affected_since(self.aversion)
        if affected_nodes is None or attr_nodes is None:
            self._cold_build(ls, state, dsts)
            return None
        affected_nodes = set(affected_nodes) | set(attr_nodes)
        changed = self._diff_pairs(ls, affected_nodes)
        if changed is None or len(changed) > ENGINE_MAX_CHANGED_PAIRS:
            self._cold_build(ls, state, dsts)
            return None
        ov_flips, label_flips = self._diff_nodes(ls, affected_nodes)
        if self.src_name in ov_flips:
            # the root's own drain state gates route selection broadly
            self._cold_build(ls, state, dsts)
            return None
        # an overload flip changes the EFFECTIVE weight (INF <-> w) of
        # every edge out of the node even though raw metrics are
        # untouched: inject those pairs so the membership tests run with
        # eff() consulting the old vs new overload maps (node_users
        # alone cannot recover destinations that should START routing
        # through a just-undrained node)
        for x in ov_flips:
            for link in ls.links_from_node(x):
                if not link.is_up():
                    continue
                pair = (x, link.other_node(x))
                if pair not in changed:
                    w = self.eff_w.get(
                        pair, min(int(link.metric_from(x)), INF - 1)
                    )
                    sig = self.attr_sig.get(pair, ())
                    changed[pair] = (w, w, sig, sig)
        if len(changed) > ENGINE_MAX_CHANGED_PAIRS:
            self._cold_build(ls, state, dsts)
            return None

        graph = state.graph
        ep = sorted(
            {graph.node_index[u] for (u, v), _ in changed.items()}
            | {graph.node_index[v] for (u, v), _ in changed.items()}
        )
        if len(ep) > ENGINE_MAX_ENDPOINTS:
            self._cold_build(ls, state, dsts)
            return None
        if not ep:
            ep = [self.sid]

        # one fused dispatch: all-pairs + view + old/new endpoint rows
        # (+ on the fast path: speculative masked re-solve of every
        # destination against the RESIDENT masks, row-diffed on device)
        from openr_tpu.ops import spf_sparse

        view_srcs = spf_sparse.ell_source_batch(graph, ls, self.src_name)
        srcs_dev, w_sv = spf_sparse._batch_args(graph, view_srcs)
        ep_ids = _pad_ids(ep)
        use_fast = getattr(self, "masks_t", None) is not None
        dm_new_dev = None
        # increase-edge delta for the warm-started fixed point: pairs
        # whose collapsed min weight went UP since d_prev_dev's epoch.
        # An overload flip changes effective weights without touching
        # the raw metrics the tight test runs on — force a cold seed.
        inc = None
        if not ov_flips:
            inc = [
                (graph.node_index[u], graph.node_index[v], int(w_old))
                for (u, v), (w_old, w_new, _so, _sn) in changed.items()
                if w_new > w_old
            ]
            # both the single-chip and the sharded dispatches thread
            # the delta into the warm-seeded fixed point now
            _counters()["decision.ksp2_warm_dispatches"] += 1
        if self._mesh is not None and use_fast:
            # mesh twin of the fused speculative dispatch; nothing is
            # donated (residents keep their NamedSharding placement),
            # the rebind below is a plain replace
            d_all_dev, dm_new_dev, packed = (
                spf_sparse.sharded_ell_all_view_rows_masked(
                    state, srcs_dev, w_sv, ep_ids, self.d_prev_dev,
                    self.masks_t, self.dm_dev, self.sid,
                    ENGINE_ROW_BUDGET, len(self.dsts), self._mesh,
                    inc=inc,
                )
            )
        elif self._mesh is not None:
            if _fast_path_enabled():
                # fast path requested but no resident masks on-mesh
                # (budget refusal at cold build): typed, not silent
                self._note_mesh_fallback("no_resident_masks")
            d_all_dev, packed = spf_sparse.sharded_ell_all_view_rows(
                state, srcs_dev, w_sv, ep_ids, self.d_prev_dev,
                self._mesh, inc=inc,
            )
        elif use_fast:
            # openr-lint: disable=donation-hazard -- intentional: the
            # dispatch consumes the previous epoch's resident
            # d_prev_dev/dm_dev (dead after this call, no retry path)
            # and both are rebound to the fresh outputs right below
            d_all_dev, dm_new_dev, packed = spf_sparse.ell_all_view_rows_masked(
                state, srcs_dev, w_sv, ep_ids, self.d_prev_dev,
                self.masks_t, self.dm_dev, self.sid, ENGINE_ROW_BUDGET,
                inc=inc, defer=True,
            )
        else:
            # openr-lint: disable=donation-hazard -- intentional: same
            # consume-and-rebind discipline as the fast path above
            d_all_dev, packed = spf_sparse.ell_all_view_rows(
                state, srcs_dev, w_sv, ep_ids, self.d_prev_dev, inc=inc,
                defer=True,
            )
        # the single-chip dispatches DONATE d_prev_dev (and dm_dev on
        # the fast path): adopt the outputs NOW, before any fallback
        # below can hand the dead buffers to _cold_build (which reuses
        # d_prev_dev as its placeholder). The sharded dispatches donate
        # nothing, so for them this is a plain rebind.
        self.d_prev_dev = d_all_dev
        if dm_new_dev is not None:
            self.dm_dev = dm_new_dev
        if not isinstance(packed, np.ndarray):
            # single-chip deferred dispatch: the packed readback was
            # kicked copy_to_host_async inside the wrapper — reap it
            # AFTER the residents adopted the donated outputs so a
            # reap failure can never hand dead buffers to _cold_build
            packed = _da.reap_read(packed, kicked=True)
        b = len(view_srcs)
        p = len(ep_ids)
        view_packed = packed[: 2 * b]
        rows_new = {int(i): packed[2 * b + x] for x, i in enumerate(ep_ids)}
        rows_old = {
            int(i): packed[2 * b + p + x] for x, i in enumerate(ep_ids)
        }
        self._preload_view(ls, graph, view_srcs, view_packed)
        d_new_src = view_packed[0].astype(np.int64)

        aff1, aff2 = self._affected_dsts(
            ls, graph, changed, d_new_src, rows_new, rows_old
        )
        dst_set = set(self.dst_pos)
        # slot-map drift: a band patch that changes a node's in-edge
        # SET re-packs that row's slot assignments, silently re-aiming
        # every resident mask bit stored for those slots (soak repro
        # seed 40018: a dropped link shifted two slots and a
        # destination's masked solve excluded the wrong edges,
        # yielding a metric-15 second path where the truth was 8).
        # Metric-only patches keep the slot map stable. Destinations
        # whose stored paths touch a re-slotted node join aff1 — the
        # stale-mask bucket, re-solved with FRESH masks.
        # only the fast path holds RESIDENT masks; the slow path
        # rebuilds masks fresh from the current slot_of every event,
        # so there is nothing to go stale there
        if (
            graph.slot_of is not None
            and getattr(self, "masks_t", None) is not None
        ):
            for nm in affected_nodes:
                nid = graph.node_index.get(nm)
                if nid is None:
                    continue
                new_map = graph.slot_of.get(nid, {})
                old_map = self._slot_maps.get(nid)
                if old_map is not None and old_map != new_map:
                    if nm == self.src_name:
                        # every destination's mask holds its first-hop
                        # bits in the ROOT's row (build_edge_masks
                        # sets both endpoint rows), and node_users
                        # never indexes the root — a re-slotted root
                        # stales every mask
                        aff1 |= set(self.dst_pos)
                    else:
                        aff1 |= self.node_users.get(nm, set())
                self._slot_maps[nid] = new_map
        aff1 &= dst_set
        aff2 &= dst_set
        # label/overload materialization extras: paths are unchanged
        # (distance tests cover path changes) but the ROUTES built from
        # them embed labels / drain state — invalidate route reuse only
        route_extra: Set[str] = set()
        for x in ov_flips | label_flips:
            if x in self.dst_pos:
                route_extra.add(x)
            route_extra |= self.node_users.get(x, set())
        route_extra &= dst_set
        affected = aff1 | aff2 | route_extra | (self.host_dsts & dst_set)

        if len(affected) * ENGINE_FULL_REBUILD_FRACTION > len(dsts):
            self._cold_build(ls, state, dsts)
            return None

        if use_fast:
            # parse the on-device row diff: meta row carries the top-K
            # changed row ids and the total count
            meta = packed[2 * b + 2 * p]
            ids = meta[:ENGINE_ROW_BUDGET]
            count = int(meta[ENGINE_ROW_BUDGET])
            changed_rows = packed[2 * b + 2 * p + 1 :]
            # the speculative matrix was adopted right after the
            # dispatch, so dispatch-2 corrections scatter into the
            # CURRENT resident state
            row_map = {}
            if count <= ENGINE_ROW_BUDGET:
                for x, i in enumerate(ids):
                    if int(i) >= 0:
                        row_map[self.dsts[int(i)]] = changed_rows[x]
            else:
                # budget overflow: one extra readback of the full
                # matrix (rare — means a large fraction of rows moved);
                # under the mesh the batch carries pad rows — drop them
                dm_full = np.asarray(
                    _da.reap_read(dm_new_dev)
                )[: len(self.dsts)]
                moved = np.flatnonzero((dm_full != self.dm).any(axis=1))
                row_map = {self.dsts[int(i)]: dm_full[int(i)] for i in moved}
            # host-fallback dsts: adopt moved speculative rows into the
            # host mirror (keeps the overflow diff and future row
            # budgets quiet) but never re-trace from them
            for dst in self.host_dsts & set(row_map):
                self.dm[self.dst_pos[dst]] = row_map[dst]
            a_retrace = (
                (aff2 | set(row_map)) - aff1 - self.host_dsts
            ) & dst_set
            ok = True
            if aff1:
                # first paths changed: masks are stale for these — the
                # speculative rows are garbage by construction; re-solve
                # with fresh masks (dispatch 2) and scatter corrections
                ok = self._recompute(ls, state, sorted(aff1), d_new_src)
            if not ok:
                self._cold_build(ls, state, dsts)
                return None
            if a_retrace:
                unrealized = self._retrace_only(
                    ls, graph, sorted(a_retrace), row_map
                )
                if unrealized:
                    # masks drifted for these: full per-dst repair
                    if not self._recompute(
                        ls, state, sorted(unrealized), d_new_src
                    ):
                        self._cold_build(ls, state, dsts)
                        return None
            # a moved speculative row means the destination's second
            # paths may have changed even when no membership test
            # fired — its routes must not be served from the reuse
            # cache (the soak's stale-route half of the same finding)
            affected |= set(row_map) & dst_set
        else:
            recompute = sorted(aff1 | aff2)
            if recompute:
                ok = self._recompute(ls, state, recompute, d_new_src)
                if not ok:
                    self._cold_build(ls, state, dsts)
                    return None
        self._prime_all(ls)

        # commit snapshots
        for pair, (_w_old, w_new, _sig_old, sig_new) in changed.items():
            if w_new >= INF and sig_new is None:
                self.eff_w.pop(pair, None)
                self.attr_sig.pop(pair, None)
                for end in pair:
                    self.pairs_by_node.get(end, set()).discard(pair)
            else:
                self.eff_w[pair] = w_new
                self.attr_sig[pair] = sig_new
                for end in pair:
                    self.pairs_by_node.setdefault(end, set()).add(pair)
        for x in ov_flips:
            self.ov[x] = ls.is_node_overloaded(x)
        for x in label_flips:
            db = ls.get_adjacency_databases().get(x)
            self.node_label[x] = db.node_label if db else 0
        if any(
            w_old >= INF or w_new >= INF
            for (w_old, w_new, _so, _sn) in changed.values()
        ):
            self.ecc_hops = ls.get_max_hops_to_node(self.src_name)
        self.d_base = d_new_src.astype(np.int32)
        self.version = ls.topology_version
        self.aversion = ls.attributes_version
        _counters()["decision.ksp2_incremental_syncs"] += 1
        _counters()["decision.ksp2_affected_dsts"] += len(affected)
        self.last_affected = affected
        return affected

    # -- cold build --------------------------------------------------------

    def _note_mesh_fallback(self, reason: str) -> None:
        """The speculative fast path could not run mesh-wide: bump the
        typed counter AND stamp the active trace span — the drop
        forfeits the warm-dispatch win exactly when sharding activates,
        so it must never be silent (issue 7 satellite)."""
        _counters()["decision.ksp2.spec_mesh_fallbacks"] += 1
        from openr_tpu.telemetry import get_tracer

        tracer = get_tracer()
        span = tracer.span_active(
            "decision.ksp2.spec_mesh_fallback", reason=reason
        )
        tracer.end_span_active(span, reason=reason)

    def _cold_build(self, ls: LinkState, state, dsts: List[str]) -> None:
        from openr_tpu.decision import spf_solver as _ss
        from openr_tpu.ops import spf_sparse
        import jax
        import jax.numpy as jnp

        self.valid = False
        graph = state.graph
        self.state = state
        self.dsts = list(dsts)
        self.band_shapes = tuple(graph.bands)
        # per-node slot-map snapshot for drift detection (see sync):
        # inner dicts are immutable-in-practice (ell_patch replaces a
        # node's map wholesale), so references compare by content later
        self._slot_maps = (
            dict(graph.slot_of) if graph.slot_of is not None else {}
        )
        self._mesh_knob = _ENGINE_MESH
        self._mesh = (
            _ENGINE_MESH
            if _ENGINE_MESH is not None
            and graph.n_pad % _ENGINE_MESH.devices.size == 0
            else None
        )
        self.sid = graph.node_index.get(self.src_name)
        if self.sid is None:
            return
        self.dst_pos = {d: i for i, d in enumerate(dsts)}
        n = graph.n_pad

        # fused dispatch seeds the resident all-pairs matrix AND serves
        # the view; d_prev is a placeholder on the cold path
        view_srcs = spf_sparse.ell_source_batch(graph, ls, self.src_name)
        srcs_dev, w_sv = spf_sparse._batch_args(graph, view_srcs)
        placeholder = getattr(self, "d_prev_dev", None)
        if placeholder is None or placeholder.shape != (n, n):
            if self._mesh is not None:
                # allocate the placeholder ALREADY row-sharded: an
                # unsharded [n, n] zeros would commit n^2 x 4 B to the
                # default device — exactly the single-chip footprint
                # the mesh mode exists to avoid
                from jax.sharding import NamedSharding, PartitionSpec

                placeholder = jax.jit(
                    lambda: jnp.zeros((n, n), dtype=jnp.int32),
                    out_shardings=NamedSharding(
                        self._mesh,
                        PartitionSpec(spf_sparse.SOURCES_AXIS, None),
                    ),
                )()
            else:
                placeholder = jnp.zeros((n, n), dtype=jnp.int32)
        if self._mesh is not None:
            d_all_dev, packed = spf_sparse.sharded_ell_all_view_rows(
                state, srcs_dev, w_sv,
                np.asarray([self.sid], np.int32),
                placeholder, self._mesh,
            )
        else:
            # the dispatch DONATES the placeholder (which may be the
            # previous d_prev_dev): drop our reference first so a
            # failed dispatch can't leave a dead buffer behind for the
            # next cold build to reuse
            self.d_prev_dev = None
            d_all_dev, packed = spf_sparse.ell_all_view_rows(
                state, srcs_dev, w_sv,
                np.asarray([self.sid], np.int32),
                placeholder, defer=True,
            )
            packed = _da.reap_read(packed, kicked=True)
        b = len(view_srcs)
        self._preload_view(ls, graph, view_srcs, packed[: 2 * b])
        self.d_base = packed[0].astype(np.int32)
        self.d_prev_dev = d_all_dev

        # first paths traced from the device base row (identical to the
        # host get_kth_paths(.., 1) trace — same canonical order)
        cands_of = make_cands_of(ls, graph.node_index)
        transit_blocked = {
            name
            for name in graph.node_names
            if ls.is_node_overloaded(name) and name != self.src_name
        }
        self.first_paths: Dict[str, List[List[Link]]] = {}
        self.second_paths: Dict[str, List[List[Link]]] = {}
        self.excl: Dict[str, Set[Link]] = {}
        self.node_users: Dict[str, Set[str]] = {}
        traced = self._trace_many(
            ls, graph, cands_of, transit_blocked, dsts, self.d_base,
            True, [set()] * len(dsts),
        )
        for dst, paths in zip(dsts, traced):
            self.first_paths[dst] = paths
            self.excl[dst] = {l for p in paths for l in p}

        # masked rows for every destination, chunked like the original
        # prefetch; second paths traced from them
        self.dm = np.full((len(dsts), n), INF, dtype=np.int32)
        self.host_dsts: Set[str] = set()
        self.masks_t = None  # set below; must be None while the
        self.dm_dev = None  # chunked solves run (no resident scatter)
        self._solve_masked_batches(
            ls, state, dsts, cands_of, transit_blocked
        )
        self._prime_all(ls)

        # fast path (1 device round trip per metric-churn event): keep
        # every destination's edge masks and masked rows RESIDENT so
        # the next event's fused dispatch can speculatively re-solve
        # and row-diff them on device. Gated on the same mask-memory
        # budget as the chunked dispatch.
        slots = sum(band.rows * band.k for band in graph.bands)
        ndev = self._mesh.devices.size if self._mesh is not None else 1
        # under the mesh the destination batch pads to a device
        # multiple so the mask stack / dm residents stripe evenly over
        # the batch axis (ShardingPlan.batch3 / rows); the budget is
        # charged for the PADDED batch — what the device actually holds
        b_pad = -(-len(dsts) // ndev) * ndev
        if (
            _fast_path_enabled()
            and dsts
            and b_pad * 2 * max(1, slots) <= _ss.KSP2_DEVICE_MASK_BUDGET
        ):
            excl_sets = [self.excl[d] for d in dsts]
            # pad rows carry empty exclusion sets: their (unmasked)
            # speculative solves are diff-masked out by d_real in the
            # sharded dispatch, so their churn never reads back
            excl_sets += [set()] * (b_pad - len(dsts))
            masks_all, _ok = spf_sparse.build_edge_masks(graph, excl_sets)
            if self._mesh is not None:
                from openr_tpu.parallel.mesh import ShardingPlan

                plan = ShardingPlan(self._mesh)
                self.masks_t = tuple(
                    plan.place(m, plan.batch3) for m in masks_all
                )
                dm_pad = np.full((b_pad, n), INF, dtype=np.int32)
                dm_pad[: len(dsts)] = self.dm
                self.dm_dev = plan.place(dm_pad, plan.rows)
            else:
                self.masks_t = tuple(jnp.asarray(m) for m in masks_all)
                self.dm_dev = jnp.asarray(self.dm)
        elif _fast_path_enabled() and self._mesh is not None and dsts:
            # speculative path requested but the padded mask stack
            # exceeds the device budget: typed drop, never silent
            self._note_mesh_fallback("mask_budget")

        # graph-attribute snapshots for churn diffing
        self.eff_w, self.attr_sig = {}, {}
        for name in graph.node_names:
            if name not in graph.node_index:
                continue
            sigs = self._node_sigs(ls, name)
            weights = self._min_weights(sigs)
            for other, sig in sigs.items():
                self.eff_w[(name, other)] = weights[other]
                self.attr_sig[(name, other)] = sig
        self.pairs_by_node = {}
        for pair in self.eff_w:
            self.pairs_by_node.setdefault(pair[0], set()).add(pair)
            self.pairs_by_node.setdefault(pair[1], set()).add(pair)
        self.ov = {
            name: ls.is_node_overloaded(name)
            for name in graph.node_names
        }
        self.node_label = {
            name: db.node_label
            for name, db in ls.get_adjacency_databases().items()
        }
        self.ecc_hops = ls.get_max_hops_to_node(self.src_name)
        self.version = ls.topology_version
        self.aversion = ls.attributes_version
        self.valid = True
        _counters()["decision.ksp2_cold_builds"] += 1

    # -- diffing -----------------------------------------------------------

    @staticmethod
    def _node_sigs(ls: LinkState, a: str) -> Dict[str, Tuple]:
        """Materialization-relevant attributes of every (a, other) link
        direction in ONE pass over a's ordered links: next-hop
        addresses, interfaces, adj labels, and canonical link identity
        (identity changes can reorder the deterministic trace's
        candidate list). One pass matters: per-pair scans made diffing
        a single churn event O(degree^2) on high-degree spines."""
        sigs: Dict[str, List[Tuple]] = {}
        for link in ls.ordered_links_from_node(a):
            if not link.is_up():
                continue
            sigs.setdefault(link.other_node(a), []).append(
                (
                    link.iface_from(a),
                    link.nh_v4_from(a).addr,
                    link.nh_v6_from(a).addr,
                    link.adj_label_from(a),
                    link.metric_from(a),
                )
            )
        return {other: tuple(s) for other, s in sigs.items()}

    @staticmethod
    def _min_weights(sigs: Dict[str, Tuple]) -> Dict[str, int]:
        """Collapsed min-metric per neighbor, derived from the sig
        tuples (metric is each sig's last element) — the ONE source of
        the min(metric, INF-1) reduction."""
        return {
            other: min(min(int(s[-1]), INF - 1) for s in sig_list)
            for other, sig_list in sigs.items()
        }

    def _diff_pairs(
        self, ls: LinkState, affected_nodes: Set[str]
    ) -> Optional[Dict[Tuple[str, str], Tuple]]:
        """Directed pairs incident to the affected nodes whose collapsed
        min-metric or materialization attributes changed:
        (u, v) -> (w_old, w_new, sig_old, sig_new). Parallel links are
        first-class: the pair model keeps MIN weights (exact for
        first-path membership; a conservative lower bound for the
        masked-graph membership test) while the per-link sigs catch
        sibling-only changes, and the per-link ELL slots
        (spf_sparse.compile_ell direction="in") make every member
        individually maskable (reference: LinkState.h:82)."""
        changed: Dict[Tuple[str, str], Tuple] = {}
        graph_index = self.state.graph.node_index
        seen_pairs: Set[Tuple[str, str]] = set()
        # one links pass per origin node, not per pair
        sig_cache: Dict[str, Dict[str, Tuple]] = {}
        w_cache: Dict[str, Dict[str, int]] = {}

        def node_view(a: str):
            if a not in sig_cache:
                sig_cache[a] = self._node_sigs(ls, a)
                w_cache[a] = self._min_weights(sig_cache[a])
            return sig_cache[a], w_cache[a]

        for x in affected_nodes:
            if x not in graph_index:
                return None  # node set changed
            neighbors: Set[str] = set()
            for link in ls.links_from_node(x):
                if not link.is_up():
                    continue
                neighbors.add(link.other_node(x))
            # pairs that vanished entirely (link down/removed: neither
            # direction survives in the current link set) — probed via
            # the incident-pair index, NOT a scan of every pair (at 4k
            # nodes that scan made each churn event O(affected x E))
            for (u, v) in list(self.pairs_by_node.get(x, ())):
                if (u, v) in seen_pairs:
                    continue
                other = v if u == x else u
                if other not in neighbors:
                    changed[(u, v)] = (
                        self.eff_w.get((u, v), INF), INF, None, None,
                    )
                    seen_pairs.add((u, v))
            for other in neighbors:
                for pair in ((x, other), (other, x)):
                    if pair in seen_pairs:
                        continue
                    seen_pairs.add(pair)
                    a, bnode = pair
                    sigs_a, ws_a = node_view(a)
                    w_new = ws_a.get(bnode, INF)
                    sig_new = sigs_a.get(bnode, ())
                    w_old = self.eff_w.get(pair, INF)
                    sig_old = self.attr_sig.get(pair, ())
                    if w_old != w_new or sig_old != sig_new:
                        changed[pair] = (w_old, w_new, sig_old, sig_new)
        return changed

    def _diff_nodes(
        self, ls: LinkState, affected_nodes: Set[str]
    ) -> Tuple[Set[str], Set[str]]:
        ov_flips = {
            x
            for x in affected_nodes
            if self.ov.get(x, False) != ls.is_node_overloaded(x)
        }
        dbs = ls.get_adjacency_databases()
        label_flips = {
            x
            for x in affected_nodes
            if self.node_label.get(x, 0)
            != (dbs[x].node_label if x in dbs else 0)
        }
        return ov_flips, label_flips

    # -- affected-set computation -----------------------------------------

    def _affected_dsts(
        self,
        ls: LinkState,
        graph,
        changed: Dict[Tuple[str, str], Tuple],
        d_new_src: np.ndarray,
        rows_new: Dict[int, np.ndarray],
        rows_old: Dict[int, np.ndarray],
    ) -> Tuple[Set[str], Set[str]]:
        """Returns (first-path affected, masked/second-path affected) —
        split because the former invalidates the destination's MASKS
        (forcing a fresh masked solve) while the latter only needs the
        second paths re-derived."""
        index = graph.node_index
        dst_ids = np.asarray(
            [index[d] for d in self.dsts], dtype=np.int64
        )
        d_old_src = self.d_base.astype(np.int64)
        d_new = d_new_src  # already int64
        inf = np.int64(INF)

        aff = d_new[dst_ids] != d_old_src[dst_ids]
        aff2_vec = np.zeros(len(self.dsts), dtype=bool)

        dm = self.dm.astype(np.int64, copy=False)
        dm_total = dm[np.arange(len(self.dsts)), dst_ids]

        def eff(w, origin, ov_map):
            if w >= INF:
                return inf
            if ov_map.get(origin, False) and origin != self.src_name:
                return inf
            return np.int64(w)

        ov_new = {
            x: ls.is_node_overloaded(x) for x in graph.node_names
        }
        for (u, v), (w_old, w_new, _so, _sn) in changed.items():
            uid, vid = index[u], index[v]
            r_old_v = rows_old[vid].astype(np.int64, copy=False)
            r_new_v = rows_new[vid].astype(np.int64, copy=False)
            wo = eff(w_old, u, self.ov)
            wn = eff(w_new, u, ov_new)
            # first-path DAG membership, old and new graphs (exact)
            if wo < inf:
                lhs = d_old_src[uid] + wo + r_old_v[dst_ids]
                valid = (
                    (d_old_src[uid] < inf)
                    & (r_old_v[dst_ids] < inf)
                )
                aff |= valid & (lhs == d_old_src[dst_ids])
            if wn < inf:
                lhs = d_new[uid] + wn + r_new_v[dst_ids]
                valid = (d_new[uid] < inf) & (r_new_v[dst_ids] < inf)
                aff |= valid & (lhs == d_new[dst_ids])
            # masked-graph membership bound (conservative: base
            # distances lower-bound masked distances). A destination
            # with dm_total == INF is disconnected in its masked graph;
            # metric-only churn cannot create connectivity, so those
            # rows are only dirtied by a link APPEARING (w: INF ->
            # finite) — without this guard the <= test against INF
            # fires for every disconnected row and the engine
            # degenerates to cold rebuilds.
            reachable_m = dm_total < inf
            if wo < inf:
                lhs = dm[:, uid] + wo + r_old_v[dst_ids]
                valid = (
                    (dm[:, uid] < inf)
                    & (r_old_v[dst_ids] < inf)
                    & reachable_m
                )
                aff2_vec |= valid & (lhs <= dm_total)
            if wn < inf:
                lhs = d_new[uid] + wn + r_new_v[dst_ids]
                valid = (
                    (d_new[uid] < inf)
                    & (r_new_v[dst_ids] < inf)
                    & reachable_m
                )
                aff2_vec |= valid & (lhs <= dm_total)
            if wo >= inf and wn < inf:
                # edge usable where it was not (link appeared, or its
                # origin was undrained — hence EFFECTIVE weights, not
                # raw: overload flips are injected with equal raw w):
                # disconnected masked rows may reconnect
                aff2_vec |= ~reachable_m
        aff1 = {self.dsts[i] for i in np.flatnonzero(aff)}
        aff2 = {self.dsts[i] for i in np.flatnonzero(aff2_vec)}
        return aff1, aff2

    # -- recompute ---------------------------------------------------------

    def _retrace_only(
        self, ls: LinkState, graph, dsts: List[str],
        row_map: Dict[str, np.ndarray],
    ) -> Set[str]:
        """Fast-path update for destinations whose MASKS are unchanged:
        adopt the speculative masked row (when it moved) and re-trace
        second paths with the current weights. First paths and
        exclusion sets stay as cached.

        Returns the destinations whose row could NOT be realized by a
        trace (a finite masked total with no path walking to it): that
        means the resident masks drifted from the destination's true
        exclusion set, so the speculative row is bogus — the caller
        must _recompute them from scratch (fresh first paths + masks).
        The mixed-churn soak caught exactly this as a silently dropped
        second path (seed 9013: stale masks yielded total 6 where the
        true masked distance was 8, the trace found nothing, and the
        destination was never invalidated)."""
        cands_of = make_cands_of(ls, graph.node_index)
        transit_blocked = {
            name
            for name in graph.node_names
            if ls.is_node_overloaded(name) and name != self.src_name
        }
        for dst in dsts:
            row = row_map.get(dst)
            if row is not None:
                self.dm[self.dst_pos[dst]] = row
            for path in self.second_paths.get(dst, []):
                for x in _path_nodes(self.src_name, path):
                    users = self.node_users.get(x)
                    if users is not None:
                        users.discard(dst)
        traced = self._trace_many(
            ls, graph, cands_of, transit_blocked, dsts,
            np.ascontiguousarray(
                self.dm[[self.dst_pos[d] for d in dsts]]
            ),
            False, [self.excl[d] for d in dsts],
        )
        unrealized: Set[str] = set()
        for dst, paths in zip(dsts, traced):
            if not paths:
                # empty trace: either the row is finite but unwalkable
                # (masks drifted toward extra paths) or INF where the
                # true masked graph has a path (masks drifted toward
                # extra exclusions) — indistinguishable without fresh
                # masks, and a genuinely second-path-less destination
                # just re-confirms cheaply. Recompute all of them.
                unrealized.add(dst)
                continue
            self.second_paths[dst] = paths
            for path in paths:
                for x in _path_nodes(self.src_name, path):
                    self.node_users.setdefault(x, set()).add(dst)
        return unrealized

    def _recompute(
        self, ls: LinkState, state, affected: List[str],
        d_new_src: np.ndarray,
    ) -> bool:
        from openr_tpu.decision import spf_solver as _ss
        from openr_tpu.ops import spf_sparse

        graph = state.graph
        cands_of = make_cands_of(ls, graph.node_index)
        transit_blocked = {
            name
            for name in graph.node_names
            if ls.is_node_overloaded(name) and name != self.src_name
        }
        for dst in affected:
            # drop stale reverse-index entries
            for path in self.first_paths.get(dst, []) + self.second_paths.get(
                dst, []
            ):
                for x in _path_nodes(self.src_name, path):
                    users = self.node_users.get(x)
                    if users is not None:
                        users.discard(dst)
        traced = self._trace_many(
            ls, graph, cands_of, transit_blocked, affected,
            d_new_src.astype(np.int32), True,
            [set()] * len(affected),
        )
        for dst, paths in zip(affected, traced):
            self.first_paths[dst] = paths
            self.excl[dst] = {l for p in paths for l in p}

        self.host_dsts -= set(affected)
        self._solve_masked_batches(
            ls, state, affected, cands_of, transit_blocked
        )
        return True

    def _solve_masked_batches(
        self, ls, state, dsts, cands_of, transit_blocked
    ) -> None:
        """Masked-SPF rows + second-path traces + dm/node_users updates
        for a destination subset (shared by cold build and incremental
        recompute; the two loops MUST stay identical — fallback
        accounting drifting between them was a review finding)."""
        from openr_tpu.decision import spf_solver as _ss
        from openr_tpu.ops import spf_sparse

        graph = state.graph
        chunk = _ss._ksp2_chunk(graph)

        def _submit(batch):
            """Stage 1 of the relay pipeline: mask build + (async)
            masked solve + resident masks/dm scatter, all chained on
            the device stream. Returns the in-flight context
            ``(batch, ok, drows_dev, drows)`` — exactly one of the
            last two is set, depending on the mesh path."""
            # pad to a power-of-two bucket (capped at the chunk) so the
            # masked kernel compiles a handful of shapes, not one per
            # distinct affected-set size
            bucket = 8
            while bucket < len(batch):
                bucket *= 2
            bucket = min(bucket, chunk)
            if self._mesh is not None:
                # sharded batches divide destinations over the mesh
                ndev = self._mesh.devices.size
                bucket = max(bucket, ndev)
                bucket = ((bucket + ndev - 1) // ndev) * ndev
            excl_sets = [self.excl[d] for d in batch]
            pad = bucket - len(batch)
            masks, ok = spf_sparse.build_edge_masks(
                graph, excl_sets + [set()] * pad
            )
            drows_dev = None
            if self._mesh is not None:
                drows = spf_sparse.sharded_ell_masked_distances_resident(
                    state, self.sid, masks, self._mesh
                )
            else:
                # committed chain: the masked rows are kicked
                # copy_to_host_async; the resident scatter below chains
                # off the DEVICE rows, and the host copy is reaped once
                drows_dev = spf_sparse.ell_masked_distances_resident(
                    state, self.sid, masks, defer=True
                )
                drows = None
            _counters()["decision.ksp2_device_batches"] += 1
            if getattr(self, "masks_t", None) is not None:
                # fast path: keep the RESIDENT masks and masked-row
                # matrix in sync so the next event's speculative solve
                # uses current exclusions
                import jax.numpy as jnp

                ids = jnp.asarray(
                    np.asarray(
                        [self.dst_pos[d] for d in batch], np.int32
                    )
                )
                self.masks_t = tuple(
                    m_res.at[ids].set(jnp.asarray(m_new[: len(batch)]))
                    for m_res, m_new in zip(self.masks_t, masks)
                )
                rows_src = (
                    drows_dev[: len(batch)]
                    if drows_dev is not None
                    else jnp.asarray(drows[: len(batch)])
                )
                self.dm_dev = self.dm_dev.at[ids].set(rows_src)
            return batch, ok, drows_dev, drows

        def _settle(batch, ok, drows_dev, drows):
            """Stage 2: reap the masked rows, settle dm + fallback
            accounting, trace second paths — host work the NEXT
            chunk's already-submitted solve overlaps."""
            if drows is None:
                drows = _da.reap_read(drows_dev, kicked=True)
            traceable: List[int] = []
            for i, dst in enumerate(batch):
                if not ok[i]:
                    _counters()["decision.ksp2_host_fallbacks"] += 1
                    self.host_dsts.add(dst)
                    self.second_paths.pop(dst, None)
                    # keep the (unrepresentable-mask) solve row anyway:
                    # it is deterministic, so the fast path's on-device
                    # row diff stays quiet for this destination instead
                    # of burning a gather slot every event; host_dsts
                    # membership keeps it out of every cache read
                    self.dm[self.dst_pos[dst]] = drows[i]
                    continue
                self.dm[self.dst_pos[dst]] = drows[i]
                traceable.append(i)
            traced = self._trace_many(
                ls, graph, cands_of, transit_blocked,
                [batch[i] for i in traceable],
                np.ascontiguousarray(np.asarray(drows)[traceable]),
                False, [self.excl[batch[i]] for i in traceable],
            )
            for i, paths in zip(traceable, traced):
                self.second_paths[batch[i]] = paths

        # ONE-DEEP relay pipeline: chunk i+1's masked solve is
        # submitted before chunk i's rows are reaped, so the relay
        # round trip amortizes across in-flight chunks. Safe because
        # ``self.excl`` is fixed for the whole call (every chunk's
        # masks derive from the same exclusion table) and the settle
        # stage touches only host mirrors. The mesh path degrades to
        # eager per-chunk order — the sharded solve already returns
        # host rows, so there is nothing in flight to overlap.
        inflight = None
        for start in range(0, len(dsts), chunk):
            staged = _submit(dsts[start : start + chunk])
            if inflight is not None:
                if staged[2] is not None:
                    _da.note_pipelined_dispatch(2)
                    _da.note_overlapped_reap()
                _settle(*inflight)
            inflight = staged
        if inflight is not None:
            _settle(*inflight)
        for dst in dsts:
            if dst in self.host_dsts:
                continue
            for path in self.first_paths[dst] + self.second_paths.get(
                dst, []
            ):
                for x in _path_nodes(self.src_name, path):
                    self.node_users.setdefault(x, set()).add(dst)

    def _trace_arrays(self, ls, graph, cands_of, transit_blocked):
        """Per-event cache of the native tracer's int-encoded candidate
        structure. One build serves every trace site of the event (cold
        build first paths, recompute, retrace, masked second paths);
        None when the native core is unavailable (callers fall back to
        the Python tracer)."""
        from openr_tpu.graph import native_spf

        if not native_spf.is_available():
            return None
        key = (ls.topology_version, ls.attributes_version)
        cached = getattr(self, "_tarrays", None)
        if (
            cached is not None
            and cached[0] == key
            and cached[1] is graph
        ):
            return cached[2]
        arrays = _TraceArrays(graph, cands_of, transit_blocked)
        self._tarrays = (key, graph, arrays)
        return arrays

    def _trace_many(
        self, ls, graph, cands_of, transit_blocked, dsts, rows,
        shared_row, excls,
    ) -> List[List[List[Link]]]:
        """THE trace front-end for every per-event path enumeration:
        native batch when the core is available, else the Python tracer
        per destination — one site to keep the two byte-identical.
        ``rows``: one [n_pad] row (shared_row) or [len(dsts), n_pad];
        ``excls``: per-dst exclusion sets (empty for first paths)."""
        arrays = self._trace_arrays(ls, graph, cands_of, transit_blocked)
        if arrays is not None:
            got = arrays.trace(
                self.sid,
                np.asarray(
                    [graph.node_index[d] for d in dsts], np.int32
                ),
                rows, shared_row, excls,
            )
            if got is not None:
                return got
        shared_preds: Optional[Dict[str, list]] = (
            {} if shared_row else None
        )
        row_list = rows.tolist() if shared_row else None
        return [
            trace_paths_from_row(
                self.src_name, dst, graph.node_index,
                row_list if shared_row else rows[i].tolist(),
                excls[i], cands_of, transit_blocked,
                preds_cache=(
                    shared_preds if not excls[i] else None
                ),
            )
            for i, dst in enumerate(dsts)
        ]

    # -- priming / view preload -------------------------------------------

    def _prime_all(self, ls: LinkState) -> None:
        for dst in self.dsts:
            if dst in self.host_dsts:
                continue  # LinkState computes these lazily (host SPF)
            ls.prime_kth_paths(
                self.src_name, dst, 1, self.first_paths[dst]
            )
            ls.prime_kth_paths(
                self.src_name, dst, 2, self.second_paths.get(dst, [])
            )

    def _preload_view(self, ls, graph, view_srcs, view_packed) -> None:
        from openr_tpu.decision import spf_solver as _ss

        _ss._ELL_RESIDENT.preload_view(
            ls, graph, list(view_srcs), np.asarray(view_packed)
        )
