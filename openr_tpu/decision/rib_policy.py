"""RibPolicy: TTL'd centrally-injected route transforms.

Behavioral parity with the reference ``openr/decision/RibPolicy.{h,cpp}``
and the thrift shapes in ``openr/if/OpenrCtrl.thrift`` (RibPolicy,
RibPolicyStatement, RibRouteAction/Weight): statements match routes by
prefix and set per-next-hop weights (by neighbor, by area, or default);
zero-weight next-hops are dropped and routes left with no next-hops are
deleted. A policy is only effective within its TTL — the Decision module
schedules a rebuild at expiry so effects revert.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.decision.rib import RibUnicastEntry
from openr_tpu.types import IpPrefix, NextHop


@dataclass
class RibRouteActionWeight:
    """reference: OpenrCtrl.thrift:94 RibRouteActionWeight."""

    default_weight: int = 0
    area_to_weight: Dict[str, int] = field(default_factory=dict)
    neighbor_to_weight: Dict[str, int] = field(default_factory=dict)


@dataclass
class RibRouteAction:
    """reference: OpenrCtrl.thrift:114 RibRouteAction."""

    set_weight: Optional[RibRouteActionWeight] = None


@dataclass
class RibPolicyStatement:
    """reference: OpenrCtrl.thrift:124 RibPolicyStatement."""

    name: str = ""
    prefixes: Tuple[IpPrefix, ...] = ()
    action: RibRouteAction = field(default_factory=RibRouteAction)

    def __post_init__(self) -> None:
        if not isinstance(self.prefixes, tuple):
            self.prefixes = tuple(self.prefixes)
        self._prefix_set: Set[IpPrefix] = set(self.prefixes)

    def match(self, route: RibUnicastEntry) -> bool:
        return route.prefix in self._prefix_set

    def apply_action(
        self, route: RibUnicastEntry
    ) -> Optional[RibUnicastEntry]:
        """Set next-hop weights; drop zero-weight next-hops. Returns a
        TRANSFORMED COPY (None = no match): the input entry is shared
        with the solver's route-reuse caches, and mutating it in place
        would make the policy effect permanent — an expired policy
        could never restore the dropped next-hops of a reused route.
        reference: RibPolicyStatement::applyAction."""
        if not self.match(route) or self.action.set_weight is None:
            return None
        weights = self.action.set_weight
        new_nexthops: Set[NextHop] = set()
        for nh in route.nexthops:
            weight = weights.default_weight
            if nh.area is not None and nh.area in weights.area_to_weight:
                weight = weights.area_to_weight[nh.area]
            if (
                nh.neighbor_node_name is not None
                and nh.neighbor_node_name in weights.neighbor_to_weight
            ):
                weight = weights.neighbor_to_weight[nh.neighbor_node_name]
            if weight <= 0:
                continue  # zero weight: next-hop dropped
            new_nexthops.add(
                NextHop(
                    address=nh.address,
                    weight=weight,
                    mpls_action=nh.mpls_action,
                    metric=nh.metric,
                    area=nh.area,
                    neighbor_node_name=nh.neighbor_node_name,
                )
            )
        return replace(route, nexthops=new_nexthops)


@dataclass
class PolicyChange:
    updated_routes: List[IpPrefix] = field(default_factory=list)
    deleted_routes: List[IpPrefix] = field(default_factory=list)


class RibPolicy:
    def __init__(
        self, statements: List[RibPolicyStatement], ttl_secs: float = 300.0
    ):
        self.statements = list(statements)
        self.ttl_secs = ttl_secs
        self._valid_until = time.monotonic() + ttl_secs

    def get_ttl_remaining_s(self) -> float:
        return max(0.0, self._valid_until - time.monotonic())

    def is_active(self) -> bool:
        return time.monotonic() < self._valid_until

    def match(self, route: RibUnicastEntry) -> bool:
        return any(s.match(route) for s in self.statements)

    def apply_action(
        self, route: RibUnicastEntry
    ) -> Optional[RibUnicastEntry]:
        # first successful match/action terminates processing
        for statement in self.statements:
            if statement.match(route):
                return statement.apply_action(route)
        return None

    def apply_policy(
        self, unicast_routes: Dict[IpPrefix, RibUnicastEntry]
    ) -> PolicyChange:
        """Transform all matching routes; delete ones whose next-hop set
        becomes empty. reference: RibPolicy::applyPolicy."""
        change = PolicyChange()
        if not self.is_active():
            return change
        for prefix, route in list(unicast_routes.items()):
            new_route = self.apply_action(route)
            if new_route is None:
                continue
            if not new_route.nexthops:
                del unicast_routes[prefix]
                change.deleted_routes.append(prefix)
            else:
                unicast_routes[prefix] = new_route
                change.updated_routes.append(prefix)
        return change
