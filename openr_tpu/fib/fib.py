"""Fib module: program computed routes into the platform agent.

Behavioral parity with the reference ``openr/fib/Fib.{h,cpp}``:

- consumes DecisionRouteUpdate deltas (processRouteUpdates, Fib.cpp:316)
- incremental add/delete programming with retry + exponential backoff on
  agent errors (updateRoutes, Fib.cpp:542); a failed program marks the
  state dirty and a later retry falls back to full ``syncFib``
  (syncRouteDb, Fib.cpp:674)
- keepalive polling of the agent's aliveSince: an agent restart triggers
  a full resync (Fib.cpp:86-103)
- publishes programmed deltas on the fib-updates queue and advertises the
  ``fibtime:<node>`` perf key into the KvStore for ordered-FIB
- dry-run mode: keep state, skip programming
- longest-prefix-match and route lookup APIs for the ctrl surface
  (Fib.cpp:164 longestPrefixMatch)
"""

from __future__ import annotations

import ipaddress
import time
from typing import Dict, List, Optional

from openr_tpu.monitor.monitor import push_log_sample
from openr_tpu.decision.rib import DecisionRouteUpdate
from openr_tpu.telemetry import get_registry, get_tracer
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.fib_service import FibService
from openr_tpu.types import (
    IpPrefix,
    MplsRoute,
    RouteDatabase,
    UnicastRoute,
)
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils.eventbase import ExponentialBackoff, OpenrEventBase

# client id Fib programs under (reference: thrift ClientID::OPENR = 786)
OPENR_CLIENT_ID = 786


class Fib:
    def __init__(
        self,
        my_node_name: str,
        agent: FibService,
        route_updates_queue: ReplicateQueue,
        fib_updates_queue: Optional[ReplicateQueue] = None,
        kvstore_client=None,
        area: str = "0",
        dry_run: bool = False,
        keepalive_interval_s: float = 1.0,
        retry_min_s: float = 0.05,
        retry_max_s: float = 2.0,
        log_sample_queue: Optional[ReplicateQueue] = None,
        graceful_restart_hold_s: float = 0.0,
    ):
        self.my_node_name = my_node_name
        self.agent = agent
        self.evb = OpenrEventBase(name=f"fib:{my_node_name}")
        self.fib_updates_queue = fib_updates_queue or ReplicateQueue(
            name=f"fibUpdates:{my_node_name}"
        )
        self._kvstore_client = kvstore_client
        self._area = area
        self._log_sample_queue = log_sample_queue
        self.dry_run = dry_run
        # desired state (what Decision wants programmed)
        self.unicast_routes: Dict[IpPrefix, UnicastRoute] = {}
        self.mpls_routes: Dict[int, MplsRoute] = {}
        self._synced_once = False
        self._dirty = False
        self._backoff = ExponentialBackoff(retry_min_s, retry_max_s)
        self._retry_timer = None
        self._agent_alive_since: Optional[int] = None
        # graceful restart: a warm-booted process serves the
        # journal-recovered RouteDatabase and HOLDS the previously
        # programmed routes (no deletes, no churn) until Decision
        # re-converges or the hold timer fires — either way ONE full
        # sync_fib reconciles the agent table; routes never flap.
        self.graceful_restart_hold_s = graceful_restart_hold_s
        self._gr_active = False
        self._gr_timer = None
        self.counters = {
            "fib.route_programming_failures": 0,
            "fib.sync_fib_calls": 0,
            "fib.routes_programmed": 0,
            "fib.routes_deleted": 0,
            "fib.agent_restarts": 0,
            "fib.unacked_reprogrammed": 0,
            "fib.graceful_restarts": 0,
            "fib.gr_reconciles": 0,
            "fib.gr_hold_expirations": 0,
        }
        # prefixes/labels a failed delta left in unknown agent state
        # (the program call may have partially landed before the
        # transport died). The recovery sync re-programs the FULL
        # desired state, so these are re-acknowledged in bulk; the
        # counter makes the re-program visible.
        self._unacked_prefixes: set = set()
        self._unacked_labels: set = set()
        # bounded perf-event history served via getPerfDb
        # (reference: Fib keeps a PerfDatabase, if/OpenrCtrl.thrift:312)
        from collections import deque

        self.perf_db = deque(maxlen=32)
        self.evb.add_queue_reader(
            route_updates_queue.get_reader(f"fib:{my_node_name}"),
            self._on_route_update,
        )
        self._keepalive = self.evb.schedule_periodic(
            keepalive_interval_s, self._check_agent, jitter_first=True
        )

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        # capture the agent's liveness baseline before any traffic so a
        # restart between start and the first keepalive is still detected
        try:
            self._agent_alive_since = self.agent.alive_since()
        except Exception:
            self._agent_alive_since = None
        self.evb.run_in_thread()
        if self._gr_active and self.graceful_restart_hold_s > 0:
            self._gr_timer = self.evb.schedule_timeout(
                self.graceful_restart_hold_s, self._on_gr_hold_expired
            )

    def stop(self) -> None:
        self._keepalive.cancel()
        if self._gr_timer is not None:
            self._gr_timer.cancel()
            self._gr_timer = None
        self.evb.stop()
        self.evb.join()

    # -- graceful restart -------------------------------------------------

    def start_graceful_restart(
        self, route_db: RouteDatabase, hold_s: Optional[float] = None
    ) -> None:
        """Seed the desired state from a recovered RouteDatabase and
        enter the graceful-restart hold: the previous life's routes are
        presumed still programmed in the agent, so nothing is deleted
        or re-programmed until Decision re-converges (first route
        update) or the hold timer expires — then a single ``sync_fib``
        reconciles the table. Call BEFORE ``start()``."""
        if hold_s is not None:
            self.graceful_restart_hold_s = hold_s
        for r in route_db.unicast_routes:
            self.unicast_routes[r.dest] = r
        for r in route_db.mpls_routes:
            self.mpls_routes[r.label] = r
        self._gr_active = True
        # the agent table already holds these routes from the previous
        # life — do NOT treat the boot as never-synced (that would
        # force an immediate full sync and defeat the hold)
        self._synced_once = True
        self._dirty = False
        self.counters["fib.graceful_restarts"] += 1
        get_registry().counter_bump("fib.graceful_restarts")

    def _cancel_graceful_restart(self) -> None:
        self._gr_active = False
        if self._gr_timer is not None:
            self._gr_timer.cancel()
            self._gr_timer = None

    def _end_graceful_restart(self) -> bool:
        """Reconcile: one full sync replaces the held table with the
        current desired state. Unchanged routes are re-asserted, never
        withdrawn — the no-flap contract."""
        self._cancel_graceful_restart()
        self.counters["fib.gr_reconciles"] += 1
        return self._sync_route_db()

    def _on_gr_hold_expired(self) -> None:
        self._gr_timer = None
        if not self._gr_active:
            return
        # Decision never re-converged within the hold: stop waiting and
        # reconcile with what the journal recovered
        self.counters["fib.gr_hold_expirations"] += 1
        if not self._end_graceful_restart():
            self._mark_dirty()

    # -- route updates ----------------------------------------------------

    def _on_route_update(self, update: DecisionRouteUpdate) -> None:
        """reference: Fib.cpp:316 processRouteUpdates."""
        t0 = time.perf_counter()
        trace = getattr(update, "trace", None)
        program_span = (
            trace.begin_span("fib.program") if trace is not None else None
        )
        if update.perf_events is not None:
            update.perf_events.add(self.my_node_name, "FIB_ROUTE_DB_RECVD")
            self.perf_db.append(update.perf_events)
        # apply to desired state
        for prefix in update.unicast_routes_to_delete:
            self.unicast_routes.pop(prefix, None)
        for prefix, entry in update.unicast_routes_to_update.items():
            self.unicast_routes[prefix] = entry.to_unicast_route()
        for label in update.mpls_routes_to_delete:
            self.mpls_routes.pop(label, None)
        for entry in update.mpls_routes_to_update:
            self.mpls_routes[entry.label] = entry.to_mpls_route()

        if self._gr_active:
            # first update after a warm boot: Decision re-converged, so
            # end the hold with the one reconciling sync (the delta is
            # subsumed by the full desired state)
            ok = self._end_graceful_restart()
        elif not self._synced_once or self._dirty:
            ok = self._sync_route_db()
        else:
            ok = self._program_delta(update)
        if not ok:
            self._mark_dirty()

        # publish what we programmed (even in dry run: observers track
        # intended state)
        self.fib_updates_queue.push(update)
        duration_ms = (time.perf_counter() - t0) * 1000.0
        get_registry().observe("fib.program_ms", duration_ms)
        if trace is not None:
            trace.end_span(program_span, ok=ok)
            # end of the line: publication -> debounce -> rebuild ->
            # program. finish() validates span closure/nesting and
            # feeds convergence.e2e_ms.
            get_tracer().finish(trace, ok=ok)
        if ok and update.perf_events is not None and update.perf_events.events:
            # reference: Fib.cpp:891 logPerfEvents -> ROUTE_CONVERGENCE;
            # duration = first perf event (the triggering update entering
            # the pipeline) to routes-programmed, NOT just Fib-local
            # time. Only logged when programming SUCCEEDED — a failed
            # attempt has not converged.
            events = update.perf_events.events
            push_log_sample(
                self._log_sample_queue,
                node_name=self.my_node_name,
                event="ROUTE_CONVERGENCE",
                perf_events=[
                    f"{e.node_name}.{e.event_descr}" for e in events
                ],
                duration_ms=max(
                    0, int(time.time() * 1000) - events[0].unix_ts
                ),
            )
        self._advertise_fib_time(duration_ms)

    def _program_delta(self, update: DecisionRouteUpdate) -> bool:
        if self.dry_run:
            return True
        try:
            to_delete = [
                p
                for p in update.unicast_routes_to_delete
                if not self._is_do_not_install(p)
            ]
            if to_delete:
                self.agent.delete_unicast_routes(OPENR_CLIENT_ID, to_delete)
                self.counters["fib.routes_deleted"] += len(to_delete)
            to_add = [
                e.to_unicast_route()
                for e in update.unicast_routes_to_update.values()
                if not e.do_not_install
            ]
            if to_add:
                self.agent.add_unicast_routes(OPENR_CLIENT_ID, to_add)
                self.counters["fib.routes_programmed"] += len(to_add)
            if update.mpls_routes_to_delete:
                self.agent.delete_mpls_routes(
                    OPENR_CLIENT_ID, list(update.mpls_routes_to_delete)
                )
            if update.mpls_routes_to_update:
                self.agent.add_mpls_routes(
                    OPENR_CLIENT_ID,
                    [e.to_mpls_route() for e in update.mpls_routes_to_update],
                )
            return True
        except Exception:
            self.counters["fib.route_programming_failures"] += 1
            # the delta's targets are now in unknown agent state until
            # the recovery sync re-programs the full desired state
            self._unacked_prefixes.update(update.unicast_routes_to_delete)
            self._unacked_prefixes.update(update.unicast_routes_to_update)
            self._unacked_labels.update(update.mpls_routes_to_delete)
            self._unacked_labels.update(
                e.label for e in update.mpls_routes_to_update
            )
            return False

    def _is_do_not_install(self, prefix: IpPrefix) -> bool:
        route = self.unicast_routes.get(prefix)
        return route is not None and route.do_not_install

    def _sync_route_db(self) -> bool:
        """Full-state sync with the agent (reference: Fib.cpp:674)."""
        if self.dry_run:
            self._synced_once = True
            self._dirty = False
            return True
        try:
            self.counters["fib.sync_fib_calls"] += 1
            self.agent.sync_fib(
                OPENR_CLIENT_ID,
                [
                    r
                    for r in self.unicast_routes.values()
                    if not r.do_not_install
                ],
            )
            self.agent.sync_mpls_fib(
                OPENR_CLIENT_ID, list(self.mpls_routes.values())
            )
            self._synced_once = True
            self._dirty = False
            self._backoff.report_success()
            unacked = len(self._unacked_prefixes) + len(self._unacked_labels)
            if unacked:
                # the full sync just re-asserted every desired route,
                # covering everything a failed delta left unknown
                self.counters["fib.unacked_reprogrammed"] += unacked
                self._unacked_prefixes.clear()
                self._unacked_labels.clear()
            return True
        except Exception:
            self.counters["fib.route_programming_failures"] += 1
            return False

    def _mark_dirty(self) -> None:
        self._dirty = True
        self._backoff.report_error()
        if self._retry_timer is None:
            self._retry_timer = self.evb.schedule_timeout(
                self._backoff.get_time_remaining_until_retry(), self._retry
            )

    def _retry(self) -> None:
        self._retry_timer = None
        if not self._dirty:
            return
        if not self._sync_route_db():
            self._mark_dirty()

    # -- agent keepalive --------------------------------------------------

    def _check_agent(self) -> None:
        """Detect agent restart via aliveSince; full resync when it moves
        (reference: Fib.cpp keepAliveCheck)."""
        try:
            alive = self.agent.alive_since()
        except Exception:
            return
        if self._agent_alive_since is None:
            self._agent_alive_since = alive
            return
        if alive != self._agent_alive_since:
            self._agent_alive_since = alive
            self.counters["fib.agent_restarts"] += 1
            # an agent restart voids graceful restart's premise (the
            # held routes are gone from its table) — reconcile now via
            # the restart resync instead of waiting out the hold
            self._cancel_graceful_restart()
            # the restarted agent lost its table: every desired route
            # is effectively unacknowledged until the sync lands
            self._unacked_prefixes.update(self.unicast_routes)
            self._unacked_labels.update(self.mpls_routes)
            if not self._sync_route_db():
                self._mark_dirty()

    # -- perf key ---------------------------------------------------------

    def _advertise_fib_time(self, ms: float) -> None:
        if self._kvstore_client is None:
            return
        try:
            self._kvstore_client.persist_key(
                self._area,
                keyutil.fib_time_key(self.my_node_name),
                str(int(ms) or 1).encode(),
            )
        except Exception:
            pass

    # -- public (thread-safe) APIs ---------------------------------------

    def get_route_db(self) -> RouteDatabase:
        def build() -> RouteDatabase:
            return RouteDatabase(
                this_node_name=self.my_node_name,
                unicast_routes=list(self.unicast_routes.values()),
                mpls_routes=list(self.mpls_routes.values()),
            ).canonicalize()

        return self.evb.call_and_wait(build)

    def get_unicast_routes(
        self, prefixes: Optional[List[IpPrefix]] = None
    ) -> List[UnicastRoute]:
        def collect():
            if not prefixes:
                return sorted(
                    self.unicast_routes.values(), key=lambda r: r.dest
                )
            return [
                self.unicast_routes[p]
                for p in prefixes
                if p in self.unicast_routes
            ]

        return self.evb.call_and_wait(collect)

    def longest_prefix_match(self, addr: str) -> Optional[UnicastRoute]:
        """reference: Fib.cpp:164 longestPrefixMatch."""
        ip = ipaddress.ip_address(addr)

        def find() -> Optional[UnicastRoute]:
            best = None
            best_len = -1
            for prefix, route in self.unicast_routes.items():
                try:
                    net = ipaddress.ip_network(
                        f"{prefix.prefix_address.to_str()}/{prefix.prefix_length}",
                        strict=False,
                    )
                except ValueError:
                    continue
                if ip.version == net.version and ip in net:
                    if prefix.prefix_length > best_len:
                        best_len = prefix.prefix_length
                        best = route
            return best

        return self.evb.call_and_wait(find)

    def get_counters(self) -> Dict[str, int]:
        return self.evb.call_and_wait(lambda: dict(self.counters))
