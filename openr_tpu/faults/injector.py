"""Deterministic, seedable fault injection at the pipeline's seams.

Injection points are plain function calls (``fault_point("site.name")``)
placed at the real failure surfaces — device dispatch, delta
readback/consume, cold device rebuild, KvStore peer sync/flood, the Fib
thrift transport, netlink programming. A disarmed process pays one
attribute read per site crossing; nothing else.

Tests (and ``tools/chaos_report.py``) arm a site with a
``FaultSchedule``:

- ``FaultSchedule.fail_once()`` — raise on the next crossing only;
- ``FaultSchedule.fail_n(n)`` — raise on the next ``n`` crossings;
- ``FaultSchedule.fail_with_probability(p, seed)`` — raise on each
  crossing with probability ``p`` from a private ``random.Random(seed)``
  stream, so a chaos run replays bit-for-bit from its seed;
- ``FaultSchedule.delay(seconds, n)`` — sleep instead of raising (models
  a slow transport rather than a dead one).

Every fired fault bumps ``faults.injected.<site>`` (or
``faults.delayed.<site>``) in the process registry, which is how the
chaos soak proves its coverage floor. The injector is process-global:
``get_injector().reset()`` between tests.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional, Tuple

from openr_tpu.telemetry import get_registry


class FaultInjected(Exception):
    """Raised by an armed injection site when its schedule fires."""

    def __init__(self, site: str) -> None:
        super().__init__(f"injected fault at {site}")
        self.site = site


class FaultSchedule:
    """Decides, per crossing of one armed site, whether to fire.

    Mutates its own counters under the injector lock; deterministic for
    a given (constructor args, crossing sequence).
    """

    __slots__ = ("mode", "remaining", "probability", "delay_s", "_rng",
                 "fires", "period", "_crossings")

    def __init__(
        self,
        mode: str,
        remaining: Optional[int] = None,
        probability: float = 0.0,
        delay_s: float = 0.0,
        seed: int = 0,
        period: int = 0,
    ) -> None:
        self.mode = mode
        self.remaining = remaining  # None = unlimited
        self.probability = probability
        self.delay_s = delay_s
        self._rng = random.Random(seed)
        self.fires = 0
        self.period = int(period)  # fire every k-th crossing (0 = off)
        self._crossings = 0

    # -- constructors ------------------------------------------------
    @classmethod
    def fail_once(cls) -> "FaultSchedule":
        return cls("fail", remaining=1)

    @classmethod
    def fail_n(cls, n: int) -> "FaultSchedule":
        return cls("fail", remaining=int(n))

    @classmethod
    def fail_with_probability(cls, p: float, seed: int) -> "FaultSchedule":
        return cls("fail", probability=float(p), seed=seed)

    @classmethod
    def fail_every(cls, k: int) -> "FaultSchedule":
        """Fire on every k-th crossing: deterministic periodic loss
        (the twin's lossy-flood scenarios want a fixed drop cadence
        that replays identically, which probability schedules only
        give per-seed)."""
        return cls("fail", period=int(k))

    @classmethod
    def delay(
        cls, seconds: float, n: Optional[int] = None
    ) -> "FaultSchedule":
        return cls("delay", remaining=n, delay_s=float(seconds))

    # -- evaluation --------------------------------------------------
    def should_fire(self) -> bool:
        if self.period:
            self._crossings += 1
            if self._crossings % self.period:
                return False
            self.fires += 1
            return True
        if self.remaining is not None:
            if self.remaining <= 0:
                return False
            self.remaining -= 1
            self.fires += 1
            return True
        if self._rng.random() < self.probability:
            self.fires += 1
            return True
        return False


class FaultInjector:
    """Process-global registry of named injection sites."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._registered: Dict[str, None] = {}
        self._armed: Dict[str, FaultSchedule] = {}
        # read lock-free on every site crossing; only flips under lock
        self.any_armed = False

    # -- site registry -----------------------------------------------
    def register(self, site: str) -> str:
        with self._lock:
            self._registered[site] = None
        return site

    def list_sites(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._registered)

    # -- arming ------------------------------------------------------
    def arm(self, site: str, schedule: FaultSchedule) -> None:
        with self._lock:
            self._registered[site] = None
            self._armed[site] = schedule
            self.any_armed = True

    def disarm(self, site: str) -> None:
        with self._lock:
            self._armed.pop(site, None)
            self.any_armed = bool(self._armed)

    def reset(self) -> None:
        """Disarm every site (registered names survive)."""
        with self._lock:
            self._armed.clear()
            self.any_armed = False

    # -- the crossing ------------------------------------------------
    def check(self, site: str) -> None:
        with self._lock:
            schedule = self._armed.get(site)
            fire = schedule is not None and schedule.should_fire()
            delay_s = schedule.delay_s if fire else 0.0
            mode = schedule.mode if fire else ""
        if not fire:
            return
        if mode == "delay":
            get_registry().counter_bump(f"faults.delayed.{site}")
            time.sleep(delay_s)
            return
        get_registry().counter_bump(f"faults.injected.{site}")
        raise FaultInjected(site)

    def consume(self, site: str) -> bool:
        """Non-raising crossing for seams that CORRUPT rather than
        fail (e.g. ``device.corrupt_resident``): the caller mutates its
        own state when this returns True. Fired crossings still bump
        ``faults.injected.<site>`` so chaos coverage floors see them;
        ``delay`` schedules make no sense here and are treated as
        fires."""
        with self._lock:
            schedule = self._armed.get(site)
            fire = schedule is not None and schedule.should_fire()
        if not fire:
            return False
        get_registry().counter_bump(f"faults.injected.{site}")
        return True


class DeviceLostError(RuntimeError):
    """An accelerator died under resident state.

    Raised by the ``device.lost`` seam (and recognized when the runtime
    raises its own device-loss flavored ``XlaRuntimeError``); the
    dispatch/consume fault boundaries poison the residents and the
    ladder's recover rung rebuilds them from the host mirrors.
    """

    def __init__(self, site: str = "device.lost") -> None:
        super().__init__(f"device lost at {site}")
        self.site = site


# Substrings the XLA runtime uses for a lost/failed device; matched
# case-insensitively against the exception text.
_DEVICE_LOSS_MARKERS = (
    "device lost",
    "device is lost",
    "device failure",
    "deadline exceeded waiting for device",
    "hbm is corrupted",
    "data loss:",
)


def is_device_loss(exc: BaseException) -> bool:
    """True when ``exc`` means the accelerator (not the program) died.

    Covers the typed ``DeviceLostError``, the ``device.lost`` injection
    seam, and real ``XlaRuntimeError`` texts carrying a device-loss
    marker.
    """
    if isinstance(exc, DeviceLostError):
        return True
    if isinstance(exc, FaultInjected) and exc.site == "device.lost":
        return True
    if type(exc).__name__ == "XlaRuntimeError":
        msg = str(exc).lower()
        return any(m in msg for m in _DEVICE_LOSS_MARKERS)
    return False


_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    return _INJECTOR


def register_fault_site(site: str) -> str:
    """Module-import-time site declaration (shows up in list_sites()
    even before anything arms it)."""
    return _INJECTOR.register(site)


def fault_point(site: str) -> None:
    """The per-crossing hook host code calls. Disarmed cost: one
    attribute read and a falsy branch."""
    if not _INJECTOR.any_armed:
        return
    _INJECTOR.check(site)


def consume_fault(site: str) -> bool:
    """Non-raising sibling of ``fault_point`` for corrupting seams.
    Same disarmed cost: one attribute read and a falsy branch."""
    if not _INJECTOR.any_armed:
        return False
    return _INJECTOR.consume(site)
