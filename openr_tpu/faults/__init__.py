"""Fault-injection harness and graceful-degradation supervisor.

``injector`` provides named, seedable injection points at the
pipeline's real seams; ``supervisor`` owns the HEALTHY → DEGRADED →
FALLBACK ladder walked by the route engine and Decision when those
seams fail for real.
"""

from openr_tpu.faults.injector import (
    DeviceLostError,
    FaultInjected,
    FaultInjector,
    FaultSchedule,
    consume_fault,
    fault_point,
    get_injector,
    is_device_loss,
    register_fault_site,
)
from openr_tpu.faults.supervisor import (
    DegradationSupervisor,
    HealthState,
    LadderExhausted,
)

__all__ = [
    "DegradationSupervisor",
    "DeviceLostError",
    "FaultInjected",
    "FaultInjector",
    "FaultSchedule",
    "HealthState",
    "consume_fault",
    "LadderExhausted",
    "fault_point",
    "get_injector",
    "is_device_loss",
    "register_fault_site",
]
