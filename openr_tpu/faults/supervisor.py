"""Degradation supervisor: a bounded recovery ladder with a breaker.

One ``DegradationSupervisor`` guards one compute pipeline (the
route-sweep engine, the Decision SPF solve). Each call to ``run``
walks a caller-supplied ladder of rungs — e.g. warm ELL re-solve →
drain + cold device rebuild → host fallback — executing each rung AT
MOST ONCE, so a walk always terminates in ≤ len(rungs) attempts; there
is no retry loop to become unbounded. Every rung must produce the same
externally visible result (bit-identical route product), which the
parity suite proves per rung.

Health is a three-state machine exported as a registry gauge
(``<name>.health``: 0 HEALTHY / 1 DEGRADED / 2 FALLBACK) and stamped
into any active trace whenever a walk leaves the warm path:

- success on rung 0            → HEALTHY (a ``self_heals`` bump if we
  were degraded);
- success on a middle rung     → DEGRADED (the device path still works
  from cold, so the next walk probes warm again immediately);
- success on the last rung     → FALLBACK, and the circuit breaker
  (``utils/eventbase.ExponentialBackoff``) opens: until
  ``can_try_now()``, later walks start directly at the held fallback
  rung instead of hammering a dead device path. When the backoff
  elapses, one walk re-probes from rung 0 — success self-heals back to
  HEALTHY, failure re-opens the breaker with a longer delay.

If every rung fails the walk raises ``LadderExhausted`` carrying the
per-rung causes; the caller's event loop surfaces it like any other
module error (state stays FALLBACK, breaker open).
"""

from __future__ import annotations

import threading
import zlib
from enum import IntEnum
from typing import Any, Callable, List, Optional, Sequence, Tuple

from openr_tpu.telemetry import get_flight_recorder, get_registry, get_tracer
from openr_tpu.utils.eventbase import ExponentialBackoff

Rung = Tuple[str, Callable[[], Any]]


class HealthState(IntEnum):
    HEALTHY = 0
    DEGRADED = 1
    FALLBACK = 2


class LadderExhausted(RuntimeError):
    """Every rung of a degradation ladder failed in one walk."""

    def __init__(
        self, name: str, failures: List[Tuple[str, BaseException]]
    ) -> None:
        detail = "; ".join(
            f"{rung}: {type(exc).__name__}: {exc}" for rung, exc in failures
        )
        super().__init__(f"{name}: all ladder rungs failed ({detail})")
        self.failures = failures


class DegradationSupervisor:
    """Walks a recovery ladder and owns the health state machine."""

    def __init__(
        self,
        name: str,
        backoff_min_s: float = 0.05,
        backoff_max_s: float = 2.0,
        backoff_jitter: bool = True,
        backoff_seed: Optional[int] = None,
    ) -> None:
        self.name = name
        self.state = HealthState.HEALTHY
        # decorrelated jitter ON by default: supervisors that all
        # degraded on one event must not re-probe in lockstep. The seed
        # defaults to a name hash so each supervisor gets a distinct
        # but replayable stream.
        seed = (
            backoff_seed if backoff_seed is not None
            else zlib.crc32(name.encode("utf-8"))
        )
        self.breaker = ExponentialBackoff(
            backoff_min_s, backoff_max_s,
            jitter=backoff_jitter, seed=seed,
        )
        self.walks = 0
        self._held_rung = 0
        self._lock = threading.RLock()
        get_registry().gauge(
            f"{name}.health", lambda: float(int(self.state))
        )

    # ------------------------------------------------------------------
    def run(self, rungs: Sequence[Rung]) -> Any:
        """Walk the ladder once; first rung to succeed wins."""
        reg = get_registry()
        with self._lock:
            self.walks += 1
            reg.counter_bump(f"{self.name}.ladder_walks")
            start = 0
            if self.state is not HealthState.HEALTHY:
                if self.breaker.can_try_now():
                    reg.counter_bump(f"{self.name}.probes")
                else:
                    # breaker open: go straight to the rung that last
                    # worked instead of hammering the failed path
                    start = min(self._held_rung, len(rungs) - 1)
            failures: List[Tuple[str, BaseException]] = []
            for i in range(start, len(rungs)):
                rung_name, fn = rungs[i]
                try:
                    result = fn()
                except Exception as exc:
                    failures.append((rung_name, exc))
                    reg.counter_bump(
                        f"{self.name}.rung_failures.{rung_name}"
                    )
                    continue
                self._note_success(i, len(rungs), rung_name, start)
                return result
            # nothing worked: stay broken, keep the breaker open so the
            # next walk still skips ahead, and surface the causes
            reg.counter_bump(f"{self.name}.ladder_exhausted")
            # openr-lint: disable=shared-state -- health gauge reads this single enum reference unlocked; a GIL-atomic stale read only ages one scrape
            self.state = HealthState.FALLBACK
            self.breaker.report_error()
            self._held_rung = len(rungs) - 1
            get_flight_recorder().anomaly(
                "ladder_exhausted",
                reason=f"{self.name}: all {len(rungs)} rungs failed",
                ladder=self.name,
                rungs=[r for r, _ in failures],
            )
            raise LadderExhausted(self.name, failures)

    # ------------------------------------------------------------------
    def _note_success(
        self, index: int, total: int, rung_name: str, start: int
    ) -> None:
        reg = get_registry()
        prev = self.state
        if index == 0:
            new = HealthState.HEALTHY
            self.breaker.report_success()
            self._held_rung = 0
        elif index == total - 1:
            new = HealthState.FALLBACK
            self.breaker.report_error()
            self._held_rung = index
            reg.counter_bump(f"{self.name}.fallbacks")
        else:
            # the device path recovered from cold: close the breaker so
            # the very next walk re-probes the warm rung
            new = HealthState.DEGRADED
            self.breaker.report_success()
            self._held_rung = 0
            reg.counter_bump(f"{self.name}.degradations")
        if prev is not HealthState.HEALTHY and new is HealthState.HEALTHY:
            reg.counter_bump(f"{self.name}.self_heals")
        if new is not prev:
            reg.counter_bump(f"{self.name}.health_transitions")
        self.state = new
        if index > 0 or start > 0 or prev is not new:
            tracer = get_tracer()
            span = tracer.span_active(f"{self.name}.ladder")
            tracer.end_span_active(
                span,
                rung=rung_name,
                health=new.name,
                rungs_tried=index - start + 1,
            )
            get_flight_recorder().note(
                "ladder",
                name=self.name,
                rung=rung_name,
                health=new.name,
                rungs_tried=index - start + 1,
            )
