"""Fib route-programming benchmark: 10-9000 routes.

Mirrors openr/fib/tests/FibBenchmark.cpp:286-289 — time from pushing a
DecisionRouteUpdate to the routes being programmed in the (mock)
platform agent, plus incremental single-route updates against a full
table.

Run:  python -m benchmarks.bench_fib [--full]
Prints one JSON line per case.
"""

from __future__ import annotations

import argparse
import json
import time

from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
from openr_tpu.fib.fib import OPENR_CLIENT_ID, Fib
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.fib_service import MockFibAgent
from openr_tpu.types import (
    BinaryAddress,
    IpPrefix,
    NextHop,
    PrefixEntry,
)


def make_entry(i):
    prefix = IpPrefix.from_str(f"fd00:{i >> 8:x}:{i & 0xff:x}::/64")
    return RibUnicastEntry(
        prefix=prefix,
        nexthops={
            NextHop(
                address=BinaryAddress.from_str("fe80::1", if_name="eth0"),
                metric=10,
            )
        },
        best_prefix_entry=PrefixEntry(prefix=prefix),
        best_area="0",
    )


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.002)
    return False


def bench_program(n):
    agent = MockFibAgent()
    route_q = ReplicateQueue(name="bench:routeUpdates")
    fib = Fib("bench-node", agent, route_q)
    fib.start()
    try:
        update = DecisionRouteUpdate(
            unicast_routes_to_update={
                (e := make_entry(i)).prefix: e for i in range(n)
            }
        )
        t0 = time.perf_counter()
        route_q.push(update)
        ok = wait_for(lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) >= n)
        program_ms = (time.perf_counter() - t0) * 1000
        assert ok, "routes never landed in the agent"

        # incremental: one route against the full table
        extra = make_entry(n + 1)
        t0 = time.perf_counter()
        route_q.push(
            DecisionRouteUpdate(
                unicast_routes_to_update={extra.prefix: extra}
            )
        )
        ok = wait_for(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) >= n + 1
        )
        incr_ms = (time.perf_counter() - t0) * 1000
        assert ok
        print(
            json.dumps(
                {
                    "bench": f"fib.program_{n}_routes",
                    "program_ms": round(program_ms, 2),
                    "incremental_1_route_ms": round(incr_ms, 2),
                }
            ),
            flush=True,
        )
    finally:
        fib.stop()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--full", action="store_true")
    args = p.parse_args(argv)
    sizes = [10, 100, 1000] + ([9000] if args.full else [])
    for n in sizes:
        bench_program(n)


if __name__ == "__main__":
    main()
