"""PersistentStore benchmark: write/load at 10-10k keys.

Mirrors openr/config-store/tests/PersistentStoreBenchmark.cpp:161-174.

Run:  python -m benchmarks.bench_config_store [--full]
Prints one JSON line per case.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

from openr_tpu.config_store.persistent_store import PersistentStore


def bench(n):
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "store.bin")
        store = PersistentStore(path, save_throttle_s=0.0)
        try:
            payload = {"drained": True, "seq": list(range(8))}
            t0 = time.perf_counter()
            for i in range(n):
                store.store(f"key-{i}", payload)
            write_ms = (time.perf_counter() - t0) * 1000
        finally:
            store.stop()

        # cold load from disk
        store2 = PersistentStore(path, save_throttle_s=0.0)
        try:
            t0 = time.perf_counter()
            loaded = sum(
                1 for i in range(n) if store2.load(f"key-{i}") is not None
            )
            load_ms = (time.perf_counter() - t0) * 1000
            assert loaded == n
        finally:
            store2.stop()
    print(
        json.dumps(
            {
                "bench": f"config_store.{n}_keys",
                "write_ms": round(write_ms, 2),
                "load_ms": round(load_ms, 2),
            }
        ),
        flush=True,
    )


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--full", action="store_true")
    args = p.parse_args(argv)
    for n in [10, 100, 1000] + ([10000] if args.full else []):
        bench(n)


if __name__ == "__main__":
    main()
