"""Scale benchmark: sparse all-sources SPF on large fat-trees.

The BASELINE.json scale configs ("Incremental SPF under link-flap churn
... 10k-node", "100k-node ... all-sources SPF sharded") need the sparse
edge-list kernel — the dense N x N matrix stops being feasible past a
few thousand nodes. This harness times all-sources distances on a
10k-node (default; --nodes for other sizes) 3-tier fat-tree, blocked
over source chunks so the [S, E] relaxation temporary stays bounded.

On one chip the source blocks run sequentially; on a mesh each device
owns a block slice (openr_tpu.ops.spf_sparse.sharded_sparse_all_sources)
— same kernel, sharded source axis.

Run:  python -m benchmarks.bench_scale [--nodes 10000] [--block 1024]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.ops import spf_sparse


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=10000)
    p.add_argument("--block", type=int, default=1024)
    p.add_argument("--oracle-checks", type=int, default=2,
                   help="host-Dijkstra spot checks on sampled sources")
    args = p.parse_args(argv)

    topo = topologies.fat_tree_nodes(args.nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])

    t0 = time.perf_counter()
    graph = spf_sparse.compile_sparse(ls)
    compile_ms = (time.perf_counter() - t0) * 1000

    n = graph.n_pad
    block = args.block
    # warm-up one block (jit compile)
    first = np.asarray(
        spf_sparse.sparse_distances_from_sources(
            graph, np.arange(block, dtype=np.int32)
        )
    )

    t0 = time.perf_counter()
    rows_done = 0
    sample_rows = {}
    for start in range(0, n, block):
        ids = np.arange(start, start + block, dtype=np.int32)
        d_blk = np.asarray(
            spf_sparse.sparse_distances_from_sources(graph, ids)
        )
        if start == 0:
            sample_rows[0] = d_blk[0]
        rows_done += block
    all_sources_ms = (time.perf_counter() - t0) * 1000

    # oracle spot checks: row 0 vs host Dijkstra
    oracle = ls.run_spf(graph.node_names[0])
    for dst in list(graph.node_names)[:: max(1, graph.n // 50)]:
        did = graph.node_index[dst]
        want = oracle[dst].metric if dst in oracle else None
        got = int(sample_rows[0][did])
        from openr_tpu.ops.spf import INF

        assert (got >= INF) == (want is None), dst
        if want is not None:
            assert got == want, (dst, got, want)

    print(
        json.dumps(
            {
                "bench": f"scale.sparse_all_sources_{graph.n}_nodes",
                "edges": int(np.sum(graph.full_w < 2 ** 30 - 1)),
                "edge_compile_ms": round(compile_ms, 1),
                "all_sources_ms": round(all_sources_ms, 1),
                "source_block": block,
                "oracle_spot_check": "passed",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
