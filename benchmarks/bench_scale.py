"""Scale benchmark: sparse all-sources SPF on large fat-trees.

The BASELINE.json scale configs ("Incremental SPF under link-flap churn
... 10k-node", "100k-node ... all-sources SPF sharded") need the sparse
edge-list kernel — the dense N x N matrix stops being feasible past a
few thousand nodes. This harness times all-sources distances on a
10k-node (default; --nodes for other sizes) 3-tier fat-tree, blocked
over source chunks so the [S, E] relaxation temporary stays bounded.

On one chip the source blocks run sequentially; on a mesh each device
owns a block slice (openr_tpu.ops.spf_sparse.sharded_sparse_all_sources)
— same kernel, sharded source axis.

Run:  python -m benchmarks.bench_scale [--nodes 10000] [--block 1024]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from openr_tpu.graph.linkstate import LinkState
from openr_tpu.utils.compile_cache import enable as _enable_compile_cache
from openr_tpu.models import topologies
from openr_tpu.ops import spf_sparse

_enable_compile_cache()


def _relay_rtt_ms() -> float:
    """Median of five MINIMAL dispatch+readback round trips — the fixed
    per-readback transport cost. Recorded in churn artifacts so a
    median measured through the axon relay tunnel decomposes into host
    work + k RTTs; a colocated production host pays microseconds where
    the tunnel pays tens of ms, so this field is what makes
    tunnel-measured event medians comparable to CPU-measured ones."""
    import statistics

    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda v: v + 1)
    x = jnp.zeros((8,), jnp.int32)
    np.asarray(f(x))  # warm the compile
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(f(x))
        ts.append((time.perf_counter() - t0) * 1000)
    return round(statistics.median(ts), 2)


def _host_touches_by_tag() -> dict:
    """Per-tag ``ops.host_touches.<tag>`` p50s from the live registry:
    which event-window tags ran, and how many host turnarounds each
    cost per window (2 == the warm committed-dispatch contract)."""
    from openr_tpu.telemetry import get_registry

    out = {}
    for name, h in get_registry().histograms().items():
        if name.startswith("ops.host_touches.") and h.count:
            out[name[len("ops.host_touches."):]] = {
                "p50": round(h.percentile(0.50), 1),
                "count": h.count,
            }
    return out


def _get_profiler():
    from openr_tpu.telemetry import get_profiler

    return get_profiler()


def _chained_device_only_ms(step, readback, k: int = 4,
                            reps: int = 5) -> float:
    """Per-dispatch device time via K data-dependent chained dispatches
    against ONE readback: the fixed transport cost (the ~70ms axon
    relay RTT) cancels in (T_K - T_1) / (K - 1). ``step(prev)`` issues
    the next dispatch (prev is None on the first); ``readback(result)``
    forces one device->host sync. Shared by every bench in this module
    — the methodology must stay identical across benches."""
    import statistics

    def time_chain(kk: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(kk):
            out = step(out)
        readback(out)
        return (time.perf_counter() - t0) * 1000.0

    time_chain(1)  # warm any K=1 cache path
    t1 = statistics.median(time_chain(1) for _ in range(reps))
    tk = statistics.median(time_chain(k) for _ in range(reps))
    return round(max(0.0, (tk - t1) / (k - 1)), 3)


def _latency_percentiles(samples) -> dict:
    """Nearest-rank p50/p95/p99 for a per-event latency sample list —
    the DeltaPath-style distribution account every churn leg reports
    alongside its median (means hide the warm/cold split)."""
    if not samples:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
    import math

    ordered = sorted(samples)
    n = len(ordered)

    def rank(q: float) -> float:
        # nearest-rank: ceil(q*n)-th smallest, 1-indexed
        return round(
            ordered[min(n - 1, max(0, math.ceil(q * n) - 1))], 3
        )

    return {
        "p50_ms": rank(0.50),
        "p95_ms": rank(0.95),
        "p99_ms": rank(0.99),
    }


def churn_bench(nodes: int, churn_events: int) -> dict:
    """Incremental reconvergence under link-flap churn at ``nodes`` scale
    (BASELINE.json config 4) over the resident ELL graph: per event the
    host patches O(degree) edge rows, one fused dispatch re-solves the
    {src} + neighbors view, one readback returns it. Returns the result
    dict (shared by ``--churn`` here and the official ``bench.py``)."""
    import statistics

    from openr_tpu.ops import spf_sparse
    from dataclasses import replace

    topo = topologies.fat_tree_nodes(nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    graph = spf_sparse.compile_ell(ls)

    my_node = next(k for k in sorted(topo.adj_dbs) if k.startswith("rsw"))
    churn_node = next(
        k for k in sorted(topo.adj_dbs) if k.startswith("fsw")
    )
    srcs = spf_sparse.ell_source_batch(graph, ls, my_node)

    state = spf_sparse.EllState(graph)

    def churn(step):
        db = ls.get_adjacency_databases()[churn_node]
        adjs = list(db.adjacencies)
        a0 = adjs[0]
        adjs[0] = replace(a0, metric=2 + step % 5)
        ls.update_adjacency_database(
            replace(db, adjacencies=tuple(adjs))
        )
        return {churn_node, a0.other_node_name}

    def reconverge(affected):
        nonlocal srcs
        patched = spf_sparse.ell_patch(state.graph, ls, sorted(affected))
        if patched is None:
            # node set changed / row outgrew its class: full recompile
            # (renumbers node ids, so the source batch must be rebuilt)
            state.__init__(spf_sparse.compile_ell(ls))
            patched = state.graph
            srcs = spf_sparse.ell_source_batch(patched, ls, my_node)
        return np.asarray(state.reconverge(patched, srcs))

    packed = reconverge({my_node})  # warm-up compile
    # oracle gate on the warm result
    oracle = ls.run_spf(my_node)
    from openr_tpu.ops.spf import INF

    d0 = packed[: len(srcs)][0]
    for dst in list(graph.node_names)[:: max(1, graph.n // 50)]:
        did = graph.node_index[dst]
        want = oracle[dst].metric if dst in oracle else None
        assert (int(d0[did]) >= INF) == (want is None), dst
        if want is not None:
            assert int(d0[did]) == want, dst

    reconverge(churn(99))  # compile the patch-bucket program
    c0 = dict(spf_sparse.ELL_COUNTERS)
    samples = []
    for step in range(churn_events):
        affected = churn(step)
        t0 = time.perf_counter()
        reconverge(affected)
        samples.append((time.perf_counter() - t0) * 1000)
    c1 = dict(spf_sparse.ELL_COUNTERS)
    # post-churn oracle gate: the WARM-started path must still match
    # the host Dijkstra bit-for-bit after the whole mixed sequence
    packed = reconverge(churn(churn_events))
    oracle = ls.run_spf(my_node)
    d_after = packed[: len(srcs)][0]
    for dst in list(graph.node_names)[:: max(1, graph.n // 50)]:
        did = graph.node_index[dst]
        want = oracle[dst].metric if dst in oracle else None
        assert (int(d_after[did]) >= INF) == (want is None), dst
        if want is not None:
            assert int(d_after[did]) == want, dst
    import jax

    platform = jax.devices()[0].platform
    device_only = _chained_device_only_ms(
        lambda _prev: state.reconverge(state.graph, srcs),
        np.asarray,
        k=8,
    )
    median = round(statistics.median(samples), 1)
    return {
        "bench": f"scale.ell_churn_reconverge_{graph.n}_nodes",
        "events": churn_events,
        "median_ms": median,
        # nearest-rank p90 (index 8 of 10, not the max)
        "p90_ms": round(
            sorted(samples)[max(0, -(-len(samples) * 9 // 10) - 1)], 1
        ),
        **_latency_percentiles(samples),
        "device_only_ms": device_only,
        "host_overhead_ms": round(max(0.0, median - device_only), 3),
        "incremental_syncs": c1["ell_incremental_syncs"]
        - c0["ell_incremental_syncs"],
        "warm_solves": c1["ell_warm_solves"] - c0["ell_warm_solves"],
        "cold_solves": c1["ell_cold_solves"] - c0["ell_cold_solves"],
        "widen_events": c1["ell_widen_events"] - c0["ell_widen_events"],
        "platform": platform,
        "oracle_spot_check": "passed",
    }


def run_churn(args):
    print(
        json.dumps(churn_bench(args.nodes, args.churn_events)),
        flush=True,
    )


def convergence_trace_bench(
    nodes: int,
    churn_events: int = 6,
    trace_path: str = "",
    solver_backend: str = "device",
) -> dict:
    """Per-event convergence latency through the REAL module pipeline —
    KvStore publication -> Decision debounce + solve -> Fib program —
    with the telemetry tracer accounting every stage. Unlike the
    solver-only churn legs this measures the daemon path the north-star
    claim is actually about, and emits the trace artifact the claim can
    be audited against (``trace_path``: JSONL, one trace per line,
    loadable span-by-span; plus ``<trace_path>.chrome.json`` for
    chrome://tracing / Perfetto)."""
    import os
    from dataclasses import replace

    import jax

    from openr_tpu.decision.decision import Decision
    from openr_tpu.fib.fib import Fib
    from openr_tpu.kvstore.wrapper import KvStoreWrapper
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.platform.fib_service import MockFibAgent
    from openr_tpu.telemetry import get_registry, get_tracer
    from openr_tpu.types import (
        DEFAULT_AREA,
        TTL_INFINITY,
        KeySetParams,
        Value,
    )
    from openr_tpu.utils import keys as keyutil
    from openr_tpu.utils import wire

    topo = topologies.fat_tree_nodes(nodes)
    rsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("rsw"))
    fsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("fsw"))

    store = KvStoreWrapper(f"bench:{rsw}")
    route_q = ReplicateQueue(name="routeUpdates")
    decision = Decision(
        rsw,
        kvstore_updates_queue=store.store.updates_queue,
        route_updates_queue=route_q,
        debounce_min_s=0.01,
        debounce_max_s=0.25,
        solver_backend=solver_backend,
    )
    fib = Fib(rsw, MockFibAgent(), route_q, keepalive_interval_s=30.0)
    tracer = get_tracer()
    n_ring0 = len(tracer.traces())

    versions: dict = {}

    def publish(key: str, payload: bytes, originator: str) -> None:
        v = versions[key] = versions.get(key, 0) + 1
        store.set_key(key, payload, version=v, originator=originator)

    def wait_until(pred, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.005)
        return pred()

    store.start()
    decision.start()
    fib.start()
    try:
        # BULK initial load: one set_key_vals publication for the whole
        # topology so Decision sees ONE debounce window and does ONE
        # full cold build — per-key publishing at 10k+ nodes streams for
        # minutes, each debounce firing a partial-topology rebuild (and
        # a fresh jit compile at that partial shape)
        initial: dict = {}
        for name in sorted(topo.adj_dbs):
            key = keyutil.adj_key(name)
            payload = wire.dumps(topo.adj_dbs[name])
            versions[key] = 1
            initial[key] = Value(
                version=1,
                originator_id=name,
                value=payload,
                ttl=TTL_INFINITY,
                hash=wire.generate_hash(1, name, payload),
            )
        for name in sorted(topo.prefix_dbs):
            key = keyutil.prefix_db_key(name)
            payload = wire.dumps(topo.prefix_dbs[name])
            versions[key] = 1
            initial[key] = Value(
                version=1,
                originator_id=name,
                value=payload,
                ttl=TTL_INFINITY,
                hash=wire.generate_hash(1, name, payload),
            )
        store.store.set_key_vals(
            DEFAULT_AREA, KeySetParams(key_vals=initial)
        )
        # initial convergence (includes the solver's first compiles)
        assert wait_until(
            lambda: len(fib.get_route_db().unicast_routes) > 0, 1800.0
        ), "initial convergence timed out"
        # settle any still-debouncing startup publications
        wait_until(lambda: False, 0.6)

        n_before = len(tracer.traces())
        for step in range(churn_events):
            db = topo.adj_dbs[fsw]
            adjs = list(db.adjacencies)
            adjs[0] = replace(adjs[0], metric=2 + step % 5)
            db = replace(db, adjacencies=tuple(adjs))
            topo.adj_dbs[fsw] = db
            want = len(tracer.traces())
            publish(keyutil.adj_key(fsw), wire.dumps(db), fsw)
            # one traced publication -> FIB cycle per event: wait for
            # the trace to retire before the next churn so debounce
            # merges never collapse the sample count
            assert wait_until(
                lambda: len(tracer.traces()) > want, 120.0
            ), f"churn event {step} never completed a trace"
    finally:
        fib.stop()
        decision.stop()
        store.stop()

    churn_traces = tracer.traces()[n_before:]
    complete = [t for t in churn_traces if t.complete and t.well_formed()]
    e2e = [t.e2e_ms for t in complete if t.e2e_ms is not None]

    artifact = None
    if trace_path:
        os.makedirs(
            os.path.dirname(os.path.abspath(trace_path)), exist_ok=True
        )
        with open(trace_path, "w") as f:
            f.write(
                "\n".join(
                    json.dumps(t.to_dict()) for t in churn_traces
                )
                + "\n"
            )
        with open(trace_path + ".chrome.json", "w") as f:
            json.dump(tracer.chrome_trace(), f)
        artifact = trace_path

    span_ms = {}
    for span_name in ("decision.debounce", "decision.rebuild", "fib.program"):
        durs = [
            s.dur_ms
            for t in complete
            for s in t.spans
            if s.name == span_name and s.dur_ms is not None
        ]
        if durs:
            span_ms[span_name] = _latency_percentiles(durs)

    snap = get_registry().snapshot()
    return {
        "bench": f"scale.convergence_trace_{nodes}_nodes",
        "events": churn_events,
        "traces_complete": len(complete),
        "traces_incomplete": len(churn_traces) - len(complete),
        "unclosed_spans": snap.get("telemetry.traces_unclosed_spans", 0),
        "median_ms": (
            round(sorted(e2e)[len(e2e) // 2], 3) if e2e else None
        ),
        **_latency_percentiles(e2e),
        "span_ms": span_ms,
        "trace_artifact": artifact,
        "platform": jax.devices()[0].platform,
        "solver_backend": solver_backend,
        "ring_total": len(tracer.traces()) - n_ring0,
    }


def ksp2_churn_bench(nodes: int, churn_events: int,
                     ksp2_dst_count: int = 0,
                     sp_only: bool = False) -> dict:
    """Fabric churn rebuild through the full SpfSolver — the
    incremental-KSP2-engine path (BASELINE.json config 2 axis;
    reference semantics: Decision.cpp:908 selectBestPathsKsp2).
    Shared by the scale harness and the official bench.py artifact.

    ``ksp2_dst_count`` > 0 marks only that many (evenly sampled)
    prefixes as KSP2_ED_ECMP and leaves the rest SP_ECMP — the
    realistic large-fabric shape (KSP2 is a per-prefix opt-in) and the
    one that scales the ENGINE to 10k+ nodes: the all-pairs event
    dispatch covers the whole graph while host path tracing stays
    bounded by the KSP2 destination count.

    ``sp_only=True`` keeps every prefix SP_ECMP — the north-star
    framing (BASELINE.json: full-SPF reconvergence of one node's
    RouteDb at 100k): per event the device re-solves the
    {source}+neighbors view in one fused dispatch and the SP route
    reuse dirty test bounds the host rebuild to O(changed) prefixes;
    no all-pairs state exists at all."""
    import statistics
    from dataclasses import replace

    import jax

    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import (
        SPF_COUNTERS,
        SpfSolver,
    )
    from openr_tpu.types.lsdb import (
        PrefixForwardingAlgorithm,
        PrefixForwardingType,
    )

    if sp_only and ksp2_dst_count > 0:
        raise ValueError(
            "sp_only excludes ksp2_dst_count: pick one shape"
        )
    all_ksp2 = ksp2_dst_count <= 0 and not sp_only
    topo = topologies.fat_tree_nodes(
        nodes,
        forwarding_algorithm=(
            PrefixForwardingAlgorithm.KSP2_ED_ECMP
            if all_ksp2
            else PrefixForwardingAlgorithm.SP_ECMP
        ),
        forwarding_type=PrefixForwardingType.SR_MPLS,
    )
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    ps = PrefixState()
    if ksp2_dst_count > 0:
        names = sorted(topo.prefix_dbs)
        stride = max(1, len(names) // ksp2_dst_count)
        chosen = set(names[::stride][:ksp2_dst_count])
        for name in names:
            pdb = topo.prefix_dbs[name]
            if name in chosen:
                pdb = replace(
                    pdb,
                    prefix_entries=tuple(
                        replace(
                            e,
                            forwarding_algorithm=(
                                PrefixForwardingAlgorithm.KSP2_ED_ECMP
                            ),
                        )
                        for e in pdb.prefix_entries
                    ),
                )
            topo.prefix_dbs[name] = pdb
    for pdb in topo.prefix_dbs.values():
        ps.update_prefix_database(pdb)
    area_ls = {topo.area: ls}
    rsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("rsw"))
    fsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("fsw"))
    solver = SpfSolver(rsw, backend="device")
    t0 = time.perf_counter()
    solver.build_route_db(rsw, area_ls, ps)
    cold_ms = (time.perf_counter() - t0) * 1000

    def churn(step):
        db = ls.get_adjacency_databases()[fsw]
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=2 + step % 5)
        ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))

    # one full metric cycle warms every jit shape (engine cold build +
    # each masked-batch bucket) before the timed window
    for step in range(5):
        churn(step)
        solver.build_route_db(rsw, area_ls, ps)

    from openr_tpu.decision.spf_solver import get_spf_counters

    from openr_tpu.telemetry import get_registry

    _reg = get_registry()
    pd0 = _reg.counter_get("ops.pipelined_dispatches")
    or0 = _reg.counter_get("ops.overlapped_reaps")
    before = get_spf_counters()
    samples = []
    for step in range(churn_events):
        churn(step)
        t0 = time.perf_counter()
        solver.build_route_db(rsw, area_ls, ps)
        samples.append((time.perf_counter() - t0) * 1000)
    after = get_spf_counters()
    pipelined = _reg.counter_get("ops.pipelined_dispatches") - pd0
    overlapped = _reg.counter_get("ops.overlapped_reaps") - or0

    # SPECULATED leg: stage the warm view solve while a debounce
    # timer would have idled (the decision terminal's move), then
    # rebuild — the staged SpfView adopts (ops.spec_hits) and the
    # rebuild's solve window starts already warm
    spec_d0 = _reg.counter_get("ops.spec_dispatches")
    spec_h0 = _reg.counter_get("ops.spec_hits")
    spec_samples = []
    for step in range(3):
        churn(churn_events + step)
        solver.speculate_views(rsw, area_ls)
        t0 = time.perf_counter()
        solver.build_route_db(rsw, area_ls, ps)
        spec_samples.append((time.perf_counter() - t0) * 1000)
    spec_dispatches = _reg.counter_get("ops.spec_dispatches") - spec_d0
    spec_hits = _reg.counter_get("ops.spec_hits") - spec_h0

    _pd_hist = _reg.histograms().get("ops.pipeline_depth")
    _occ_hist = _reg.histograms().get("ops.host_touches.ksp2_window")
    relay_rtt = _relay_rtt_ms()
    batches_per_event = round(
        (SPF_COUNTERS["decision.ksp2_device_batches"]
         - before["decision.ksp2_device_batches"])
        / max(1, churn_events),
        2,
    )
    overlapped_per_event = overlapped / max(1, churn_events)
    return {
        "bench": (
            f"scale.fabric_{ls.num_nodes}_sp_churn_rebuild"
            if sp_only
            else f"scale.fabric_{ls.num_nodes}_ksp2_churn_rebuild"
        ),
        "ksp2_dsts": (
            0
            if sp_only
            else ksp2_dst_count if not all_ksp2 else ls.num_nodes
        ),
        "sp_route_reuses_per_event": round(
            (SPF_COUNTERS["decision.sp_route_reuses"]
             - before["decision.sp_route_reuses"])
            / max(1, churn_events),
            1,
        ),
        "events": churn_events,
        "median_ms": round(statistics.median(samples), 1),
        "p90_ms": round(
            sorted(samples)[max(0, -(-len(samples) * 9 // 10) - 1)], 1
        ),
        **_latency_percentiles(samples),
        "cold_build_ms": round(cold_ms, 1),
        "platform": jax.devices()[0].platform,
        "ksp2_host_fallbacks": SPF_COUNTERS[
            "decision.ksp2_host_fallbacks"
        ] - before["decision.ksp2_host_fallbacks"],
        # incremental device syncs per kind: the engine's fused
        # all-pairs dispatch (KSP2 shapes), plus the resident ELL band
        # deltas (the SpfView path) reported separately — they cover
        # the SAME events, so summing would double-count
        "incremental_syncs": after["decision.ksp2_incremental_syncs"]
        - before["decision.ksp2_incremental_syncs"],
        "ell_incremental_syncs": (
            after.get("decision.ell_incremental_syncs", 0)
            - before.get("decision.ell_incremental_syncs", 0)
        ),
        "warm_solves": after.get("decision.ell_warm_solves", 0)
        - before.get("decision.ell_warm_solves", 0),
        "warm_dispatches": after.get("decision.ksp2_warm_dispatches", 0)
        - before.get("decision.ksp2_warm_dispatches", 0),
        "ell_full_compiles": after["decision.ell_full_compiles"]
        - before["decision.ell_full_compiles"],
        "prewarms": after["decision.ell_prewarms"]
        - before["decision.ell_prewarms"],
        # device ROUND TRIPS per event: on a relay-backed chip each
        # dispatch+readback pays the transport RTT, so this is the
        # fixed-cost multiplier of the e2e median (the speculative
        # 1-RTT fast path exists to drive it to 1)
        "device_batches_per_event": batches_per_event,
        "relay_rtt_ms": relay_rtt,
        # pipelined-window fields (PR 16): the KSP2 relay runs one
        # chunk deep — chunk i+1's masked solve is on the stream
        # before chunk i's reap lands — so of the k chunk round trips
        # per event, ``overlapped`` hid their host turnaround behind
        # device work; the amortized RTT is what each chunk
        # EFFECTIVELY pays once the overlap is netted out
        "pipelined_dispatches_per_event": round(
            pipelined / max(1, churn_events), 2
        ),
        "overlapped_reaps_per_event": round(overlapped_per_event, 2),
        "pipeline_depth_median": (
            round(_pd_hist.percentile(0.50), 1)
            if _pd_hist is not None and _pd_hist.count else None
        ),
        "window_occupancy_touches_p50": (
            round(_occ_hist.percentile(0.50), 1)
            if _occ_hist is not None and _occ_hist.count else None
        ),
        "relay_rtt_amortized_ms": round(
            relay_rtt
            * max(0.0, batches_per_event - overlapped_per_event)
            / max(1.0, batches_per_event),
            2,
        ) if batches_per_event else relay_rtt,
        # speculated-rebuild economics: hit rate and the per-event
        # median when the view solve was staged during the debounce
        "spec_dispatches": int(spec_dispatches),
        "spec_hit_rate": (
            round(spec_hits / spec_dispatches, 2)
            if spec_dispatches else None
        ),
        "spec_median_ms": round(statistics.median(spec_samples), 1),
    }


def all_sources_bench(
    nodes: int, block: int, kernel: str = "ell",
    max_blocks: int = 0,
) -> dict:
    """All-sources SPF at ``nodes`` scale (BASELINE.json config 5 axis).
    kernel="ell": sliced-ELL gather+reduce blocks (the TPU-fast path);
    kernel="edges": the flat dst-sorted edge list + segment-min (kept
    for comparison — segment-min lowers to serialized scatters on TPU).
    Device-only per-block time is isolated by chaining K block solves
    against one readback, same as bench.py (relay transport cancels)."""
    import statistics

    import jax

    topo = topologies.fat_tree_nodes(nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    platform = jax.devices()[0].platform

    t0 = time.perf_counter()
    if kernel == "ell":
        graph = spf_sparse.compile_ell(ls)
        state = spf_sparse.EllState(graph)
        edges = int(
            sum((w < 2 ** 30 - 1).sum() for w in graph.w)
        )
        import jax.numpy as jnp

        def solve_block(ids):
            if not isinstance(ids, jax.Array):
                ids = jnp.asarray(np.asarray(ids, dtype=np.int32))
            return spf_sparse.ell_distances_from_sources(
                graph, ids, state=state
            )

    else:
        graph = spf_sparse.compile_sparse(ls)
        edges = int(np.sum(graph.full_w < 2 ** 30 - 1))

        def solve_block(ids):
            return spf_sparse.sparse_distances_from_sources(graph, ids)

    compile_ms = (time.perf_counter() - t0) * 1000

    n = graph.n_pad
    # warm-up one block (jit compile)
    np.asarray(solve_block(np.arange(block, dtype=np.int32)))

    # device-only per-block FIRST (chain K data-dependent solves, one
    # readback — fixed transport cancels in the K-vs-1 difference): the
    # full sweep below pushes the whole [N, N] product through the
    # relay (~20 MB/s observed), and that backlog would otherwise
    # inflate the chained timing by 2 orders of magnitude
    device_only_block_ms = None
    if platform != "cpu":
        ids0 = np.arange(block, dtype=np.int32)
        device_only_block_ms = _chained_device_only_ms(
            # data dependence: seed block i from block i-1's result
            lambda d: solve_block(
                ids0 if d is None else (ids0 + d[0, 0] % n) % n
            ),
            lambda d: np.asarray(d[0, 0]),
        )

    # e2e streaming sweep: solve + read back every block ([N, N] int32
    # product on the host at the end — transfer-dominated on the relay)
    import jax.numpy as jnp

    id_blocks = [
        jnp.asarray(np.arange(s, s + block, dtype=np.int32) % n)
        for s in range(0, n, block)
    ]
    if max_blocks > 0:
        # at 100k the full [N, N] readback is ~40 GB — measure a
        # representative slice and extrapolate (device_only_* already
        # covers the compute claim; the sweep is transfer-bound)
        id_blocks = id_blocks[:max_blocks]
    t0 = time.perf_counter()
    sample_row0 = None
    for i, ids in enumerate(id_blocks):
        d_blk = np.asarray(solve_block(ids))
        if i == 0:
            sample_row0 = d_blk[0]
    all_sources_ms = (time.perf_counter() - t0) * 1000

    # oracle spot checks: row 0 vs host Dijkstra
    oracle = ls.run_spf(graph.node_names[0])
    for dst in list(graph.node_names)[:: max(1, graph.n // 50)]:
        did = graph.node_index[dst]
        want = oracle[dst].metric if dst in oracle else None
        got = int(sample_row0[did])
        from openr_tpu.ops.spf import INF

        assert (got >= INF) == (want is None), dst
        if want is not None:
            assert got == want, (dst, got, want)

    n_blocks = -(-n // block)
    out = {
        "bench": f"scale.{kernel}_all_sources_{graph.n}_nodes",
        "kernel": kernel,
        "edges": edges,
        "edge_compile_ms": round(compile_ms, 1),
        "all_sources_ms": round(all_sources_ms, 1),
        "source_block": block,
        "swept_blocks": len(id_blocks),
        "total_blocks": n_blocks,
        "platform": platform,
        "oracle_spot_check": "passed",
    }
    if device_only_block_ms is not None:
        out["device_only_block_ms"] = device_only_block_ms
        out["device_only_all_sources_ms"] = round(
            device_only_block_ms * n_blocks, 1
        )
        # the remainder of the e2e sweep is host<->device transfer: the
        # [N, N] int32 product read back block-by-block (~20 MB/s
        # through the axon relay; orders of magnitude faster on a
        # directly-attached chip)
        out["readback_mb"] = round(n * block * len(id_blocks) * 4 / 1e6, 1)
        out["transfer_ms"] = round(
            max(
                0.0,
                all_sources_ms - device_only_block_ms * len(id_blocks),
            ),
            1,
        )
    return out


def route_sweep_bench(
    nodes: int, block: int, max_blocks: int = 0,
    backend: str = "ell",
) -> dict:
    """All-sources sweep with route selection CONSUMED ON-DEVICE
    (ops.route_sweep): per destination block the device computes every
    source's per-destination metric + ECMP next-hop mask, reads back
    only digests + sampled route rows. This is the transfer-fixed
    version of the config-5 axis — e2e tracks device compute instead of
    the [N, N] readback (414 MB at 10k, 40 GB at 100k).

    Oracle: sampled nodes' full route tables vs the host Dijkstra
    (reference runSpf / getNextHopsWithMetric semantics)."""
    import statistics

    import jax
    import jax.numpy as jnp

    from openr_tpu.ops import route_sweep
    from openr_tpu.ops.spf import INF

    topo = topologies.fat_tree_nodes(nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    platform = jax.devices()[0].platform

    t0 = time.perf_counter()
    if backend == "grouped":
        from openr_tpu.ops import spf_grouped

        graph = spf_grouped.compile_out_grouped(ls)
    else:
        graph = route_sweep.compile_out_ell(ls)
    # one sample per tier: a rack, a fabric and a spine switch see
    # different band shapes and ECMP fanouts
    samples = []
    for prefix in ("rsw", "fsw", "ssw"):
        nm = next(
            (k for k in graph.node_names if k.startswith(prefix)), None
        )
        if nm is not None:
            samples.append(nm)
    if backend == "grouped":
        sweeper = spf_grouped.GroupedRouteSweeper(graph, samples)
        edges = int(sum(
            (seg.w < INF).sum()
            for band in graph.bands for seg in band.segments
        ))
    else:
        sweeper = route_sweep.RouteSweeper(graph, samples)
        edges = int(sum((w < INF).sum() for w in graph.w))
    compile_ms = (time.perf_counter() - t0) * 1000

    n = graph.n_pad
    ids0 = np.arange(block, dtype=np.int32)
    np.asarray(sweeper.solve_block(ids0))  # jit warm-up

    # device-only per-block via K data-dependent chained dispatches
    # against one readback (fixed relay transport cancels)
    device_only_block_ms = None
    impl_ms = None
    if platform != "cpu":
        ids0_dev = jnp.asarray(ids0)

        def chain_ms():
            return _chained_device_only_ms(
                lambda p: sweeper.solve_block(
                    ids0_dev if p is None else (ids0 + p[0, 1] % n) % n
                ),
                lambda p: np.asarray(p[0, 0]),
            )

        if backend == "grouped":
            # contraction impl CHOSEN BY MEASUREMENT on real hardware
            # (same contract as the dense min-plus path): time jnp and
            # pallas at the bench shapes, run the winner, keep both
            # numbers in the artifact
            from openr_tpu.ops import spf_grouped

            impl_ms = {}
            ref = None
            for impl in ("jnp", "pallas", "pallas_t"):
                spf_grouped.set_grouped_impl(impl)
                try:
                    got = np.asarray(
                        sweeper.solve_block(ids0_dev)
                    )  # compile + parity gate vs the jnp product
                    if impl == "jnp":
                        # the gate's reference MUST be the jnp product:
                        # seeding it from a surviving pallas variant
                        # would let a shared pallas lowering bug
                        # parity-check against itself
                        ref = got
                    elif ref is None:
                        impl_ms["parity_unverified"] = impl
                    elif not np.array_equal(ref, got):
                        # parity failure is a CORRECTNESS signal, not an
                        # ordinary probe error: record it distinctly so a
                        # pallas/jnp divergence on real hardware is
                        # front-and-center in the artifact rather than
                        # buried in an _error string
                        impl_ms["parity_failed"] = impl
                        raise RuntimeError("pallas/jnp divergence")
                    impl_ms[impl] = chain_ms()
                except Exception as e:  # pallas probe must not kill jnp
                    impl_ms[impl] = None
                    impl_ms[f"{impl}_error"] = (
                        f"{type(e).__name__}: {e}"
                    )
            timed = [
                (v, k) for k, v in impl_ms.items()
                if isinstance(v, (int, float))
            ]
            if not timed:
                raise RuntimeError(
                    f"both contraction impls failed: {impl_ms}"
                )
            winner = min(timed)[1]
            spf_grouped.set_grouped_impl(winner)
            device_only_block_ms = impl_ms[winner]
        else:
            device_only_block_ms = chain_ms()

    # e2e sweep: every destination block solved AND route-selected on
    # device; the host receives digests + sampled route rows only
    n_sweep = min(n, max_blocks * block) if max_blocks > 0 else n
    t0 = time.perf_counter()
    if max_blocks > 0:
        # partial sweep: first K blocks through the same path, id
        # uploads up front in one async burst (same discipline as
        # sweep(); a per-block upload would serialize a relay RTT)
        blocks = [
            jnp.asarray(
                np.arange(start, start + block, dtype=np.int32) % n
            )
            for start in range(0, n_sweep, block)
        ]
        total = 0
        for ids in blocks:
            packed = np.asarray(sweeper.solve_block(ids))
            total += int(packed[:, 1].sum())
        result = None
    else:
        result = sweeper.sweep(block=block)
    e2e_ms = (time.perf_counter() - t0) * 1000

    out = {
        "bench": f"scale.route_sweep_{graph.n}_nodes",
        "kernel": f"{backend}_route_sweep",
        "edges": edges,
        "edge_compile_ms": round(compile_ms, 1),
        "e2e_ms": round(e2e_ms, 1),
        "source_block": block,
        "swept_blocks": -(-n_sweep // block),
        "total_blocks": -(-n // block),
        "samples": samples,
        "platform": platform,
        # readback per block: digest + nh_total + S metrics + S masks
        "readback_kb": round(
            n_sweep * (2 + len(samples) * (1 + sweeper.samp_v.shape[1] // 32))
            * 4 / 1024, 1
        ),
    }
    if device_only_block_ms is not None:
        out["device_only_block_ms"] = device_only_block_ms
        out["device_only_all_sources_ms"] = round(
            device_only_block_ms * (-(-n // block)), 1
        )
    if impl_ms is not None:
        out["impl_ms"] = impl_ms
        from openr_tpu.ops import spf_grouped

        out["impl"] = spf_grouped.get_grouped_impl()
    if result is not None:
        # oracle gate: every sample node's complete route table
        for nm in samples:
            got = result.routes_from(nm)
            oracle = ls.run_spf(nm)
            for dst in list(graph.node_names)[:: max(1, graph.n // 50)]:
                if dst == nm:
                    continue
                want = oracle.get(dst)
                if want is None:
                    assert dst not in got, (nm, dst)
                    continue
                g_metric, g_nhs = got[dst]
                assert g_metric == want.metric, (nm, dst)
                assert g_nhs == set(want.next_hops), (nm, dst)
        out["oracle_spot_check"] = "passed"
        out["route_rows_total"] = int(result.nh_totals[: graph.n].sum())
    return out


def route_engine_churn_bench(
    nodes: int, churn_events: int, churn_kind: str = "metric",
    sharded: bool = False, backend: str = "ell",
) -> dict:
    """Incremental NETWORK-WIDE route reconvergence (ops.route_engine):
    per churn event, ONE fused dispatch re-solves only the affected
    destination rows of the resident route product and reads back
    their digests + sample route rows — the route-server analogue of
    the reference's incremental Decision rebuild, at all-destinations
    scope. Parity gate: engine digests vs a from-scratch full sweep.

    ``churn_kind="metric"`` wiggles one adjacency's metric per event;
    ``"link"`` alternates REMOVING and RESTORING a leaf adjacency —
    real topology churn (LinkState.cpp:565-719 semantics), proving
    structure events ride the same incremental dispatch."""
    import statistics
    from dataclasses import replace

    import jax

    from openr_tpu.ops import dispatch_accounting as da
    from openr_tpu.ops import route_engine, route_sweep
    from openr_tpu.telemetry import get_registry

    topo = topologies.fat_tree_nodes(nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    names = sorted(topo.adj_dbs)
    rsw = next(k for k in names if k.startswith("rsw"))
    fsw = next(k for k in names if k.startswith("fsw"))

    mesh = None
    if sharded:
        from openr_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(jax.devices())
    cls = (
        route_engine.GroupedRouteSweepEngine
        if backend == "grouped"
        else route_engine.RouteSweepEngine
    )
    t0 = time.perf_counter()
    engine = cls(ls, [rsw], mesh=mesh)
    cold_ms = (time.perf_counter() - t0) * 1000

    # link-churn state: the adjacency pair currently removed
    churn_rsw = next(
        k for k in names if k.startswith("rsw") and k != rsw
    )
    pulled: dict = {}

    def drop_link(u, v):
        for x, y in ((u, v), (v, u)):
            db = ls.get_adjacency_databases()[x]
            keep, gone = [], []
            for a in db.adjacencies:
                (gone if a.other_node_name == y else keep).append(a)
            pulled[(x, y)] = tuple(gone)
            ls.update_adjacency_database(
                replace(db, adjacencies=tuple(keep))
            )

    def restore_link(u, v):
        for x, y in ((u, v), (v, u)):
            db = ls.get_adjacency_databases()[x]
            ls.update_adjacency_database(
                replace(
                    db,
                    adjacencies=tuple(
                        list(db.adjacencies) + list(pulled.pop((x, y)))
                    ),
                )
            )

    def churn(step):
        if churn_kind == "link":
            peer = ls.get_adjacency_databases()[churn_rsw].adjacencies[
                0
            ].other_node_name if not pulled else next(
                v for (u, v) in pulled if u == churn_rsw
            )
            if pulled:
                restore_link(churn_rsw, peer)
            else:
                drop_link(churn_rsw, peer)
            return {churn_rsw, peer}
        db = ls.get_adjacency_databases()[fsw]
        adjs = list(db.adjacencies)
        a0 = adjs[0]
        adjs[0] = replace(a0, metric=2 + step % 5)
        ls.update_adjacency_database(
            replace(db, adjacencies=tuple(adjs))
        )
        return {fsw, a0.other_node_name}

    # warm every bucket shape outside the timed window
    for step in range(4):
        engine.churn(ls, churn(step))

    # PIPELINED timed loop: every event defers its host-side delta
    # apply, which then rides the NEXT event's dispatch window (the
    # double-buffer overlap) — per-event wall time is dispatch + the
    # overlapped consume of the previous delta, never a dedicated
    # host readback stall
    samples = []
    records = []  # PendingDelta | (moved, bytes, rows, overlap_ms)
    # per-event frontier probe stats (only events that hit the
    # overflow policy contribute; engine.last_* is per-probe state)
    frontier_rows, frontier_cells, frontier_jumps = [], [], []
    # committed-dispatch accounting: per-event host touches (submit
    # phases + reap phases, 2 = the contract) and the window's
    # blocking-sync total (0 on the warm path — every readback was
    # kicked at submit time)
    touches = []
    _reg = get_registry()
    sync0 = _reg.counter_get("ops.blocking_syncs")
    disp0 = _reg.counter_get("ops.host_dispatches")
    for step in range(churn_events):
        affected = churn(step)
        probe0 = engine.frontier_resolves + engine.frontier_fallbacks
        t0 = time.perf_counter()
        with da.event_window("bench_churn") as win:
            out = engine.churn(ls, affected, defer_consume=True)
        samples.append((time.perf_counter() - t0) * 1000)
        touches.append(win.touches)
        if (
            engine.frontier_resolves + engine.frontier_fallbacks
            > probe0
            and engine.last_frontier_rows >= 0
        ):
            frontier_rows.append(engine.last_frontier_rows)
            frontier_cells.append(engine.last_frontier_cells)
            frontier_jumps.append(engine.last_frontier_jumps)
        if isinstance(out, route_engine.PendingDelta):
            records.append(out)
        elif out is not None and out != []:
            # full-width refresh: its delta was consumed inline
            records.append((
                out, engine.last_readback_bytes,
                engine.last_delta_rows, 0.0,
            ))
        else:
            # cold rebuild (None) or detection no-op ([])
            records.append((out, 0, 0, 0.0))
    t0 = time.perf_counter()
    engine.flush()  # drain the tail event's delta
    drain_ms = (time.perf_counter() - t0) * 1000
    blocking_syncs = _reg.counter_get("ops.blocking_syncs") - sync0
    host_dispatches = _reg.counter_get("ops.host_dispatches") - disp0

    # device-only per-event cost with the fixed transport cancelled:
    # K data-dependent deferred churn dispatches against ONE drain,
    # (T_K - T_1)/(K - 1) — the denominator of host_overhead_ratio
    _extra = [churn_events]

    def _chain_step(_prev):
        _extra[0] += 1
        return engine.churn(ls, churn(_extra[0]), defer_consume=True)

    device_only_ms = _chained_device_only_ms(
        _chain_step, lambda _out: engine.flush(), k=4, reps=3
    )

    # PIPELINED BURST + SPECULATION leg: the same churn stream
    # delivered the way the debounce terminal hands it over — multi
    # -event bursts whose windows submit back to back under ONE
    # pipeline drain (window N+1 on the stream before window N's reap
    # lands), then single windows whose composition was speculatively
    # dispatched while a debounce timer would have idled. Harvested
    # from the committed-dispatch registry: touches per DRAIN (~2 for
    # a whole burst vs 2 per window), window occupancy per drain,
    # pipeline depth, and the speculation hit rate.
    spec_d0 = _reg.counter_get("ops.spec_dispatches")
    spec_h0 = _reg.counter_get("ops.spec_hits")
    _step = [churn_events + 100]
    burst_samples = []
    for _ in range(3):
        evs = []
        for _k in range(3):
            _step[0] += 1
            evs.append(lambda s=_step[0]: churn(s))
        t0 = time.perf_counter()
        engine.churn_burst(ls, evs)
        burst_samples.append((time.perf_counter() - t0) * 1000)
    for _ in range(3):
        _step[0] += 1
        affected = churn(_step[0])
        engine.speculate_churn(ls, [affected])
        engine.churn_window(ls, [affected])
    spec_dispatches = _reg.counter_get("ops.spec_dispatches") - spec_d0
    spec_hits = _reg.counter_get("ops.spec_hits") - spec_h0
    _hists = _reg.histograms()

    def _drain_p50(name):
        h = _hists.get(name)
        if h is None or not h.count:
            return None
        return round(h.percentile(0.50), 1)

    windows_per_drain = _drain_p50("ops.windows_per_drain")
    relay_rtt = _relay_rtt_ms()

    affected_counts = []
    rb_bytes, delta_rows, overlap_ms = [], [], []
    for rec in records:
        if isinstance(rec, route_engine.PendingDelta):
            affected_counts.append(len(rec.names))
            rb_bytes.append(rec.readback_bytes)
            delta_rows.append(rec.delta_rows)
            overlap_ms.append(rec.overlap_ms)
        else:
            moved, b, r, o = rec
            affected_counts.append(
                len(moved) if moved is not None else -1
            )
            rb_bytes.append(b)
            delta_rows.append(r)
            overlap_ms.append(o)
    full_product_bytes = (
        engine._packed_dev.shape[0] * engine._packed_dev.shape[1] * 4
    )

    # parity gate on the final (fully drained) state
    full = route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [rsw], block=1024)
    )
    assert route_sweep.digests_by_name(engine.result) == full

    return {
        "bench": f"scale.route_engine_churn_{engine.graph.n}_nodes",
        "churn_kind": churn_kind,
        "engine_backend": backend,
        "sharded_devices": (
            mesh.devices.size if mesh is not None else 0
        ),
        "events": churn_events,
        "median_ms": round(statistics.median(samples), 1),
        "p90_ms": round(
            sorted(samples)[max(0, -(-len(samples) * 9 // 10) - 1)], 1
        ),
        **_latency_percentiles(samples),
        "cold_build_ms": round(cold_ms, 1),
        "affected_dsts_median": (
            int(statistics.median(incr))
            if (incr := [c for c in affected_counts if c >= 0])
            else None
        ),
        "cold_rebuilds_in_window": sum(
            1 for c in affected_counts if c < 0
        ),
        "incremental_events": engine.incremental_events,
        "full_refreshes": engine.full_refreshes,
        # structural-churn / frontier re-solve accounting: how many
        # events were link-level (weight to/from INF), how many of the
        # overflow events rode the frontier path vs fell back to the
        # full-width refresh, and how big the cones were
        "structural_events": engine.structural_events,
        "frontier_resolves": engine.frontier_resolves,
        "frontier_fallbacks": engine.frontier_fallbacks,
        "frontier_rows_median": (
            int(statistics.median(frontier_rows))
            if frontier_rows else None
        ),
        "frontier_cells_median": (
            round(statistics.median(frontier_cells), 1)
            if frontier_cells else None
        ),
        "frontier_jumps_median": (
            int(statistics.median(frontier_jumps))
            if frontier_jumps else None
        ),
        # delta-compacted readback accounting: bytes per event scale
        # with CHANGED rows, not the [n_pad, W] product width
        "readback_bytes_median": int(statistics.median(rb_bytes)),
        "readback_bytes_max": max(rb_bytes),
        "full_product_bytes": full_product_bytes,
        "delta_rows_median": int(statistics.median(delta_rows)),
        "delta_rows_max": max(delta_rows),
        "overlap_ms_median": round(statistics.median(overlap_ms), 3),
        "pipeline_drain_ms": round(drain_ms, 3),
        # committed-dispatch contract fields: 2 touches/event on the
        # warm path (one submit run + one reap run), 0 blocking syncs
        # (every readback kicked at submit), and the e2e-vs-device
        # ratio the host-overhead runbook recipe triages from
        "host_touches_per_event": round(
            statistics.median(touches), 1
        ),
        "host_touches_max": max(touches),
        "blocking_syncs_per_event": round(
            blocking_syncs / max(1, churn_events), 3
        ),
        "host_dispatches_per_event": round(
            host_dispatches / max(1, churn_events), 2
        ),
        "device_only_ms": device_only_ms,
        "host_overhead_ratio": round(
            statistics.median(samples) / max(device_only_ms, 1e-3), 2
        ),
        # MEASURED ratio (telemetry.profiler): window wall over sampled
        # block-for-ready device time — the headline number; the
        # derived chained-dispatch ratio above stays for comparison
        "host_overhead_ratio_measured": (
            _get_profiler().host_overhead_ratio() or None
        ),
        "host_touches_by_tag": _host_touches_by_tag(),
        # pipelined-window fields (PR 16): burst wall time, drains and
        # their occupancy/touch budget, speculation economics, and the
        # relay RTT amortized over the windows sharing one drain —
        # the number that shows ~2 touches per DRAIN, not per window
        "pipeline_burst_median_ms": round(
            statistics.median(burst_samples), 1
        ),
        "pipeline_drains": int(
            _reg.counter_get("ops.pipeline_drains")
        ),
        "pipeline_depth_median": _drain_p50("ops.pipeline_depth"),
        "touches_per_drain_p50": _drain_p50("ops.touches_per_drain"),
        "windows_per_drain_p50": windows_per_drain,
        "spec_dispatches": int(spec_dispatches),
        "spec_hit_rate": (
            round(spec_hits / spec_dispatches, 2)
            if spec_dispatches else None
        ),
        "relay_rtt_ms": relay_rtt,
        "relay_rtt_amortized_ms": round(
            relay_rtt / max(1.0, windows_per_drain or 1.0), 2
        ),
        "platform": jax.devices()[0].platform,
        "oracle_spot_check": "passed",
    }


def link_churn_bench(
    nodes: int, churn_events: int = 10,
    sharded: bool = False, backend: str = "ell",
) -> dict:
    """Paired structural-vs-metric churn legs through the resident
    route engine: the SAME topology and event count, once as metric
    wiggles (the bucketed baseline) and once as alternating link
    remove/restore (overflow events that ride the frontier re-solve).
    Reports the link-vs-metric median ratio — the PR 6 target is the
    link leg landing within ~2x of the metric leg — plus the
    frontier-vs-full split and cone-size medians for the link leg."""
    metric = route_engine_churn_bench(
        nodes, churn_events, churn_kind="metric",
        sharded=sharded, backend=backend,
    )
    link = route_engine_churn_bench(
        nodes, churn_events, churn_kind="link",
        sharded=sharded, backend=backend,
    )
    out = dict(link)
    out["bench"] = link["bench"].replace(
        "route_engine_churn", "link_churn"
    )
    out["metric_churn_median_ms"] = metric["median_ms"]
    out["metric_churn_p90_ms"] = metric["p90_ms"]
    out["link_vs_metric_ratio"] = round(
        link["median_ms"] / max(metric["median_ms"], 1e-9), 3
    )
    overflowed = link["frontier_resolves"] + link["full_refreshes"]
    out["frontier_fraction"] = (
        round(link["frontier_resolves"] / overflowed, 3)
        if overflowed else None
    )
    out["meets_2x_target"] = bool(
        link["median_ms"] <= 2.0 * metric["median_ms"]
    )
    return out


def sharded_churn_bench(
    nodes: int, churn_events: int = 10, backend: str = "ell",
) -> dict:
    """Paired sharded-vs-single churn legs plus the resharding-free
    contract accounting (issue 7): the SAME metric-churn scenario once
    over all visible devices and once single-chip, with the registry
    deltas that prove the sharded leg never paid an implicit XLA copy —
    ``ops.reshard_events`` must stay 0 across the sharded run — and the
    per-shard overlapped-readback volume (``ops.shard_readback_bytes``,
    ``ops.shard_consume_overlap_ms``). On one real chip the 8-way
    virtual mesh measures sharded dispatch overhead; on a real slice
    the ratio is the scale-out win."""
    from openr_tpu.telemetry import get_registry

    reg = get_registry()

    def contract():
        return (
            reg.counter_get("ops.reshard_events"),
            reg.counter_get("ops.shard_readback_bytes"),
        )

    r0, b0 = contract()
    sharded = route_engine_churn_bench(
        nodes, churn_events, churn_kind="metric",
        sharded=True, backend=backend,
    )
    r1, b1 = contract()
    single = route_engine_churn_bench(
        nodes, churn_events, churn_kind="metric",
        sharded=False, backend=backend,
    )

    # lazily registered: only a mesh engine's deferred consume
    # observes it, so a missing histogram means the sharded leg never
    # overlapped a readback (that would be a bug worth seeing here)
    hist = reg.histograms().get("ops.shard_consume_overlap_ms")
    out = dict(sharded)
    out["bench"] = sharded["bench"].replace(
        "route_engine_churn", "sharded_churn"
    )
    out["reshard_events"] = r1 - r0
    out["resharding_free"] = bool(r1 - r0 == 0)
    out["shard_readback_bytes"] = b1 - b0
    out["shard_consume_overlap_ms"] = (
        hist.stats() if hist is not None else None
    )
    out["single_chip_median_ms"] = single["median_ms"]
    out["single_chip_p90_ms"] = single["p90_ms"]
    out["sharded_vs_single_ratio"] = round(
        sharded["median_ms"] / max(single["median_ms"], 1e-9), 3
    )
    return out


def ell_kernel_bench(nodes: int = 1000, sources: int = 256) -> dict:
    """Paired jnp-vs-pallas sliced-ELL relax kernel leg (issue 18):
    the SAME all-sources solve on one fat-tree, once per impl — a
    bit-identity oracle gate between the two (the relax algebra has a
    unique int32 fixed point, so any mismatch is a kernel bug, not
    noise), per-relax device time via the shared chained methodology,
    and the measured winner fed into the autotuner's family-keyed
    ``ell_relax`` persistence so later ``impl="auto"`` processes
    inherit this measurement instead of re-timing a synthetic probe.
    On CPU the pallas leg runs in interpret mode — its number is a
    correctness witness there, not a speed claim; the winner is only
    recorded off-CPU for the same reason the min-plus probe is."""
    import functools

    import jax
    import jax.numpy as jnp

    from openr_tpu.ops import autotune
    from openr_tpu.ops.pallas_ell import vmem_bytes

    topo = topologies.fat_tree_nodes(nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    graph = spf_sparse.compile_ell(ls)
    k_max = max(b.k for b in graph.bands)
    s = min(sources, graph.n)
    src_ids = np.arange(s, dtype=np.int32)

    srcs_t = tuple(jnp.asarray(x) for x in graph.src)
    ws_t = tuple(jnp.asarray(x) for x in graph.w)
    ov = jnp.asarray(graph.overloaded)
    d_init = jnp.full((s, graph.n_pad), spf_sparse.INF, jnp.int32)
    d_init = d_init.at[np.arange(s), src_ids].set(0)

    @functools.partial(jax.jit, static_argnames=("bands", "impl"))
    def relax_step(d, st, wt, o, bands, impl):
        return spf_sparse._ell_relax(d, bands, st, wt, o, impl=impl)

    prev = spf_sparse.get_ell_relax_impl()
    device_ms: dict = {}
    solved: dict = {}
    try:
        for impl in ("jnp", "pallas"):
            try:
                spf_sparse.set_ell_relax_impl(impl)
                solved[impl] = np.asarray(
                    spf_sparse.ell_distances_from_sources(
                        graph, src_ids
                    )
                )

                def step(prev_d, impl=impl):
                    return relax_step(
                        d_init if prev_d is None else prev_d,
                        srcs_t, ws_t, ov, graph.bands, impl,
                    )

                device_ms[impl] = _chained_device_only_ms(
                    step, np.asarray, k=8
                )
            except Exception as e:  # noqa: BLE001 - loser, not fatal
                device_ms[f"{impl}_error"] = f"{type(e).__name__}: {e}"
    finally:
        spf_sparse.set_ell_relax_impl(prev)

    parity = (
        "jnp" in solved and "pallas" in solved
        and bool(np.array_equal(solved["jnp"], solved["pallas"]))
    )
    timed = {
        k: v for k, v in device_ms.items()
        if isinstance(v, (int, float))
    }
    winner = min(timed, key=timed.get) if timed else "jnp"
    platform = jax.devices()[0].platform
    recorded = False
    if parity and timed and platform != "cpu":
        try:
            autotune.get_autotuner().record(
                "ell_relax", f"{graph.n_pad}x{k_max}", winner, timed
            )
            recorded = True
        except Exception:  # noqa: BLE001 - persistence is best-effort
            pass
    return {
        "bench": "ell_kernel",
        "nodes": graph.n,
        "n_pad": graph.n_pad,
        "k_max": k_max,
        "bands": len(graph.bands),
        "sources": s,
        "platform": platform,
        "device_ms": device_ms,
        "oracle_parity": parity,
        "winner": winner,
        "winner_recorded": recorded,
        "vmem_bytes": vmem_bytes(graph.n_pad, k_max),
    }


def sustained_load_bench(
    nodes: int = 1000, rate: int = 240, duration_s: float = 4.0,
    p99_slo_ms: float = 5000.0, seed: int = 20260805,
) -> dict:
    """Sustained-load leg through the REAL KvStore→Decision→Fib
    pipeline (openr_tpu.load): a seeded open-loop publication stream at
    a fixed target rate with admission control (shed-by-coalescing +
    rate-adaptive debounce) and the pipelined Decision emit stage on,
    followed by a short binary-search max-sustainable-rate estimate
    against the p99 convergence SLO. Reports the e2e latency
    distribution, shed/coalesce counters, queue high-watermark, and the
    oracle-parity verdict (shedded live RouteDatabase vs unshedded
    replay)."""
    from openr_tpu.load import AdmissionConfig
    from openr_tpu.load.harness import SustainedLoadHarness

    harness = SustainedLoadHarness(
        nodes=nodes,
        seed=seed,
        solver_backend="host",
        debounce_max_s=0.05,
        admission=AdmissionConfig(shed_depth=4, cap_s=0.5),
        pipelined_emit=True,
    )
    t0 = time.perf_counter()
    harness.start(initial_timeout_s=600.0)
    start_s = time.perf_counter() - t0
    try:
        rep = harness.run_fixed_rate(
            rate, duration_s, p99_slo_ms=p99_slo_ms
        )
        search = harness.find_max_sustainable_rate(
            p99_slo_ms=p99_slo_ms,
            lo=max(25, rate // 2),
            hi=rate * 2,
            duration_s=max(1.5, duration_s / 2),
            max_probes=3,
        )
        parity = harness.check_parity()
    finally:
        harness.stop()
    out = rep.to_dict()
    out["bench"] = f"scale.sustained_load_{nodes}_e2e_ms"
    out["start_s"] = round(start_s, 3)
    out["median_ms"] = out["e2e_ms"]["p50"]
    out["p99_ms"] = out["e2e_ms"]["p99"]
    out["max_sustainable"] = search
    out["oracle_parity"] = bool(parity)
    return out


def multi_tenant_bench(
    tenants: int = 8, rounds: int = 20, seed: int = 20260805,
) -> dict:
    """Multi-tenant batched-worlds leg (ops.world_batch): B mixed-size
    tenant graphs under per-round metric churn, solved two ways —

    - SEQUENTIAL: one warm ``EllState.reconverge`` fused dispatch per
      tenant per round (the pre-tenancy status quo: N engine calls),
    - BATCHED: one ``WorldManager.solve_views`` round (one dispatch
      per shape bucket + delta-compacted readback).

    Reports per-tenant dispatch cost both ways, the batched/sequential
    ratio (the ISSUE 9 acceptance gate is <= 0.5x at B=8), bucket
    compile counts, and the tenancy counter deltas. Parity is asserted
    on the final round — a fast bench must still be a correct one.

    The fleet is mixed-size (grids + meshes, 9..126 nodes, varying
    degree) but sized to COALESCE under the arbiter's shape rounding:
    a dispatch amortizes per-call overhead across exactly the tenants
    that share a bucket, so the bench measures the design's target
    regime — many similar-scale worlds, one executable. A fleet
    spanning many buckets degrades toward the sequential cost by
    construction (each extra bucket is one more dispatch per round);
    the parity gates in tests/tools cover that shape, the throughput
    gate lives here."""
    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies
    from openr_tpu.ops.spf_sparse import (
        EllState,
        compile_ell,
        ell_patch,
        ell_source_batch,
    )
    from openr_tpu.ops.world_batch import TENANCY_COUNTERS, WorldManager

    def mk_topos():
        base = [
            topologies.grid(3),
            topologies.grid(5),
            topologies.grid(7),
            topologies.random_mesh(24, 3, seed=seed % 1000 + 7),
            topologies.random_mesh(48, 4, seed=seed % 1000 + 11),
            topologies.random_mesh(80, 4, seed=seed % 1000 + 13),
            topologies.random_mesh(104, 3, seed=seed % 1000 + 17),
            topologies.random_mesh(126, 3, seed=seed % 1000 + 19),
        ]
        while len(base) < tenants:
            base.append(
                topologies.random_mesh(
                    40, 3, seed=seed % 1000 + 23 + len(base)
                )
            )
        return base[:tenants]

    def mk_ls(topo):
        ls = LinkState(area=topo.area)
        for _name, adj_db in sorted(topo.adj_dbs.items()):
            ls.update_adjacency_database(adj_db)
        return ls

    def wiggle(ls, root, metric):
        from dataclasses import replace

        adj_db = ls.get_adjacency_databases()[root]
        adjs = list(adj_db.adjacencies)
        adjs[0] = replace(adjs[0], metric=metric)
        ls.update_adjacency_database(
            replace(adj_db, adjacencies=tuple(adjs))
        )

    # -- sequential: one warm EllState per tenant --------------------------
    seq_ls = [mk_ls(t) for t in mk_topos()]
    seq_roots = [
        sorted(ls.get_adjacency_databases())[0] for ls in seq_ls
    ]
    states = [EllState(compile_ell(ls)) for ls in seq_ls]
    versions = [ls.topology_version for ls in seq_ls]
    for i, (ls, st) in enumerate(zip(seq_ls, states)):
        np.asarray(
            st.reconverge(
                st.graph, ell_source_batch(st.graph, ls, seq_roots[i])
            )
        )
    seq_round_ms = []
    for r in range(rounds):
        for i, ls in enumerate(seq_ls):
            wiggle(ls, seq_roots[i], 40 + r)
        t0 = time.perf_counter()
        for i, (ls, st) in enumerate(zip(seq_ls, states)):
            affected = ls.affected_since(versions[i])
            versions[i] = ls.topology_version
            patched = ell_patch(
                st.graph, ls, sorted(affected), widen=True
            )
            np.asarray(
                st.reconverge(
                    patched, ell_source_batch(patched, ls, seq_roots[i])
                )
            )
        seq_round_ms.append(1000.0 * (time.perf_counter() - t0))

    # -- batched: one WorldManager over the same churn ---------------------
    bat_ls = [mk_ls(t) for t in mk_topos()]
    bat_roots = [
        sorted(ls.get_adjacency_databases())[0] for ls in bat_ls
    ]
    items = [
        (f"bt{i}", ls, root)
        for i, (ls, root) in enumerate(zip(bat_ls, bat_roots))
    ]
    compiles0 = TENANCY_COUNTERS["bucket_compiles"]
    counters0 = {k: TENANCY_COUNTERS[k] for k in TENANCY_COUNTERS}
    mgr = WorldManager(slots_per_bucket=max(8, tenants))
    mgr.solve_views(items)  # warmup (bucket compiles land here)
    bat_round_ms = []
    views = None
    for r in range(rounds):
        for i, ls in enumerate(bat_ls):
            wiggle(ls, bat_roots[i], 40 + r)
        t0 = time.perf_counter()
        views = mgr.solve_views(items)
        bat_round_ms.append(1000.0 * (time.perf_counter() - t0))

    # final-round parity: the batched rows must match a cold oracle
    from openr_tpu.ops.spf_sparse import ell_view_batch_packed

    parity = True
    for (tid, ls, root), (_g, srcs, packed) in zip(items, views):
        graph = compile_ell(ls)
        ref = np.asarray(
            ell_view_batch_packed(
                graph, ell_source_batch(graph, ls, root)
            )
        )
        parity = parity and np.array_equal(packed, ref)

    seq_med = sorted(seq_round_ms)[len(seq_round_ms) // 2]
    bat_med = sorted(bat_round_ms)[len(bat_round_ms) // 2]
    return {
        "bench": f"scale.multi_tenant_{tenants}_dispatch_ms",
        "tenants": tenants,
        "rounds": rounds,
        "sequential_round_ms": round(seq_med, 3),
        "batched_round_ms": round(bat_med, 3),
        "sequential_per_tenant_ms": round(seq_med / tenants, 4),
        "batched_per_tenant_ms": round(bat_med / tenants, 4),
        "batched_vs_sequential_ratio": round(bat_med / seq_med, 4),
        "bucket_compiles": TENANCY_COUNTERS["bucket_compiles"]
        - compiles0,
        "buckets": mgr.bucket_count(),
        "parity": bool(parity),
        "tenancy_counters": {
            k: TENANCY_COUNTERS[k] - counters0[k]
            for k in counters0
        },
    }


def recovery_bench(
    nodes: int = 200, boots: int = 3, seed: int = 20260805,
) -> dict:
    """Crash-recovery leg (openr_tpu.state): cold boot vs warm boot.

    A Decision journals a fat-tree LSDB plus a short churn tail through
    ``StatePlane`` (checkpoint + WAL + engine snapshot), then the
    process "crashes" (device caches dropped). Two boot paths race from
    the same crash point, ``boots`` times each:

    - COLD: a fresh Decision replays every publication from scratch and
      pays the cold ELL build + first solve,
    - WARM: open the backing store, ``recover()`` (journal over
      checkpoint), ``warm_boot()`` — the resident ELL state is seeded
      from the persisted snapshot and the rebuild reconverges warm.

    Reports both boot medians, the warm/cold ratio (the recovery
    design's payoff: warm << cold), the journal/checkpoint shape the
    recovery replayed, and route parity between the two boots — a fast
    warm boot that diverges is a failed one."""
    import os
    import shutil
    import tempfile
    from dataclasses import replace

    from openr_tpu.config_store.persistent_store import PersistentStore
    from openr_tpu.decision import spf_solver
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.spf_solver import reset_device_caches
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.state import StatePlane
    from openr_tpu.telemetry import get_registry
    from openr_tpu.types import Publication, Value
    from openr_tpu.utils import keys as keyutil
    from openr_tpu.utils import wire

    reg = get_registry()
    # route the bench area through the resident sliced-ELL path (the
    # one the state plane snapshots)
    spf_solver.SPARSE_NODE_THRESHOLD = 4
    topo = topologies.fat_tree_nodes(nodes)
    n = len(topo.adj_dbs)
    node = next(m for m in sorted(topo.adj_dbs) if m.startswith("rsw"))
    area = topo.area
    workdir = tempfile.mkdtemp(prefix="openr_tpu_bench_recovery_")
    path = os.path.join(workdir, "state.bin")
    versions: dict = {}
    published: list = []

    def make_decision(name, plane=None):
        return Decision(
            node,
            kvstore_updates_queue=ReplicateQueue(name=f"bkv-{name}"),
            route_updates_queue=ReplicateQueue(name=f"brt-{name}"),
            state_plane=plane,
        )

    def kv_value(key, originator, payload):
        versions[key] = versions.get(key, 0) + 1
        return Value(
            version=versions[key],
            originator_id=originator,
            value=payload,
        )

    try:
        store = PersistentStore(path)
        plane = StatePlane(store, checkpoint_every=4)
        live = make_decision("live", plane)
        initial = {}
        for adj_db in topo.adj_dbs.values():
            initial[keyutil.adj_key(adj_db.this_node_name)] = kv_value(
                keyutil.adj_key(adj_db.this_node_name),
                adj_db.this_node_name,
                wire.dumps(adj_db),
            )
        for pdb in topo.prefix_dbs.values():
            initial[keyutil.prefix_db_key(pdb.this_node_name)] = kv_value(
                keyutil.prefix_db_key(pdb.this_node_name),
                pdb.this_node_name,
                wire.dumps(pdb),
            )
        published.append(initial)
        plane.on_kvstore_merge(area, initial)
        live.process_publication(
            Publication(key_vals=dict(initial), area=area)
        )
        live.rebuild_routes("BENCH")
        live.checkpoint_state()
        # short churn tail so recovery replays a real WAL, not just the
        # checkpoint
        mutated = dict(topo.adj_dbs)
        for i, name in enumerate(sorted(mutated)[:4]):
            adj_db = mutated[name]
            adjs = list(adj_db.adjacencies)
            adjs[0] = replace(adjs[0], metric=10 + i)
            mutated[name] = replace(adj_db, adjacencies=tuple(adjs))
            kv = {
                keyutil.adj_key(name): kv_value(
                    keyutil.adj_key(name), name,
                    wire.dumps(mutated[name]),
                )
            }
            published.append(kv)
            plane.on_kvstore_merge(area, kv)
            live.process_publication(
                Publication(key_vals=dict(kv), area=area)
            )
            live.rebuild_routes("BENCH")
        live.checkpoint_state()
        routes_live = wire.dumps(live.route_db.to_route_db(node))
        store.stop()

        warm_ms, cold_ms = [], []
        warm_seeds0 = reg.counter_get("state.warm_seeds")
        rec = None
        routes_warm = routes_cold = None
        for _ in range(boots):
            # warm: store open + recover + warm_boot, from a crashed
            # process (resident device state gone)
            reset_device_caches()
            t0 = time.perf_counter()
            store2 = PersistentStore(path)
            plane2 = StatePlane(store2)
            rec = plane2.recover()
            warm = make_decision("warm", plane2)
            warm.warm_boot(rec)
            warm_ms.append(1000.0 * (time.perf_counter() - t0))
            routes_warm = wire.dumps(warm.route_db.to_route_db(node))
            store2.stop()

            # cold: replay every publication from scratch
            reset_device_caches()
            t0 = time.perf_counter()
            cold = make_decision("cold")
            for kv in published:
                cold.process_publication(
                    Publication(key_vals=dict(kv), area=area)
                )
            cold.rebuild_routes("BENCH")
            cold_ms.append(1000.0 * (time.perf_counter() - t0))
            routes_cold = wire.dumps(cold.route_db.to_route_db(node))

        warm_med = sorted(warm_ms)[len(warm_ms) // 2]
        cold_med = sorted(cold_ms)[len(cold_ms) // 2]
        return {
            "bench": f"scale.recovery_{n}_warm_boot_ms",
            "nodes": n,
            "boots": boots,
            "warm_boot_ms": round(warm_med, 3),
            "cold_boot_ms": round(cold_med, 3),
            "warm_vs_cold_ratio": round(
                warm_med / max(cold_med, 1e-9), 4
            ),
            "journal_replayed": rec.journal_replayed,
            "had_checkpoint": rec.had_checkpoint,
            "warm_seeds": reg.counter_get("state.warm_seeds")
            - warm_seeds0,
            "parity": bool(
                routes_warm == routes_cold == routes_live
            ),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def integrity_audit_bench(
    nodes: int = 1000, churn_events: int = 24, seed: int = 0,
) -> dict:
    """Integrity-plane overhead leg (openr_tpu.integrity): the same
    metric-churn loop timed twice on one warm resident engine —
    auditing DISARMED (nothing registered; Decision's hook is one
    registry check) vs ARMED as shipped (production defaults: the
    wall-clock ``min_interval_s`` rate limit gates the hook, so a
    churn storm pays at most one audit pass per second and the MEDIAN
    event pays only the early-return check). Acceptance gate: armed
    e2e median within 5% of disarmed, zero violations on healthy
    state, and the audited product bit-identical to the from-scratch
    host sweep. The full forced audit pass (tiers 1+2 + row oracle)
    is timed separately — that is the cost one event per rate-limit
    window absorbs, reported for sizing, not gated on the median."""
    import statistics
    from dataclasses import replace

    import jax

    from openr_tpu.integrity.auditor import IntegrityAuditor
    from openr_tpu.ops import route_engine, route_sweep
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    topo = topologies.fat_tree_nodes(nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    names = sorted(topo.adj_dbs)
    rsw = next(k for k in names if k.startswith("rsw"))
    fsw = next(k for k in names if k.startswith("fsw"))
    engine = route_engine.RouteSweepEngine(ls, [rsw])

    def churn(step):
        db = ls.get_adjacency_databases()[fsw]
        adjs = list(db.adjacencies)
        a0 = adjs[0]
        adjs[0] = replace(a0, metric=2 + step % 5)
        ls.update_adjacency_database(
            replace(db, adjacencies=tuple(adjs))
        )
        return {fsw, a0.other_node_name}

    # warm the dispatch shapes AND the audit kernels outside both
    # timed windows — the jit compiles must not land in either median
    aud = IntegrityAuditor(seed=seed)
    aud.register(engine)
    for step in range(8):
        engine.churn(ls, churn(step))
    assert aud.audit_now()[-1]["verdict"] == "clean"
    # the cost one event per rate-limit window absorbs: a full forced
    # pass, oracle included (steady-state passes skip the oracle
    # ``oracle_every - 1`` times out of ``oracle_every``)
    t0 = time.perf_counter()
    assert aud.audit_now()[-1]["verdict"] == "clean"
    audit_pass_ms = (time.perf_counter() - t0) * 1000
    aud.unregister(engine)

    def timed_loop(step0, audit):
        samples = []
        for step in range(step0, step0 + churn_events):
            affected = churn(step)
            t0 = time.perf_counter()
            engine.churn(ls, affected)
            if audit:
                aud.on_converge()
            samples.append((time.perf_counter() - t0) * 1000)
        return samples

    disarmed = timed_loop(8, audit=False)
    v0 = sum(
        reg.counter_get(f"integrity.violations.{t}")
        for t in ("residual", "digest", "oracle")
    )
    a0 = reg.counter_get("integrity.audits")
    aud.register(engine)
    armed = timed_loop(8 + churn_events, audit=True)
    aud.unregister(engine)

    audits = reg.counter_get("integrity.audits") - a0
    violations = (
        sum(
            reg.counter_get(f"integrity.violations.{t}")
            for t in ("residual", "digest", "oracle")
        )
        - v0
    )
    # parity gate: the audited resident product vs a from-scratch
    # full sweep — an audit plane that perturbs routes is a bug
    full = route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [rsw], block=1024)
    )
    assert route_sweep.digests_by_name(engine.result) == full
    dis_med = statistics.median(disarmed)
    arm_med = statistics.median(armed)
    overhead = (arm_med - dis_med) / max(dis_med, 1e-9)
    return {
        "bench": f"scale.integrity_audit_{engine.graph.n}_churn_ms",
        "nodes": engine.graph.n,
        "events": churn_events,
        "disarmed_median_ms": round(dis_med, 3),
        "armed_median_ms": round(arm_med, 3),
        "audit_overhead_pct": round(100.0 * overhead, 2),
        "overhead_within_5pct": bool(overhead < 0.05),
        "audit_pass_ms": round(audit_pass_ms, 3),
        "audits": audits,
        "violations": violations,
        "platform": jax.devices()[0].platform,
        "oracle_spot_check": "passed",
    }


def fleet_twin_bench(
    nodes: int = 16, events: int = 10, seed: int = 20260805,
) -> dict:
    """Digital-twin leg (openr_tpu.twin): per-event fleet
    reconvergence solved two ways over the SAME LSDB stream —

    - BATCHED: the twin's one ``solve_views`` wave (all N vantages in
      one dispatch, vantage-view packing sharing one compiled graph),
    - SEQUENTIAL: N single-tenant ``solve_view`` calls per event (the
      pre-twin status quo: each vantage its own dispatch).

    Both sides measure device-view production only (route-db builds
    are identical host work either way); the final event's packed
    views are compared bit for bit — a fast bench must still be a
    correct one. ``make twin-smoke`` is the hard CI gate; this leg
    folds the fleet-throughput numbers into the official artifact."""
    import time as _time

    import jax
    import numpy as np

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.load.generator import LoadGenerator
    from openr_tpu.models import topologies
    from openr_tpu.ops.world_batch import TENANCY_COUNTERS, WorldManager
    from openr_tpu.twin import FabricTwin
    from openr_tpu.types import AdjacencyDatabase
    from openr_tpu.utils import keys as keyutil
    from openr_tpu.utils import wire

    topo = topologies.ring(nodes)
    roots = sorted(topo.adj_dbs)
    twin = FabricTwin(topo)
    twin.converge()  # warm the fleet bucket
    items = [(twin._tid(n), twin.ls, n) for n in roots]

    seq_mgr = WorldManager(slots_per_bucket=1, max_resident=nodes)
    ls_seq = LinkState(topo.area)
    for n in roots:
        ls_seq.update_adjacency_database(topo.adj_dbs[n])
    for r in roots:
        seq_mgr.solve_view(f"seq/{r}", ls_seq, r)  # warm each world

    gen = LoadGenerator(topo, seed=seed % 1000)
    gen.initial_key_vals()
    batched_s = seq_s = 0.0
    applied = 0
    twin_dispatches = 0
    while applied < events:
        ev = gen.next_event()
        if not keyutil.is_adj_key(ev.key):
            continue  # prefix events cost no SPF wave on either side
        applied += 1
        db = wire.loads(ev.payload, AdjacencyDatabase)
        twin.ls.update_adjacency_database(db)
        d0 = TENANCY_COUNTERS["dispatches"]
        t0 = _time.perf_counter()
        views_b = twin.manager.solve_views(items)
        batched_s += _time.perf_counter() - t0
        twin_dispatches += TENANCY_COUNTERS["dispatches"] - d0
        ls_seq.update_adjacency_database(db)
        t0 = _time.perf_counter()
        views_s = [
            seq_mgr.solve_view(f"seq/{r}", ls_seq, r) for r in roots
        ]
        seq_s += _time.perf_counter() - t0
    parity = all(
        sb == ss
        and np.array_equal(np.asarray(pb), np.asarray(ps))
        for (_gb, sb, pb), (_gs, ss, ps) in zip(views_b, views_s)
    )
    assert parity, "fleet twin bench diverged from sequential oracle"
    twin.close()
    return {
        "vantages": nodes,
        "events": applied,
        "batched_ms_per_event": round(1000.0 * batched_s / applied, 3),
        "sequential_ms_per_event": round(1000.0 * seq_s / applied, 3),
        "ratio": round(batched_s / seq_s, 4) if seq_s else None,
        "dispatches_per_event": twin_dispatches / float(applied),
        "parity": parity,
        "platform": jax.devices()[0].platform,
    }


def solver_service_bench(
    tenants: int = 64, rounds: int = 10, submitters: int = 8,
    seed: int = 20260806,
) -> dict:
    """Solver-as-a-service leg (openr_tpu.serve): B tenants of mixed
    SLO class driven through a live ``SolverService`` wave loop by
    ``submitters`` concurrent threads (the in-process stand-in for
    client daemons — the TCP wire is the smoke gate's job, the
    scheduler is this leg's). Each round every submitter churns one
    metric per tenant and solicits a solve; concurrent submission is
    what makes requests pile into shared waves.

    Reports per-class latency percentiles (enqueue -> delivery),
    aggregate solves/s, waves and mean requests-per-wave, the wave
    join / preemption counter deltas, and the service-overhead ratio:
    served mean per-solve cost vs the same fleet solved as one direct
    ``WorldManager.solve_views`` batch per round (the scheduler-free
    floor). Parity is asserted on the final round — a fast server must
    still be a correct one."""
    import threading as _threading

    import jax

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies
    from openr_tpu.ops.spf_sparse import (
        compile_ell,
        ell_source_batch,
        ell_view_batch_packed,
    )
    from openr_tpu.ops.world_batch import TENANCY_COUNTERS, WorldManager
    from openr_tpu.serve.service import SolverService
    from openr_tpu.serve.slo import SLO_TABLE

    def mk_ls(i):
        kind = i % 3
        if kind == 0:
            topo = topologies.grid(3 + i % 3)
        elif kind == 1:
            topo = topologies.ring(8 + 2 * (i % 4))
        else:
            topo = topologies.random_mesh(
                20 + i % 16, 3, seed=seed % 1000 + i
            )
        ls = LinkState(area=topo.area)
        for _name, adj_db in sorted(topo.adj_dbs.items()):
            ls.update_adjacency_database(adj_db)
        return ls

    def wiggle(ls, root, metric):
        from dataclasses import replace

        adj_db = ls.get_adjacency_databases()[root]
        adjs = list(adj_db.adjacencies)
        adjs[0] = replace(adjs[0], metric=metric)
        ls.update_adjacency_database(
            replace(adj_db, adjacencies=tuple(adjs))
        )

    classes = sorted(SLO_TABLE)
    fleet = []
    for i in range(tenants):
        ls = mk_ls(i)
        fleet.append((
            f"b{i}", ls, sorted(ls.get_adjacency_databases())[0],
            classes[i % len(classes)],
        ))

    svc = SolverService(
        manager=WorldManager(
            slots_per_bucket=max(64, tenants), max_resident=2 * tenants
        )
    ).start()
    lat_ms: dict = {cls: [] for cls in classes}
    lat_lock = _threading.Lock()
    try:
        for tid, _ls, _root, slo in fleet:
            svc.register(tid, slo)
        # warmup: cold placements + one churn round, so both the cold
        # and the warm-incremental dispatch executables (and the delta
        # readback) are compiled before the measured rounds
        for tid, ls, root, _slo in fleet:
            svc.solve(tid, ls, root)
        for tid, ls, root, _slo in fleet:
            wiggle(ls, root, 39)
            svc.solve(tid, ls, root)
        joins0 = TENANCY_COUNTERS["wave_joins"]
        pre0 = TENANCY_COUNTERS["wave_preemptions"]
        waves0 = svc.waves()

        shard = max(1, -(-len(fleet) // submitters))
        shards = [
            fleet[i : i + shard] for i in range(0, len(fleet), shard)
        ]

        def drive(mine, r):
            for tid, ls, root, slo in mine:
                wiggle(ls, root, 40 + r)
                t0 = time.perf_counter()
                svc.solve(tid, ls, root)
                ms = 1000.0 * (time.perf_counter() - t0)
                with lat_lock:
                    lat_ms[slo].append(ms)

        t_serve0 = time.perf_counter()
        for r in range(rounds):
            threads = [
                _threading.Thread(target=drive, args=(mine, r))
                for mine in shards
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        serve_s = time.perf_counter() - t_serve0
        waves = svc.waves() - waves0
        joins = TENANCY_COUNTERS["wave_joins"] - joins0
        preemptions = TENANCY_COUNTERS["wave_preemptions"] - pre0

        # parity on the final round's state, tenant-by-tenant
        parity = True
        for tid, ls, root, _slo in fleet[:: max(1, tenants // 8)]:
            graph = compile_ell(ls)
            ref = np.asarray(ell_view_batch_packed(
                graph, ell_source_batch(graph, ls, root)
            ))
            _g, _srcs, packed = svc.solve(tid, ls, root)
            if not np.array_equal(packed, ref):
                parity = False
    finally:
        svc.stop()

    # scheduler-free floor: the same fleet, one direct batched
    # solve_views per round on a private manager
    mgr = WorldManager(
        slots_per_bucket=max(64, tenants), max_resident=2 * tenants
    )
    direct_ls = [mk_ls(i) for i in range(tenants)]
    direct = [
        (f"d{i}", ls, sorted(ls.get_adjacency_databases())[0])
        for i, ls in enumerate(direct_ls)
    ]
    mgr.solve_views(direct)  # warmup
    t0 = time.perf_counter()
    for r in range(rounds):
        for _tid, ls, root in direct:
            wiggle(ls, root, 40 + r)
        mgr.solve_views(direct)
    direct_s = time.perf_counter() - t0

    def pct(samples, q):
        if not samples:
            return None
        w = sorted(samples)
        return round(
            w[min(len(w) - 1, max(0, int(round(q * (len(w) - 1)))))], 3
        )

    total = rounds * tenants
    return {
        "tenants": tenants,
        "rounds": rounds,
        "submitters": submitters,
        "solves_per_s": round(total / serve_s, 1) if serve_s else None,
        "latency_ms": {
            cls: {"p50": pct(s, 0.5), "p99": pct(s, 0.99)}
            for cls, s in sorted(lat_ms.items())
        },
        "waves": waves,
        "requests_per_wave": round(total / waves, 2) if waves else None,
        "wave_joins": joins,
        "wave_preemptions": preemptions,
        "served_ms_per_solve": round(1000.0 * serve_s / total, 3),
        "direct_ms_per_solve": round(1000.0 * direct_s / total, 3),
        "service_overhead_ratio": (
            round(serve_s / direct_s, 3) if direct_s else None
        ),
        "parity": parity,
        "platform": jax.devices()[0].platform,
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=10000)
    p.add_argument("--block", type=int, default=1024)
    p.add_argument("--max-blocks", type=int, default=0,
                   help="sweep only the first K source blocks (0 = all); "
                        "the 100k full-product readback is ~40 GB")
    p.add_argument("--kernel", choices=("ell", "edges"), default="ell")
    p.add_argument("--churn", action="store_true",
                   help="run the incremental ELL churn scenario instead "
                        "of all-sources")
    p.add_argument("--churn-events", type=int, default=10)
    p.add_argument("--routes-churn", action="store_true",
                   help="incremental network-wide route reconvergence "
                        "via the resident route engine")
    p.add_argument("--churn-kind", choices=("metric", "link"),
                   default="metric",
                   help="routes-churn event type: metric wiggle, or "
                        "alternating link remove/restore (topology "
                        "churn on the incremental path)")
    p.add_argument("--link-churn", action="store_true",
                   help="paired metric+link churn legs through the "
                        "resident route engine: link-vs-metric median "
                        "ratio, frontier-vs-full split, cone medians")
    p.add_argument("--sharded-churn", action="store_true",
                   help="paired sharded-vs-single metric-churn legs "
                        "with the resharding-free contract deltas "
                        "(ops.reshard_events, shard readback bytes, "
                        "consume-overlap histogram)")
    p.add_argument("--sharded", action="store_true",
                   help="routes-churn: shard the resident engine over "
                        "all visible devices (the past-12k design; on "
                        "one chip this measures the sharded dispatch "
                        "overhead)")
    p.add_argument("--routes", action="store_true",
                   help="all-sources sweep with on-device route "
                        "selection (digest + sample readback only)")
    p.add_argument("--traces", action="store_true",
                   help="convergence-trace leg: churn through the real "
                        "KvStore->Decision->Fib pipeline with the "
                        "telemetry tracer on, emitting a per-event "
                        "trace artifact + latency percentiles")
    p.add_argument("--trace-path", default="churn_traces.jsonl",
                   help="traces leg: JSONL artifact path (a "
                        ".chrome.json twin is written next to it)")
    p.add_argument("--solver-churn", action="store_true",
                   help="full SpfSolver churn rebuild of one node's "
                        "RouteDb (the north-star framing)")
    p.add_argument("--ksp2-dsts", type=int, default=0,
                   help="solver-churn: mark this many prefixes "
                        "KSP2_ED_ECMP (0 = every prefix KSP2)")
    p.add_argument("--sp-only", action="store_true",
                   help="solver-churn: keep every prefix SP_ECMP "
                        "(no KSP2 engine state at all)")
    p.add_argument("--backend", choices=("ell", "grouped"),
                   default="ell",
                   help="route-sweep relaxation backend: per-edge ELL "
                        "gather, or block-bipartite grouped (dense)")
    p.add_argument("--multi-tenant", action="store_true",
                   help="batched-worlds leg: B mixed-size tenant "
                        "graphs under churn, one batched dispatch vs "
                        "N sequential warm engine calls")
    p.add_argument("--tenants", type=int, default=8)
    p.add_argument("--integrity-audit", action="store_true",
                   help="integrity-plane overhead leg: the same warm "
                        "metric-churn loop audited every event vs "
                        "disarmed (gate: armed median within 5%)")
    args = p.parse_args(argv)
    if args.integrity_audit:
        print(
            json.dumps(
                integrity_audit_bench(
                    args.nodes, max(12, args.churn_events)
                )
            ),
            flush=True,
        )
        return
    if args.multi_tenant:
        print(
            json.dumps(
                multi_tenant_bench(
                    args.tenants, rounds=max(20, args.churn_events)
                )
            ),
            flush=True,
        )
        return
    if args.churn:
        run_churn(args)
        return
    if args.traces:
        print(
            json.dumps(
                convergence_trace_bench(
                    args.nodes, args.churn_events,
                    trace_path=args.trace_path,
                )
            ),
            flush=True,
        )
        return
    if args.solver_churn:
        print(
            json.dumps(
                ksp2_churn_bench(
                    args.nodes, args.churn_events,
                    ksp2_dst_count=args.ksp2_dsts,
                    sp_only=args.sp_only,
                )
            ),
            flush=True,
        )
        return
    if args.link_churn:
        print(
            json.dumps(
                link_churn_bench(
                    args.nodes, args.churn_events,
                    sharded=args.sharded,
                    backend=args.backend,
                )
            ),
            flush=True,
        )
        return
    if args.sharded_churn:
        print(
            json.dumps(
                sharded_churn_bench(
                    args.nodes, args.churn_events,
                    backend=args.backend,
                )
            ),
            flush=True,
        )
        return
    if args.routes_churn:
        print(
            json.dumps(
                route_engine_churn_bench(
                    args.nodes, args.churn_events,
                    churn_kind=args.churn_kind,
                    sharded=args.sharded,
                    backend=args.backend,
                )
            ),
            flush=True,
        )
        return
    if args.routes:
        print(
            json.dumps(
                route_sweep_bench(
                    args.nodes, args.block, max_blocks=args.max_blocks,
                    backend=args.backend,
                )
            ),
            flush=True,
        )
        return
    print(
        json.dumps(
            all_sources_bench(
                args.nodes, args.block, args.kernel,
                max_blocks=args.max_blocks,
            )
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
