"""Decision pipeline benchmark: full route build + incremental churn.

Mirrors the reference parameter grids
(openr/decision/tests/DecisionBenchmark.cpp:12-29 — BM_DecisionGrid at
10/100/1000[/10000] nodes SP_ECMP and 10/100 KSP2_ED_ECMP,
BM_DecisionFabric at 344/1000 SP_ECMP; fixture generators
openr/decision/tests/RoutingBenchmarkUtils.cpp:205 createGrid, :356
createFabric). Each case measures (a) the cold full route build and
(b) the incremental rebuild after one adjacency metric change, through
the same SpfSolver the daemon uses.

Run:  python -m benchmarks.bench_decision [--backend device|host|native]
      [--full]   # adds the 10000-node grid / 5000-node fabric points
Prints one JSON line per case.
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    PrefixEntry,
)
from openr_tpu.types.lsdb import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


def load(topo, forwarding=None):
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    ps = PrefixState()
    for pdb in topo.prefix_dbs.values():
        if forwarding is not None:
            ftype, falgo = forwarding
            pdb = type(pdb)(
                this_node_name=pdb.this_node_name,
                prefix_entries=tuple(
                    PrefixEntry(
                        prefix=e.prefix,
                        type=e.type,
                        forwarding_type=ftype,
                        forwarding_algorithm=falgo,
                    )
                    for e in pdb.prefix_entries
                ),
                area=pdb.area,
            )
        ps.update_prefix_database(pdb)
    return ls, ps


def churn_one_metric(ls, node, step):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    a0 = adjs[0]
    adjs[0] = Adjacency(
        other_node_name=a0.other_node_name,
        if_name=a0.if_name,
        other_if_name=a0.other_if_name,
        metric=2 + step % 5,
        next_hop_v6=a0.next_hop_v6,
        next_hop_v4=a0.next_hop_v4,
        adj_label=a0.adj_label,
    )
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=db.this_node_name,
            is_overloaded=db.is_overloaded,
            adjacencies=tuple(adjs),
            node_label=db.node_label,
            area=db.area,
        )
    )


def run_case(name, topo, my_node, churn_node, backend, forwarding=None,
             iters=3):
    ls, ps = load(topo, forwarding)
    area_ls = {topo.area: ls}
    solver = SpfSolver(my_node, backend=backend)

    t0 = time.perf_counter()
    rdb = solver.build_route_db(my_node, area_ls, ps)
    cold_ms = (time.perf_counter() - t0) * 1000
    n_routes = len(rdb.unicast_routes) if rdb else 0

    samples = []
    for it in range(iters):
        churn_one_metric(ls, churn_node, it)
        t0 = time.perf_counter()
        solver.build_route_db(my_node, area_ls, ps)
        samples.append((time.perf_counter() - t0) * 1000)
    print(
        json.dumps(
            {
                "bench": f"decision.{name}",
                "backend": backend,
                "nodes": len(topo.adj_dbs),
                "unicast_routes": n_routes,
                "cold_build_ms": round(cold_ms, 2),
                "churn_rebuild_ms": round(statistics.median(samples), 2),
            }
        ),
        flush=True,
    )


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--backend", default="device",
                   choices=["device", "host", "native"])
    p.add_argument("--full", action="store_true",
                   help="include the largest (slow) parameter points")
    args = p.parse_args(argv)

    grid_sizes = [10, 100, 1000] + ([10000] if args.full else [])
    for n in grid_sizes:
        side = max(2, int(n ** 0.5))
        topo = topologies.grid(side)
        run_case(
            f"grid_{side * side}_sp_ecmp", topo, "node-0", "node-1",
            args.backend,
        )

    ksp2 = (PrefixForwardingType.SR_MPLS,
            PrefixForwardingAlgorithm.KSP2_ED_ECMP)
    # 1000 exceeds the reference's KSP2 grid (10/100) — BASELINE config 2
    for n in [10, 100, 1000]:
        side = max(2, int(n ** 0.5))
        topo = topologies.grid(side)
        run_case(
            f"grid_{side * side}_ksp2_ed_ecmp", topo, "node-0", "node-1",
            args.backend, forwarding=ksp2,
        )

    fabric_sizes = [344, 1000] + ([5000] if args.full else [])
    for n in fabric_sizes:
        topo = topologies.fat_tree_nodes(n)
        rsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("rsw"))
        fsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("fsw"))
        run_case(
            f"fabric_{len(topo.adj_dbs)}_sp_ecmp", topo, rsw, fsw,
            args.backend,
        )

    if args.full:
        # fabric-scale KSP2: the device-batched masked-SPF prefetch's
        # home turf (one dispatch replaces N per-destination Dijkstras)
        topo = topologies.fat_tree_nodes(1000)
        rsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("rsw"))
        fsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("fsw"))
        run_case(
            f"fabric_{len(topo.adj_dbs)}_ksp2_ed_ecmp", topo, rsw, fsw,
            args.backend, forwarding=ksp2,
        )


if __name__ == "__main__":
    main()
