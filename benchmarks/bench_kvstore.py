"""KvStore benchmark: merge / dump / flood at 10-10k keys.

Mirrors openr/kvstore/tests/KvStoreBenchmark.cpp:294-312 (mergeKeyValues
and dumpAll at 10/100/1000/10000 keys, flood propagation between peered
stores).

Run:  python -m benchmarks.bench_kvstore [--full]
Prints one JSON line per case.
"""

from __future__ import annotations

import argparse
import json
import time

from openr_tpu.kvstore.store import merge_key_values
from openr_tpu.kvstore.wrapper import KvStoreWrapper, link_bidirectional
from openr_tpu.types.kvstore import Value


def make_kvs(n, version=1):
    return {
        f"prefix:node-{i}": Value(
            version=version,
            originator_id=f"node-{i}",
            value=(b"v" * 100) + str(i).encode(),
            ttl=-1,
            ttl_version=0,
        )
        for i in range(n)
    }


def bench_merge(n, iters=10):
    base = make_kvs(n, version=1)
    incoming = make_kvs(n, version=2)
    samples = []
    for _ in range(iters):
        store = dict(base)
        t0 = time.perf_counter()
        merge_key_values(store, incoming)
        samples.append((time.perf_counter() - t0) * 1000)
    print(
        json.dumps(
            {
                "bench": f"kvstore.merge_{n}_keys",
                "merge_ms": round(min(samples), 3),
            }
        ),
        flush=True,
    )


def bench_dump(n, iters=10):
    store = KvStoreWrapper(f"dump-{n}")
    store.start()
    try:
        for key, val in make_kvs(n).items():
            store.set_key(key, val.value, version=1,
                          originator=val.originator_id)
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            dumped = store.dump()
            samples.append((time.perf_counter() - t0) * 1000)
        assert len(dumped) == n
        print(
            json.dumps(
                {
                    "bench": f"kvstore.dump_{n}_keys",
                    "dump_ms": round(min(samples), 3),
                }
            ),
            flush=True,
        )
    finally:
        store.stop()


def bench_flood(n):
    """Time for n keys set on store A to appear on peered store B."""
    a = KvStoreWrapper(f"flood-a-{n}")
    b = KvStoreWrapper(f"flood-b-{n}")
    a.start()
    b.start()
    try:
        link_bidirectional(a, b)
        deadline = time.time() + 30
        while time.time() < deadline:
            states = dict(a.peer_states())
            if all(s == "INITIALIZED" for s in states.values()) and states:
                break
            time.sleep(0.01)
        t0 = time.perf_counter()
        for key, val in make_kvs(n).items():
            a.set_key(key, val.value, version=1,
                      originator=val.originator_id)
        last_key = f"prefix:node-{n - 1}"
        deadline = time.time() + max(30.0, n * 0.01)
        while time.time() < deadline:
            if b.get_key(last_key) is not None and len(b.dump()) >= n:
                break
            time.sleep(0.005)
        flood_ms = (time.perf_counter() - t0) * 1000
        assert len(b.dump()) >= n, "flood did not converge"
        print(
            json.dumps(
                {
                    "bench": f"kvstore.flood_{n}_keys",
                    "flood_ms": round(flood_ms, 3),
                }
            ),
            flush=True,
        )
    finally:
        a.stop()
        b.stop()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--full", action="store_true")
    args = p.parse_args(argv)
    sizes = [10, 100, 1000] + ([10000] if args.full else [])
    for n in sizes:
        bench_merge(n)
    for n in sizes:
        bench_dump(n)
    for n in sizes:
        bench_flood(n)


if __name__ == "__main__":
    main()
