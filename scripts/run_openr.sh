#!/bin/bash
#
# Env-file driven launcher for the openr-tpu daemon — the operational
# entry point, mirroring the reference's scripts/run_openr.sh shape
# (reference: /root/reference/openr/scripts/run_openr.sh): defaults
# here, node-specific overrides in an env file (/etc/sysconfig/openr
# by default), or a JSON config path as the first argument.
#
#   run_openr.sh                    # env-file driven (gflags surface)
#   run_openr.sh /data/openr.json   # explicit JSON config
#
# NOTE: for correct drain-state persistence across reboots point
# CONFIG_STORE_FILEPATH somewhere persistent (the reference's own
# advice), e.g. /data/openr_config_store.bin.

set -u

# openr-tpu invocation: a python module, not a compiled binary
OPENR="${OPENR:-python3 -m openr_tpu.main}"
SYSCONFIG="${SYSCONFIG:-/etc/sysconfig/openr}"

# Defaults (sorted) — override in ${SYSCONFIG}
AREAS=""
CONFIG=""
CONFIG_STORE_FILEPATH="/tmp/openr_tpu_config_store.json"
DOMAIN=openr
DRYRUN=false
ENABLE_FLOOD_OPTIMIZATION=false
ENABLE_KVSTORE_THRIFT=false
ENABLE_NETLINK_FIB_HANDLER=true
ENABLE_PREFIX_ALLOC=false
ENABLE_SEGMENT_ROUTING=false
ENABLE_V4=false
ENABLE_WATCHDOG=true
IFACE_REGEX_EXCLUDE=""
IFACE_REGEX_INCLUDE=""
IS_FLOOD_ROOT=false
KVSTORE_KEY_TTL_MS=300000
KVSTORE_SYNC_INTERVAL_S=60
NODE_NAME="${HOSTNAME:-}"
OPENR_CTRL_PORT=2018
PREFIX_FWD_ALGO_KSP2_ED_ECMP=0
PREFIX_FWD_TYPE_MPLS=0
SEED_PREFIX=""
SPARK_HOLD_TIME_S=30

# Node overrides
if [ -f "${SYSCONFIG}" ]; then
  # shellcheck disable=SC1090
  . "${SYSCONFIG}"
fi

# An empty boolean override in ${SYSCONFIG} (FLAG= — the sysconfig
# idiom for "use the default") must fall back to the script default,
# not become an explicit --flag= (which the gflags parser reads as
# false).
DRYRUN="${DRYRUN:-false}"
ENABLE_V4="${ENABLE_V4:-false}"
ENABLE_WATCHDOG="${ENABLE_WATCHDOG:-true}"
ENABLE_SEGMENT_ROUTING="${ENABLE_SEGMENT_ROUTING:-false}"
ENABLE_PREFIX_ALLOC="${ENABLE_PREFIX_ALLOC:-false}"
ENABLE_FLOOD_OPTIMIZATION="${ENABLE_FLOOD_OPTIMIZATION:-false}"
IS_FLOOD_ROOT="${IS_FLOOD_ROOT:-false}"
ENABLE_KVSTORE_THRIFT="${ENABLE_KVSTORE_THRIFT:-false}"
ENABLE_NETLINK_FIB_HANDLER="${ENABLE_NETLINK_FIB_HANDLER:-true}"

# Explicit JSON config wins over the env surface
if [ -n "${1:-}" ]; then
  CONFIG="$1"
fi

if [ -n "${CONFIG}" ]; then
  echo "Starting openr-tpu with config: ${CONFIG}"
  exec ${OPENR} --config "${CONFIG}"
fi

if [ -z "${NODE_NAME}" ] || [ "${NODE_NAME}" = "localhost" ]; then
  echo "ERROR: No hostname found for the node, bailing out." >&2
  exit 1
fi

ARGS="--node_name=${NODE_NAME}"
ARGS="${ARGS} --domain=${DOMAIN}"
ARGS="${ARGS} --config_store_filepath=${CONFIG_STORE_FILEPATH}"
ARGS="${ARGS} --kvstore_key_ttl_ms=${KVSTORE_KEY_TTL_MS}"
ARGS="${ARGS} --kvstore_sync_interval_s=${KVSTORE_SYNC_INTERVAL_S}"
ARGS="${ARGS} --spark2_heartbeat_hold_time_s=${SPARK_HOLD_TIME_S}"
ARGS="${ARGS} --openr_ctrl_port=${OPENR_CTRL_PORT}"
[ -n "${AREAS}" ] && ARGS="${ARGS} --areas=${AREAS}"
[ -n "${IFACE_REGEX_INCLUDE}" ] && \
  ARGS="${ARGS} --iface_regex_include=${IFACE_REGEX_INCLUDE}"
[ -n "${IFACE_REGEX_EXCLUDE}" ] && \
  ARGS="${ARGS} --iface_regex_exclude=${IFACE_REGEX_EXCLUDE}"
[ -n "${SEED_PREFIX}" ] && ARGS="${ARGS} --seed_prefix=${SEED_PREFIX}"
# Booleans are passed explicitly as --flag=true/false: several gflags
# default to true (e.g. enable_watchdog), so only appending the positive
# form would make FLAG=false a silent no-op.
ARGS="${ARGS} --dryrun=${DRYRUN}"
ARGS="${ARGS} --enable_v4=${ENABLE_V4}"
ARGS="${ARGS} --enable_watchdog=${ENABLE_WATCHDOG}"
ARGS="${ARGS} --enable_segment_routing=${ENABLE_SEGMENT_ROUTING}"
ARGS="${ARGS} --enable_prefix_alloc=${ENABLE_PREFIX_ALLOC}"
ARGS="${ARGS} --enable_flood_optimization=${ENABLE_FLOOD_OPTIMIZATION}"
ARGS="${ARGS} --is_flood_root=${IS_FLOOD_ROOT}"
ARGS="${ARGS} --enable_kvstore_thrift=${ENABLE_KVSTORE_THRIFT}"
ARGS="${ARGS} --enable_netlink_fib_handler=${ENABLE_NETLINK_FIB_HANDLER}"
[ "${PREFIX_FWD_TYPE_MPLS}" != "0" ] && \
  ARGS="${ARGS} --prefix_fwd_type_mpls"
[ "${PREFIX_FWD_ALGO_KSP2_ED_ECMP}" != "0" ] && \
  ARGS="${ARGS} --prefix_algo_type_ksp2_ed_ecmp"

echo "Starting openr-tpu: ${OPENR} ${ARGS}"
# shellcheck disable=SC2086
exec ${OPENR} ${ARGS}
