# openr-tpu build/test entry points (Layer 0).
#
# The native SPF core (native/spfcore.cpp) also builds lazily on first
# use (openr_tpu/graph/native_spf.py); this makes the build explicit
# for packaging/CI. Python deps (jax, numpy, pytest) come from the
# environment — see pyproject.toml.

# tier1 uses pipefail/PIPESTATUS (bash-only)
SHELL    := /bin/bash

CXX      ?= g++
CXXFLAGS ?= -O3 -std=c++17 -fPIC -pthread
NATIVE    = native/libspfcore.so

.PHONY: all native test test-fast tier1 lint-analysis race-smoke churn-smoke telemetry-smoke chaos-smoke load-smoke tenancy-smoke recovery-smoke integrity-smoke twin-smoke dispatch-smoke kernel-smoke pipeline-smoke multichip-smoke serve-smoke obs-smoke replay-smoke fleet-smoke bench clean install

all: native

native: $(NATIVE)

$(NATIVE): native/spfcore.cpp
	$(CXX) $(CXXFLAGS) -shared $< -o $@

install:
	pip install -e .

# full suite on the virtual 8-device CPU mesh (conftest pins CPU)
test: native
	python -m pytest tests/ -q

test-fast: native
	python -m pytest tests/ -q -x -m "not slow"

# invariant linters (openr_tpu/analysis; --list-rules for the full
# registry). Pure-ast pass, no jax import, a few seconds on the whole
# tree. Exit 1 on any unsuppressed finding OR any stale suppression (a
# directive shielding nothing); suppressions need a reason (see
# docs/RUNBOOK.md "Invariant lint triage").
lint-analysis:
	python -m openr_tpu.analysis --audit-suppressions

# the ROADMAP tier-1 gate, verbatim (CPU-pinned, bounded, dot-counted);
# the invariant linters run first — a finding or a degradation-contract
# regression fails the gate before the test suite spends its budget.
# load-smoke runs before the heavy chaos/fleet legs: its throughput
# floor is wall-clock-sensitive and deserves a cold machine, not one
# the storm legs just saturated
tier1: native lint-analysis load-smoke race-smoke chaos-smoke tenancy-smoke recovery-smoke integrity-smoke twin-smoke dispatch-smoke kernel-smoke pipeline-smoke serve-smoke obs-smoke replay-smoke fleet-smoke
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# fast guard for the incremental churn path: fails if the device
# pipeline regresses to zero incremental syncs / warm solves, or if
# metric churn starts reading the full packed product back per event
# (delta-compacted readback contract, tests/test_route_engine_delta.py).
# The link-churn leg (tests/test_frontier_parity.py) adds the frontier
# regression guard: a localized structural event silently taking the
# full-width path while its frontier is below threshold fails here
churn-smoke: native
	env JAX_PLATFORMS=cpu python -m pytest tests/test_churn_smoke.py tests/test_incremental_parity.py tests/test_route_engine_delta.py tests/test_frontier_parity.py -q -m "not slow"

# thread-provenance race gate (openr_tpu.analysis races/racedep): the
# whole-tree shared-state rule must report zero unsuppressed findings
# with every suppression reasoned and zero stale, the racedep sanitizer
# must convict a seeded two-thread unlocked overlap (and stay silent on
# its lock-guarded twin) under deterministic barrier scheduling, and
# lockdep inversions must carry static role attribution. JSON artifact
# at /tmp/openr_tpu_race_smoke.json. See docs/RUNBOOK.md "Race triage"
# when it fails.
race-smoke:
	env JAX_PLATFORMS=cpu python -m tools.race_smoke --out /tmp/openr_tpu_race_smoke.json

# observability gate: small churn scenario through the real pipeline;
# fails if any registered histogram is empty, any trace span is left
# unclosed, or fewer complete publication->FIB traces than events
telemetry-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.telemetry_smoke

# robustness gate: seeded fault storm through the supervised engine /
# Decision / platform paths; fails if any supervisor fails to
# self-heal, the post-storm product diverges from the fault-free
# oracle, or the fault-coverage floor is missed. JSON artifact at
# /tmp/openr_tpu_chaos_smoke.json (tools/chaos_report.py)
chaos-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.chaos_report --smoke --out /tmp/openr_tpu_chaos_smoke.json

# service-plane gate: seeded sustained-load run (>= 120 events/s at 1k
# nodes on CPU) through the real KvStore->Decision->Fib pipeline with
# admission control + pipelined emit; fails on unbounded queue growth,
# malformed traces, or a shed-by-coalescing parity breach vs the
# unshedded oracle replay. Also emits the rate ladder + a
# max-sustainable-rate estimate. JSON artifact at
# /tmp/openr_tpu_load_smoke.json (tools/load_report.py)
load-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.load_report --smoke --out /tmp/openr_tpu_load_smoke.json

# tenant-plane gate (ops.world_batch): B=8 mixed-size tenants across
# shape buckets — batched-vs-sequential bit parity under churn, a
# zero-compile ceiling after bucket warmup, and the evict->rehydrate
# round trip (warm, not cold). See docs/RUNBOOK.md "Tenant residency
# triage" when it fails.
tenancy-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.tenancy_smoke --out /tmp/openr_tpu_tenancy_smoke.json

# crash-recovery gate (openr_tpu.state): checkpointed warm boot must
# be bit-identical to the cold oracle with zero cold ELL solves and
# zero jit compiles on rehydrate; an injected device.lost must recover
# within the ladder; Fib graceful restart must reconcile with exactly
# one sync and zero deletes (routes never flap). See docs/RUNBOOK.md
# "Crash recovery triage" when it fails.
recovery-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.recovery_smoke --out /tmp/openr_tpu_recovery_smoke.json

# integrity gate (openr_tpu.integrity): seeded bit flips in resident
# device state (ELL, grouped, world-batch) must be convicted within
# one audit pass, quarantined, and healed WARM — bit-identical to the
# host oracle with zero route deletes; a quarantined engine must
# refuse the warm rung. See docs/RUNBOOK.md "Corruption triage" when
# it fails.
integrity-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.integrity_smoke --out /tmp/openr_tpu_integrity_smoke.json

# digital-twin gate (openr_tpu.twin): a 16-vantage fleet must solve
# as ONE batched dispatch wave bit-identical to 16 independently-run
# Decision pipelines, join/warm-churn retrace-free, and the fleet
# analyzer must catch an injected micro-loop and transient blackhole
# (and report clean after the heal wave). See docs/RUNBOOK.md "Fleet
# what-if triage" when it fails.
twin-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.twin_smoke --out /tmp/openr_tpu_twin_smoke.json

# committed-dispatch gate (openr_tpu.ops.route_engine): a warm event
# window must cost at most 2 host touches (one submit run, one reap
# run) with zero blocking syncs, an identical second pass must cost
# zero AOT/jit compiles, and both the incremental result and the
# debounced churn_window batch must be bit-identical to the
# from-scratch oracle. See docs/RUNBOOK.md "Host-overhead triage"
# when it fails.
dispatch-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.dispatch_smoke --out /tmp/openr_tpu_dispatch_smoke.json

# sliced-ELL kernel gate (openr_tpu.ops.pallas_ell, interpret mode):
# all-pairs distances must be bit-identical between the jnp and pallas
# relax impls on a fat-tree and a random mesh, an ell_relax autotuner
# winner must round-trip through the v2 family-keyed persistence
# (measure -> persist -> reload, no re-measure), and a warmed churn
# pass with the kernel armed via impl="auto" must cost zero AOT/jit
# compiles. See docs/RUNBOOK.md "Kernel regression triage" when it
# fails.
kernel-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.kernel_smoke --out /tmp/openr_tpu_kernel_smoke.json

# pipelined event-window gate (PR 16): a warm multi-event burst must
# cost at most 2 host touches per pipeline DRAIN (not per window) with
# ops.pipelined_dispatches witnessing depth >= 2, speculation must
# adopt on match and cancel (counted) on mismatch with both paths
# bit-identical to the sequential oracle, and warm bursts at depths
# 1..3 must cost zero AOT/jit compiles. See docs/RUNBOOK.md
# "Speculation-miss storm" when the cancel counters climb.
pipeline-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.pipeline_smoke --out /tmp/openr_tpu_pipeline_smoke.json

# sharded-dispatch gate on the virtual 8-device CPU mesh (conftest
# pins the device count): pipelined==eager bit-identity across a
# shard-boundary event, zero reshards / zero implicit transfers under
# jax.transfer_guard across a 5-event churn run, and the KSP2
# speculative fast path dispatching mesh-wide (typed fallback counter
# when it can't). Same contracts a real multi-chip run must hold.
multichip-smoke: native
	env JAX_PLATFORMS=cpu OPENR_KSP2_FAST=1 python -m pytest \
	  tests/test_route_engine_delta.py::TestMeshPipelining \
	  tests/test_route_engine_delta.py::TestShardedNoReshard \
	  tests/test_ksp2_engine.py::TestMeshShardedEngine \
	  -q -m "not slow"

# serving-plane gate (openr_tpu.serve): ONE device-owning solver
# service process serving B>=64 tenants from 4 jax-free client OS
# processes over the ctrl wire — bit parity vs the oracle replay,
# ZERO jit compiles across the whole client storm after warmup,
# per-class p99 under the 100ms CPU-scaled SLO, and premium p99 <=
# standard p99 under a seeded mixed-class storm. See docs/RUNBOOK.md
# "SLO breach triage" when it fails.
serve-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.serve_smoke --out /tmp/openr_tpu_serve_smoke.json

# flight-recorder / device-time-attribution gate: armed-vs-disarmed
# profiling overhead (<5% on a ~1k-event warm churn leg), one forced
# anomaly per trigger class (touch_budget, p99_breach, reshard,
# quarantine, ladder_exhausted, compile_after_warmup) each dumping a
# well-formed post-mortem bundle, and attribution consistency against
# dispatch accounting. See docs/RUNBOOK.md "Post-mortem triage".
obs-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.obs_smoke --out /tmp/openr_tpu_obs_smoke.json

# incident-replay gate (openr_tpu.twin.replay): a seeded flap-free
# churn storm + forced micro-loop must dump a self-contained bundle
# (journal slice + verifying LSDB anchor) that a FRESH OS process
# replays to the same anomaly class with bit-identical per-vantage
# route digests twice in a row and parity vs the live twin at dump
# time. --nodes 1008 is the acceptance-scale run on real hardware.
# See docs/RUNBOOK.md "Replay an incident".
replay-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.replay_smoke --out /tmp/openr_tpu_replay_smoke.json

# fleet-plane gate (openr_tpu.fleet): two-service bring-up with hot
# standbys, a multi-process client storm through SLO-class placement
# (load.multi_client --services mode), a live migration that must land
# WARM (zero cold solves, zero jit compiles on the destination,
# bit-identical SP + FIB products vs the never-migrated oracle), and a
# primary kill mid-schedule whose standby promotion must take exactly
# one reconcile with ZERO route deletes. See docs/RUNBOOK.md
# "Failover and migration triage" when it fails.
fleet-smoke: native
	env JAX_PLATFORMS=cpu python -m tools.fleet_smoke --out /tmp/openr_tpu_fleet_smoke.json

# the official reconvergence benchmark (one JSON line; probes the real
# accelerator with retries, degrades to CPU with evidence)
bench: native
	python bench.py

clean:
	rm -f $(NATIVE)
	find . -name __pycache__ -type d -exec rm -rf {} +
