# openr-tpu build/test entry points (Layer 0).
#
# The native SPF core (native/spfcore.cpp) also builds lazily on first
# use (openr_tpu/graph/native_spf.py); this makes the build explicit
# for packaging/CI. Python deps (jax, numpy, pytest) come from the
# environment — see pyproject.toml.

CXX      ?= g++
CXXFLAGS ?= -O3 -std=c++17 -fPIC -pthread
NATIVE    = native/libspfcore.so

.PHONY: all native test test-fast bench clean install

all: native

native: $(NATIVE)

$(NATIVE): native/spfcore.cpp
	$(CXX) $(CXXFLAGS) -shared $< -o $@

install:
	pip install -e .

# full suite on the virtual 8-device CPU mesh (conftest pins CPU)
test: native
	python -m pytest tests/ -q

test-fast: native
	python -m pytest tests/ -q -x -m "not slow"

# the official reconvergence benchmark (one JSON line; probes the real
# accelerator with retries, degrades to CPU with evidence)
bench: native
	python bench.py

clean:
	rm -f $(NATIVE)
	find . -name __pycache__ -type d -exec rm -rf {} +
