"""KvStoreSnooper: live-tail the KvStore publication stream of a running
daemon (reference: openr/kvstore/tools/KvStoreSnooper.cpp).

usage: kvstore_snooper.py [host:]port [--prefix adj:]
"""

from __future__ import annotations

import sys

from openr_tpu.ctrl.server import CtrlClient


def main() -> None:
    args = sys.argv[1:]
    if not args:
        print(__doc__)
        return
    target = args[0]
    prefix = ""
    if "--prefix" in args:
        prefix = args[args.index("--prefix") + 1]
    host, _, port = target.rpartition(":")
    client = CtrlClient(host or "127.0.0.1", int(port))
    try:
        # snapshot first, then live events
        snapshot = client.call("get_kvstore_keys_filtered", prefix=prefix)
        print(f"--- snapshot: {len(snapshot)} keys ---")
        for key, value in sorted(snapshot.items()):
            print(
                f"{key}  v={value.get('version')} "
                f"orig={value.get('originator_id')} ttl={value.get('ttl')}"
            )
        print("--- live stream (ctrl-c to stop) ---")
        for event in client.stream("subscribe_kvstore_filtered"):
            if event is None:
                continue
            for key, value in sorted(event.get("key_vals", {}).items()):
                if prefix and not key.startswith(prefix):
                    continue
                print(
                    f"UPDATE {key}  v={value.get('version')} "
                    f"orig={value.get('originator_id')}"
                )
            for key in event.get("expired_keys", []):
                if prefix and not key.startswith(prefix):
                    continue
                print(f"EXPIRED {key}")
    except KeyboardInterrupt:
        pass
    finally:
        client.close()


if __name__ == "__main__":
    main()
