#!/usr/bin/env python
"""Observability-plane gate (``make obs-smoke``) and report artifact.

Exercises the always-on profiling plane and the flight recorder
(``openr_tpu/telemetry/profiler.py`` + ``flight.py``) end to end on
the real churn pipeline and fails loudly if the contract regressed:

- OVERHEAD BUDGET: a ~1k-event warm churn leg timed with the plane
  ARMED (profiler sampling + flight ring + window records) vs DISARMED
  must cost < 5% extra wall clock (best-of-3 paired rounds, so one
  scheduler hiccup can't fail the gate),
- TRIGGER COVERAGE: every anomaly trigger class — touch_budget,
  p99_breach, reshard, quarantine, ladder_exhausted,
  compile_after_warmup — is forced once through its real entry point
  (a churn window over budget, a latency spike, a reshard delta, a
  corrupted resident + forced audit, an all-failing degradation
  ladder, a post-warmup cold build) and each must fire
  (``flight.triggers.<name>``) and dump (``flight.dumps.<name>``) a
  WELL-FORMED bundle: JSON loads, ring records non-empty, device-time
  attribution non-empty, sibling Chrome trace present,
- ATTRIBUTION CONSISTENCY: the per-tag attributed call counts must be
  positive and no larger than ``ops.host_dispatches`` (every profiled
  call IS a counted dispatch), with real sampled device time
  (``ops.profile_samples`` > 0) and a live window wall/device ratio.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_obs_smoke.json``); exit 0 on pass, 1 with a reason
list on fail. Runs CPU-pinned — this gates the observability plane,
not kernels.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the gate measures the plane itself: pin it on regardless of ambient
# env so a developer's OPENR_PROFILE=0 can't vacuously pass the gate
os.environ["OPENR_PROFILE"] = "1"
os.environ["OPENR_FLIGHT"] = "1"
os.environ.pop("OPENR_TOUCH_BUDGET", None)

# allow direct invocation (python tools/obs_smoke.py) in addition
# to module mode (python -m tools.obs_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEQ = (7, 3, 11, 5)

_BUNDLE_KEYS = (
    "trigger", "reason", "ts", "records", "counters",
    "attribution", "host_overhead_ratio",
)


def _load(topo):
    from openr_tpu.graph.linkstate import LinkState

    ls = LinkState(area=topo.area)
    for _name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    return ls


def _mutate_metric(ls, node, i, metric):
    from dataclasses import replace

    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {node, adjs[i].other_node_name}


def _churn_round(engine, ls, node, n_events, tag) -> float:
    """One timed warm churn leg: n_events metric flips, each inside a
    committed event window; returns wall seconds. When the flight
    recorder is armed, each event also pays the Decision adoption
    site's event-journal append (serialize + b64 the adopted value,
    one pub note + one wave mark) so the armed-vs-disarmed A/B gates
    the journal ring's overhead too, not just the activity ring's."""
    import base64

    from openr_tpu.ops import dispatch_accounting as da
    from openr_tpu.telemetry import get_flight_recorder
    from openr_tpu.utils import wire

    fr = get_flight_recorder()
    t0 = time.perf_counter()
    for i in range(n_events):
        with da.event_window(tag):
            engine.churn(
                ls, _mutate_metric(ls, node, 0, SEQ[i % len(SEQ)]),
                defer_consume=True,
            )
        if fr.enabled:
            db = ls.get_adjacency_databases()[node]
            fr.journal_note(
                "0", f"adj:{node}",
                value_b64=base64.b64encode(wire.dumps(db)).decode(),
                version=i + 1, originator=node,
            )
            fr.journal_mark("wave", window=tag)
    engine.flush()
    return time.perf_counter() - t0


def _assert_bundle(trigger, dump_dir, failures) -> None:
    """A trigger's newest bundle must be a loadable post-mortem with
    evidence in it: ring records, device-time attribution, and the
    sibling Chrome trace."""
    paths = [
        p for p in sorted(glob.glob(
            os.path.join(dump_dir, f"postmortem-{trigger}-*.json")
        ))
        if not p.endswith("-trace.json")
    ]
    if not paths:
        failures.append(f"{trigger}: dump counted but no bundle on disk")
        return
    path = paths[-1]
    try:
        with open(path) as fh:
            bundle = json.load(fh)
    except (OSError, ValueError) as exc:
        failures.append(f"{trigger}: bundle unreadable ({exc})")
        return
    for key in _BUNDLE_KEYS:
        if key not in bundle:
            failures.append(f"{trigger}: bundle missing {key!r}")
    if not bundle.get("records"):
        failures.append(f"{trigger}: bundle flight ring is empty")
    attr = bundle.get("attribution") or {}
    if not attr:
        failures.append(f"{trigger}: bundle attribution is empty")
    elif not any(
        row.get("device_samples") for row in attr.values()
    ):
        failures.append(
            f"{trigger}: bundle attribution has no sampled device time"
        )
    trace_path = path[:-len(".json")] + "-trace.json"
    try:
        with open(trace_path) as fh:
            json.load(fh)
    except (OSError, ValueError):
        failures.append(f"{trigger}: sibling Chrome trace missing/bad")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--out", default="/tmp/openr_tpu_obs_smoke.json",
        help="JSON artifact path",
    )
    ap.add_argument(
        "--events", type=int,
        default=int(os.environ.get("OPENR_OBS_EVENTS", "168")),
        help="churn events per timed round (3 paired rounds x 2 "
             "configs -> ~1k events at the default)",
    )
    args = ap.parse_args()

    from openr_tpu.faults import DegradationSupervisor, LadderExhausted
    from openr_tpu.integrity import get_auditor
    from openr_tpu.models import topologies
    from openr_tpu.ops import dispatch_accounting as da
    from openr_tpu.ops import route_engine
    from openr_tpu.telemetry import (
        get_flight_recorder,
        get_profiler,
        get_registry,
        install_default_triggers,
        reset_flight_recorder,
        reset_profiler,
    )

    failures: list = []
    report: dict = {"gates": {}}
    reg = get_registry()
    dump_dir = tempfile.mkdtemp(prefix="openr_tpu_obs_flight_")
    report["dump_dir"] = dump_dir

    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = _load(topo)
    names = sorted(ls.get_adjacency_databases().keys())
    engine = route_engine.RouteSweepEngine(ls, [names[0]])
    rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))

    # -- warmup: compile the chain + exercise the armed plane once so
    # lazy init (annotation class import, tag state) is out of the
    # timed rounds
    reset_profiler(sample_every=4)
    reset_flight_recorder(
        dump_dir=dump_dir, min_dump_interval_s=0.0, max_dumps=64
    )
    for metric in SEQ + SEQ:
        with da.event_window("obs_warmup"):
            engine.churn(
                ls, _mutate_metric(ls, rsw, 0, metric), defer_consume=True
            )
    engine.flush()

    # -- gate: armed-vs-disarmed overhead on the warm churn leg -------
    # paired rounds back to back so drift hits both configs; best-of-3
    # ratio gates (one scheduler hiccup in an armed round must not fail
    # a plane that is actually cheap)
    pairs = 3
    armed_ms, disarmed_ms, ratios = [], [], []
    for _ in range(pairs):
        reset_profiler(enabled=False)
        reset_flight_recorder(enabled=False, dump_dir=dump_dir)
        off = _churn_round(engine, ls, rsw, args.events, "obs_churn")
        # production config: default sampling cadence, live ring, no
        # triggers armed (trigger cost is covered by the trigger legs)
        reset_profiler()
        reset_flight_recorder(
            dump_dir=dump_dir, min_dump_interval_s=0.0, max_dumps=64
        )
        on = _churn_round(engine, ls, rsw, args.events, "obs_churn")
        disarmed_ms.append(round(off * 1000.0, 2))
        armed_ms.append(round(on * 1000.0, 2))
        ratios.append(round(on / max(off, 1e-9), 4))
    overhead = min(ratios)
    report["overhead"] = {
        "events_per_round": args.events,
        "events_total": pairs * 2 * args.events,
        "disarmed_ms": disarmed_ms,
        "armed_ms": armed_ms,
        "ratios": ratios,
        "best_ratio": overhead,
        "budget": 1.05,
    }
    if overhead >= 1.05:
        failures.append(
            f"armed profiling overhead {overhead:.3f}x disarmed "
            f"(ratios {ratios}); budget is <1.05x"
        )
    report["gates"]["overhead_budget"] = overhead < 1.05

    # -- trigger coverage: arm the standing set + force each class ----
    reset_profiler(sample_every=2)
    fr = reset_flight_recorder(
        dump_dir=dump_dir, min_dump_interval_s=0.0, max_dumps=64
    )
    fr = install_default_triggers()
    prof = get_profiler()

    def force(name, fn):
        fired0 = reg.counter_get(f"flight.triggers.{name}")
        dumps0 = reg.counter_get(f"flight.dumps.{name}")
        fn()
        # a no-op window retirement flushes any dump the trigger
        # deferred because it fired inside a solve window
        with da.event_window("obs_flush"):
            pass
        fired = reg.counter_get(f"flight.triggers.{name}") - fired0
        dumped = reg.counter_get(f"flight.dumps.{name}") - dumps0
        if fired < 1:
            failures.append(f"{name}: trigger did not fire")
        if dumped < 1:
            failures.append(f"{name}: no post-mortem bundle counted")
        else:
            _assert_bundle(name, dump_dir, failures)
        report["gates"][f"trigger_{name}"] = fired >= 1 and dumped >= 1

    # touch_budget: budget 0 means ANY host touch in a window is over
    def force_touch_budget():
        fr.set_touch_budget(0)
        try:
            with da.event_window("obs_budget"):
                engine.churn(
                    ls, _mutate_metric(ls, rsw, 0, 13), defer_consume=True
                )
            engine.flush()
        finally:
            fr.set_touch_budget(None)

    force("touch_budget", force_touch_budget)

    # p99_breach: baseline the default convergence trigger, then land
    # a latency spike far above any real sample this process produced
    def force_p99():
        for _ in range(48):
            reg.observe("convergence.e2e_ms", 1.0)
        fr.check_triggers()  # >= min_samples: baseline set
        for _ in range(8):
            reg.observe("convergence.e2e_ms", 60000.0)
        fr.check_triggers()  # p99 >> factor x baseline: fires

    force("p99_breach", force_p99)

    # reshard: the counter-delta trigger baselined during the legs
    # above; one reshard event is one anomaly
    def force_reshard():
        fr.check_triggers()
        reg.counter_bump("ops.reshard_events")
        fr.check_triggers()

    force("reshard", force_reshard)

    # quarantine: flip resident bits on the live engine; the forced
    # audit convicts, quarantines, heals — and fires the anomaly
    def force_quarantine():
        engine.corrupt_resident(seed=7)
        get_auditor().audit_now()

    force("quarantine", force_quarantine)

    # ladder_exhausted: every rung fails in one walk
    def force_ladder():
        sup = DegradationSupervisor(
            "obs_ladder", backoff_min_s=0.001, backoff_max_s=0.002
        )

        def boom():
            raise RuntimeError("forced for obs smoke")

        try:
            sup.run([("warm", boom), ("cold", boom)])
        except LadderExhausted:
            pass
        else:
            failures.append("ladder_exhausted: exhaustion did not raise")

    force("ladder_exhausted", force_ladder)

    # compile_after_warmup: declare warmup done, then cold-build an
    # engine for a topology this process never compiled — the AOT
    # compile after the marker is the anomaly (LAST: the legs above
    # must run un-warm so their own cold paths can't fire this)
    def force_compile():
        prof.mark_warm()
        topo2 = topologies.fat_tree(
            pods=5, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls2 = _load(topo2)
        names2 = sorted(ls2.get_adjacency_databases().keys())
        route_engine.RouteSweepEngine(ls2, [names2[0]])
        fr.check_triggers()

    force("compile_after_warmup", force_compile)

    # -- gate: attribution consistent with dispatch accounting --------
    attribution = prof.attribution()
    report["attribution"] = attribution
    calls = sum(
        int(row.get("calls", 0)) for row in attribution.values()
    )
    samples = sum(
        int(row.get("device_samples", 0)) for row in attribution.values()
    )
    dispatches = reg.counter_get("ops.host_dispatches")
    ratio = prof.host_overhead_ratio()
    report["attributed_calls"] = calls
    report["device_samples"] = samples
    report["host_dispatches"] = dispatches
    report["host_overhead_ratio"] = ratio
    if calls <= 0:
        failures.append("no dispatches carried host-time attribution")
    if samples <= 0:
        failures.append("no dispatch was sampled for device time")
    if calls > dispatches:
        failures.append(
            f"attributed {calls} calls but only {dispatches} host "
            "dispatches counted — attribution is double-counting"
        )
    if not reg.counter_get("ops.profile_samples"):
        failures.append("ops.profile_samples never counted")
    if not ratio or ratio <= 0.0:
        failures.append(
            "ops.host_overhead_ratio gauge is dead (no window pairs)"
        )
    report["gates"]["attribution_consistency"] = (
        0 < calls <= dispatches and samples > 0 and bool(ratio)
    )

    report["counters"] = {
        k: reg.counter_get(k)
        for k in (
            "ops.host_dispatches", "ops.profile_samples",
            "flight.ring_overflows", "flight.dropped_while_frozen",
            "flight.trigger_errors", "flight.dump_errors",
            "flight.dumps_suppressed", "flight.journal_evictions",
            "flight.dump_truncations",
        )
    }
    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("OBS SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"obs smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
