#!/usr/bin/env python
"""Thread-provenance race gate (``make race-smoke``) and report
artifact.

Exercises both halves of the race detector
(``openr_tpu.analysis.rules.races`` static, ``analysis.racedep``
runtime) and fails loudly if either regressed:

- STATIC CLEAN: the whole-tree ``shared-state`` rule must report ZERO
  unsuppressed findings, every suppression must carry a reason, and
  the suppression-staleness audit must report ZERO stale directives
  (a directive shielding nothing is rot that hides regressions),
- ROLE MAP ALIVE: role inference must still see the load-bearing
  roles — the event-base role, the solver wave loop, the ctrl
  connection threads and at least one executor role — over a sane
  number of role-carrying methods (an empty map means the fixpoint
  silently died and the rule passes vacuously),
- RUNTIME CONVICTION: the racedep sanitizer must convict a seeded
  two-thread unlocked write/read overlap under DETERMINISTIC barrier
  scheduling (no sleeps, no real race required to strike) with both
  static role names attributed, and must stay SILENT on the
  lock-guarded twin of the same schedule,
- LOCKDEP ATTRIBUTION: a seeded lock-order inversion must carry the
  acquiring thread's registered role name in its violation.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_race_smoke.json``); exit 0 on pass, 1 with a reason
list on fail. Pure host-side — no jax import, sub-10s on the whole
tree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

# allow direct invocation (python tools/race_smoke.py) in addition
# to module mode (python -m tools.race_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: roles that must survive in the inferred map — each one anchors a
#: cross-thread seam the rule exists to watch
_LOAD_BEARING_ROLES = ("evb", "solver-wave-loop", "ctrl")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _static_leg(report_out: dict, reasons: list) -> None:
    from openr_tpu.analysis.core import STALE_RULE, run_analysis
    from openr_tpu.analysis.rules.races import SharedStateRule

    rule = SharedStateRule()
    report = run_analysis(
        _repo_root(), rules=[rule], audit_suppressions=True
    )
    unsup = [f for f in report.unsuppressed if f.rule == rule.id]
    stale = [f for f in report.findings if f.rule == STALE_RULE]
    reasonless = [
        f for f in report.findings
        if f.suppressed and f.rule == rule.id and not f.reason
    ]
    roles_seen = set()
    for roles in rule.role_map.values():
        roles_seen.update(roles)
    missing = [r for r in _LOAD_BEARING_ROLES if r not in roles_seen]
    has_executor = any(r.startswith("ex:") for r in roles_seen)

    report_out["static"] = {
        "files_scanned": report.files_scanned,
        "unsuppressed": [f.to_dict() for f in unsup],
        "suppressed": sum(
            1 for f in report.findings
            if f.suppressed and f.rule == rule.id
        ),
        "stale_suppressions": len(stale),
        "role_carrying_methods": len(rule.role_map),
        "roles_seen": sorted(roles_seen),
        "duration_s": round(report.duration_s, 3),
    }
    if unsup:
        reasons.append(
            f"shared-state: {len(unsup)} unsuppressed finding(s)"
        )
    if reasonless:
        reasons.append(
            f"shared-state: {len(reasonless)} suppression(s) "
            "without a reason"
        )
    if stale:
        reasons.append(
            f"suppression audit: {len(stale)} stale directive(s)"
        )
    if missing:
        reasons.append(
            f"role map lost load-bearing role(s): {missing}"
        )
    if not has_executor:
        reasons.append("role map lost every executor (ex:*) role")
    if len(rule.role_map) < 50:
        reasons.append(
            "role fixpoint collapsed: only "
            f"{len(rule.role_map)} role-carrying methods"
        )


def _runtime_leg(report_out: dict, reasons: list) -> None:
    """Deterministic barrier-scheduled conviction: the overlap is
    forced by schedule, not by timing — thread W writes unlocked,
    thread R reads unlocked strictly after (barrier order), and the
    tracker must convict WITHOUT the race ever striking."""
    from openr_tpu.analysis.lockdep import (
        LockDepTracker,
        TrackedLock,
        set_thread_role,
    )
    from openr_tpu.analysis.racedep import RaceTracker, SharedState

    def schedule(locked: bool):
        dep = LockDepTracker()
        race = RaceTracker(lockdep=dep)
        state = SharedState("SolverService", tracker=race)
        mu = TrackedLock("SolverService._cv", tracker=dep)
        gate = threading.Barrier(2)
        errs = []

        def writer():
            try:
                set_thread_role("solver-wave-loop")
                if locked:
                    with mu:
                        state.waves = 1
                else:
                    state.waves = 1
                gate.wait()  # publish strictly before the read
            except Exception as exc:  # pragma: no cover - harness bug
                errs.append(repr(exc))

        def reader():
            try:
                set_thread_role("ctrl")
                gate.wait()  # read strictly after the write
                if locked:
                    with mu:
                        _ = state.waves
                else:
                    _ = state.waves
            except Exception as exc:  # pragma: no cover - harness bug
                errs.append(repr(exc))

        tw = threading.Thread(target=writer, name="race-smoke-wave")
        tr = threading.Thread(target=reader, name="race-smoke-ctrl")
        tw.start(); tr.start(); tw.join(); tr.join()
        if errs:
            reasons.append(f"runtime harness error: {errs}")
        return race

    unlocked = schedule(locked=False)
    locked = schedule(locked=True)

    report_out["runtime"] = {
        "unlocked_violations": [str(v) for v in unlocked.violations],
        "unlocked_roles": [
            list(v.roles) for v in unlocked.violations
        ],
        "locked_violations": [str(v) for v in locked.violations],
    }
    if len(unlocked.violations) != 1:
        reasons.append(
            "racedep failed to convict the seeded unlocked overlap "
            f"({len(unlocked.violations)} violations)"
        )
    else:
        got = set(unlocked.violations[0].roles)
        if got != {"solver-wave-loop", "ctrl"}:
            reasons.append(
                f"racedep conviction lost role attribution: {got}"
            )
    if locked.violations:
        reasons.append(
            "racedep convicted the lock-guarded twin "
            f"({len(locked.violations)} violations) — false positive"
        )


def _lockdep_leg(report_out: dict, reasons: list) -> None:
    from openr_tpu.analysis.lockdep import (
        LockDepTracker,
        TrackedLock,
        set_thread_role,
    )

    dep = LockDepTracker()
    a = TrackedLock("KvStoreDb._lock", tracker=dep)
    b = TrackedLock("Registry._lock", tracker=dep)

    def fwd():
        set_thread_role("evb")
        with a:
            with b:
                pass

    def rev():
        set_thread_role("solver-wave-loop")
        with b:
            with a:
                pass

    t1 = threading.Thread(target=fwd, name="race-smoke-fwd")
    t1.start(); t1.join()
    t2 = threading.Thread(target=rev, name="race-smoke-rev")
    t2.start(); t2.join()

    report_out["lockdep"] = {
        "violations": [str(v) for v in dep.violations],
        "roles": [v.witness.role for v in dep.violations],
    }
    if len(dep.violations) != 1:
        reasons.append(
            "lockdep failed to flag the seeded inversion "
            f"({len(dep.violations)} violations)"
        )
    elif dep.violations[0].witness.role != "solver-wave-loop":
        reasons.append(
            "lockdep violation lost role attribution: "
            f"{dep.violations[0].witness.role!r}"
        )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_race_smoke.json"
    )
    args = parser.parse_args(argv)

    report: dict = {}
    reasons: list = []
    _static_leg(report, reasons)
    _runtime_leg(report, reasons)
    _lockdep_leg(report, reasons)

    report["pass"] = not reasons
    report["reasons"] = reasons
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(
        "race-smoke: "
        f"{report['static']['files_scanned']} files, "
        f"{report['static']['role_carrying_methods']} role-carrying "
        "methods, "
        f"{report['static']['stale_suppressions']} stale, "
        f"{len(report['runtime']['unlocked_violations'])} runtime "
        "conviction(s)"
    )
    if reasons:
        for r in reasons:
            print(f"race-smoke FAIL: {r}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
