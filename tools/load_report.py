#!/usr/bin/env python
"""Sustained-load gate (``make load-smoke``) and report artifact.

Drives the REAL KvStore→Decision→Fib pipeline with the seeded open-loop
generator (``openr_tpu.load``) at a fixed target rate, with admission
control (shed-by-coalescing + rate-adaptive debounce) and the pipelined
Decision emit stage enabled, then fails loudly if the service-plane
contract regressed:

- the publisher could not hold the floor rate (>= 120 events/s at 1k
  nodes on CPU in smoke mode — best of three windows; the floor is a
  regression tripwire an order below healthy-machine throughput, not a
  capacity claim, because a shared single-core CI box swings 140-220
  ev/s run to run on zero code change),
- the pipeline failed to drain after the window (unbounded queue
  growth), or the reader high-watermark blew past the admission band,
- any finished trace was malformed, or no end-to-end convergence
  samples were collected,
- the shedded live RouteDatabase is not bit-identical to the unshedded
  oracle replay of the full journaled event stream.

Also probes a max-sustainable-rate estimate (binary search against a
p99 convergence SLO) and reports the per-rate ladder with p50/p95/p99
e2e latency, shed/coalesce counters, and the WARM/cold solve mix.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_load_report.json``). ``--smoke`` shrinks the window
and search budget for the tier-1 gate; exit 0 on pass, 1 with a reason
list on fail. Runs CPU-pinned — this gates service-plane machinery,
not kernels.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/load_report.py) in addition
# to module mode (python -m tools.load_report)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=20260805)
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="short window + small search budget for the tier-1 gate",
    )
    parser.add_argument("--nodes", type=int, default=1000)
    parser.add_argument(
        "--rates",
        default="",
        help="comma-separated fixed-rate ladder (events/s); "
        "default 240 smoke / 120,240,360 full",
    )
    parser.add_argument(
        "--duration", type=float, default=0.0,
        help="seconds per fixed-rate window (default 3 smoke / 5 full)",
    )
    parser.add_argument(
        "--slo-ms", type=float, default=5000.0,
        help="p99 e2e convergence SLO for the max-rate search",
    )
    parser.add_argument(
        # 120 not 200: the floor must sit below the noise band of the
        # slowest machine that runs the gate (observed 140-220 ev/s on
        # a loaded single-core box, zero code change) while still
        # tripping on a genuine 2x publisher regression
        "--min-rate", type=float, default=120.0,
        help="achieved-rate floor the gate enforces on the first rung",
    )
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_load_report.json"
    )
    args = parser.parse_args(argv)

    from openr_tpu import testing

    testing.pin_host_cpu()

    from openr_tpu.load import AdmissionConfig
    from openr_tpu.load.harness import SustainedLoadHarness

    rates = (
        [int(r) for r in args.rates.split(",") if r]
        if args.rates
        else ([240] if args.smoke else [120, 240, 360])
    )
    duration = args.duration or (3.0 if args.smoke else 5.0)
    admission = AdmissionConfig(shed_depth=4, cap_s=0.5)

    failures: list = []
    t0 = time.perf_counter()
    harness = SustainedLoadHarness(
        nodes=args.nodes,
        seed=args.seed,
        solver_backend="host",
        debounce_max_s=0.05,
        admission=admission,
        pipelined_emit=True,
    )
    harness.start(initial_timeout_s=600.0)
    start_s = time.perf_counter() - t0

    ladder = []
    floor_attempts = []
    try:
        for rate in rates:
            rep = harness.run_fixed_rate(
                rate, duration, p99_slo_ms=args.slo_ms
            )
            ladder.append(rep.to_dict())
        first = ladder[0]

        # the throughput floor is the one wall-clock-sensitive gate in
        # tier-1: on a loaded single-core box a rung can miss the floor
        # with zero code regression. Best-of-3, same as the obs-smoke
        # overhead gate — every attempt lands in the artifact so a
        # genuine regression (all three low) stays loud.
        floor_attempts.append(first["achieved_rate"])
        while (
            first["achieved_rate"] < args.min_rate
            and len(floor_attempts) < 3
        ):
            retry = harness.run_fixed_rate(
                rates[0], duration, p99_slo_ms=args.slo_ms
            ).to_dict()
            floor_attempts.append(retry["achieved_rate"])
            if retry["achieved_rate"] > first["achieved_rate"]:
                first = retry
                ladder[0] = retry

        if first["achieved_rate"] < args.min_rate:
            failures.append(
                f"publisher held {first['achieved_rate']:.1f} ev/s < "
                f"floor {args.min_rate:.0f} at {args.nodes} nodes "
                f"(best of {len(floor_attempts)}: "
                f"{', '.join(f'{a:.1f}' for a in floor_attempts)})"
            )
        for rep in ladder:
            if not rep["drained"]:
                failures.append(
                    f"rate {rep['rate']}: pipeline failed to drain "
                    "(unbounded queue growth)"
                )
            if rep["depth_hwm"] > 16 * admission.shed_depth:
                failures.append(
                    f"rate {rep['rate']}: reader high-watermark "
                    f"{rep['depth_hwm']} blew past the admission band"
                )
            if rep["traces_malformed"]:
                failures.append(
                    f"rate {rep['rate']}: {rep['traces_malformed']} "
                    "malformed traces"
                )
        if first["e2e_samples"] == 0:
            failures.append("no end-to-end convergence samples collected")

        # binary-search max sustainable rate against the p99 SLO
        # (informational: the estimate lands in the artifact; the gate
        # rests on the fixed-rate rungs + parity above/below)
        search = harness.find_max_sustainable_rate(
            p99_slo_ms=args.slo_ms,
            lo=max(25, rates[0] // 2),
            hi=rates[-1] * 2,
            duration_s=max(1.5, duration / 2),
            max_probes=3 if args.smoke else 6,
        )

        # parity last: the oracle replays the FULL journal (every
        # published event across all rungs and probes), unshedded
        if not harness.check_parity():
            failures.append(
                "shedded live RouteDatabase != unshedded oracle replay"
            )
    finally:
        harness.stop()
    elapsed = time.perf_counter() - t0

    report = {
        "seed": args.seed,
        "smoke": args.smoke,
        "nodes": args.nodes,
        "start_s": round(start_s, 3),
        "elapsed_s": round(elapsed, 3),
        "slo_p99_ms": args.slo_ms,
        "ladder": ladder,
        "floor_attempts": [round(a, 1) for a in floor_attempts],
        "max_sustainable": search,
        "failures": failures,
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report, indent=1, sort_keys=True))
    if failures:
        print(f"LOAD GATE: FAIL ({len(failures)})", file=sys.stderr)
        return 1
    print(f"LOAD GATE: PASS (report: {args.out})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
