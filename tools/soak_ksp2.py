"""Soak the incremental KSP2 engine: long randomized mutation streams,
device (engine + fast path) vs fresh host solver, byte-exact
RouteDatabase parity at every step.

All prefixes are KSP2_ED_ECMP, so every event exercises the engine's
invalidation algebra (first/second path membership tests, masked
re-solve, speculative fast path) plus the label/overload
materialization extras. Churn classes: metric wiggles, overload flips,
node-label changes, link drop/restore.

Run:  python -m tools.soak_ksp2 [--seeds 12] [--steps 40]
Prints one JSON line per seed; exits non-zero on the first break.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from dataclasses import replace

from openr_tpu.decision import spf_solver as _ss
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SPF_COUNTERS, SpfSolver
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.types.lsdb import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


def _build(kind: str, n: int):
    kwargs = dict(
        forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        forwarding_type=PrefixForwardingType.SR_MPLS,
    )
    topo = (
        topologies.grid(n, **kwargs)
        if kind == "grid"
        else topologies.fat_tree_nodes(n, **kwargs)
    )
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    ps = PrefixState()
    for pdb in topo.prefix_dbs.values():
        ps.update_prefix_database(pdb)
    return topo, ls, ps


def soak_one(seed: int, kind: str, n: int, steps: int) -> dict:
    rng = random.Random(seed)
    topo, ls_d, ps_d = _build(kind, n)
    _t, ls_h, ps_h = _build(kind, n)
    names = sorted(topo.adj_dbs)
    root = next(
        (k for k in names if k.startswith("rsw")), names[0]
    )
    dev = SpfSolver(root, backend="device")
    host = SpfSolver(root, backend="host")
    pulled: dict = {}

    def mutate(ls):
        node = rng.choice(names)
        db = ls.get_adjacency_databases()[node]
        r = rng.random()
        if r < 0.5 and db.adjacencies:
            i = rng.randrange(len(db.adjacencies))
            adjs = list(db.adjacencies)
            adjs[i] = replace(adjs[i], metric=1 + rng.randrange(9))
            ls.update_adjacency_database(
                replace(db, adjacencies=tuple(adjs))
            )
        elif r < 0.7:
            ls.update_adjacency_database(
                replace(db, is_overloaded=not db.is_overloaded)
            )
        elif r < 0.85 and db.adjacencies:
            key = (id(ls), node)
            if key in pulled:
                adj = pulled.pop(key)
                db = ls.get_adjacency_databases()[node]
                ls.update_adjacency_database(
                    replace(
                        db,
                        adjacencies=tuple(
                            list(db.adjacencies) + [adj]
                        ),
                    )
                )
            else:
                i = rng.randrange(len(db.adjacencies))
                adjs = list(db.adjacencies)
                pulled[key] = adjs.pop(i)
                ls.update_adjacency_database(
                    replace(db, adjacencies=tuple(adjs))
                )
        else:
            ls.update_adjacency_database(
                replace(
                    db, node_label=51000 + rng.randrange(500)
                )
            )

    t0 = time.time()
    syncs0 = SPF_COUNTERS["decision.ksp2_incremental_syncs"]
    for step in range(steps):
        st = rng.getstate()
        mutate(ls_d)
        rng.setstate(st)
        mutate(ls_h)
        d = dev.build_route_db(root, {topo.area: ls_d}, ps_d)
        h = host.build_route_db(root, {topo.area: ls_h}, ps_h)
        if d.to_route_db(root) != h.to_route_db(root):
            return {
                "seed": seed, "kind": kind, "n": n,
                "step": step, "parity": "BROKEN",
            }
    return {
        "seed": seed, "kind": kind, "n": n, "steps": steps,
        "parity": "ok",
        "incremental_syncs": SPF_COUNTERS[
            "decision.ksp2_incremental_syncs"
        ] - syncs0,
        "wall_s": round(time.time() - t0, 1),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, default=12)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--fast-path", action="store_true", default=True)
    args = p.parse_args()
    # engine active regardless of destination count; fast path on
    # (covers the speculative resident-masks dispatch off-TPU too)
    _ss.KSP2_DEVICE_MIN_DSTS = 1
    import os

    os.environ.setdefault("OPENR_KSP2_FAST", "1")
    worlds = [("grid", 5), ("fabric", 120)]
    rc = 0
    for seed in range(args.seeds):
        kind, n = worlds[seed % len(worlds)]
        out = soak_one(seed, kind, n, args.steps)
        print(json.dumps(out), flush=True)
        if out.get("parity") != "ok":
            rc = 1
            break
    return rc


if __name__ == "__main__":
    sys.exit(main())
