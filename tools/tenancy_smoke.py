#!/usr/bin/env python
"""Tenant-plane gate (``make tenancy-smoke``) and report artifact.

Exercises the multi-tenant batched-worlds subsystem
(``openr_tpu.ops.world_batch``) end to end with B=8 mixed-size tenants
spanning two shape buckets, then fails loudly if the tenancy contract
regressed:

- per-tenant BIT PARITY: every batched view (cold build, metric churn,
  link flap, overload flip) must equal the sequential single-graph
  engine's ``ell_view_batch_packed`` output byte for byte,
- COMPILE FLATNESS: once the shape buckets are warm, new tenants
  joining them (and warm churn re-solves) must cost ZERO jit compiles
  (``jax.compile_count`` ceiling == 0 after warmup),
- EVICTION ROUND TRIP: overcommitting a 2-slot bucket must evict to
  host snapshots and REHYDRATE WARM on re-admission (rehydrations and
  warm_solves counted, zero cold solves, bits still identical),
- the batched-vs-sequential per-tenant dispatch timing ratio is
  measured and reported (the hard <=0.5x gate lives in the bench leg,
  where iteration counts make it stable; here it is an artifact
  field).

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_tenancy_smoke.json``); exit 0 on pass, 1 with a
reason list on fail. Runs CPU-pinned — this gates the tenant plane's
bookkeeping and kernels, not device throughput.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/tenancy_smoke.py) in addition
# to module mode (python -m tools.tenancy_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_tenants():
    import numpy as np  # noqa: F401

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies

    topos = [
        topologies.grid(3),
        topologies.grid(4),
        topologies.grid(5),
        topologies.random_mesh(20, 3, seed=7),
        topologies.random_mesh(30, 4, seed=11),
        topologies.random_mesh(48, 4, seed=13),
        topologies.random_mesh(64, 3, seed=17),
        topologies.random_mesh(150, 3, seed=19),
    ]
    lss = []
    for topo in topos:
        ls = LinkState(area=topo.area)
        for _name, db in sorted(topo.adj_dbs.items()):
            ls.update_adjacency_database(db)
        lss.append(ls)
    return [
        (f"t{i}", ls, sorted(ls.get_adjacency_databases())[0])
        for i, ls in enumerate(lss)
    ]


def _mutate_metric(ls, node, i, metric):
    from dataclasses import replace

    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))


def _flap_link(ls, node):
    from dataclasses import replace

    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    dropped = adjs.pop(0)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return dropped


def _restore_link(ls, node, adj):
    from dataclasses import replace

    db = ls.get_adjacency_databases()[node]
    ls.update_adjacency_database(
        replace(db, adjacencies=tuple(list(db.adjacencies) + [adj]))
    )


def _check_parity(mgr, items, tag, failures):
    import numpy as np

    from openr_tpu.ops.spf_sparse import (
        compile_ell,
        ell_source_batch,
        ell_view_batch_packed,
    )

    views = mgr.solve_views(items)
    bad = 0
    for (tid, ls, root), (_g, srcs, packed) in zip(items, views):
        graph = compile_ell(ls)
        ref_srcs = ell_source_batch(graph, ls, root)
        ref = np.asarray(ell_view_batch_packed(graph, ref_srcs))
        if srcs != ref_srcs or not np.array_equal(packed, ref):
            bad += 1
    if bad:
        failures.append(f"{tag}: {bad}/{len(items)} tenants diverged")
    return bad == 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_tenancy_smoke.json"
    )
    parser.add_argument(
        "--timing-rounds",
        type=int,
        default=5,
        help="rounds for the informational batched-vs-seq timing",
    )
    args = parser.parse_args(argv)

    from openr_tpu.ops.spf_sparse import (
        compile_ell,
        ell_source_batch,
        ell_view_batch_packed,
    )
    from openr_tpu.ops.world_batch import TENANCY_COUNTERS, WorldManager
    from openr_tpu.telemetry import get_registry, jax_hooks

    hooks_live = jax_hooks.install()
    reg = get_registry()
    failures: list = []
    report: dict = {"gates": {}}

    # -- gate 1: B=8 mixed-size parity across cold + churn ----------------
    items = _build_tenants()
    mgr = WorldManager(slots_per_bucket=8)
    _check_parity(mgr, items, "cold", failures)
    report["gates"]["cold_parity"] = not failures
    for _tid, ls, root in items[::2]:
        _mutate_metric(ls, root, 0, 55)
    _check_parity(mgr, items, "metric-churn", failures)
    ls3 = items[3][1]
    node3 = sorted(ls3.get_adjacency_databases())[1]
    dropped = _flap_link(ls3, node3)
    _check_parity(mgr, items, "link-down", failures)
    _restore_link(ls3, node3, dropped)
    _check_parity(mgr, items, "link-up", failures)
    report["gates"]["churn_parity"] = not failures
    report["buckets"] = mgr.bucket_count()
    if mgr.bucket_count() < 2:
        failures.append(
            "expected mixed-size tenants to span >=2 shape buckets"
        )

    # -- gate 2: compile-count ceiling ------------------------------------
    if hooks_live:
        compiles0 = reg.counter_get("jax.compile_count")
        join = [
            (f"j{i}", ls, root)
            for i, (_t, ls, root) in enumerate(_build_tenants())
        ]
        for _tid, ls, root in join:
            _mutate_metric(ls, root, 0, 33)
        mgr.solve_views(join)
        for _tid, ls, root in items[::2]:
            _mutate_metric(ls, root, 0, 66)
        mgr.solve_views(items)
        compile_delta = reg.counter_get("jax.compile_count") - compiles0
        report["gates"]["compile_delta_after_warmup"] = compile_delta
        if compile_delta > 0:
            failures.append(
                f"jit retraced {compile_delta}x after bucket warmup "
                "(bucket join / warm churn must be retrace-free)"
            )
    else:
        report["gates"]["compile_delta_after_warmup"] = None

    # -- gate 3: eviction round trip --------------------------------------
    ev_items = [
        (f"e{i}", ls, root)
        for i, (_t, ls, root) in enumerate(_build_tenants()[:3])
    ]
    small = WorldManager(slots_per_bucket=2)
    ev0 = TENANCY_COUNTERS["evictions"]
    _check_parity(small, ev_items, "evict-wave", failures)
    if TENANCY_COUNTERS["evictions"] - ev0 < 1:
        failures.append("overcommitted bucket produced no evictions")
    evicted = [
        t
        for t in (small._tenants[tid] for tid, _ls, _r in ev_items)
        if t.slot is None and t.solved
    ]
    if not evicted:
        failures.append("no solved tenant was evicted to host snapshot")
    else:
        tid = evicted[0].tenant_id
        idx = [t for t, _ls, _r in ev_items].index(tid)
        ls = ev_items[idx][1]
        _mutate_metric(
            ls, sorted(ls.get_adjacency_databases())[0], 0, 123
        )
        r0 = TENANCY_COUNTERS["rehydrations"]
        w0 = TENANCY_COUNTERS["warm_solves"]
        c0 = TENANCY_COUNTERS["cold_solves"]
        _check_parity(small, ev_items, "rehydrate", failures)
        if TENANCY_COUNTERS["rehydrations"] - r0 < 1:
            failures.append("re-admission did not count a rehydration")
        if TENANCY_COUNTERS["warm_solves"] - w0 < 1:
            failures.append("rehydrated tenant did not solve WARM")
        if TENANCY_COUNTERS["cold_solves"] - c0 > 0:
            failures.append(
                "rehydration paid a cold solve (journal replay broken)"
            )
    report["gates"]["eviction_round_trip"] = not any(
        "rehydrat" in f or "evict" in f for f in failures
    )

    # -- informational timing: batched vs sequential ----------------------
    t_batched = t_seq = 0.0
    for round_i in range(max(1, args.timing_rounds)):
        for _tid, ls, root in items:
            _mutate_metric(ls, root, 0, 40 + round_i)
        t0 = time.perf_counter()
        mgr.solve_views(items)
        t_batched += time.perf_counter() - t0
        t0 = time.perf_counter()
        for _tid, ls, root in items:
            graph = compile_ell(ls)
            ell_view_batch_packed(
                graph, ell_source_batch(graph, ls, root)
            )
        t_seq += time.perf_counter() - t0
    report["timing"] = {
        "rounds": args.timing_rounds,
        "batched_ms_per_round": 1000.0 * t_batched / args.timing_rounds,
        "sequential_cold_ms_per_round": (
            1000.0 * t_seq / args.timing_rounds
        ),
        "ratio": (t_batched / t_seq) if t_seq else None,
    }

    report["counters"] = {
        f"tenancy.{k}": TENANCY_COUNTERS[k] for k in TENANCY_COUNTERS
    }
    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("TENANCY SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"tenancy smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
