#!/usr/bin/env python
"""Committed-dispatch gate (``make dispatch-smoke``) and report
artifact.

Exercises the fused event→patch→warm-solve→delta-compact chain
(``openr_tpu.ops.route_engine``) end to end on a 3-pod fat-tree, then
fails loudly if the committed-dispatch contract regressed:

- HOST-TOUCH BUDGET: every warm event window costs at most 2 host
  touches (one submit run, one reap run) and ZERO blocking syncs —
  readbacks must ride the ``copy_to_host_async`` lane,
- COMPILE FLATNESS: an identical second pass over the warmed metric
  sequence must cost ZERO AOT compiles and ZERO backend jit compiles
  (``ops.aot_compiles`` and ``jax.compile_count`` deltas both 0, with
  ``ops.aot_hits`` climbing and ``ops.aot_fallbacks`` pinned at 0),
- PARITY: the incrementally maintained routes after all events must be
  bit-identical to a from-scratch ``all_sources_route_sweep`` oracle,
  and a debounced ``churn_window`` batch must equal the same events
  applied one ``churn()`` at a time.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_dispatch_smoke.json``); exit 0 on pass, 1 with a
reason list on fail. Runs CPU-pinned — this gates the dispatch
contract and executable reuse, not device throughput.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/dispatch_smoke.py) in addition
# to module mode (python -m tools.dispatch_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load(topo):
    from openr_tpu.graph.linkstate import LinkState

    ls = LinkState(area=topo.area)
    for _name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    return ls


def _mutate_metric(ls, node, i, metric):
    from dataclasses import replace

    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {node, adjs[i].other_node_name}


SEQ = (7, 3, 11, 5)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--out", default="/tmp/openr_tpu_dispatch_smoke.json",
        help="JSON artifact path",
    )
    args = ap.parse_args()

    from openr_tpu.models import topologies
    from openr_tpu.ops import dispatch_accounting as da
    from openr_tpu.ops import route_engine, route_sweep
    from openr_tpu.telemetry import get_registry

    failures: list = []
    report: dict = {"gates": {}}
    reg = get_registry()

    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = _load(topo)
    names = sorted(ls.get_adjacency_databases().keys())
    engine = route_engine.RouteSweepEngine(ls, [names[0]])
    rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))

    # -- warmup pass: compiles the chain once per (tag, bucket) key -----
    for metric in SEQ:
        engine.churn(ls, _mutate_metric(ls, rsw, 0, metric))
    report["warmup_aot_compiles"] = reg.counter_get("ops.aot_compiles")

    # -- gate: compile flatness + host-touch budget on the warm pass ----
    compiles0 = reg.counter_get("ops.aot_compiles")
    jax0 = reg.counter_get("jax.compile_count")
    hits0 = reg.counter_get("ops.aot_hits")
    touches = []
    for metric in SEQ:
        with da.event_window("smoke") as win:
            engine.churn(
                ls, _mutate_metric(ls, rsw, 0, metric),
                defer_consume=True,
            )
        touches.append(win.touches)
        if win.touches > 2:
            failures.append(
                f"warm event (metric={metric}) took {win.touches} host "
                "touches (budget is 2: one submit, one reap)"
            )
        if win.blocking_syncs:
            failures.append(
                f"warm event (metric={metric}) paid "
                f"{win.blocking_syncs} blocking sync(s); readbacks must "
                "ride the async lane"
            )
    engine.flush()
    compile_delta = reg.counter_get("ops.aot_compiles") - compiles0
    jax_delta = reg.counter_get("jax.compile_count") - jax0
    if compile_delta:
        failures.append(
            f"warm pass AOT-compiled {compile_delta} time(s); the "
            "executable cache must serve every warm dispatch"
        )
    if jax_delta:
        failures.append(
            f"warm pass triggered {jax_delta} backend jit compile(s)"
        )
    if reg.counter_get("ops.aot_hits") - hits0 < len(SEQ):
        failures.append("warm pass did not register AOT cache hits")
    if reg.counter_get("ops.aot_fallbacks"):
        failures.append(
            "AOT executable invocation fell back to plain jit "
            "(ops.aot_fallbacks > 0)"
        )
    report["gates"]["host_touch_budget"] = not any(
        "touches" in f or "blocking" in f for f in failures
    )
    report["gates"]["compile_flatness"] = (
        compile_delta == 0 and jax_delta == 0
    )
    report["warm"] = {
        "host_touches_per_event": touches,
        "aot_compile_delta": compile_delta,
        "jax_compile_delta": jax_delta,
    }

    # -- gate: parity vs a from-scratch oracle of the final state -------
    got = route_sweep.digests_by_name(engine.result)
    oracle = route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [names[0]], block=64)
    )
    if got != oracle:
        bad = sorted(n for n in oracle if got.get(n) != oracle[n])
        failures.append(
            f"incremental result diverged from oracle at {len(bad)} "
            f"node(s): {bad[:5]}"
        )
    report["gates"]["oracle_parity"] = got == oracle

    # -- gate: batched window == sequential, bit for bit ----------------
    ls_a, ls_b = _load(topo), _load(topo)
    seq_eng = route_engine.RouteSweepEngine(ls_a, [names[0]])
    bat_eng = route_engine.RouteSweepEngine(ls_b, [names[0]])
    fsw = next(
        n for n in seq_eng.graph.node_names if n.startswith("fsw")
    )
    events = [(rsw, 0, 7), (fsw, 0, 5), (rsw, 1, 9)]
    for node, i, metric in events:
        seq_eng.churn(ls_a, _mutate_metric(ls_a, node, i, metric))
    sets = [
        _mutate_metric(ls_b, node, i, metric)
        for node, i, metric in events
    ]
    bat_eng.churn_window(ls_b, sets)
    d_seq = route_sweep.digests_by_name(seq_eng.result)
    d_bat = route_sweep.digests_by_name(bat_eng.result)
    if d_seq != d_bat:
        failures.append(
            "churn_window batch diverged from the same events applied "
            "sequentially"
        )
    report["gates"]["batched_window_parity"] = d_seq == d_bat

    report["counters"] = {
        k: reg.counter_get(k)
        for k in (
            "ops.host_dispatches",
            "ops.blocking_syncs", "ops.async_reaps",
            "ops.aot_compiles", "ops.aot_hits", "ops.aot_fallbacks",
            "jax.compile_count",
        )
    }
    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("DISPATCH SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"dispatch smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
