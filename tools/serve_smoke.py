#!/usr/bin/env python
"""Solver-service gate (``make serve-smoke``) and report artifact.

Exercises solver-as-a-service (``openr_tpu.serve``) the way production
would run it: ONE device-owning service process (this one) serving
B>=64 tenants from >=3 jax-free client OS processes over the ctrl
wire, with continuous-batching waves and SLO-class admission. Fails
loudly if the serving contract regressed:

- WIRE PARITY: every view digest every client reads, every round, must
  equal the jax-free oracle replay of the same deterministic world +
  churn schedule (``load.multi_client.oracle_digests``) — bit
  identity through register/update/solve/decode,
- ZERO-COMPILE WAVE JOINS: after the service warms its bucket, the
  whole multi-process client storm (cold tenant joins, churn
  re-solves, mid-wave joins) must cost ZERO jit compiles
  (``jax.compile_count`` delta == 0),
- SLO: per-class p99 solve latency (client-observed, wire included)
  must sit under the class target (default 100ms — the CPU-scaled
  smoke gate), and requests must actually JOIN in-flight waves
  (``tenancy.wave_joins`` > 0) rather than serialize,
- CLASS ORDERING: under a seeded in-process mixed-class storm pushed
  through a budget-capped wave loop, premium p99 must not exceed
  standard p99 (admission preemption is what buys it — counted in
  ``tenancy.wave_preemptions``).

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_serve_smoke.json``); exit 0 on pass, 1 with a reason
list on fail. Runs CPU-pinned — this gates the serving plane's
scheduling and wire contracts, not device throughput.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/serve_smoke.py) in addition
# to module mode (python -m tools.serve_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KINDS = [("grid", 3), ("ring", 8), ("mesh", 20)]


def _client_specs(clients: int, per_client: int):
    from openr_tpu.load.multi_client import TenantSpec
    from openr_tpu.serve.slo import SLO_TABLE

    classes = sorted(SLO_TABLE)
    specs = {}
    for c in range(clients):
        lst = []
        for j in range(per_client):
            kind, size = KINDS[(c + j) % len(KINDS)]
            lst.append(TenantSpec(
                tenant_id=f"c{c}t{j}",
                kind=kind,
                size=size,
                seed=c * per_client + j,
                slo=classes[(c * per_client + j) % len(classes)],
            ))
        specs[f"c{c}"] = lst
    return specs


def _p99(samples):
    if not samples:
        return 0.0
    window = sorted(samples)
    n = len(window)
    return window[min(n - 1, max(0, int(round(0.99 * (n - 1)))))]


def _warmup(svc):
    """Compile the bucket executables the client storm will ride:
    cold place + solve, a warm churn re-solve, and a late join into
    the already-warm bucket — after this, client traffic must be
    retrace-free."""
    from dataclasses import replace

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies

    def _load(kind, size, seed):
        topo = {
            "grid": lambda: topologies.grid(size),
            "ring": lambda: topologies.ring(size),
            "mesh": lambda: topologies.random_mesh(
                size, 3, seed=seed or 7
            ),
        }[kind]()
        ls = LinkState(area=topo.area)
        for name in sorted(topo.adj_dbs):
            ls.update_adjacency_database(topo.adj_dbs[name])
        return ls

    worlds = []
    for i, (kind, size) in enumerate(KINDS):
        ls = _load(kind, size, 1000 + i)
        worlds.append((f"warm{i}", ls,
                       sorted(ls.get_adjacency_databases())[0]))
    for tid, ls, root in worlds:
        svc.register(tid)
        svc.solve(tid, ls, root)
    for tid, ls, root in worlds:
        node = sorted(ls.get_adjacency_databases())[0]
        db = ls.get_adjacency_databases()[node]
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=17)
        ls.update_adjacency_database(
            replace(db, adjacencies=tuple(adjs))
        )
        svc.solve(tid, ls, root)
    # late join: a NEW tenant entering the warm bucket
    ls = _load("grid", 3, 2000)
    svc.register("warm-join")
    svc.solve(
        "warm-join", ls, sorted(ls.get_adjacency_databases())[0]
    )
    for tid, _ls, _root in worlds + [("warm-join", None, None)]:
        svc.detach(tid, warm=False)


def _storm_gate(report, failures, storm_tenants, wave_budget):
    """Seeded mixed-class storm through a budget-capped wave loop:
    every request enqueued BEFORE the loop starts, so admission order
    (class priority, seq) alone decides which wave each rides."""
    import random

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies
    from openr_tpu.ops.world_batch import (
        TENANCY_COUNTERS,
        WorldManager,
    )
    from openr_tpu.serve.service import SolverService
    from openr_tpu.serve.slo import SLO_TABLE

    classes = sorted(SLO_TABLE)
    rng = random.Random(20260806)
    svc = SolverService(
        manager=WorldManager(slots_per_bucket=64, max_resident=128),
        wave_budget=wave_budget,
    )
    order = [classes[i % len(classes)] for i in range(storm_tenants)]
    rng.shuffle(order)
    pre0 = TENANCY_COUNTERS["wave_preemptions"]
    done = {}
    waiters = []
    t_start = time.perf_counter()
    for i, slo in enumerate(order):
        topo = topologies.grid(3)
        ls = LinkState(area=topo.area)
        for name in sorted(topo.adj_dbs):
            ls.update_adjacency_database(topo.adj_dbs[name])
        tid = f"s{i}"
        svc.register(tid, slo)
        req = svc.request_solve(
            tid, ls, sorted(ls.get_adjacency_databases())[0]
        )

        def _wait(req=req, slo=slo):
            req.wait(120)
            done.setdefault(slo, []).append(
                (time.perf_counter() - t_start) * 1000.0
            )

        th = threading.Thread(target=_wait)
        th.start()
        waiters.append(th)
    svc.start()
    try:
        for th in waiters:
            th.join(120)
    finally:
        svc.stop()
    p99 = {cls: _p99(done.get(cls, [])) for cls in classes}
    preemptions = TENANCY_COUNTERS["wave_preemptions"] - pre0
    report["storm"] = {
        "tenants": storm_tenants,
        "wave_budget": wave_budget,
        "p99_ms": p99,
        "preemptions": preemptions,
    }
    if p99["premium"] > p99["standard"]:
        failures.append(
            "premium p99 {:.2f}ms exceeds standard p99 {:.2f}ms "
            "under the mixed-class storm".format(
                p99["premium"], p99["standard"]
            )
        )
    if preemptions < 1:
        failures.append(
            "the shuffled storm produced no counted wave preemptions"
        )
    report["gates"]["premium_p99_le_standard"] = (
        p99["premium"] <= p99["standard"]
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_serve_smoke.json"
    )
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--tenants-per-client", type=int, default=16)
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument(
        "--slo-ms",
        type=float,
        default=float(os.environ.get("OPENR_SERVE_SLO_MS", "100")),
    )
    parser.add_argument("--storm-tenants", type=int, default=60)
    args = parser.parse_args(argv)

    import tempfile

    from openr_tpu.ctrl.server import CtrlServer
    from openr_tpu.ctrl.solver import SolverCtrlHandler
    from openr_tpu.load import multi_client
    from openr_tpu.ops.world_batch import (
        TENANCY_COUNTERS,
        WorldManager,
    )
    from openr_tpu.serve.service import SolverService
    from openr_tpu.telemetry import get_registry, jax_hooks

    hooks_live = jax_hooks.install()
    reg = get_registry()
    failures: list = []
    report: dict = {
        "gates": {},
        "clients": args.clients,
        "tenants": args.clients * args.tenants_per_client,
        "rounds": args.rounds,
        "slo_ms": args.slo_ms,
    }

    svc = SolverService(
        manager=WorldManager(slots_per_bucket=64, max_resident=128)
    ).start()
    srv = CtrlServer(SolverCtrlHandler(svc))
    srv.start()
    try:
        _warmup(svc)
        # warmup done: from here any compile is an anomaly the flight
        # recorder's compile-after-warmup trigger would convict
        from openr_tpu.telemetry import get_profiler

        get_profiler().mark_warm()
        compiles0 = (
            reg.counter_get("jax.compile_count") if hooks_live else 0
        )
        joins0 = TENANCY_COUNTERS["wave_joins"]

        specs = _client_specs(args.clients, args.tenants_per_client)
        t0 = time.perf_counter()
        with tempfile.TemporaryDirectory() as out_dir:
            procs = multi_client.spawn_clients(
                "127.0.0.1", srv.port, specs, args.rounds, out_dir
            )
            results = multi_client.harvest(procs)
        report["storm_wall_s"] = round(time.perf_counter() - t0, 3)

        compile_delta = (
            reg.counter_get("jax.compile_count") - compiles0
            if hooks_live
            else None
        )
        wave_joins = TENANCY_COUNTERS["wave_joins"] - joins0

        # -- gate 1: every client finished every round cleanly ------------
        errors = [e for r in results for e in r.get("errors", [])]
        short = [
            r["client_id"]
            for r in results
            if r.get("rounds", 0) != args.rounds
        ]
        if errors:
            failures.append(f"client errors: {errors}")
        if short:
            failures.append(f"clients short of {args.rounds} rounds: {short}")
        report["gates"]["clients_clean"] = not errors and not short

        # -- gate 2: wire parity vs the oracle replay ---------------------
        all_specs = [s for lst in specs.values() for s in lst]
        oracle = multi_client.oracle_digests(all_specs, args.rounds)
        diverged = []
        for r in results:
            for tid, digs in r.get("digests", {}).items():
                if digs != oracle[tid]:
                    diverged.append(tid)
        if diverged:
            failures.append(
                f"{len(diverged)} tenants diverged from the oracle "
                f"replay: {diverged[:8]}"
            )
        report["gates"]["wire_parity"] = not diverged

        # -- gate 3: B>=64 tenants actually served ------------------------
        served = sum(len(r.get("digests", {})) for r in results)
        report["tenants_served"] = served
        if served < 64:
            failures.append(
                f"only {served} tenants served (gate needs >= 64)"
            )
        report["gates"]["b64_tenants"] = served >= 64

        # -- gate 4: zero-compile wave joins ------------------------------
        report["gates"]["compile_delta_after_warmup"] = compile_delta
        if compile_delta is not None and compile_delta > 0:
            failures.append(
                f"jit retraced {compile_delta}x during the client "
                "storm (wave joins must be retrace-free after warmup)"
            )
        report["wave_joins"] = wave_joins
        if wave_joins < 1:
            failures.append(
                "no request joined an in-flight wave (continuous "
                "batching is not batching)"
            )
        report["gates"]["wave_joins"] = wave_joins >= 1

        # -- gate 5: per-class p99 under the SLO --------------------------
        lat = {}
        for r in results:
            for cls, samples in r.get("latencies_ms", {}).items():
                lat.setdefault(cls, []).extend(samples)
        p99 = {cls: round(_p99(s), 3) for cls, s in sorted(lat.items())}
        report["client_p99_ms"] = p99
        report["server_p99_ms"] = {
            cls: round(svc.class_p99(cls), 3) for cls in sorted(lat)
        }
        for cls, v in p99.items():
            if v > args.slo_ms:
                failures.append(
                    f"{cls} client p99 {v:.2f}ms breaches the "
                    f"{args.slo_ms:.0f}ms smoke SLO"
                )
        report["gates"]["slo_p99"] = all(
            v <= args.slo_ms for v in p99.values()
        )

        # -- per-stage attribution: every class p99 above must be
        # explainable by a measured stage cost, not a bench-side model
        attribution = svc.stage_attribution()
        report["stage_attribution"] = attribution
        report["host_overhead_ratio_measured"] = attribution[
            "host_overhead_ratio"
        ]
        if not attribution["stages"]:
            failures.append(
                "stage attribution is empty — the serve p99s are not "
                "attributable to any measured dispatch stage"
            )
        report["gates"]["stage_attribution"] = bool(
            attribution["stages"]
        )

        # -- gate: cross-wire trace continuity ----------------------------
        # every client process stamped a trace context into its RPCs;
        # each client's trace id must surface in at least one of the
        # service's wave flight records (client span ids adopted at
        # _run_waves), proving a client-observed breach is chaseable to
        # the exact service wave that served it
        from openr_tpu.telemetry import get_flight_recorder

        wave_spans = [
            s
            for rec in get_flight_recorder().records()
            if rec.get("kind") == "wave"
            for s in rec.get("client_spans", [])
        ]
        client_traces = [
            r["trace_id"] for r in results if r.get("trace_id")
        ]
        missing = [
            t for t in client_traces
            if not any(s.startswith(t + ".") for s in wave_spans)
        ]
        report["trace_continuity"] = {
            "client_traces": len(client_traces),
            "wave_spans_recorded": len(wave_spans),
            "missing": missing,
        }
        if not client_traces:
            failures.append(
                "no client reported a trace id (trace stamping is dead)"
            )
        if missing:
            failures.append(
                f"{len(missing)} client trace ids never surfaced in "
                f"service wave records: {missing[:4]}"
            )
        report["gates"]["trace_continuity"] = (
            bool(client_traces) and not missing
        )
    finally:
        srv.stop()
        svc.stop()

    # -- gate 6: premium beats standard under a seeded storm --------------
    _storm_gate(report, failures, args.storm_tenants, wave_budget=8)

    report["counters"] = {
        f"tenancy.{k}": TENANCY_COUNTERS[k] for k in TENANCY_COUNTERS
    }
    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("SERVE SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"serve smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
