#!/usr/bin/env python
"""Pipelined event-window gate (``make pipeline-smoke``) and report
artifact.

Exercises the PR 16 pipelining plane end to end on a 3-pod fat-tree:
multi-event bursts whose committed dispatches submit back to back
under one ``pipeline_drain`` (window N+1 on the stream before window
N's reap lands), plus the speculative dispatch path (stage the
debounce backlog's most-likely composition, adopt on match, cancel on
mismatch). Fails loudly if the pipeline contract regressed:

- TOUCH-PER-DRAIN BUDGET: a warm multi-event burst costs at most 2
  host touches for the WHOLE drain (one submit run, one settle run),
  zero blocking syncs, with ``ops.pipelined_dispatches`` witnessing
  that depth >= 2 actually happened and ``ops.windows_per_drain``
  matching the burst size,
- SPEC-CANCEL PARITY: a speculation staged for one composition and
  then invalidated by a different final backlog must be CANCELLED
  (``ops.spec_cancels`` climbs, never silent) and the committed
  replay must be bit-identical to the sequential oracle; a matching
  composition must ADOPT (``ops.spec_hits``) with the same parity,
- COMPILE FLATNESS: warm bursts at pipeline depths 1, 2 and 3 must
  cost ZERO AOT compiles and ZERO backend jit compiles — pipelining
  reuses the same per-(tag, bucket) executables as the eager path.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_pipeline_smoke.json``); exit 0 on pass, 1 with a
reason list on fail. Runs CPU-pinned — this gates the dispatch
pipeline contract, not device throughput.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/pipeline_smoke.py) in addition
# to module mode (python -m tools.pipeline_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load(topo):
    from openr_tpu.graph.linkstate import LinkState

    ls = LinkState(area=topo.area)
    for _name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    return ls


def _mutate_metric(ls, node, i, metric):
    from dataclasses import replace

    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {node, adjs[i].other_node_name}


SEQ = (7, 3, 11, 5)


def _safe_edges(ls, sample_names, count):
    """(node, slot) pairs whose BOTH endpoints avoid the engine's
    sample nodes: a window touching a sample node's adjacencies
    deliberately refuses speculation/bursting (the sample-band refresh
    mutates sweeper state early), so the smoke must churn elsewhere to
    exercise the pipelined path."""
    out = []
    sample = set(sample_names)
    for node in sorted(ls.get_adjacency_databases().keys()):
        if node in sample:
            continue
        adjs = ls.get_adjacency_databases()[node].adjacencies
        for i, a in enumerate(adjs):
            if a.other_node_name in sample:
                continue
            out.append((node, i))
            break  # one slot per node keeps the sets disjoint
        if len(out) == count:
            return out
    raise RuntimeError("topology too small for sample-free churn set")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--out", default="/tmp/openr_tpu_pipeline_smoke.json",
        help="JSON artifact path",
    )
    args = ap.parse_args()

    from openr_tpu.models import topologies
    from openr_tpu.ops import dispatch_accounting as da
    from openr_tpu.ops import route_engine, route_sweep
    from openr_tpu.telemetry import get_registry

    failures: list = []
    report: dict = {"gates": {}}
    reg = get_registry()

    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = _load(topo)
    names = sorted(ls.get_adjacency_databases().keys())
    engine = route_engine.RouteSweepEngine(ls, [names[0]])
    rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))
    # three churn edges clear of the sample band: a window touching a
    # sample node's adjacencies refuses to speculate/burst by design
    (e0, e1, e2) = _safe_edges(ls, engine.sample_names, 3)

    # -- warmup: compile the chain (eager) and the burst path once -----
    for metric in SEQ:
        engine.churn(ls, _mutate_metric(ls, rsw, 0, metric))
    engine.churn_burst(ls, [
        lambda: _mutate_metric(ls, e0[0], e0[1], 4),
        lambda: _mutate_metric(ls, e1[0], e1[1], 6),
    ])
    report["warmup_aot_compiles"] = reg.counter_get("ops.aot_compiles")

    # -- gate: touch-per-drain budget on a warm depth-3 burst ----------
    compiles0 = reg.counter_get("ops.aot_compiles")
    jax0 = reg.counter_get("jax.compile_count")
    piped0 = reg.counter_get("ops.pipelined_dispatches")
    drains = []
    for depth, metrics in ((1, (8,)), (2, (9, 12)), (3, (13, 5, 7))):
        events = []
        for k, metric in enumerate(metrics):
            node, slot = (e0, e1, e2)[k]
            events.append(
                lambda n=node, s=slot, m=metric:
                _mutate_metric(ls, n, s, m)
            )
        with da.pipeline_drain("smoke_drain") as w:
            engine.churn_burst(ls, events)
        drains.append({
            "burst_size": depth,
            "touches": w.touches,
            "windows": w.windows,
            "blocking_syncs": w.blocking_syncs,
        })
        if w.touches > 2:
            failures.append(
                f"warm burst of {depth} window(s) took {w.touches} "
                "host touches (budget is 2 per DRAIN: one submit run, "
                "one settle run)"
            )
        if w.blocking_syncs:
            failures.append(
                f"warm burst of {depth} window(s) paid "
                f"{w.blocking_syncs} blocking sync(s)"
            )
        if w.windows != depth:
            failures.append(
                f"drain folded {w.windows} window(s), expected {depth} "
                "(ops.windows_per_drain accounting drifted)"
            )
    pipelined_delta = reg.counter_get("ops.pipelined_dispatches") - piped0
    if pipelined_delta < 3:  # depth-2 burst: 1 witness; depth-3: 2
        failures.append(
            "multi-window bursts did not witness pipelined dispatches "
            f"(ops.pipelined_dispatches +{pipelined_delta}, expected "
            ">= 3): window N+1 must submit before window N's reap"
        )
    report["gates"]["touch_per_drain_budget"] = not any(
        "touches" in f or "blocking" in f or "drain folded" in f
        for f in failures
    )
    report["gates"]["pipelined_dispatch_witness"] = pipelined_delta >= 3
    report["drains"] = drains

    # -- gate: compile flatness across pipeline depths -----------------
    compile_delta = reg.counter_get("ops.aot_compiles") - compiles0
    jax_delta = reg.counter_get("jax.compile_count") - jax0
    if compile_delta:
        failures.append(
            f"warm bursts AOT-compiled {compile_delta} time(s); "
            "pipelining must reuse the eager path's executables"
        )
    if jax_delta:
        failures.append(
            f"warm bursts triggered {jax_delta} backend jit compile(s)"
        )
    report["gates"]["compile_flatness"] = (
        compile_delta == 0 and jax_delta == 0
    )
    report["warm"] = {
        "aot_compile_delta": compile_delta,
        "jax_compile_delta": jax_delta,
        "pipelined_dispatches": pipelined_delta,
    }

    # -- gate: pipelined == eager-sequential oracle, bit for bit -------
    got = route_sweep.digests_by_name(engine.result)
    oracle = route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [names[0]], block=64)
    )
    if got != oracle:
        bad = sorted(n for n in oracle if got.get(n) != oracle[n])
        failures.append(
            f"pipelined result diverged from oracle at {len(bad)} "
            f"node(s): {bad[:5]}"
        )
    report["gates"]["oracle_parity"] = got == oracle

    # -- gate: speculation hit AND cancel, both bit-identical ----------
    ls_a, ls_b = _load(topo), _load(topo)
    seq_eng = route_engine.RouteSweepEngine(ls_a, [names[0]])
    spec_eng = route_engine.RouteSweepEngine(ls_b, [names[0]])
    for metric in SEQ:  # warm both
        seq_eng.churn(ls_a, _mutate_metric(ls_a, rsw, 0, metric))
        spec_eng.churn(ls_b, _mutate_metric(ls_b, rsw, 0, metric))
    hits0 = reg.counter_get("ops.spec_hits")
    cancels0 = reg.counter_get("ops.spec_cancels")

    # HIT: speculate the exact final composition, then deliver it
    aff_a = _mutate_metric(ls_a, e0[0], e0[1], 9)
    aff_b = _mutate_metric(ls_b, e0[0], e0[1], 9)
    spec_eng.speculate_churn(ls_b, [aff_b])
    spec_eng.churn_window(ls_b, [aff_b])
    seq_eng.churn(ls_a, aff_a)
    hit_delta = reg.counter_get("ops.spec_hits") - hits0
    hit_parity = (
        route_sweep.digests_by_name(spec_eng.result)
        == route_sweep.digests_by_name(seq_eng.result)
    )
    if hit_delta < 1:
        failures.append(
            "matching speculation was not adopted (ops.spec_hits flat)"
        )
    if not hit_parity:
        failures.append(
            "adopted speculation diverged from the sequential oracle"
        )

    # CANCEL: speculate one composition, then grow the backlog — the
    # mismatch must cancel (counted) and the committed replay must
    # still equal the sequential chain
    aff_b1 = _mutate_metric(ls_b, e0[0], e0[1], 11)
    spec_eng.speculate_churn(ls_b, [aff_b1])
    aff_b2 = _mutate_metric(ls_b, e1[0], e1[1], 4)
    spec_eng.churn_window(ls_b, [aff_b1, aff_b2])
    aff_a1 = _mutate_metric(ls_a, e0[0], e0[1], 11)
    aff_a2 = _mutate_metric(ls_a, e1[0], e1[1], 4)
    seq_eng.churn_window(ls_a, [aff_a1, aff_a2])
    cancel_delta = reg.counter_get("ops.spec_cancels") - cancels0
    cancel_parity = (
        route_sweep.digests_by_name(spec_eng.result)
        == route_sweep.digests_by_name(seq_eng.result)
    )
    if cancel_delta < 1:
        failures.append(
            "mismatched speculation was not cancelled "
            "(ops.spec_cancels flat): misses must never be silent"
        )
    if not cancel_parity:
        failures.append(
            "cancelled speculation's committed replay diverged from "
            "the sequential oracle"
        )
    report["gates"]["spec_hit_parity"] = hit_delta >= 1 and hit_parity
    report["gates"]["spec_cancel_parity"] = (
        cancel_delta >= 1 and cancel_parity
    )
    report["speculation"] = {
        "spec_hits_delta": hit_delta,
        "spec_cancels_delta": cancel_delta,
    }

    report["counters"] = {
        k: reg.counter_get(k)
        for k in (
            "ops.host_dispatches", "ops.blocking_syncs",
            "ops.async_reaps", "ops.pipeline_drains",
            "ops.pipelined_dispatches", "ops.overlapped_reaps",
            "ops.spec_dispatches", "ops.spec_hits",
            "ops.spec_cancels", "ops.spec_skips",
            "ops.burst_cancels",
            "ops.aot_compiles", "ops.aot_hits", "jax.compile_count",
        )
    }
    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("PIPELINE SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"pipeline smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
