#!/usr/bin/env python
"""Integrity gate (``make integrity-smoke``) and report artifact.

Exercises the silent-corruption audit plane end to end and fails
loudly if the detect/quarantine/heal contract regressed:

- ENGINE CORRUPTION (ELL + grouped): the ``device.corrupt_resident``
  seam flips resident bits during a live churn; the very next forced
  audit must convict (one of the three tiers), quarantine, and heal
  WARM — the healed route product bit-identical to a from-scratch
  host oracle, the served digests unchanged for every untouched
  route, and ZERO route deletes (routes never flap),
- WORLD-BATCH CORRUPTION: the same seam fired inside
  ``solve_views`` lands after the dispatches settle; the audit heals
  by re-placing from the settle-on-success mirrors and the next
  ``solve_views`` serves bit-identical views with zero warm or cold
  re-solves,
- LADDER POISONING: a quarantined engine must refuse to serve another
  warm solve — the next churn walks past the warm rung
  (``route_engine.rung_failures.warm`` bumps) and rebuilds clean,
- AUDIT ACCOUNTING: every conviction is visible as
  ``integrity.violations.<tier>`` + ``integrity.quarantines`` +
  ``integrity.heals`` with no ``integrity.heal_failures`` and no
  contained ``integrity.audit_errors``.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_integrity_smoke.json``); exit 0 on pass, 1 with a
reason list on fail. Runs CPU-pinned — this gates audit machinery,
not kernels.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import replace

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/integrity_smoke.py) in addition
# to module mode (python -m tools.integrity_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _linkstate():
    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies

    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = LinkState(area=topo.area)
    for _name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    return ls


def _make_engine(kind, ls):
    from openr_tpu.faults import DegradationSupervisor
    from openr_tpu.ops import route_engine

    names = sorted(ls.get_adjacency_databases())
    cls = (
        route_engine.RouteSweepEngine
        if kind == "ell"
        else route_engine.GroupedRouteSweepEngine
    )
    engine = cls(ls, [names[0]])
    engine.supervisor = DegradationSupervisor(
        "route_engine", backoff_min_s=0.001, backoff_max_s=0.002
    )
    return engine, names


def _mutate(ls, name, metric):
    db = ls.get_adjacency_databases()[name]
    adjs = list(db.adjacencies)
    adjs[0] = replace(adjs[0], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {name, adjs[0].other_node_name}


def _host_digests(ls, names):
    from openr_tpu.ops import route_sweep

    return route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [names[0]], block=64)
    )


def _engine_corruption_leg(kind, report, failures):
    from openr_tpu.faults import FaultSchedule, get_injector
    from openr_tpu.integrity import get_auditor, quarantine_active
    from openr_tpu.ops import route_engine, route_sweep
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    ls = _linkstate()
    engine, names = _make_engine(kind, ls)
    aud = get_auditor()
    if aud.audit_now()[-1]["verdict"] != "clean":
        failures.append(f"{kind}: pristine engine failed its first audit")

    before = route_sweep.digests_by_name(engine.result)
    moved = engine.churn(ls, _mutate(ls, names[0], 7))
    report[f"{kind}_routes_moved"] = len(moved or ())
    if not moved:
        failures.append(f"{kind}: metric churn moved no routes")
    settled = route_sweep.digests_by_name(engine.result)
    if set(settled) != set(before):
        failures.append(f"{kind}: route deletes on a metric churn")

    # corrupt the settled residents, then audit: detection + warm heal
    # within ONE forced pass, the served digests untouched throughout
    q0 = reg.counter_get("integrity.quarantines")
    h0 = reg.counter_get("integrity.heals")
    hf0 = reg.counter_get("integrity.heal_failures")
    engine.corrupt_resident(seed=7)
    verdict = aud.audit_now()[-1]
    report[f"{kind}_verdict"] = verdict
    if verdict["verdict"] != "healed":
        failures.append(
            f"{kind}: audit verdict {verdict['verdict']!r} "
            f"(tier {verdict.get('tier')!r}), want healed in one pass"
        )
    if reg.counter_get("integrity.quarantines") - q0 != 1:
        failures.append(f"{kind}: conviction did not count a quarantine")
    if reg.counter_get("integrity.heals") - h0 != 1:
        failures.append(f"{kind}: heal did not count")
    if reg.counter_get("integrity.heal_failures") - hf0:
        failures.append(f"{kind}: heal failures counted")
    if quarantine_active():
        failures.append(f"{kind}: quarantine still active after heal")
    if route_sweep.digests_by_name(engine.result) != settled:
        failures.append(
            f"{kind}: served digests changed across quarantine + heal"
        )
    if settled != _host_digests(ls, names):
        failures.append(
            f"{kind}: healed route product diverged from host oracle"
        )

    # the seam itself: fired mid-churn the flip lands BEFORE the warm
    # body, so it is either convicted by the next audit or legitimately
    # overwritten by the re-solve — bit parity is the invariant either
    # way, and the injection must count exactly once
    fired0 = reg.counter_get("faults.injected.device.corrupt_resident")
    get_injector().arm(
        route_engine.FAULT_CORRUPT, FaultSchedule.fail_once()
    )
    engine.churn(ls, _mutate(ls, names[0], 1))
    get_injector().disarm(route_engine.FAULT_CORRUPT)
    fired = reg.counter_get(
        "faults.injected.device.corrupt_resident"
    ) - fired0
    if fired != 1:
        failures.append(
            f"{kind}: corruption seam fired {fired}x on churn (want 1)"
        )
    seam_verdict = aud.audit_now()[-1]
    report[f"{kind}_seam_verdict"] = seam_verdict
    if seam_verdict["verdict"] not in ("healed", "clean"):
        failures.append(
            f"{kind}: seam corruption left verdict "
            f"{seam_verdict['verdict']!r}"
        )
    if route_sweep.digests_by_name(engine.result) != _host_digests(
        ls, names
    ):
        failures.append(f"{kind}: post-seam product diverged from oracle")
    aud.unregister(engine)


def _ladder_poison_leg(report, failures):
    from openr_tpu.integrity import get_auditor
    from openr_tpu.ops import route_sweep
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    ls = _linkstate()
    engine, names = _make_engine("ell", ls)
    engine.corrupt_resident(seed=11)
    engine.quarantine("integrity smoke: manual quarantine")
    walks0 = reg.counter_get("route_engine.rung_failures.warm")
    engine.churn(ls, _mutate(ls, names[0], 13))
    walks = reg.counter_get("route_engine.rung_failures.warm") - walks0
    report["poisoned_warm_rung_walks"] = walks
    if walks != 1:
        failures.append(
            f"quarantined engine served the warm rung ({walks} walks)"
        )
    if route_sweep.digests_by_name(engine.result) != _host_digests(
        ls, names
    ):
        failures.append("ladder rebuild of a poisoned engine diverged")
    get_auditor().unregister(engine)


def _world_corruption_leg(report, failures):
    import numpy as np

    from openr_tpu.faults import FaultSchedule, get_injector
    from openr_tpu.integrity import get_auditor
    from openr_tpu.ops import route_engine
    from openr_tpu.ops import world_batch as wb
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    manager = wb.WorldManager(slots_per_bucket=4, max_resident=8)
    items = []
    for i in range(2):
        ls = _linkstate()
        names = sorted(ls.get_adjacency_databases())
        items.append((f"tenant{i}", ls, names[i]))
    views = manager.solve_views(items)
    before = [np.array(v[2], copy=True) for v in views]
    aud = get_auditor()
    if aud.audit_now()[-1]["verdict"] != "clean":
        failures.append("world: pristine manager failed its first audit")

    q0 = reg.counter_get("tenancy.quarantines")
    h0 = reg.counter_get("tenancy.integrity_heals")
    get_injector().arm(
        route_engine.FAULT_CORRUPT, FaultSchedule.fail_once()
    )
    manager.solve_views(items)
    get_injector().disarm(route_engine.FAULT_CORRUPT)
    verdict = aud.audit_now()[-1]
    report["world_verdict"] = verdict
    if verdict["verdict"] != "healed":
        failures.append(
            f"world: audit verdict {verdict['verdict']!r} "
            f"(tier {verdict.get('tier')!r}), want healed"
        )
    if reg.counter_get("tenancy.quarantines") - q0 != 1:
        failures.append("world: conviction did not count a quarantine")
    if reg.counter_get("tenancy.integrity_heals") - h0 != 1:
        failures.append("world: mirror re-placement heal did not count")

    # the heal is pure re-placement: the next solve serves the exact
    # pre-corruption bits without a single warm or cold re-solve
    warm0 = reg.counter_get("tenancy.warm_solves")
    cold0 = reg.counter_get("tenancy.cold_solves")
    views2 = manager.solve_views(items)
    warm = reg.counter_get("tenancy.warm_solves") - warm0
    cold = reg.counter_get("tenancy.cold_solves") - cold0
    report["world_post_heal_resolves"] = warm + cold
    if warm or cold:
        failures.append(
            f"world: heal paid {warm} warm + {cold} cold re-solves"
        )
    if not all(
        np.array_equal(a, v2[2]) for a, v2 in zip(before, views2)
    ):
        failures.append("world: post-heal views diverged (route flap)")
    aud.unregister(manager)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_integrity_smoke.json"
    )
    args = parser.parse_args(argv)

    from openr_tpu import testing

    testing.pin_host_cpu()

    from openr_tpu.faults import get_injector
    from openr_tpu.integrity import reset_auditor
    from openr_tpu.telemetry import get_registry, jax_hooks

    jax_hooks.install()
    get_injector().reset()
    reset_auditor()
    reg = get_registry()
    errors0 = reg.counter_get("integrity.audit_errors")
    failures: list = []
    report: dict = {}
    t0 = time.perf_counter()
    try:
        _engine_corruption_leg("ell", report, failures)
        _engine_corruption_leg("grouped", report, failures)
        _ladder_poison_leg(report, failures)
        _world_corruption_leg(report, failures)
    finally:
        get_injector().reset()
        reset_auditor()
    errors = reg.counter_get("integrity.audit_errors") - errors0
    report["audit_errors"] = errors
    if errors:
        failures.append(f"{errors} audit errors were contained (want 0)")
    report["elapsed_s"] = round(time.perf_counter() - t0, 3)
    report["failures"] = failures
    report["passed"] = not failures

    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report, indent=1, sort_keys=True))
    if failures:
        print(f"INTEGRITY GATE: FAIL ({len(failures)})", file=sys.stderr)
        return 1
    print(f"INTEGRITY GATE: PASS (report: {args.out})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
