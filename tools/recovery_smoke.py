#!/usr/bin/env python
"""Crash-recovery gate (``make recovery-smoke``) and report artifact.

Exercises the crash-safe state plane end to end and fails loudly if
the recovery contract regressed:

- WARM-BOOT PARITY: a Decision journaling through ``StatePlane`` is
  "crashed" (device caches dropped, process state rebuilt from the
  backing ``PersistentStore`` alone); the warm-booted RouteDatabase
  must be BIT-IDENTICAL to the crashed instance's last product and to
  a cold oracle replaying the same publications,
- WARM REHYDRATION: the warm boot must seed the resident ELL state
  from the persisted snapshot (``state.warm_seeds`` >= 1) and its
  rebuild must reconverge WARM — zero cold ELL solves and ZERO jit
  compiles beyond persistent-cache hits (``jax.compile_count`` delta
  == 0: every dispatch shape was warmed before the crash),
- DEVICE-LOSS LADDER: an injected ``device.lost`` at the dispatch
  seam must recover within the ladder (DEGRADED via the recover rung,
  one typed rebuild, bit parity vs the host oracle, self-heal to
  HEALTHY on the next churn),
- FIB GRACEFUL RESTART: a warm-booted Fib holding recovered routes
  must reconcile with exactly ONE ``sync_fib`` and ZERO deletes when
  Decision re-converges — and on hold-timer expiry when it never does
  (routes never flap either way).

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_recovery_smoke.json``); exit 0 on pass, 1 with a
reason list on fail. Runs CPU-pinned — this gates recovery machinery,
not kernels.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from dataclasses import replace

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/recovery_smoke.py) in addition
# to module mode (python -m tools.recovery_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _publish(decision, plane, area, kv):
    from openr_tpu.types import Publication

    if plane is not None:
        plane.on_kvstore_merge(area, kv)
    decision.process_publication(Publication(key_vals=dict(kv), area=area))


def _topo_key_vals(topo, versions):
    from openr_tpu.types import Value
    from openr_tpu.utils import keys as keyutil
    from openr_tpu.utils import wire

    kv = {}
    for db in topo.adj_dbs.values():
        k = keyutil.adj_key(db.this_node_name)
        versions[k] = versions.get(k, 0) + 1
        kv[k] = Value(
            version=versions[k],
            originator_id=db.this_node_name,
            value=wire.dumps(db),
        )
    for pdb in topo.prefix_dbs.values():
        k = keyutil.prefix_db_key(pdb.this_node_name)
        versions[k] = versions.get(k, 0) + 1
        kv[k] = Value(
            version=versions[k],
            originator_id=pdb.this_node_name,
            value=wire.dumps(pdb),
        )
    return kv


def _adj_key_val(db, versions):
    from openr_tpu.types import Value
    from openr_tpu.utils import keys as keyutil
    from openr_tpu.utils import wire

    k = keyutil.adj_key(db.this_node_name)
    versions[k] = versions.get(k, 0) + 1
    return {
        k: Value(
            version=versions[k],
            originator_id=db.this_node_name,
            value=wire.dumps(db),
        )
    }


def _warm_boot_leg(workdir, hooks_live, report, failures):
    from openr_tpu.config_store.persistent_store import PersistentStore
    from openr_tpu.decision import spf_solver
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.spf_solver import reset_device_caches
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.models import topologies
    from openr_tpu.ops.spf_sparse import ELL_COUNTERS
    from openr_tpu.state import StatePlane
    from openr_tpu.telemetry import get_registry
    from openr_tpu.utils import wire

    reg = get_registry()
    # route the test areas through the resident sliced-ELL path (the
    # one the state plane snapshots)
    spf_solver.SPARSE_NODE_THRESHOLD = 4
    topo = topologies.fat_tree_nodes(24)
    node = next(n for n in sorted(topo.adj_dbs) if n.startswith("rsw"))
    path = os.path.join(workdir, "state.bin")

    def make_decision(name, plane=None):
        return Decision(
            node,
            kvstore_updates_queue=ReplicateQueue(name=f"kv-{name}"),
            route_updates_queue=ReplicateQueue(name=f"routes-{name}"),
            state_plane=plane,
        )

    store = PersistentStore(path)
    # cadence of 4 so the churn run crosses a real checkpoint cut AND
    # leaves a journal tail — recovery exercises both layers
    plane = StatePlane(store, checkpoint_every=4)
    d1 = make_decision("live", plane)
    versions = {}
    initial = _topo_key_vals(topo, versions)
    _publish(d1, plane, topo.area, initial)
    d1.rebuild_routes("RECOVERY_SMOKE")
    d1.checkpoint_state()

    # churn a few metrics so the snapshot carries a real journal tail
    mutated = dict(topo.adj_dbs)
    churned = []
    for i, name in enumerate(sorted(mutated)[:4]):
        db = mutated[name]
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=10 + i)
        mutated[name] = replace(db, adjacencies=tuple(adjs))
        kv = _adj_key_val(mutated[name], versions)
        churned.append(kv)
        _publish(d1, plane, topo.area, kv)
        d1.rebuild_routes("RECOVERY_SMOKE")
    d1.checkpoint_state()
    routes_live = wire.dumps(d1.route_db.to_route_db(node))
    report["journal_len_at_crash"] = plane.journal_length()
    store.stop()

    # crash: resident device state and in-process LSDB are gone; only
    # the backing store survives
    reset_device_caches()

    store2 = PersistentStore(path)
    plane2 = StatePlane(store2)
    rec = plane2.recover()
    report["recovered_areas"] = len(rec.key_vals_by_area)
    report["journal_replayed"] = rec.journal_replayed
    report["had_checkpoint"] = rec.had_checkpoint
    if not rec.had_checkpoint:
        failures.append("recovery never saw a checkpoint cut")
    if rec.journal_replayed < 1:
        failures.append(
            "recovery replayed no journal records (WAL tail missing)"
        )
    warm0 = reg.counter_get("state.warm_seeds")
    cold_solves0 = ELL_COUNTERS["ell_cold_solves"]
    compiles0 = reg.counter_get("jax.compile_count") if hooks_live else None
    d2 = make_decision("warm", plane2)
    warm = d2.warm_boot(rec)
    routes_warm = wire.dumps(d2.route_db.to_route_db(node))
    report["warm_engines"] = warm
    report["warm_seeds"] = reg.counter_get("state.warm_seeds") - warm0

    if routes_warm != routes_live:
        failures.append(
            "warm-boot RouteDatabase diverged from the crashed instance"
        )
    if warm < 1 or reg.counter_get("state.warm_seeds") - warm0 < 1:
        failures.append("warm boot did not seed a warm engine")
    cold_delta = ELL_COUNTERS["ell_cold_solves"] - cold_solves0
    if cold_delta:
        failures.append(
            f"warm-boot rebuild paid {cold_delta} cold ELL solves"
        )
    if hooks_live:
        compile_delta = reg.counter_get("jax.compile_count") - compiles0
        report["rehydrate_compile_delta"] = compile_delta
        if compile_delta > 0:
            failures.append(
                f"warm boot jit-compiled {compile_delta}x (every "
                "dispatch shape was warmed before the crash)"
            )
    else:
        report["rehydrate_compile_delta"] = None

    # cold oracle: replay every publication from scratch, no plane
    d3 = make_decision("oracle")
    _publish(d3, None, topo.area, initial)
    for kv in churned:
        _publish(d3, None, topo.area, kv)
    d3.rebuild_routes("ORACLE")
    if routes_warm != wire.dumps(d3.route_db.to_route_db(node)):
        failures.append("warm-boot RouteDatabase diverged from cold oracle")
    store2.stop()
    report["warm_boot_parity"] = not any("warm-boot" in f for f in failures)


def _device_loss_leg(report, failures):
    from openr_tpu.faults import (
        DegradationSupervisor,
        FaultSchedule,
        HealthState,
        get_injector,
    )
    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies
    from openr_tpu.ops import route_engine, route_sweep
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = LinkState(area=topo.area)
    for _name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    names = sorted(ls.get_adjacency_databases())
    engine = route_engine.RouteSweepEngine(ls, [names[0]])
    engine.supervisor = DegradationSupervisor(
        "route_engine", backoff_min_s=0.001, backoff_max_s=0.002
    )

    def mutate(metric):
        db = ls.get_adjacency_databases()[names[0]]
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=metric)
        ls.update_adjacency_database(
            replace(db, adjacencies=tuple(adjs))
        )
        return {names[0], adjs[0].other_node_name}

    rebuilds0 = reg.counter_get("recovery.device_rebuilds")
    get_injector().arm("device.lost", FaultSchedule.fail_once())
    engine.churn(ls, mutate(31))
    get_injector().disarm("device.lost")
    degraded = engine.supervisor.state is HealthState.DEGRADED
    rebuilt = reg.counter_get("recovery.device_rebuilds") - rebuilds0
    report["device_loss_rebuilds"] = rebuilt
    if not degraded:
        failures.append(
            "device.lost did not land on the recover rung "
            f"(state {engine.supervisor.state.name})"
        )
    if rebuilt != 1:
        failures.append(f"expected 1 device rebuild, saw {rebuilt}")
    host = route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [names[0]], block=64)
    )
    if route_sweep.digests_by_name(engine.result) != host:
        failures.append("post-recovery route product diverged from oracle")
    engine.churn(ls, mutate(32))
    if engine.supervisor.state is not HealthState.HEALTHY:
        failures.append(
            "engine did not self-heal after device-loss recovery"
        )
    report["device_loss_recovered"] = not any(
        "device" in f or "recover" in f for f in failures
    )


def _fib_gr_leg(report, failures):
    from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
    from openr_tpu.fib.fib import OPENR_CLIENT_ID, Fib
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.platform.fib_service import MockFibAgent
    from openr_tpu.types import BinaryAddress, IpPrefix, NextHop

    def entry(prefix):
        return RibUnicastEntry(
            prefix=IpPrefix.from_str(prefix),
            nexthops={
                NextHop(
                    address=BinaryAddress.from_str(
                        "fe80::1", if_name="if0"
                    ),
                    metric=1,
                )
            },
        )

    def push(q, entries):
        update = DecisionRouteUpdate()
        for e in entries:
            update.unicast_routes_to_update[e.prefix] = e
        q.push(update)

    def wait_until(pred, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.01)
        return pred()

    prefixes = ["fd00:1::/64", "fd00:2::/64", "fd00:3::/64"]
    agent = MockFibAgent()
    # previous life: program the routes, capture its RouteDatabase
    q0 = ReplicateQueue(name="rs-prev")
    prev = Fib("node-a", agent, q0, keepalive_interval_s=30.0)
    prev.start()
    push(q0, [entry(p) for p in prefixes])
    if not wait_until(
        lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 3
    ):
        failures.append("fib previous life failed to program routes")
    rdb = prev.get_route_db()
    prev.stop()

    # warm boot with graceful restart: Decision re-converges in time
    syncs0 = agent.counters["sync_fib"]
    deletes0 = agent.counters["delete_unicast"]
    q1 = ReplicateQueue(name="rs-gr")
    fib = Fib(
        "node-a", agent, q1,
        keepalive_interval_s=30.0,
        graceful_restart_hold_s=30.0,
    )
    fib.start_graceful_restart(rdb)
    fib.start()
    push(q1, [entry(p) for p in prefixes] + [entry("fd00:4::/64")])
    ok = wait_until(lambda: fib.counters["fib.gr_reconciles"] == 1)
    fib.stop()
    sync_delta = agent.counters["sync_fib"] - syncs0
    delete_delta = agent.counters["delete_unicast"] - deletes0
    report["gr_reconcile_syncs"] = sync_delta
    report["gr_reconcile_deletes"] = delete_delta
    if not ok or sync_delta != 1:
        failures.append(
            f"graceful restart reconciled with {sync_delta} syncs "
            "(want exactly 1)"
        )
    if delete_delta:
        failures.append(
            f"graceful restart deleted {delete_delta} routes (flap!)"
        )
    if len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) != 4:
        failures.append("post-reconcile agent table wrong")

    # hold-timer expiry: Decision never re-converges
    syncs1 = agent.counters["sync_fib"]
    q2 = ReplicateQueue(name="rs-exp")
    fib2 = Fib(
        "node-a", agent, q2,
        keepalive_interval_s=30.0,
        graceful_restart_hold_s=0.05,
    )
    fib2.start_graceful_restart(rdb)
    fib2.start()
    expired = wait_until(
        lambda: fib2.counters["fib.gr_hold_expirations"] == 1
    )
    wait_until(lambda: agent.counters["sync_fib"] == syncs1 + 1)
    fib2.stop()
    report["gr_hold_expirations"] = fib2.counters[
        "fib.gr_hold_expirations"
    ]
    if not expired or agent.counters["sync_fib"] - syncs1 != 1:
        failures.append(
            "hold-timer expiry did not reconcile with exactly one sync"
        )
    report["fib_gr_no_flap"] = not any("flap" in f or "sync" in f
                                       for f in failures)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_recovery_smoke.json"
    )
    args = parser.parse_args(argv)

    from openr_tpu import testing

    testing.pin_host_cpu()

    from openr_tpu.faults import get_injector
    from openr_tpu.telemetry import jax_hooks

    hooks_live = jax_hooks.install()
    get_injector().reset()
    failures: list = []
    report: dict = {}
    workdir = tempfile.mkdtemp(prefix="openr_tpu_recovery_")
    t0 = time.perf_counter()
    try:
        _warm_boot_leg(workdir, hooks_live, report, failures)
        _device_loss_leg(report, failures)
        _fib_gr_leg(report, failures)
    finally:
        get_injector().reset()
        shutil.rmtree(workdir, ignore_errors=True)
    report["elapsed_s"] = round(time.perf_counter() - t0, 3)
    report["failures"] = failures
    report["passed"] = not failures

    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report, indent=1, sort_keys=True))
    if failures:
        print(f"RECOVERY GATE: FAIL ({len(failures)})", file=sys.stderr)
        return 1
    print(f"RECOVERY GATE: PASS (report: {args.out})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
