"""Healthy-relay watcher: capture on-chip bench evidence whenever the
TPU relay is up.

The axon relay serving the one real TPU chip has died mid-round in
every round so far (see BENCH_r03.json relay_outage_note). The official
end-of-round ``bench.py`` run can therefore degrade to a CPU fallback
through no fault of the framework. This watcher closes the evidence
gap: it probes the relay on a fixed cadence and, inside any healthy
window, re-runs the OFFICIAL bench command and preserves the parsed
result as ``BENCH_r{N}_midround.json`` — the exact artifact
``bench.py`` embeds as ``last_known_tpu`` when it has to fall back.

It also runs the scale benches (10k all-sources ELL + fabric-1008 KSP2
churn) and appends them, timestamped, to ``SCALE_r{N}_captures.jsonl``
so the freshest on-chip scale numbers survive an outage too.

Run (backgrounded, from the repo root):
    python tools/tpu_watcher.py --round 4 &

Everything is subprocess-isolated under hard timeouts — the relay has
hung jax.devices() itself before — so the watcher never wedges.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_TIMEOUT_S = 90
PROBE_PERIOD_S = 240
HEARTBEAT_PERIOD_S = 15 * 60
# a capture is "fresh enough" for this long; afterwards a healthy probe
# triggers a re-capture so the preserved artifact tracks the newest code
CAPTURE_TTL_S = 45 * 60
BENCH_TIMEOUT_S = 1500
SCALE_TIMEOUT_S = 2700  # the 100k leg probes three contraction impls


def log(msg: str) -> None:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(f"[{stamp}] {msg}", flush=True)


def probe() -> bool:
    healthy, _ = probe_detail()
    return healthy


def probe_detail() -> tuple[bool, str]:
    """Probe the relay; return (healthy, detail) where detail names the
    failure mode (timeout / nonzero exit / cpu-only) for the heartbeat."""
    code = (
        "import jax, jax.numpy as jnp, numpy as np\n"
        "d = jax.devices()[0]\n"
        "x = jnp.ones((8, 8), jnp.float32)\n"
        "assert float(np.asarray(x @ x).sum()) == 512.0\n"
        "print('PLATFORM=' + d.platform)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timeout after {PROBE_TIMEOUT_S}s"
    out = proc.stdout.decode(errors="replace")
    for line in out.splitlines():
        if line.startswith("PLATFORM="):
            platform = line.split("=", 1)[1]
            if platform != "cpu":
                return True, f"healthy ({platform})"
            return False, "backend came up cpu-only"
    return False, f"probe exited rc={proc.returncode} without a platform"


def heartbeat(
    round_no: int, healthy: bool, detail: str, state: dict
) -> None:
    """Append a probe heartbeat to SCALE_r{N}_captures.jsonl on a coarse
    cadence so 'relay down all round' is itself a committed, driver-visible
    artifact (not just prose), even when no capture ever lands."""
    state["probes"] = state.get("probes", 0) + 1
    if healthy:
        state["healthy"] = state.get("healthy", 0) + 1
    else:
        state["last_failure"] = detail
    now = time.time()
    if now - state.get("last_write", 0.0) < HEARTBEAT_PERIOD_S:
        return
    state["last_write"] = now
    path = os.path.join(REPO, f"SCALE_r{round_no:02d}_captures.jsonl")
    rec = {
        "heartbeat": True,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "probes_total": state["probes"],
        "probes_healthy": state.get("healthy", 0),
        "last_failure": state.get("last_failure"),
        "last_probe": detail,
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def run_json(cmd: list[str], timeout_s: int):
    """Run a bench command, return its last JSON line (or None)."""
    try:
        proc = subprocess.run(
            cmd,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        log(f"timed out: {' '.join(cmd)}")
        return None
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    log(f"no JSON from: {' '.join(cmd)} rc={proc.returncode}")
    return None


def _leg_capture_times(scale_path: str) -> dict:
    """leg name -> epoch seconds of its newest ON-CHIP capture record.
    Drives per-leg freshness: an interrupted capture RESUMES at the
    legs it never reached (the relay has died mid-capture and the
    north-star 100k leg, ordered last, went unmeasured) instead of
    re-running the whole suite from the top."""
    import calendar

    out: dict = {}
    try:
        with open(scale_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                leg, utc = rec.get("leg"), rec.get("utc")
                result = rec.get("result") or {}
                if not leg or not utc:
                    continue
                if result.get("platform") != "tpu":
                    continue
                try:
                    ts = calendar.timegm(
                        time.strptime(utc, "%Y-%m-%dT%H:%M:%SZ")
                    )
                except ValueError:
                    continue
                out[leg] = max(out.get(leg, 0), ts)
    except OSError:
        pass
    return out


def capture(round_no: int) -> bool:
    """One capture pass: official bench + scale legs, each skipped
    while its last on-chip record is fresh. Returns True only when
    EVERYTHING is fresh at exit — an interrupted pass returns False so
    the main loop retries on the backoff cadence instead of waiting
    out the full capture TTL with legs missing."""
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    bench_path = os.path.join(
        REPO, f"BENCH_r{round_no:02d}_midround.json"
    )
    bench_age = time.time() - (
        os.path.getmtime(bench_path)
        if os.path.exists(bench_path)
        else 0
    )
    ok = False
    if bench_age < CAPTURE_TTL_S:
        log(f"bench.py: fresh ({int(bench_age)}s old), skipping")
        ok = True
        result = None
    else:
        result = run_json(
            [sys.executable, "bench.py"], BENCH_TIMEOUT_S
        )
        ok = (
            result is not None
            and result.get("error") is None
            and result.get("platform") == "tpu"
        )
    if ok and result is not None:
        out = {
            "note": (
                "Self-captured run of the official bench.py (identical "
                "format/command) while the axon relay was healthy, "
                f"{stamp}. Preserved by tools/tpu_watcher.py so a later "
                "relay outage cannot erase the round's on-chip evidence: "
                "bench.py embeds this file as last_known_tpu when it has "
                "to fall back to CPU."
            ),
            "utc": stamp,
            "result": result,
        }
        tmp = bench_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2)
        os.replace(tmp, bench_path)
        log(f"captured {bench_path} (value={result.get('value')}ms)")
    elif not ok:
        log(f"bench.py capture not usable: {result and result.get('platform')}")

    # scale legs: freshest on-chip numbers for SCALE_r{N}.json
    scale_path = os.path.join(
        REPO, f"SCALE_r{round_no:02d}_captures.jsonl"
    )
    legs = [
        (
            "route_sweep_10k_grouped",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--routes", "--nodes", "10000", "--backend", "grouped"],
        ),
        (
            "route_sweep_10k_ell",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--routes", "--nodes", "10000", "--backend", "ell"],
        ),
        (
            "ksp2_churn_1008",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--solver-churn", "--nodes", "1000",
             "--churn-events", "10"],
        ),
        (
            "all_sources_10k",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--nodes", "10000", "--kernel", "ell"],
        ),
        (
            # incremental NETWORK-WIDE route reconvergence at 10k: the
            # resident route engine re-solves only affected
            # destination rows per event (route_engine.py)
            "route_engine_churn_10k",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--routes-churn", "--nodes", "10000",
             "--churn-events", "10"],
        ),
        (
            # TOPOLOGY churn on the incremental path: alternating link
            # remove/restore events ride the same fused dispatch
            # (band widening in ell_patch keeps node ids stable)
            "route_engine_link_churn_10k",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--routes-churn", "--nodes", "10000",
             "--churn-events", "10", "--churn-kind", "link"],
        ),
        (
            # the grouped-backend incremental engine: the flagship
            # gather-free relaxation with resident-DR churn
            "route_engine_churn_10k_grouped",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--routes-churn", "--nodes", "10000",
             "--churn-events", "10", "--backend", "grouped"],
        ),
        (
            # grouped LINK churn: removal patches a weight slot,
            # restore rewrites the retired slot (restorable by
            # construction) — with the full-width refresh this is the
            # hardest event class that still avoids a host recompile
            "route_engine_link_churn_10k_grouped",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--routes-churn", "--nodes", "10000",
             "--churn-events", "10", "--churn-kind", "link",
             "--backend", "grouped"],
        ),
        (
            # incremental KSP2 with the ENGINE ACTIVE at 10k nodes
            # (VERDICT item 8): 256 KSP2 destinations on the 10k
            # fat-tree, all-pairs event dispatch over the full graph
            "ksp2_churn_10k_engine",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--solver-churn", "--nodes", "10000",
             "--churn-events", "5", "--ksp2-dsts", "256"],
        ),
        (
            # the 100k north-star axis: FULL 98-block sweep with
            # on-device route consumption (no 40 GB readback), grouped
            # backend with on-chip impl probing
            "route_sweep_100k_grouped",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--routes", "--nodes", "100000", "--backend", "grouped"],
        ),
        (
            # the north star AS DEFINED (BASELINE.json: full-SPF
            # reconvergence of one node's RouteDb at 100k): full
            # SpfSolver churn rebuild, all prefixes SP_ECMP, one fused
            # view dispatch + SP-route-reuse-bounded host rebuild
            "solver_churn_100k_sp",
            [sys.executable, "-m", "benchmarks.bench_scale",
             "--solver-churn", "--nodes", "100000",
             "--churn-events", "5", "--sp-only"],
        ),
    ]
    # stalest-first: legs never captured on-chip (epoch 0) run before
    # re-runs of fresh ones, and a still-fresh leg is skipped outright —
    # a healthy window is spent where the evidence gaps are
    cap_times = _leg_capture_times(scale_path)
    legs.sort(key=lambda nc: cap_times.get(nc[0], 0))
    for name, cmd in legs:
        age = time.time() - cap_times.get(name, 0)
        if age < CAPTURE_TTL_S:
            log(f"scale leg {name}: fresh ({int(age)}s old), skipping")
            continue
        r = run_json(cmd, SCALE_TIMEOUT_S)
        if r is not None:
            # stamp at APPEND time, not pass start: a cold pass can
            # outlast CAPTURE_TTL_S, and pass-start stamps would parse
            # as already-stale, defeating both the fresh-skip and the
            # end-of-pass completeness check
            leg_stamp = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            )
            with open(scale_path, "a") as f:
                f.write(json.dumps(
                    {"leg": name, "utc": leg_stamp, "result": r}
                ) + "\n")
            log(f"scale leg {name}: {r.get('platform')}")
        if not probe():
            log("relay lost mid-capture; stopping scale legs")
            return False
    cap_times = _leg_capture_times(scale_path)
    all_fresh = all(
        time.time() - cap_times.get(name, 0) < CAPTURE_TTL_S
        for name, _cmd in legs
    )
    return ok and all_fresh


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--round", type=int, required=True)
    p.add_argument("--once", action="store_true",
                   help="single probe+capture attempt, then exit")
    args = p.parse_args()
    last_capture = 0.0
    last_attempt = 0.0
    retry_backoff_s = 15 * 60  # failed capture: don't hammer the relay
    hb_state: dict = {}
    while True:
        healthy, detail = probe_detail()
        heartbeat(args.round, healthy, detail, hb_state)
        if healthy:
            due = time.time() - last_capture > CAPTURE_TTL_S
            cooled = time.time() - last_attempt > retry_backoff_s
            if due and cooled:
                log("relay healthy; capturing")
                last_attempt = time.time()
                if capture(args.round):
                    last_capture = time.time()
            else:
                log("relay healthy; capture fresh or cooling down")
        else:
            log(f"relay down: {detail}")
        if args.once:
            break
        time.sleep(PROBE_PERIOD_S)


if __name__ == "__main__":
    main()
