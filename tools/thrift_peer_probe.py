"""Probe a KvStore peer over the reference thrift wire.

Operator tool for interop debugging: dials a framed-CompactProtocol
``KvStoreService`` endpoint (this framework's peer server with
``enable_kvstore_thrift``, or a stock Open/R daemon's peer port) and
dumps keys — proving wire-level compatibility from the command line.

Run:  python tools/thrift_peer_probe.py HOST PORT [--area 0]
          [--prefix adj:] [--keys k1,k2] [--hashes-only]
"""

from __future__ import annotations

import argparse
import sys

from openr_tpu.kvstore.thrift_peer import ThriftPeerTransport
from openr_tpu.types import KeyDumpParams


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="thrift-peer-probe")
    p.add_argument("host")
    p.add_argument("port", type=int)
    p.add_argument("--area", default="0")
    p.add_argument("--prefix", default="", help="key prefix filter")
    p.add_argument(
        "--keys", default="", help="comma-separated exact keys"
    )
    p.add_argument(
        "--timeout", type=float, default=10.0, help="dial timeout (s)"
    )
    args = p.parse_args(argv)

    client = ThriftPeerTransport(args.host, args.port, args.timeout)
    try:
        if args.keys:
            pub = client.get_key_vals(
                args.area, [k for k in args.keys.split(",") if k]
            )
        else:
            pub = client.get_key_vals_filtered(
                args.area, KeyDumpParams(prefix=args.prefix)
            )
    except (OSError, RuntimeError) as exc:
        print(f"probe failed: {exc}", file=sys.stderr)
        return 1
    finally:
        client.close()

    print(
        f"area {pub.area!r}: {len(pub.key_vals)} key(s)"
        + (f" matching prefix {args.prefix!r}" if args.prefix else "")
    )
    for key in sorted(pub.key_vals):
        v = pub.key_vals[key]
        size = len(v.value) if v.value is not None else 0
        print(
            f"  {key}  v{v.version} ttl={v.ttl} ttlv={v.ttl_version} "
            f"orig={v.originator_id} {size}B"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
