"""Probe an openr-tpu (or stock Open/R) ctrl port over the THRIFT
wire — the stock-toolchain view of a node.

Dials the ctrl port with framed CompactProtocol (byte-identical to a
stock thrift client on classic framed transport) and prints the
operator snapshot: identity/version, counters, KvStore dump summary,
installed routes, adjacency and prefix databases, peers.

    python tools/thrift_ctrl_probe.py --host 127.0.0.1 --port 2018
    python tools/thrift_ctrl_probe.py --port 2018 --method getRouteDb

With --method, calls exactly one RPC and prints its raw decoded
result as JSON (bytes rendered as hex).
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from openr_tpu.ctrl.thrift_ctrl import ThriftCtrlClient  # noqa: E402


def _jsonable(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    return obj


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2018)
    p.add_argument("--method", default=None,
                   help="call one RPC and dump its decoded result")
    p.add_argument("--args", default="{}",
                   help="JSON kwargs for --method")
    args = p.parse_args()

    client = ThriftCtrlClient(args.host, args.port)
    try:
        if args.method:
            result = client.call(
                args.method, **json.loads(args.args)
            )
            print(json.dumps(_jsonable(result), indent=2, sort_keys=True))
            return 0
        node = client.call("getMyNodeName")
        version = client.call("getOpenrVersion")
        counters = client.call("getCounters")
        pub = client.call(
            "getKvStoreKeyValsFilteredArea",
            filter={"prefix": "", "originatorIds": [],
                    "ignoreTtl": False, "doNotPublishValue": True},
            area="0",
        )
        routes = client.call("getRouteDb")
        adj = client.call("getDecisionAdjacencyDbs")
        prefixes = client.call("getDecisionPrefixDbs")
        peers = client.call("getKvStorePeersArea", area="0")
        print(f"node            {node}")
        print(f"version         {version['version']} "
              f"(lowest {version['lowestSupportedVersion']})")
        print(f"counters        {len(counters)}")
        print(f"kvstore keys    {len(pub['keyVals'])}")
        print(f"unicast routes  {len(routes['unicastRoutes'])}")
        print(f"mpls routes     {len(routes['mplsRoutes'])}")
        print(f"adjacency dbs   {sorted(adj)}")
        print(f"prefix dbs      {sorted(prefixes)}")
        print(f"kvstore peers   {sorted(peers)}")
        return 0
    finally:
        client.close()


if __name__ == "__main__":
    raise SystemExit(main())
