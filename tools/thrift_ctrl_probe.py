"""Probe an openr-tpu (or stock Open/R) ctrl port over the THRIFT
wire — the stock-toolchain view of a node.

Dials the ctrl port with framed CompactProtocol (byte-identical to a
stock thrift client on classic framed transport) and prints the
operator snapshot: identity/version, counters, KvStore dump summary,
installed routes, adjacency and prefix databases, peers.

    python tools/thrift_ctrl_probe.py --host 127.0.0.1 --port 2018
    python tools/thrift_ctrl_probe.py --port 2018 --method getRouteDb

With --method, calls exactly one RPC and prints its raw decoded
result as JSON (bytes rendered as hex).
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from openr_tpu.ctrl.thrift_ctrl import ThriftCtrlClient  # noqa: E402


def _jsonable(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    return obj


def _adj_snapshot(client):
    """Current adj: keys as the thrift KeyVals map shape the long poll
    compares against."""
    pub = client.call(
        "getKvStoreKeyValsFilteredArea",
        filter={"prefix": "adj:", "originatorIds": [],
                "ignoreTtl": False, "doNotPublishValue": True},
        area="0",
    )
    return pub["keyVals"]


def _follow(client, count: int) -> int:
    """Follow adjacency-set changes over the STOCK thrift wire: the
    long-poll emulation of the reference's Rocket streaming
    subscription (docs/PROTOCOL_GUIDE.md). longPollKvStoreAdj answers
    true when the snapshot is stale or a change lands; the filtered
    re-dump then carries the delta."""
    snapshot = _adj_snapshot(client)
    print(f"following adjacency changes ({len(snapshot)} adj keys)",
          flush=True)
    seen = 0
    while count <= 0 or seen < count:
        try:
            changed = client.call(
                "longPollKvStoreAdj",
                snapshot={
                    k: {"version": v.get("version", 0),
                        "originatorId": v.get("originatorId", ""),
                        "ttl": v.get("ttl", 0),
                        "ttlVersion": v.get("ttlVersion", 0)}
                    for k, v in snapshot.items()
                },
            )
        except (ConnectionError, OSError):
            # transport hiccup (the client reconnects per call):
            # re-arm with the same snapshot rather than crashing out
            # of a long-running follow
            continue
        if not changed:
            continue  # poll timeout: re-arm with the same snapshot
        fresh = _adj_snapshot(client)
        delta = sorted(
            k for k in set(fresh) | set(snapshot)
            if fresh.get(k, {}).get("version")
            != snapshot.get(k, {}).get("version")
        )
        print(f"adjacency change: {delta}", flush=True)
        snapshot = fresh
        seen += 1
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2018)
    p.add_argument("--method", default=None,
                   help="call one RPC and dump its decoded result")
    p.add_argument("--args", default="{}",
                   help="JSON kwargs for --method")
    p.add_argument("--full", action="store_true",
                   help="dump the COMPLETE RPC surface: call every "
                        "read-only RPC and print each result")
    p.add_argument("--follow", action="store_true",
                   help="follow adjacency changes over the STOCK wire "
                        "via the long-poll emulation of the Rocket "
                        "streaming subscription (longPollKvStoreAdj + "
                        "filtered re-dump); one line per change")
    p.add_argument("--follow-count", type=int, default=0,
                   help="stop --follow after N changes (0 = forever)")
    args = p.parse_args()

    client = ThriftCtrlClient(args.host, args.port)
    try:
        if args.method:
            result = client.call(
                args.method, **json.loads(args.args)
            )
            print(json.dumps(_jsonable(result), indent=2, sort_keys=True))
            return 0
        if args.full:
            # every read-only RPC with defaultable args — the full
            # surface a stock toolchain can dump without mutating state
            calls = [
                ("getMyNodeName", {}), ("getOpenrVersion", {}),
                ("aliveSince", {}), ("getCounters", {}),
                ("getRunningConfig", {}),
                ("getRunningConfigThrift", {}),
                ("getAreasConfig", {}), ("getBuildInfo", {}),
                ("getKvStoreKeyValsFilteredArea", {
                    "filter": {"prefix": "", "originatorIds": [],
                               "ignoreTtl": False,
                               "doNotPublishValue": True},
                    "area": "0"}),
                ("getKvStorePeersArea", {"area": "0"}),
                ("getSpanningTreeInfos", {"area": "0"}),
                ("getRouteDb", {}), ("getUnicastRoutes", {}),
                ("getMplsRoutes", {}), ("getPerfDb", {}),
                ("getDecisionAdjacencyDbs", {}),
                ("getAllDecisionAdjacencyDbs", {}),
                ("getDecisionPrefixDbs", {}),
                ("getPrefixes", {}), ("getAdvertisedRoutes", {}),
                ("getReceivedRoutes", {}), ("getInterfaces", {}),
                ("getLinkMonitorAdjacencies", {}),
                ("getNeighbors", {}), ("getEventLogs", {}),
                ("getRibPolicy", {}),
            ]
            failures = 0
            for name, kwargs in calls:
                try:
                    result = client.call(name, **kwargs)
                    print(f"== {name}")
                    print(json.dumps(_jsonable(result), indent=2,
                                     sort_keys=True))
                except RuntimeError as exc:
                    # declared OpenrError (e.g. rib policy unset) is a
                    # valid wire answer, not a probe failure
                    print(f"== {name}: OpenrError: {exc}")
                except Exception as exc:
                    failures += 1
                    print(f"== {name}: FAILED: {exc}")
            return 1 if failures else 0
        if args.follow:
            return _follow(client, args.follow_count)
        node = client.call("getMyNodeName")
        version = client.call("getOpenrVersion")
        counters = client.call("getCounters")
        pub = client.call(
            "getKvStoreKeyValsFilteredArea",
            filter={"prefix": "", "originatorIds": [],
                    "ignoreTtl": False, "doNotPublishValue": True},
            area="0",
        )
        routes = client.call("getRouteDb")
        adj = client.call("getDecisionAdjacencyDbs")
        prefixes = client.call("getDecisionPrefixDbs")
        peers = client.call("getKvStorePeersArea", area="0")
        print(f"node            {node}")
        print(f"version         {version['version']} "
              f"(lowest {version['lowestSupportedVersion']})")
        print(f"counters        {len(counters)}")
        print(f"kvstore keys    {len(pub['keyVals'])}")
        print(f"unicast routes  {len(routes['unicastRoutes'])}")
        print(f"mpls routes     {len(routes['mplsRoutes'])}")
        print(f"adjacency dbs   {sorted(adj)}")
        print(f"prefix dbs      {sorted(prefixes)}")
        print(f"kvstore peers   {sorted(peers)}")
        return 0
    finally:
        client.close()


if __name__ == "__main__":
    raise SystemExit(main())
