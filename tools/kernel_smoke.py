#!/usr/bin/env python
"""Sliced-ELL kernel gate (``make kernel-smoke``) and report artifact.

Exercises the Pallas sliced-ELL relax kernel (``openr_tpu.ops.
pallas_ell``, interpret mode on CPU) against the jnp formulation and
the autotuner plumbing that arms it, then fails loudly if the kernel
contract regressed:

- INTERPRET PARITY: all-pairs distances on a 3-pod fat-tree and a
  random mesh must be BIT-IDENTICAL (int32 exact) between
  ``impl="jnp"`` and ``impl="pallas"`` — the padding/overload-masking
  contract admits no tolerance,
- AUTOTUNER ROUND-TRIP: an ``ell_relax`` winner measured into a fresh
  cache dir must persist under the v2 family-keyed schema and be
  adopted by a brand-new tuner (same winner, zero re-measures),
- COMPILE FLATNESS: with the kernel armed through ``impl="auto"``, a
  second pass over a warmed metric-churn sequence must cost ZERO AOT
  compiles and ZERO backend jit compiles — arming the kernel re-keys
  tags once at warm-up, never per event.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_kernel_smoke.json``); exit 0 on pass, 1 with a reason
list on fail. Runs CPU-pinned — this gates the kernel's algebra and
dispatch plumbing, not device throughput (bench owns that leg, see
``OPENR_BENCH_ELLKERN``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/kernel_smoke.py) in addition
# to module mode (python -m tools.kernel_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load(topo):
    from openr_tpu.graph.linkstate import LinkState

    ls = LinkState(area=topo.area)
    for _name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    return ls


def _mutate_metric(ls, node, i, metric):
    from dataclasses import replace

    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {node, adjs[i].other_node_name}


SEQ = (7, 3, 11, 5)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--out", default="/tmp/openr_tpu_kernel_smoke.json",
        help="JSON artifact path",
    )
    args = ap.parse_args()

    import jax
    import numpy as np

    from openr_tpu.models import topologies
    from openr_tpu.ops import autotune, route_engine, spf_sparse
    from openr_tpu.ops.pallas_ell import vmem_bytes
    from openr_tpu.telemetry import get_registry

    failures: list = []
    report: dict = {"gates": {}}
    reg = get_registry()
    prev_impl = spf_sparse.get_ell_relax_impl()
    prev_tuner = autotune.get_autotuner()

    # -- gate: interpret-mode bit parity on real topologies -------------
    parity_ok = True
    for name, topo in (
        ("fat_tree", topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )),
        ("random_mesh", topologies.random_mesh(
            40, degree=5, seed=3, max_metric=30
        )),
    ):
        ls = _load(topo)
        graph = spf_sparse.compile_ell(ls)
        srcs = np.arange(graph.n, dtype=np.int32)
        spf_sparse.set_ell_relax_impl("jnp")
        d_jnp = np.asarray(
            spf_sparse.ell_distances_from_sources(graph, srcs)
        )
        spf_sparse.set_ell_relax_impl("pallas")
        d_pl = np.asarray(
            spf_sparse.ell_distances_from_sources(graph, srcs)
        )
        same = bool(np.array_equal(d_jnp, d_pl))
        parity_ok = parity_ok and same
        k_max = max(b.k for b in graph.bands)
        report.setdefault("parity", {})[name] = {
            "bit_identical": same,
            "n_pad": graph.n_pad,
            "k_max": k_max,
            "vmem_bytes": vmem_bytes(graph.n_pad, k_max),
        }
        if not same:
            bad = int((d_jnp != d_pl).sum())
            failures.append(
                f"pallas kernel diverged from jnp on {name}: {bad} "
                "cell(s) differ — the bit-identity contract is broken"
            )
    report["gates"]["interpret_parity"] = parity_ok

    # -- gate: autotuner measure -> persist -> reload round-trip --------
    with tempfile.TemporaryDirectory() as cache:
        prev_env = os.environ.get("OPENR_CACHE_DIR")
        os.environ["OPENR_CACHE_DIR"] = cache
        try:
            t1 = autotune.Autotuner()
            autotune.set_autotuner(t1)
            winner = autotune.resolve_ell_relax((256, 4))
            path = os.path.join(cache, "autotune.json")
            persisted = {}
            if os.path.exists(path):
                with open(path) as fh:
                    persisted = json.load(fh)
            schema_ok = persisted.get("version") == 2
            key = f"{jax.devices()[0].platform}:ell_relax:256x4"
            entry = persisted.get("winners", {}).get(key, {})
            entry_ok = (
                entry.get("winner") == winner
                and entry.get("family") == "ell_relax"
            )
            # a fresh tuner must adopt without re-measuring
            measured = []
            t2 = autotune.Autotuner(
                measure=lambda th, reps=3: measured.append(1) or 1.0
            )
            autotune.set_autotuner(t2)
            winner2 = autotune.resolve_ell_relax((256, 4))
            adopt_ok = winner2 == winner and not measured
            report["autotune"] = {
                "winner": winner,
                "schema_version_2": schema_ok,
                "entry_family_keyed": entry_ok,
                "adopted_without_remeasure": adopt_ok,
            }
            if not schema_ok:
                failures.append(
                    "autotune persistence is not the v2 family-keyed "
                    "schema"
                )
            if not entry_ok:
                failures.append(
                    f"persisted ell_relax entry malformed: {entry}"
                )
            if not adopt_ok:
                failures.append(
                    "fresh tuner re-measured or flipped the persisted "
                    f"ell_relax winner ({winner} -> {winner2}, "
                    f"{len(measured)} re-measure(s))"
                )
            report["gates"]["autotune_round_trip"] = (
                schema_ok and entry_ok and adopt_ok
            )
        finally:
            if prev_env is None:
                os.environ.pop("OPENR_CACHE_DIR", None)
            else:
                os.environ["OPENR_CACHE_DIR"] = prev_env

    # -- gate: compile flatness with the kernel armed via auto ----------
    class _Forced(autotune.Autotuner):
        def pick(self, kernel, shape_key, candidates):
            return "pallas" if "pallas" in candidates else next(
                iter(candidates)
            )

    autotune.set_autotuner(_Forced(persist=False))
    spf_sparse.set_ell_relax_impl("auto")
    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = _load(topo)
    names = sorted(ls.get_adjacency_databases().keys())
    engine = route_engine.RouteSweepEngine(ls, [names[0]])
    rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))
    for metric in SEQ:  # warm every (tag@pallas, bucket) key
        engine.churn(ls, _mutate_metric(ls, rsw, 0, metric))
    compiles0 = reg.counter_get("ops.aot_compiles")
    jax0 = reg.counter_get("jax.compile_count")
    for metric in SEQ:
        engine.churn(ls, _mutate_metric(ls, rsw, 0, metric))
    compile_delta = reg.counter_get("ops.aot_compiles") - compiles0
    jax_delta = reg.counter_get("jax.compile_count") - jax0
    if compile_delta:
        failures.append(
            f"armed warm pass AOT-compiled {compile_delta} time(s); "
            "@pallas tags must be fully keyed at warm-up"
        )
    if jax_delta:
        failures.append(
            f"armed warm pass triggered {jax_delta} backend jit "
            "compile(s)"
        )
    report["gates"]["armed_compile_flatness"] = (
        compile_delta == 0 and jax_delta == 0
    )
    report["armed_warm"] = {
        "aot_compile_delta": compile_delta,
        "jax_compile_delta": jax_delta,
    }

    spf_sparse.set_ell_relax_impl(prev_impl)
    autotune.set_autotuner(prev_tuner)

    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("KERNEL SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"kernel smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
