"""Machine-readable invariant-lint report for CI artifacts.

``make lint-analysis`` gates on the exit code; this wrapper is the
artifact side: it runs the same checkers (with the suppression
staleness audit on) and writes the full JSON payload (every finding,
including suppressed ones with their reasons, plus the stale-directive
count) so a CI run keeps an auditable record of which invariant
exceptions existed at that commit.

Run:  python -m tools.lint_report [--out artifacts/lint_report.json]

Exit code matches ``python -m openr_tpu.analysis``: 0 only when every
finding is suppressed-with-a-reason.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from openr_tpu.analysis.core import STALE_RULE, run_analysis


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.lint_report")
    ap.add_argument(
        "targets",
        nargs="*",
        default=["openr_tpu"],
        help="files or directories relative to the repo root",
    )
    ap.add_argument(
        "--root", default=_repo_root(), help="repository root override"
    )
    ap.add_argument(
        "--out",
        default=os.path.join("artifacts", "lint_report.json"),
        help="report path ('-' for stdout)",
    )
    args = ap.parse_args(argv)

    report = run_analysis(
        args.root, targets=args.targets, audit_suppressions=True
    )
    payload = report.to_dict()
    payload["stale_suppressions"] = sum(
        1 for f in report.findings if f.rule == STALE_RULE
    )
    payload = json.dumps(payload, indent=2, sort_keys=True)
    if args.out == "-":
        print(payload)
    else:
        out = args.out
        if not os.path.isabs(out):
            out = os.path.join(args.root, out)
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
        print(out)

    n_sup = len(report.findings) - len(report.unsuppressed)
    n_stale = sum(1 for f in report.findings if f.rule == STALE_RULE)
    print(
        f"lint-report: {report.files_scanned} files, "
        f"{len(report.unsuppressed)} finding(s), {n_sup} suppressed, "
        f"{n_stale} stale suppression(s)",
        file=sys.stderr,
    )
    for f in report.unsuppressed:
        print(str(f), file=sys.stderr)
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
