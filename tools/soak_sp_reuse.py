"""Soak the SP route-reuse solver: long randomized mutation streams,
device (reuse on) vs fresh host solver, byte-exact RouteDatabase parity
at every step.

Interleaves every churn class the dirty test models: remote/local
metric wiggles, overload flips, node-label changes, link drop/restore,
prefix forwarding-type updates, and static-MPLS mutations. Any unsound
reuse (a changed input the signature misses) shows up as a parity
break naming the seed and step.

Run:  python -m tools.soak_sp_reuse [--seeds 8] [--steps 60]
Prints one JSON line per seed; exits non-zero on the first break.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from dataclasses import replace

from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import (
    SPF_COUNTERS,
    SpfSolver,
    make_next_hop,
)
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.types import BinaryAddress
from openr_tpu.types.lsdb import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


def _build(kind: str, n: int, area: str = "0"):
    kwargs = dict(
        forwarding_algorithm=PrefixForwardingAlgorithm.SP_ECMP,
        forwarding_type=PrefixForwardingType.SR_MPLS,
        area=area,
    )
    if kind == "grid":
        topo = topologies.grid(n, **kwargs)
    elif kind == "fabric":
        topo = topologies.fat_tree_nodes(n, **kwargs)
    else:
        # random_mesh prefixes default to SP_ECMP already
        topo = topologies.random_mesh(
            n, degree=4, seed=7, max_metric=9, area=area
        )
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    ps = PrefixState()
    for pdb in topo.prefix_dbs.values():
        ps.update_prefix_database(pdb)
    return topo, ls, ps


def _build_multi(n: int):
    """Two areas with a border root present in both (the multi-area
    dirty-signature path: per-area compare, unioned dirty sets)."""
    from openr_tpu.types import Adjacency, AdjacencyDatabase

    topo_a, ls_a, ps = _build("grid", 4, area="a")
    topo_b, ls_b, _ps_b = _build("fabric", n, area="b")
    for pdb in topo_b.prefix_dbs.values():
        ps.update_prefix_database(pdb)
    rsw = sorted(
        k
        for k in ls_b.get_adjacency_databases()
        if k.startswith("rsw")
    )[0]

    def border_adj(other):
        return Adjacency(
            other_node_name=other,
            if_name=f"if_node-0_{other}",
            other_if_name=f"if_{other}_node-0",
            metric=1,
        )

    ls_b.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name="node-0",
            adjacencies=(border_adj(rsw),),
            node_label=9000,
            area="b",
        )
    )
    bdb = ls_b.get_adjacency_databases()[rsw]
    ls_b.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=rsw,
            adjacencies=tuple(bdb.adjacencies)
            + (border_adj("node-0"),),
            node_label=bdb.node_label,
            area="b",
        )
    )
    topos = {"a": topo_a, "b": topo_b}
    return topos, {"a": ls_a, "b": ls_b}, ps


def soak_one(seed: int, kind: str, n: int, steps: int) -> dict:
    rng = random.Random(seed)
    if kind == "multi":
        topos_d, areas_d, ps_d = _build_multi(n)
        topos_h, areas_h, ps_h = _build_multi(n)
        root = "node-0"
        area_d, area_h = areas_d, areas_h
        names_by_area = {
            a: sorted(t.adj_dbs) for a, t in topos_d.items()
        }
        topos = topos_d
        names = names_by_area["b"]
    else:
        topo, ls_d, ps_d = _build(kind, n)
        _t, ls_h, ps_h = _build(kind, n)
        names = sorted(topo.adj_dbs)
        root = next(
            (k for k in names if k.startswith("rsw")), names[0]
        )
        area_d = {topo.area: ls_d}
        area_h = {topo.area: ls_h}
        names_by_area = None
        topos = {topo.area: topo}
    dev = SpfSolver(root, backend="device")
    host = SpfSolver(root, backend="host")
    pulled: dict = {}

    def mutate(areas, ps, step):
        area = rng.choice(sorted(areas))
        ls = areas[area]
        pool = (
            names_by_area[area] if names_by_area is not None else names
        )
        kind_w = rng.random()
        node = rng.choice(pool)
        db = ls.get_adjacency_databases()[node]
        if kind_w < 0.45 and db.adjacencies:
            # metric wiggle
            i = rng.randrange(len(db.adjacencies))
            adjs = list(db.adjacencies)
            adjs[i] = replace(
                adjs[i], metric=1 + rng.randrange(9)
            )
            ls.update_adjacency_database(
                replace(db, adjacencies=tuple(adjs))
            )
        elif kind_w < 0.6:
            ls.update_adjacency_database(
                replace(db, is_overloaded=not db.is_overloaded)
            )
        elif kind_w < 0.7:
            ls.update_adjacency_database(
                replace(db, node_label=50000 + rng.randrange(1000))
            )
        elif kind_w < 0.85 and db.adjacencies:
            # link drop or restore (per-world stash keyed by step so
            # both worlds do the identical thing)
            key = (id(ls), node)
            if key in pulled:
                adj = pulled.pop(key)
                db = ls.get_adjacency_databases()[node]
                ls.update_adjacency_database(
                    replace(
                        db,
                        adjacencies=tuple(
                            list(db.adjacencies) + [adj]
                        ),
                    )
                )
            else:
                i = rng.randrange(len(db.adjacencies))
                adjs = list(db.adjacencies)
                pulled[key] = adjs.pop(i)
                ls.update_adjacency_database(
                    replace(db, adjacencies=tuple(adjs))
                )
        elif kind_w < 0.95:
            # prefix forwarding-type flip (version bump path)
            pdb = topos[area].prefix_dbs[node]
            new_ftype = rng.choice(
                [PrefixForwardingType.IP,
                 PrefixForwardingType.SR_MPLS]
            )
            ps.update_prefix_database(
                replace(
                    pdb,
                    prefix_entries=tuple(
                        replace(e, forwarding_type=new_ftype)
                        for e in pdb.prefix_entries
                    ),
                )
            )
        else:
            # static MPLS mutation
            label = 70000 + rng.randrange(4)
            if rng.random() < 0.5:
                nh = make_next_hop(
                    BinaryAddress.from_str(
                        f"fe80::{rng.randrange(1, 99):x}"
                    ),
                    None,
                    0,
                    None,
                )
                return ("static", label, [nh])
            return ("static-del", label, None)
        return None

    t0 = time.time()
    reuses0 = SPF_COUNTERS["decision.sp_route_reuses"]
    for step in range(steps):
        rng_state = rng.getstate()
        act_d = mutate(area_d, ps_d, step)
        rng.setstate(rng_state)
        act_h = mutate(area_h, ps_h, step)
        assert (act_d is None) == (act_h is None)
        if act_d is not None:
            op, label, nhs = act_d
            for solver in (dev, host):
                if op == "static":
                    solver.update_static_mpls_routes(
                        {label: nhs}, []
                    )
                else:
                    solver.update_static_mpls_routes({}, [label])
        d = dev.build_route_db(root, area_d, ps_d)
        hdb = host.build_route_db(root, area_h, ps_h)
        if d.to_route_db(root) != hdb.to_route_db(root):
            return {
                "seed": seed, "kind": kind, "n": n,
                "step": step, "parity": "BROKEN",
            }
    return {
        "seed": seed, "kind": kind, "n": n, "steps": steps,
        "parity": "ok",
        "sp_route_reuses": SPF_COUNTERS["decision.sp_route_reuses"]
        - reuses0,
        "wall_s": round(time.time() - t0, 1),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, default=6)
    p.add_argument("--steps", type=int, default=60)
    args = p.parse_args()
    worlds = [("grid", 6), ("fabric", 120), ("mesh", 40), ("multi", 120)]
    rc = 0
    for seed in range(args.seeds):
        kind, n = worlds[seed % len(worlds)]
        out = soak_one(seed, kind, n, args.steps)
        print(json.dumps(out), flush=True)
        if out.get("parity") != "ok":
            rc = 1
            break
    return rc


if __name__ == "__main__":
    sys.exit(main())
