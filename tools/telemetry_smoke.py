#!/usr/bin/env python
"""Telemetry smoke gate (``make telemetry-smoke``).

Runs a small churn scenario through the REAL module pipeline
(KvStore -> Decision -> Fib) with the sparse threshold forced down so
the resident-ELL solve path engages, then fails loudly if the
observability spine regressed:

- any registered histogram is EMPTY (an instrumentation point went
  dead: the metric exists but nothing feeds it),
- a REQUIRED histogram is missing entirely (the stage lost its timer),
- any trace span was left unclosed or mis-nested,
- fewer complete publication->FIB traces than churn events,
- the jax compile hooks failed to install.

Exit 0 on pass, 1 with a reason list on fail. Runs CPU-pinned — this
gates instrumentation, not kernels.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/telemetry_smoke.py) in addition
# to module mode (python -m tools.telemetry_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_HISTOGRAMS = (
    "convergence.e2e_ms",
    "decision.debounce_ms",
    "decision.rebuild_ms",
    "fib.program_ms",
    "ops.ell.reconverge_ms",
    "ops.ell.host_overhead_ms",
)

# trace-health counters that must stay at zero across the scenario
ZERO_COUNTERS = (
    "telemetry.traces_unclosed_spans",
    "telemetry.traces_bad_nesting",
)


def main() -> int:
    from openr_tpu import testing

    testing.pin_host_cpu()

    from openr_tpu.decision import spf_solver as ss

    # engage the resident-ELL path at smoke scale (same trick as
    # tests/test_churn_smoke.py) so the ops-level spans/histograms run
    ss.SPARSE_NODE_THRESHOLD = 4

    from benchmarks.bench_scale import convergence_trace_bench
    from openr_tpu.telemetry import get_registry, get_tracer, jax_hooks

    reg = get_registry()
    before = {k: reg.counter_get(k) for k in ZERO_COUNTERS}
    hooks_ok = jax_hooks.install()

    result = convergence_trace_bench(
        48,
        churn_events=5,
        trace_path="/tmp/openr_tpu_telemetry_smoke.jsonl",
        solver_backend="device",
    )

    failures = []
    if not hooks_ok:
        failures.append("jax.monitoring hooks failed to install")
    if result["traces_complete"] < 5:
        failures.append(
            f"only {result['traces_complete']}/5 complete traces"
        )
    if result["traces_incomplete"]:
        failures.append(
            f"{result['traces_incomplete']} incomplete traces"
        )
    for name in ZERO_COUNTERS:
        delta = reg.counter_get(name) - before[name]
        if delta:
            failures.append(f"{name} moved by {delta}")

    hists = reg.histograms()
    for name in REQUIRED_HISTOGRAMS:
        if name not in hists:
            failures.append(f"required histogram missing: {name}")
    for name, h in sorted(hists.items()):
        if h.count == 0:
            failures.append(f"registered histogram is empty: {name}")

    # every span in the artifact closed (belt over the counters)
    for t in get_tracer().traces():
        for s in t.spans:
            if not s.closed:
                failures.append(
                    f"trace {t.trace_id}: unclosed span {s.name}"
                )

    print(json.dumps({"bench": result, "failures": failures}, indent=1))
    if failures:
        print(f"TELEMETRY SMOKE: FAIL ({len(failures)})", file=sys.stderr)
        return 1
    print("TELEMETRY SMOKE: PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
