#!/usr/bin/env python
"""Digital-twin gate (``make twin-smoke``) and report artifact.

Exercises the whole-network twin (``openr_tpu.twin``) end to end on a
16-node ring and fails loudly if the fleet contract regressed:

- PARITY VS PER-NODE ORACLES: every vantage's twin route table (cold
  build, seeded churn, scripted link flap, drain) must be
  bit-identical to an independently-run KvStore->Decision pipeline
  replaying the same surviving event log on the host backend,
- ONE WAVE / ZERO RETRACES: the cold 16-vantage fleet solves as ONE
  batched dispatch; a second same-shape fleet joins with ZERO jit
  compiles; each post-warmup topology event costs exactly one
  dispatch and zero compiles,
- DEFECT DETECTION: an injected link flap with only its endpoints
  reconverged must surface a micro-loop, an injected fresh prefix
  with only its originator reconverged must surface transient
  blackholes, and one full converge wave must return the fleet to a
  clean analyzer report,
- VANTAGE-VIEW PACKING: 16 vantages over one LSDB must reuse one
  compiled graph (``tenancy.graph_shares`` >= 15 on the cold wave).

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_twin_smoke.json``); exit 0 on pass, 1 with a reason
list on fail. Runs CPU-pinned — this gates the twin's bookkeeping and
fleet semantics, not device throughput.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/twin_smoke.py) in addition to
# module mode (python -m tools.twin_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="/tmp/openr_tpu_twin_smoke.json")
    parser.add_argument("--nodes", type=int, default=16)
    parser.add_argument("--load-events", type=int, default=10)
    args = parser.parse_args(argv)

    from openr_tpu.models import topologies
    from openr_tpu.ops.world_batch import TENANCY_COUNTERS
    from openr_tpu.telemetry import get_registry, jax_hooks
    from openr_tpu.twin import TWIN_COUNTERS, FabricTwin, ScenarioDriver

    hooks_live = jax_hooks.install()
    reg = get_registry()
    failures: list = []
    report: dict = {"gates": {}, "nodes": args.nodes}

    twin = FabricTwin(topologies.ring(args.nodes))
    drv = ScenarioDriver(twin, seed=20)

    # -- gate 1: cold fleet = one dispatch wave, bit parity ---------------
    d0 = TENANCY_COUNTERS["dispatches"]
    shares0 = TENANCY_COUNTERS["graph_shares"]
    twin.converge()
    cold_waves = TENANCY_COUNTERS["dispatches"] - d0
    report["gates"]["cold_waves"] = cold_waves
    if cold_waves != 1:
        failures.append(
            f"cold {args.nodes}-vantage fleet took {cold_waves} "
            "dispatch waves (must be exactly 1)"
        )
    shares = TENANCY_COUNTERS["graph_shares"] - shares0
    report["gates"]["graph_shares_cold"] = shares
    if shares < args.nodes - 1:
        failures.append(
            f"vantage-view packing reused the compiled graph {shares}x "
            f"(expected >= {args.nodes - 1}: one compile, rest shared)"
        )
    diverged = drv.check_parity()
    report["gates"]["cold_parity_diverged"] = diverged
    if diverged:
        failures.append(f"cold-build parity diverged: {diverged}")

    # -- gate 2: fleet join + post-warmup events retrace-free -------------
    if hooks_live:
        c0 = reg.counter_get("jax.compile_count")
        join = FabricTwin(topologies.ring(args.nodes))
        join.converge()
        join_compiles = reg.counter_get("jax.compile_count") - c0
        join.close()
        report["gates"]["fleet_join_compiles"] = join_compiles
        if join_compiles:
            failures.append(
                f"second fleet join retraced {join_compiles}x "
                "(same-shape fleets must ride warm executables)"
            )
        c0 = reg.counter_get("jax.compile_count")
        drv.run_load(args.load_events)
        load_compiles = reg.counter_get("jax.compile_count") - c0
        report["gates"]["load_compiles"] = load_compiles
        if load_compiles:
            failures.append(
                f"post-warmup load retraced {load_compiles}x"
            )
    else:
        report["gates"]["fleet_join_compiles"] = None
        drv.run_load(args.load_events)

    # -- gate 3: scripted scenario parity ---------------------------------
    drv.flap_link("node-2", "node-3")
    drv.drain("node-7")
    diverged = drv.check_parity()
    report["gates"]["scenario_parity_diverged"] = diverged
    if diverged:
        failures.append(f"flap+drain parity diverged: {diverged}")
    drv.restore_link("node-2", "node-3")
    drv.drain("node-7", False)

    # -- gate 4: analyzer catches the seeded defects, then heals ----------
    if not twin.analyze().clean:
        failures.append("converged fleet reported findings (must be clean)")
    drv.inject_micro_loop("node-0", "node-1")
    loops = len(twin.analyze().loops())
    report["gates"]["injected_micro_loops_found"] = loops
    if not loops:
        failures.append(
            "endpoint-only reconvergence after a flap surfaced no "
            "micro-loop"
        )
    twin.converge()
    drv.restore_link("node-0", "node-1")
    if not twin.analyze().clean:
        failures.append("fleet not clean after micro-loop heal wave")
    drv.inject_blackhole("node-5")
    holes = len(twin.analyze().blackholes())
    report["gates"]["injected_blackholes_found"] = holes
    if not holes:
        failures.append(
            "originator-only reconvergence after a fresh prefix "
            "surfaced no transient blackhole"
        )
    twin.converge()
    if not twin.analyze().clean:
        failures.append("fleet not clean after blackhole heal wave")
    diverged = drv.check_parity()
    report["gates"]["final_parity_diverged"] = diverged
    if diverged:
        failures.append(f"post-defect parity diverged: {diverged}")

    twin.close()
    report["counters"] = {
        f"twin.{k}": TWIN_COUNTERS[k] for k in TWIN_COUNTERS
    }
    report["events_in_log"] = len(drv.log)
    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("TWIN SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"twin smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
