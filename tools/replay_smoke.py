#!/usr/bin/env python
"""Incident-replay gate (``make replay-smoke``) and report artifact.

Forces a real incident end to end and proves the post-mortem bundle
is a self-contained, deterministic reproduction
(``openr_tpu/telemetry/flight.py`` event journal +
``openr_tpu/twin/replay.py``):

- INCIDENT: a seeded churn storm (metric + prefix events only — no
  flaps or drains, so the fabric stays connected) over an N-node ring
  twin with the event journal armed, then a forced micro-loop
  (endpoint-only reconvergence after a link flap) that the analyzer
  must convict,
- DUMP: the flight recorder cuts a bundle whose journal slice covers
  the storm and whose LSDB anchor digest self-verifies,
- FRESH-PROCESS REPLAY: a separate OS process
  (``python -m openr_tpu.twin.replay <bundle> --json --twice``)
  ingests ONLY the bundle, reconstructs the LSDB at the anchor,
  re-feeds the captured churn one wave per recorded window, and must
  reproduce the same anomaly class with bit-identical per-vantage
  route digests twice in a row and zero per-window divergence,
- PARENT PARITY: the replay's final per-vantage route digests must
  equal the digests the LIVE twin recorded at dump time.

``--nodes`` scales the storm; the default keeps the CPU-pinned tier-1
run fast, the acceptance-scale run is ``--nodes 1008`` on real
hardware. ``--fixture-out`` additionally copies the dumped bundle to
a path — this is how the ``tests/scenarios/`` regression fixtures are
(re)generated.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_replay_smoke.json``); exit 0 on pass, 1 with a
reason list on fail.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["OPENR_FLIGHT"] = "1"

# allow direct invocation (python tools/replay_smoke.py) in addition
# to module mode (python -m tools.replay_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_replay_smoke.json"
    )
    parser.add_argument(
        "--nodes", type=int,
        default=int(os.environ.get("OPENR_REPLAY_NODES", "24")),
    )
    parser.add_argument("--events", type=int, default=30)
    parser.add_argument("--seed", type=int, default=20)
    parser.add_argument(
        "--fixture-out", default=None,
        help="also copy the dumped bundle here (fixture regeneration)",
    )
    args = parser.parse_args(argv)

    from openr_tpu.load.generator import EventMix
    from openr_tpu.models import topologies
    from openr_tpu.telemetry import (
        get_registry,
        load_bundle,
        reset_flight_recorder,
    )
    from openr_tpu.twin import FabricTwin, ScenarioDriver

    reg = get_registry()
    failures: list = []
    report: dict = {
        "gates": {}, "nodes": args.nodes, "events": args.events,
    }
    dump_dir = tempfile.mkdtemp(prefix="openr_tpu_replay_flight_")
    report["dump_dir"] = dump_dir
    fr = reset_flight_recorder(
        dump_dir=dump_dir, min_dump_interval_s=0.0, max_dumps=8
    )

    # flap/drain-free churn keeps the ring connected so the forced
    # endpoint-only reconvergence below reliably forms a cycle
    mix = EventMix(
        metric_churn=0.8, link_flap=0.0,
        prefix_update=0.2, drain_flip=0.0,
    )
    twin = FabricTwin(topologies.ring(args.nodes), record_journal=True)
    drv = ScenarioDriver(twin, seed=args.seed, mix=mix)
    twin.converge()
    drv.run_load(args.events)

    # -- gate 1: the forced incident is convicted live --------------------
    drv.inject_micro_loop("node-0", "node-1")
    live_report = twin.analyze()
    loops = len(live_report.loops())
    report["gates"]["live_micro_loops"] = loops
    if not loops:
        failures.append(
            "forced endpoint-only reconvergence surfaced no live "
            "micro-loop — nothing to replay"
        )
    live_digests = {str(k): v for k, v in twin.route_digests().items()}

    # -- gate 2: the dump is cut and self-verifies -------------------------
    bundle_path = fr.dump_postmortem(
        trigger="replay_smoke",
        reason=f"seeded churn storm + forced micro-loop "
               f"({args.nodes} nodes, {args.events} events)",
    )
    twin.close()
    report["bundle"] = bundle_path
    if not bundle_path:
        failures.append("dump_postmortem produced no bundle path")
    else:
        bundle = load_bundle(bundle_path)
        journal = bundle.get("journal") or {}
        n_recs = len(journal.get("records") or [])
        anchor = journal.get("anchor") or {}
        report["journal_records"] = n_recs
        report["anchor_digest"] = anchor.get("graph_digest")
        report["dump_bytes"] = os.path.getsize(bundle_path)
        if not n_recs:
            failures.append("bundle journal slice is empty")
        if not anchor.get("lsdb"):
            failures.append("bundle LSDB anchor is empty")
        if not reg.snapshot().get("ops.flight.dump_bytes.count"):
            failures.append("ops.flight.dump_bytes histogram never fed")
        if args.fixture_out:
            shutil.copyfile(bundle_path, args.fixture_out)
            report["fixture_out"] = args.fixture_out
    report["gates"]["bundle_cut"] = bool(bundle_path)

    # -- gates 3+4: fresh-process deterministic reproduction ---------------
    verdict = None
    if bundle_path:
        proc = subprocess.run(
            [sys.executable, "-m", "openr_tpu.twin.replay",
             bundle_path, "--json", "--twice"],
            capture_output=True, text=True, timeout=1200,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        report["replay_rc"] = proc.returncode
        try:
            verdict = json.loads(proc.stdout)
        except ValueError:
            failures.append(
                f"fresh-process replay emitted no JSON verdict "
                f"(rc {proc.returncode}): {proc.stderr[-500:]}"
            )
    if verdict is not None:
        report["verdict"] = {
            k: verdict.get(k)
            for k in ("reproduced", "recorded_classes",
                      "replayed_classes", "windows", "pubs_applied",
                      "trailing_pubs", "anchor_moved", "deterministic",
                      "digests_match_recorded", "errors", "ok")
        }
        if not verdict.get("reproduced"):
            failures.append(
                "fresh-process replay did not reproduce the recorded "
                f"anomaly class (recorded "
                f"{verdict.get('recorded_classes')}, replayed "
                f"{verdict.get('replayed_classes')})"
            )
        if not verdict.get("deterministic"):
            failures.append(
                "two replays of the same bundle were not bit-identical"
            )
        if verdict.get("divergence"):
            failures.append(
                f"per-window divergence vs recorded counters: "
                f"{verdict['divergence'][:4]}"
            )
        if verdict.get("errors"):
            failures.append(f"replay errors: {verdict['errors']}")
        if verdict.get("route_digests") != live_digests:
            failures.append(
                "replayed per-vantage route digests differ from the "
                "live twin's at dump time"
            )
        report["gates"]["reproduced"] = bool(verdict.get("reproduced"))
        report["gates"]["deterministic"] = bool(
            verdict.get("deterministic")
        )
        report["gates"]["parent_parity"] = (
            verdict.get("route_digests") == live_digests
        )
    else:
        report["gates"]["reproduced"] = False
        report["gates"]["deterministic"] = False
        report["gates"]["parent_parity"] = False

    report["counters"] = {
        k: reg.counter_get(k)
        for k in (
            "flight.journal_evictions", "flight.dump_truncations",
            "flight.dump_errors", "twin.replays",
            "twin.replays_reproduced",
        )
    }
    report["failures"] = failures
    report["passed"] = not failures
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print("REPLAY SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"replay smoke passed; report at {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
