#!/usr/bin/env python
"""Fleet-plane gate (``make fleet-smoke``) and report artifact.

Brings up a two-service fleet (each with a hot standby), storms it
from jax-free multi-process clients through the controller's
placement, then runs the two transitions the fleet plane exists for —
and fails loudly if either contract regressed:

- STORM PARITY: every view digest every client reads through the
  fleet placement must equal the jax-free oracle replay
  (``load.multi_client.oracle_digests``) — the ``--services N`` mode
  of the load driver, admission by SLO class included.
- WARM MIGRATION: a tenant live-migrated between services mid-churn
  must keep serving bit-identical SP views and FIB-level
  ``RouteDatabase`` products vs the never-migrated oracle, with ZERO
  cold solves (``tenancy.cold_solves`` delta 0 AND
  ``tenancy.tenant_import_colds`` delta 0) and ZERO jit compiles
  (``jax.compile_count`` delta 0) on the destination — the snapshot +
  journal rehydration must land warm or the migration story is a lie.
- PROMOTION NO-FLAP: killing the owning primary mid-schedule and
  promoting its hot standby must take exactly one promotion
  (``fleet.promotions`` delta 1) with ZERO route deletes
  (``fleet.promotion_deletes`` delta 0 — graceful-restart semantics:
  one reconcile, no flap), and every post-promotion digest must stay
  bit-identical to the oracle continuation.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_fleet_smoke.json``); exit 0 on pass, 1 with a reason
list on fail. Runs CPU-pinned — this gates fleet-plane transitions,
not device throughput.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/fleet_smoke.py) in addition
# to module mode (python -m tools.fleet_smoke)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_fleet_smoke.json"
    )
    parser.add_argument("--services", type=int, default=2)
    parser.add_argument("--clients", type=int, default=2)
    parser.add_argument("--tenants-per-client", type=int, default=3)
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument("--drill-rounds", type=int, default=8)
    args = parser.parse_args(argv)

    from openr_tpu import testing

    testing.pin_host_cpu()

    from openr_tpu.fleet import FleetController
    from openr_tpu.load import multi_client
    from openr_tpu.ops.world_batch import TENANCY_COUNTERS
    from openr_tpu.serve.client import SolverClient
    from openr_tpu.telemetry import get_registry, jax_hooks

    hooks_live = jax_hooks.install()
    reg = get_registry()
    failures: list = []
    report: dict = {
        "gates": {},
        "services": args.services,
        "rounds": args.rounds,
    }

    fc = FleetController(services=args.services, with_standby=True)
    fc.start()
    t0 = time.perf_counter()
    try:
        ctrl_port = fc.serve_ctrl("127.0.0.1")

        # -- leg 1: multi-process storm through the placement -------
        client_specs = multi_client.fleet_specs(
            args.clients, args.tenants_per_client, size=4
        )
        endpoints = {}
        for specs in client_specs.values():
            for s in specs:
                host, port = fc.admit(s.tenant_id, s.slo)
                endpoints[s.tenant_id] = [host, port]
        owners = {
            tid: fc.owner_of(tid) for tid in endpoints
        }
        report["placement_spread"] = len(set(owners.values()))
        report["gates"]["placement_spread"] = (
            len(set(owners.values())) == min(
                args.services, len(endpoints)
            )
        )
        if not report["gates"]["placement_spread"]:
            failures.append(
                "placement left a service empty under a mixed-class "
                f"population: {owners}"
            )
        default_ep = next(iter(endpoints.values()))
        with tempfile.TemporaryDirectory() as out_dir:
            procs = multi_client.spawn_clients(
                default_ep[0], default_ep[1], client_specs,
                args.rounds, out_dir,
                endpoints=endpoints,
                controller=["127.0.0.1", ctrl_port],
            )
            results = multi_client.harvest(procs)
        errors = [
            e for r in results for e in r.get("errors", [])
        ]
        all_specs = [
            s for specs in client_specs.values() for s in specs
        ]
        oracle = multi_client.oracle_digests(all_specs, args.rounds)
        diverged = [
            tid
            for r in results
            for tid, digs in r.get("digests", {}).items()
            if digs != oracle.get(tid)
        ]
        report["gates"]["storm_clients_clean"] = not errors
        report["gates"]["storm_wire_parity"] = not diverged
        if errors:
            failures.append(f"storm client errors: {errors[:4]}")
        if diverged:
            failures.append(f"storm parity diverged: {diverged}")

        # -- leg 2: warm migration drill ----------------------------
        # A standby-free fleet so the cold/compile accounting is
        # exact: hot standbys legitimately cold-solve their FIRST
        # absorb of a replicated tenant, and TENANCY_COUNTERS is
        # process-global — the migration gate must see only the
        # migration itself.
        fm = FleetController(
            services=2, with_standby=False
        )
        fm.start()
        try:
            drill = multi_client.TenantSpec(
                tenant_id="drill", kind="grid", size=4, seed=17,
                slo="premium",
            )
            dbs = drill.build_dbs()
            host, port = fm.admit(drill.tenant_id, drill.slo)
            cli = SolverClient(host, port)
            cli.register(drill.tenant_id, slo=drill.slo)
            cli.update_world(
                drill.tenant_id, [dbs[k] for k in sorted(dbs)],
                root=drill.root_of(dbs),
                prefix_dbs=[
                    db for _k, db in sorted(
                        drill.build_prefix_dbs().items()
                    )
                ],
            )
            rounds = args.drill_rounds
            migrate_at = rounds // 2
            sp_digests, fib_digests = [], []
            src = fm.owner_of(drill.tenant_id)
            snap = {}
            for i in range(rounds):
                if i == migrate_at:
                    # everything below this point must be warm: the
                    # destination already compiled these shapes, so
                    # the migration may not compile, may not
                    # cold-solve
                    snap["compiles"] = (
                        reg.counter_get("jax.compile_count")
                        if hooks_live else 0
                    )
                    snap["colds"] = int(
                        TENANCY_COUNTERS["cold_solves"]
                    )
                    snap["import_colds"] = int(
                        TENANCY_COUNTERS["tenant_import_colds"]
                    )
                    snap["migrations"] = fm.counters().get(
                        "fleet.migrations", 0
                    )
                    fm.migrate(drill.tenant_id)
                if i > 0:
                    node = multi_client.apply_mutation(
                        dbs, drill, i
                    )
                    cli.update_world(drill.tenant_id, [dbs[node]])
                sp_digests.append(
                    cli.solve(drill.tenant_id).digest()
                )
                fib_digests.append(cli.fib(drill.tenant_id).digest)
            moved = fm.owner_of(drill.tenant_id) != src
            mig_counters = fm.counters()

            oracle_sp = multi_client.oracle_digests(
                [drill], rounds
            )[drill.tenant_id]
            oracle_fib = multi_client.oracle_fib_digests(
                [drill], rounds, every=1
            )[drill.tenant_id]

            compile_delta = (
                reg.counter_get("jax.compile_count")
                - snap["compiles"]
            ) if hooks_live else 0
            cold_delta = int(
                TENANCY_COUNTERS["cold_solves"]
            ) - snap["colds"]
            import_cold_delta = int(
                TENANCY_COUNTERS["tenant_import_colds"]
            ) - snap["import_colds"]

            report["migration"] = {
                "moved": moved,
                "compile_delta": compile_delta,
                "cold_delta": cold_delta,
                "import_cold_delta": import_cold_delta,
                "migration_ms_p50": reg.percentile(
                    "fleet.migration_ms", 50.0
                ),
            }
            report["gates"]["migration_moved"] = moved and (
                mig_counters.get("fleet.migrations", 0)
                - snap["migrations"] == 1
            )
            report["gates"]["migration_warm"] = (
                cold_delta == 0 and import_cold_delta == 0
            )
            report["gates"]["migration_zero_compiles"] = (
                compile_delta == 0
            )
            report["gates"]["migration_sp_parity"] = (
                sp_digests == oracle_sp
            )
            report["gates"]["migration_fib_parity"] = (
                fib_digests == oracle_fib
            )
            report["gates"]["client_followed_redirect"] = (
                cli.redirects >= 1
            )
            cli.close()
        finally:
            fm.stop()

        # -- leg 3: promotion drill (hot-standby fleet) -------------
        pro = multi_client.TenantSpec(
            tenant_id="pro", kind="mesh", size=5, seed=23,
            slo="standard",
        )
        pdbs = pro.build_dbs()
        host, port = fc.admit(pro.tenant_id, pro.slo)
        cli = SolverClient(
            host, port, controller=("127.0.0.1", ctrl_port)
        )
        cli.register(pro.tenant_id, slo=pro.slo)
        cli.update_world(
            pro.tenant_id, [pdbs[k] for k in sorted(pdbs)],
            root=pro.root_of(pdbs),
            prefix_dbs=[
                db for _k, db in sorted(
                    pro.build_prefix_dbs().items()
                )
            ],
        )
        rounds = args.drill_rounds
        kill_at = rounds // 2
        sp_digests, fib_digests = [], []
        snap = {
            "promotions": fc.counters().get("fleet.promotions", 0),
            "promotion_deletes": fc.counters().get(
                "fleet.promotion_deletes", 0
            ),
            "failovers": fc.counters().get(
                "fleet.failovers_detected", 0
            ),
        }
        for i in range(rounds):
            if i == kill_at:
                # the owner dies mid-schedule; the hot standby takes
                # over under graceful-restart semantics
                owner = fc.owner_of(pro.tenant_id)
                ms = fc.services()[owner]
                ms.streamer.flush(10.0)
                ms.kill_primary()
                report["promoted"] = fc.maybe_failover()
            if i > 0:
                node = multi_client.apply_mutation(pdbs, pro, i)
                cli.update_world(pro.tenant_id, [pdbs[node]])
            sp_digests.append(cli.solve(pro.tenant_id).digest())
            fib_digests.append(cli.fib(pro.tenant_id).digest)
        counters = fc.counters()
        oracle_sp = multi_client.oracle_digests(
            [pro], rounds
        )[pro.tenant_id]
        oracle_fib = multi_client.oracle_fib_digests(
            [pro], rounds, every=1
        )[pro.tenant_id]
        report["promotion"] = {
            "promotions_delta": counters.get("fleet.promotions", 0)
            - snap["promotions"],
            "deletes_delta": counters.get(
                "fleet.promotion_deletes", 0
            ) - snap["promotion_deletes"],
            "failovers_delta": counters.get(
                "fleet.failovers_detected", 0
            ) - snap["failovers"],
            "replica_lag": reg.counter_get("fleet.replica_lag"),
        }
        report["gates"]["promotion_took_over"] = (
            report["promotion"]["promotions_delta"] == 1
            and report["promotion"]["failovers_delta"] == 1
        )
        report["gates"]["promotion_zero_deletes"] = (
            report["promotion"]["deletes_delta"] == 0
        )
        report["gates"]["promotion_sp_parity"] = (
            sp_digests == oracle_sp
        )
        report["gates"]["promotion_fib_parity"] = (
            fib_digests == oracle_fib
        )
        report["gates"]["client_rode_failover"] = (
            cli.reconnects >= 1
        )
        for gate, ok in report["gates"].items():
            if not ok and not any(gate in f for f in failures):
                failures.append(f"gate failed: {gate}")
        cli.close()
    finally:
        fc.stop()
    report["elapsed_s"] = round(time.perf_counter() - t0, 3)
    report["counters"] = {
        k: v for k, v in sorted(get_registry().snapshot().items())
        if k.startswith("fleet.")
    }
    report["failures"] = failures
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    print(json.dumps(report["gates"], indent=2, sort_keys=True))
    if failures:
        print(f"FLEET GATE: FAIL ({len(failures)})", file=sys.stderr)
        return 1
    print(f"FLEET GATE: PASS (report: {args.out})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
