#!/usr/bin/env python
"""Chaos gate (``make chaos-smoke``) and report artifact.

Drives a seeded, replayable fault storm across the pipeline's
injection seams — device dispatch, delta consume, cold rebuild,
Decision SPF solve, the Fib thrift transport, netlink programming,
and the ``load.generator`` publisher seam (a chaos storm *under*
sustained load) — through the REAL supervised paths, then fails
loudly if the graceful-degradation contract regressed:

- any supervisor did not self-heal back to HEALTHY after the faults
  stopped,
- the post-storm route product is not bit-identical to a fault-free
  cold twin (or the Decision RouteDatabase to a native-backend
  oracle),
- a ladder walk was unbounded (more walks than churn events),
- the coverage floor was missed (too few faults fired, fewer than
  eleven distinct seams crossed — including ``device.lost``,
  ``state.checkpoint_write``, ``device.corrupt_resident``, and the
  fleet pair ``fleet.journal_stream``/``fleet.promote`` — or the
  lossy-publisher seam never fired),
- the lossy-load route product diverged from a survivor-replay
  oracle (dropped events must be pure no-ops),
- the kill-restart leg (checkpoint mid-storm with one injected
  checkpoint-write failure, drop process state, warm-boot from the
  backing store, replay survivors) did not land bit-identical,
- the corruption-storm leg (probabilistic ``device.corrupt_resident``
  flips across a churn run, audited each event) missed a conviction,
  failed a heal, or finished with a product that diverged from the
  fault-free oracle,
- the fleet leg (two hot-standby services under churn with the
  replica stream flapping, a live migration and a faulted-ladder
  standby promotion mid-storm) flapped a route, diverged from the
  never-migrated oracle, or left the surviving replica stream
  undrained.

Writes a JSON artifact (``--out``, default
``/tmp/openr_tpu_chaos_report.json``) with the per-site fault counts,
ladder counters, and final health gauges so a CI run leaves evidence.
``--smoke`` shrinks the event budget for the tier-1 gate; the full
soak lives in tests/test_chaos_soak.py. Exit 0 on pass, 1 with a
reason list on fail. Runs CPU-pinned — this gates robustness
machinery, not kernels.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from dataclasses import replace

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# allow direct invocation (python tools/chaos_report.py) in addition
# to module mode (python -m tools.chaos_report)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LADDER_COUNTERS = (
    "ladder_walks",
    "probes",
    "fallbacks",
    "degradations",
    "self_heals",
    "ladder_exhausted",
    "health_transitions",
)


def _injected(reg):
    prefix = "faults.injected."
    return {
        k[len(prefix):]: v
        for k, v in reg.snapshot().items()
        if k.startswith(prefix)
    }


def _engine_leg(seed, events, failures):
    import numpy as np

    from openr_tpu.faults import (
        DegradationSupervisor,
        FaultSchedule,
        HealthState,
        get_injector,
    )
    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies
    from openr_tpu.ops import route_engine, route_sweep

    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = LinkState(area=topo.area)
    for _, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    names = sorted(ls.get_adjacency_databases().keys())
    engine = route_engine.RouteSweepEngine(ls, [names[0]])
    engine.supervisor = DegradationSupervisor(
        "route_engine", backoff_min_s=0.001, backoff_max_s=0.002
    )
    rsws = [n for n in engine.graph.node_names if n.startswith("rsw")][:4]

    inj = get_injector()
    inj.arm(
        "route_engine.dispatch",
        FaultSchedule.fail_with_probability(0.5, seed=seed + 1),
    )
    inj.arm(
        "route_engine.consume",
        FaultSchedule.fail_with_probability(0.4, seed=seed + 2),
    )
    inj.arm(
        "route_engine.cold_build",
        FaultSchedule.fail_with_probability(0.5, seed=seed + 3),
    )
    inj.arm(
        "route_engine.frontier_resolve",
        FaultSchedule.fail_with_probability(0.5, seed=seed + 7),
    )
    # deterministic double device loss: the first fires mid-storm and
    # walks the recover rung; the second fires inside the recover
    # rung's own re-run, proving the ladder bounds repeated loss
    inj.arm("device.lost", FaultSchedule.fail_n(2))

    def mutate(node, metric):
        db = ls.get_adjacency_databases()[node]
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=metric)
        ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
        return {node, adjs[0].other_node_name}

    flap_rsw = [
        n for n in engine.graph.node_names if n.startswith("rsw")
    ][-1]
    pulled: list = []

    def flap():
        # alternating link remove/restore: structural churn that
        # overflows the (shrunken) bucket ladder and crosses the
        # frontier_resolve seam on every event
        if pulled:
            adjs = pulled.pop()
            for x, gone in adjs:
                db = ls.get_adjacency_databases()[x]
                ls.update_adjacency_database(replace(
                    db, adjacencies=tuple(list(db.adjacencies) + gone)
                ))
            return {flap_rsw, adjs[0][1][0].other_node_name}
        peer = ls.get_adjacency_databases()[
            flap_rsw
        ].adjacencies[0].other_node_name
        adjs = []
        for x, y in ((flap_rsw, peer), (peer, flap_rsw)):
            db = ls.get_adjacency_databases()[x]
            keep = [a for a in db.adjacencies if a.other_node_name != y]
            gone = [a for a in db.adjacencies if a.other_node_name == y]
            adjs.append((x, gone))
            ls.update_adjacency_database(
                replace(db, adjacencies=tuple(keep))
            )
        pulled.append(adjs)
        return {flap_rsw, peer}

    # shrink the bucket ladder so every event overflows into the
    # frontier-vs-full policy (where the frontier_resolve seam lives)
    buckets0 = route_engine._ROW_BUCKETS
    route_engine._ROW_BUCKETS = (8,)
    engine._k_hint = 8
    rng = random.Random(seed + 4)
    churns = 0
    try:
        for step in range(events):
            affected = (
                flap() if step % 2 else
                mutate(rng.choice(rsws), rng.randrange(1, 60))
            )
            engine.churn(ls, affected)
            churns += 1
            time.sleep(0.002)
    finally:
        route_engine._ROW_BUCKETS = buckets0
    for site in (
        "route_engine.dispatch",
        "route_engine.consume",
        "route_engine.cold_build",
        "route_engine.frontier_resolve",
        "device.lost",
    ):
        inj.disarm(site)
    for _ in range(12):
        if engine.supervisor.state is HealthState.HEALTHY:
            break
        time.sleep(0.01)
        engine.churn(ls, mutate(rng.choice(rsws), rng.randrange(1, 60)))
        churns += 1

    if engine.supervisor.state is not HealthState.HEALTHY:
        failures.append(
            f"route_engine did not self-heal: {engine.supervisor.state.name}"
        )
    if engine.supervisor.walks != churns:
        failures.append(
            f"route_engine walks {engine.supervisor.walks} != churn "
            f"events {churns} (unbounded recovery loop?)"
        )

    # bit-identity vs a fault-free cold twin of the same engine class
    twin = route_engine.RouteSweepEngine(ls, [names[0]])
    a, b = engine.result, twin.result
    for field in ("digests", "nh_totals", "sample_metrics", "sample_masks"):
        if not np.array_equal(getattr(a, field), getattr(b, field)):
            failures.append(f"route product diverged from cold twin: {field}")
    host = route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [names[0]], block=64)
    )
    if route_sweep.digests_by_name(engine.result) != host:
        failures.append("route digests diverged from host sweep oracle")
    return churns


def _corruption_storm_leg(seed, events, failures):
    from openr_tpu.faults import (
        DegradationSupervisor,
        FaultSchedule,
        get_injector,
    )
    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.integrity.auditor import IntegrityAuditor
    from openr_tpu.models import topologies
    from openr_tpu.ops import route_engine, route_sweep
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = LinkState(area=topo.area)
    for _, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    names = sorted(ls.get_adjacency_databases().keys())
    engine = route_engine.RouteSweepEngine(ls, [names[0]])
    engine.supervisor = DegradationSupervisor(
        "route_engine", backoff_min_s=0.001, backoff_max_s=0.002
    )
    rsws = [n for n in engine.graph.node_names if n.startswith("rsw")][:4]

    def mutate(node, metric):
        db = ls.get_adjacency_databases()[node]
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=metric)
        ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
        return {node, adjs[0].other_node_name}

    # a PRIVATE auditor (not the process global) so the storm audits
    # exactly this engine, on the real post-converge cadence
    # (rate limit off: the storm converges far faster than wall time)
    aud = IntegrityAuditor(oracle_every=4, seed=seed, min_interval_s=0.0)
    aud.register(engine)
    inj = get_injector()
    inj.arm(
        "device.corrupt_resident",
        FaultSchedule.fail_with_probability(0.5, seed=seed + 9),
    )
    v0 = sum(
        c for k, c in reg.snapshot().items()
        if k.startswith("integrity.violations.")
    )
    hf0 = reg.counter_get("integrity.heal_failures")
    rng = random.Random(seed + 10)
    churns = 0
    try:
        for _ in range(events):
            engine.churn(ls, mutate(rng.choice(rsws), rng.randrange(1, 60)))
            churns += 1
            # the Decision post-converge hook's cadence: tiers 1+2
            # every event, the sampled oracle every 4th
            aud.on_converge()
    finally:
        inj.disarm("device.corrupt_resident")
    final = aud.audit_now()[-1]

    convictions = sum(
        c for k, c in reg.snapshot().items()
        if k.startswith("integrity.violations.")
    ) - v0
    if convictions < 1:
        failures.append(
            "corruption storm produced zero convictions (seam dead "
            "or every flip washed)"
        )
    if reg.counter_get("integrity.heal_failures") - hf0:
        failures.append("corruption storm left failed heals behind")
    if final["verdict"] != "clean":
        failures.append(
            f"post-storm audit verdict {final['verdict']!r} (want clean)"
        )
    host = route_sweep.digests_by_name(
        route_sweep.all_sources_route_sweep(ls, [names[0]], block=64)
    )
    if route_sweep.digests_by_name(engine.result) != host:
        failures.append(
            "post-corruption-storm digests diverged from host oracle"
        )
    aud.unregister(engine)
    return churns


def _decision_leg(seed, events, failures):
    from openr_tpu.decision.decision import Decision
    from openr_tpu.faults import (
        DegradationSupervisor,
        FaultSchedule,
        HealthState,
        get_injector,
    )
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.models import topologies
    from openr_tpu.types import Publication, Value
    from openr_tpu.utils import keys as keyutil
    from openr_tpu.utils import wire

    topo = topologies.build_topology(
        "grid", [("a", "b", 1), ("b", "c", 2), ("a", "c", 5), ("c", "d", 1)]
    )
    versions = {}

    def make_decision(backend="device"):
        return Decision(
            "a",
            kvstore_updates_queue=ReplicateQueue(name="kv"),
            route_updates_queue=ReplicateQueue(name="routes"),
            solver_backend=backend,
        )

    def publish_all(d, t, vers):
        kv = {}
        for db in t.adj_dbs.values():
            k = keyutil.adj_key(db.this_node_name)
            vers[k] = vers.get(k, 0) + 1
            kv[k] = Value(
                version=vers[k],
                originator_id=db.this_node_name,
                value=wire.dumps(db),
            )
        for pdb in t.prefix_dbs.values():
            k = keyutil.prefix_db_key(pdb.this_node_name)
            vers[k] = vers.get(k, 0) + 1
            kv[k] = Value(
                version=vers[k],
                originator_id=pdb.this_node_name,
                value=wire.dumps(pdb),
            )
        d.process_publication(Publication(key_vals=kv, area=t.area))

    def publish_adj(d, db, vers):
        k = keyutil.adj_key(db.this_node_name)
        vers[k] = vers.get(k, 0) + 1
        d.process_publication(
            Publication(
                key_vals={
                    k: Value(
                        version=vers[k],
                        originator_id=db.this_node_name,
                        value=wire.dumps(db),
                    )
                },
                area=db.area,
            )
        )

    def bump(db, metric):
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=metric)
        return replace(db, adjacencies=tuple(adjs))

    d = make_decision()
    publish_all(d, topo, versions)
    d.rebuild_routes("CHAOS")
    d.supervisor = DegradationSupervisor(
        "decision", backoff_min_s=0.001, backoff_max_s=0.002
    )
    get_injector().arm(
        "decision.spf_solve",
        FaultSchedule.fail_with_probability(0.6, seed=seed + 5),
    )
    rng = random.Random(seed + 6)
    mutated = dict(topo.adj_dbs)
    rebuilds = 0
    for _ in range(events):
        node = rng.choice(("b", "c"))
        mutated[node] = bump(mutated[node], rng.randrange(1, 40))
        publish_adj(d, mutated[node], versions)
        d.rebuild_routes("CHAOS")
        rebuilds += 1
        time.sleep(0.002)
    get_injector().disarm("decision.spf_solve")
    for _ in range(12):
        if d.supervisor.state is HealthState.HEALTHY:
            break
        time.sleep(0.01)
        node = rng.choice(("b", "c"))
        mutated[node] = bump(mutated[node], rng.randrange(1, 40))
        publish_adj(d, mutated[node], versions)
        d.rebuild_routes("CHAOS")
        rebuilds += 1

    if d.supervisor.state is not HealthState.HEALTHY:
        failures.append(
            f"decision did not self-heal: {d.supervisor.state.name}"
        )
    if d.spf_solver.backend != "device":
        failures.append(
            f"decision stuck on fallback backend {d.spf_solver.backend}"
        )

    oracle = make_decision(backend="native")
    publish_all(oracle, replace(topo, adj_dbs=mutated), {})
    oracle.rebuild_routes("ORACLE")
    if dict(d.route_db.unicast_routes) != dict(
        oracle.route_db.unicast_routes
    ):
        failures.append("decision RouteDatabase diverged from native oracle")
    return rebuilds


def _platform_leg(seed, events, failures):
    from openr_tpu.faults import FaultInjected, FaultSchedule, get_injector
    from openr_tpu.platform.netlink import MockNetlinkProtocolSocket
    from openr_tpu.platform.netlink_fib_handler import NetlinkFibHandler
    from openr_tpu.platform.thrift_fib import FibThriftServer, ThriftFibAgent
    from openr_tpu.types import BinaryAddress, IpPrefix, NextHop, UnicastRoute

    def route(prefix):
        return UnicastRoute(
            dest=IpPrefix.from_str(prefix),
            next_hops=(
                NextHop(
                    address=BinaryAddress.from_str("fe80::9", if_name="eth9"),
                    metric=2,
                    area="0",
                    neighbor_node_name="peer-1",
                ),
            ),
        )

    handler = NetlinkFibHandler(MockNetlinkProtocolSocket())
    server = FibThriftServer(handler, host="127.0.0.1")
    server.start()
    client = ThriftFibAgent(
        "127.0.0.1",
        server.port,
        retry_min_s=0.002,
        retry_max_s=0.01,
        max_attempts=4,
    )
    calls = 0
    try:
        get_injector().arm(
            "fib.thrift_transport",
            FaultSchedule.fail_with_probability(0.5, seed=seed + 7),
        )
        get_injector().arm(
            "platform.netlink_program",
            FaultSchedule.fail_with_probability(0.3, seed=seed + 8),
        )
        rng = random.Random(seed + 9)
        for i in range(events):
            calls += 1
            try:
                if rng.random() < 0.7:
                    client.add_unicast_routes(
                        786, [route(f"fd00:{i % 16:x}::/64")]
                    )
                else:
                    client.delete_unicast_routes(
                        786, [route(f"fd00:{i % 16:x}::/64").dest]
                    )
            except (FaultInjected, RuntimeError):
                # bounded retry exhausted: surfaced, not looping. A
                # client-side transport fault raises FaultInjected; a
                # netlink fault on the server side comes back as a
                # peer-exception RuntimeError through the thrift wire.
                pass
        get_injector().disarm("fib.thrift_transport")
        get_injector().disarm("platform.netlink_program")
        desired = [route("fd00:aa::/64"), route("fd00:bb::/64")]
        client.sync_fib(786, desired)
        got = [r.dest for r in client.get_route_table_by_client(786)]
        if got != sorted(r.dest for r in desired):
            failures.append("fib table did not reconcile after the storm")
    finally:
        client.close()
        server.stop()
    return calls


def _load_leg(seed, events, failures):
    """Chaos under sustained load: arm the ninth seam
    (``load.generator``) so the seeded publisher goes lossy mid-storm,
    then check the dropped events were pure no-ops — the coalesced
    replay of the *surviving* stream must land bit-identical to the
    survivor-by-survivor oracle replay."""
    from openr_tpu.decision.decision import Decision
    from openr_tpu.faults import FaultSchedule, get_injector
    from openr_tpu.load import LoadGenerator, coalesce_publications
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.models import topologies
    from openr_tpu.types import Publication, Value
    from openr_tpu.utils import wire

    topo = topologies.fat_tree_nodes(24)
    node = next(n for n in sorted(topo.adj_dbs) if n.startswith("rsw"))
    gen = LoadGenerator(topo, seed=seed + 10)
    initial = gen.initial_key_vals()
    get_injector().arm(
        "load.generator",
        FaultSchedule.fail_with_probability(0.3, seed=seed + 11),
    )
    evs = gen.events(events)
    get_injector().disarm("load.generator")
    if gen.dropped == 0:
        failures.append("load.generator seam never fired")
    pubs = [
        Publication(
            key_vals={
                e.key: Value(
                    version=e.version,
                    originator_id=e.node,
                    value=e.payload,
                )
            },
            area=topo.area,
        )
        for e in evs
        if not e.dropped
    ]

    def make():
        d = Decision(
            node,
            kvstore_updates_queue=ReplicateQueue(name="kv"),
            route_updates_queue=ReplicateQueue(name="routes"),
            solver_backend="host",
        )
        d.process_publication(
            Publication(key_vals=dict(initial), area=topo.area)
        )
        d.rebuild_routes("CHAOS")
        return d

    live = make()
    for pub in coalesce_publications(pubs).publications:
        live.process_publication(pub)
    live.rebuild_routes("CHAOS")
    oracle = make()
    for pub in pubs:
        oracle.process_publication(pub)
    oracle.rebuild_routes("ORACLE")
    if wire.dumps(live.route_db.to_route_db(node)) != wire.dumps(
        oracle.route_db.to_route_db(node)
    ):
        failures.append(
            "lossy-load route db diverged from survivor-replay oracle"
        )
    return len(evs)


def _kill_restart_leg(seed, events, failures):
    """Kill-restart mid-storm: a Decision journaling through the state
    plane takes a churn storm with the ``state.checkpoint_write`` seam
    armed (one checkpoint cut FAILS — the journal must carry it), then
    the process "dies" (device caches and in-memory LSDB dropped), a
    fresh plane replays journal-over-checkpoint from the backing store
    alone, and the warm-booted RouteDatabase must be bit-identical to
    the crashed instance's last product AND to a survivor-replay
    oracle."""
    import shutil
    import tempfile

    from openr_tpu.config_store.persistent_store import PersistentStore
    from openr_tpu.decision import spf_solver as ss
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.spf_solver import reset_device_caches
    from openr_tpu.faults import FaultSchedule, get_injector
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.models import topologies
    from openr_tpu.state import StatePlane
    from openr_tpu.telemetry import get_registry
    from openr_tpu.types import Publication, Value
    from openr_tpu.utils import keys as keyutil
    from openr_tpu.utils import wire

    reg = get_registry()
    ss.SPARSE_NODE_THRESHOLD = 4  # resident-ELL path for small areas
    topo = topologies.fat_tree_nodes(24)
    node = next(n for n in sorted(topo.adj_dbs) if n.startswith("rsw"))
    workdir = tempfile.mkdtemp(prefix="openr_tpu_chaos_state_")
    path = os.path.join(workdir, "state.bin")
    versions: dict = {}
    published: list = []

    def make_decision(name, plane=None):
        return Decision(
            node,
            kvstore_updates_queue=ReplicateQueue(name=f"ckv-{name}"),
            route_updates_queue=ReplicateQueue(name=f"crt-{name}"),
            state_plane=plane,
        )

    def publish(d, plane, kv):
        published.append(kv)
        if plane is not None:
            plane.on_kvstore_merge(topo.area, kv)
        d.process_publication(
            Publication(key_vals=dict(kv), area=topo.area)
        )

    def adj_kv(db):
        k = keyutil.adj_key(db.this_node_name)
        versions[k] = versions.get(k, 0) + 1
        return {
            k: Value(
                version=versions[k],
                originator_id=db.this_node_name,
                value=wire.dumps(db),
            )
        }

    try:
        store = PersistentStore(path)
        plane = StatePlane(store, checkpoint_every=6)
        d = make_decision("live", plane)
        initial = {}
        for db in topo.adj_dbs.values():
            initial.update(adj_kv(db))
        for pdb in topo.prefix_dbs.values():
            k = keyutil.prefix_db_key(pdb.this_node_name)
            versions[k] = versions.get(k, 0) + 1
            initial[k] = Value(
                version=versions[k],
                originator_id=pdb.this_node_name,
                value=wire.dumps(pdb),
            )
        publish(d, plane, initial)
        d.rebuild_routes("CHAOS")

        # one checkpoint cut mid-storm MUST fail and be survivable
        get_injector().arm(
            "state.checkpoint_write", FaultSchedule.fail_once()
        )
        ckpt_fail0 = reg.counter_get("state.checkpoint_failures")
        rng = random.Random(seed + 13)
        mutated = dict(topo.adj_dbs)
        names = sorted(mutated)
        for _ in range(events):
            name = rng.choice(names)
            db = mutated[name]
            adjs = list(db.adjacencies)
            adjs[0] = replace(adjs[0], metric=rng.randrange(1, 50))
            mutated[name] = replace(db, adjacencies=tuple(adjs))
            publish(d, plane, adj_kv(mutated[name]))
            d.rebuild_routes("CHAOS")
            d.checkpoint_state()
        get_injector().disarm("state.checkpoint_write")
        if reg.counter_get("state.checkpoint_failures") - ckpt_fail0 < 1:
            failures.append(
                "state.checkpoint_write seam never fired mid-storm"
            )
        d.checkpoint_state()
        routes_live = wire.dumps(d.route_db.to_route_db(node))
        store.stop()

        # the kill: everything in-process is gone
        reset_device_caches()

        store2 = PersistentStore(path)
        plane2 = StatePlane(store2)
        rec = plane2.recover()
        d2 = make_decision("warm", plane2)
        d2.warm_boot(rec)
        routes_warm = wire.dumps(d2.route_db.to_route_db(node))
        if routes_warm != routes_live:
            failures.append(
                "kill-restart warm boot diverged from the crashed "
                "instance's last RouteDatabase"
            )
        oracle = make_decision("oracle")
        for kv in published:
            oracle.process_publication(
                Publication(key_vals=dict(kv), area=topo.area)
            )
        oracle.rebuild_routes("ORACLE")
        if routes_warm != wire.dumps(oracle.route_db.to_route_db(node)):
            failures.append(
                "kill-restart warm boot diverged from survivor-replay "
                "oracle"
            )
        store2.stop()
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return events


def _fleet_leg(seed, events, failures):
    """Fleet-plane chaos: a two-service hot-standby fleet under a
    seeded churn storm with the ``fleet.journal_stream`` seam
    flapping, a forced live migration mid-storm, and a primary kill
    whose standby promotion walks the ladder with its first rung
    faulted (``fleet.promote``). Gates: the survivor replay — every
    view and FIB digest across the whole storm must equal the
    never-migrated, never-promoted oracle — exactly one promotion
    with ZERO route deletes, the stream seam recovered (errors
    counted, lag drained on the surviving pair), and the client rode
    both transitions."""
    from openr_tpu.faults import FaultSchedule, get_injector
    from openr_tpu.fleet import FleetController
    from openr_tpu.fleet.controller import FAULT_PROMOTE
    from openr_tpu.fleet.journal import FAULT_JOURNAL_STREAM
    from openr_tpu.load import multi_client
    from openr_tpu.serve.client import SolverClient
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    specs = [
        multi_client.TenantSpec(
            tenant_id="fl_a", kind="grid", size=4,
            seed=seed % 97, slo="premium",
        ),
        multi_client.TenantSpec(
            tenant_id="fl_b", kind="mesh", size=5,
            seed=(seed + 1) % 97, slo="standard",
        ),
    ]
    rounds = max(4, events // len(specs))
    migrate_at = rounds // 3
    kill_at = (2 * rounds) // 3

    base = {
        "promotions": reg.counter_get("fleet.promotions"),
        "deletes": reg.counter_get("fleet.promotion_deletes"),
        "stream_errors": reg.counter_get(
            "fleet.journal_stream_errors"
        ),
    }
    fc = FleetController(services=2, with_standby=True)
    fc.start()
    sp = {s.tenant_id: [] for s in specs}
    fib = {s.tenant_id: [] for s in specs}
    try:
        ctrl_port = fc.serve_ctrl("127.0.0.1")
        worlds = {}
        clients = {}
        for s in specs:
            dbs = s.build_dbs()
            worlds[s.tenant_id] = (s, dbs)
            host, port = fc.admit(s.tenant_id, s.slo)
            cli = SolverClient(
                host, port, controller=("127.0.0.1", ctrl_port)
            )
            cli.register(s.tenant_id, slo=s.slo)
            cli.update_world(
                s.tenant_id, [dbs[k] for k in sorted(dbs)],
                root=s.root_of(dbs),
                prefix_dbs=[
                    db for _k, db in sorted(
                        s.build_prefix_dbs().items()
                    )
                ],
            )
            clients[s.tenant_id] = cli
        # the replica stream flaps from the start: the streamer must
        # recover through its backoff, never silently stall
        get_injector().arm(
            FAULT_JOURNAL_STREAM, FaultSchedule.fail_n(3)
        )
        victim = specs[0].tenant_id
        for i in range(rounds):
            if i == migrate_at:
                fc.migrate(victim)
            if i == kill_at:
                # the promote ladder's preferred rung is faulted: the
                # walk must degrade to the surrendered-suffix rung and
                # still take over — counted, never silent
                get_injector().arm(
                    FAULT_PROMOTE, FaultSchedule.fail_once()
                )
                owner = fc.owner_of(victim)
                ms = fc.services()[owner]
                ms.streamer.flush(15.0)
                ms.kill_primary()
                promoted = fc.maybe_failover()
                if promoted != [owner]:
                    failures.append(
                        f"fleet: expected promotion of {owner}, "
                        f"got {promoted}"
                    )
            for tid, (s, dbs) in worlds.items():
                cli = clients[tid]
                if i > 0:
                    node = multi_client.apply_mutation(dbs, s, i)
                    cli.update_world(tid, [dbs[node]])
                sp[tid].append(cli.solve(tid).digest())
                fib[tid].append(cli.fib(tid).digest)
        # survivor replay: the storm's full digest history vs the
        # fault-free oracle
        oracle_sp = multi_client.oracle_digests(specs, rounds)
        oracle_fib = multi_client.oracle_fib_digests(
            specs, rounds, every=1
        )
        for s in specs:
            if sp[s.tenant_id] != oracle_sp[s.tenant_id]:
                failures.append(
                    f"fleet: SP digest diverged for {s.tenant_id} "
                    "across migration/promotion"
                )
            if fib[s.tenant_id] != oracle_fib[s.tenant_id]:
                failures.append(
                    f"fleet: FIB digest diverged for {s.tenant_id} "
                    "across migration/promotion"
                )
        promotions = (
            reg.counter_get("fleet.promotions") - base["promotions"]
        )
        if promotions != 1:
            failures.append(
                f"fleet: {promotions} promotions (expected 1)"
            )
        deletes = (
            reg.counter_get("fleet.promotion_deletes")
            - base["deletes"]
        )
        if deletes != 0:
            failures.append(
                f"fleet: promotion deleted {deletes} routes "
                "(graceful restart demands 0)"
            )
        if (
            reg.counter_get("fleet.journal_stream_errors")
            <= base["stream_errors"]
        ):
            failures.append(
                "fleet: journal_stream seam never fired"
            )
        # the surviving (non-promoted) pair must drain its stream
        for name, ms in fc.services().items():
            if ms.streamer is not None:
                if not ms.streamer.flush(15.0):
                    failures.append(
                        f"fleet: {name} replica stream failed to "
                        "drain after the storm"
                    )
                elif ms.streamer.lag() != 0:
                    failures.append(
                        f"fleet: {name} replica lag "
                        f"{ms.streamer.lag()} after drain"
                    )
        if not any(
            cli.redirects >= 1 for cli in clients.values()
        ):
            failures.append(
                "fleet: no client followed the migration redirect"
            )
        for cli in clients.values():
            cli.close()
    finally:
        get_injector().disarm(FAULT_JOURNAL_STREAM)
        get_injector().disarm(FAULT_PROMOTE)
        fc.stop()
    return rounds * len(specs)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=20260805)
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="small event budget for the tier-1 gate",
    )
    parser.add_argument(
        "--out", default="/tmp/openr_tpu_chaos_report.json"
    )
    args = parser.parse_args(argv)

    from openr_tpu import testing

    testing.pin_host_cpu()

    from openr_tpu.faults import get_injector
    from openr_tpu.telemetry import get_registry

    reg = get_registry()
    get_injector().reset()
    base = _injected(reg)

    budgets = (
        {"engine": 60, "decision": 20, "platform": 20, "load": 40,
         "restart": 12, "corrupt": 20, "fleet": 12, "floor": 50}
        if args.smoke
        else {"engine": 160, "decision": 40, "platform": 40, "load": 80,
              "restart": 24, "corrupt": 48, "fleet": 24, "floor": 200}
    )

    failures: list = []
    t0 = time.perf_counter()
    events = 0
    events += _engine_leg(args.seed, budgets["engine"], failures)
    events += _corruption_storm_leg(args.seed, budgets["corrupt"], failures)
    events += _decision_leg(args.seed, budgets["decision"], failures)
    events += _platform_leg(args.seed, budgets["platform"], failures)
    events += _load_leg(args.seed, budgets["load"], failures)
    events += _kill_restart_leg(args.seed, budgets["restart"], failures)
    events += _fleet_leg(args.seed, budgets["fleet"], failures)
    elapsed = time.perf_counter() - t0

    injected = {
        site: count - base.get(site, 0)
        for site, count in _injected(reg).items()
    }
    injected = {s: c for s, c in injected.items() if c > 0}
    if sum(injected.values()) < budgets["floor"]:
        failures.append(
            f"coverage floor missed: {sum(injected.values())} faults "
            f"< {budgets['floor']}"
        )
    # the floor covers the crash, corruption, and fleet seams too:
    # ``device.lost`` (engine leg), ``state.checkpoint_write``
    # (kill-restart leg), ``device.corrupt_resident``
    # (corruption-storm leg), and the fleet pair
    # ``fleet.journal_stream`` + ``fleet.promote`` (fleet leg) must
    # all fire
    if len(injected) < 11:
        failures.append(
            f"only {len(injected)} seams crossed: {sorted(injected)}"
        )

    snap = reg.snapshot()
    report = {
        "seed": args.seed,
        "smoke": args.smoke,
        "events": events,
        "elapsed_s": round(elapsed, 3),
        "faults_injected": dict(sorted(injected.items())),
        "faults_total": sum(injected.values()),
        "sites_registered": sorted(get_injector().list_sites()),
        "health": {
            name: snap.get(f"{name}.health")
            for name in ("route_engine", "decision")
        },
        "ladder": {
            name: {
                c: snap.get(f"{name}.{c}", 0) for c in LADDER_COUNTERS
            }
            for name in ("route_engine", "decision")
        },
        "failures": failures,
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report, indent=1, sort_keys=True))
    if failures:
        print(f"CHAOS GATE: FAIL ({len(failures)})", file=sys.stderr)
        return 1
    print(f"CHAOS GATE: PASS (report: {args.out})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
