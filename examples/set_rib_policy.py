"""SetRibPolicyExample: push a weight-steering policy into Decision.

Example-parity with the reference ``examples/SetRibPolicyExample.cpp``:
connect to a node's ctrl endpoint and install a TTL'd RibPolicy that
re-weights next-hops for a prefix (e.g. drain one neighbor softly).

usage: set_rib_policy.py [host:]port PREFIX NEIGHBOR=WEIGHT ...
"""

from __future__ import annotations

import sys

from openr_tpu.ctrl.server import CtrlClient


def main() -> None:
    if len(sys.argv) < 4:
        print(__doc__)
        return
    target, prefix = sys.argv[1], sys.argv[2]
    host, _, port = target.rpartition(":")
    weights = {}
    for spec in sys.argv[3:]:
        neighbor, _, weight = spec.partition("=")
        weights[neighbor] = int(weight)

    client = CtrlClient(host or "127.0.0.1", int(port))
    try:
        client.call(
            "set_rib_policy",
            statements=[
                {
                    "name": "example-steering",
                    "prefixes": [prefix],
                    "default_weight": 1,
                    "neighbor_to_weight": weights,
                }
            ],
            ttl_secs=300,
        )
        print(f"policy installed for {prefix}: {weights} (ttl 300s)")
        print("current policy:", client.call("get_rib_policy"))
    finally:
        client.close()


if __name__ == "__main__":
    main()
