"""Thrift-wire interop demo: the reference's wire formats end to end.

Three self-contained legs, all speaking the byte-exact formats a stock
Open/R toolchain emits (framed TCompactProtocol; see
openr_tpu/utils/thrift_compact.py and utils/thrift_rpc.py):

1. two KvStores full-sync and live-flood over the thrift
   ``KvStoreService`` peer channel (KvStore.thrift:256-276);
2. a ``FibService`` client programs unicast + MPLS routes into a
   thrift-served platform agent (Platform.thrift:70-135) backed by the
   in-memory mock kernel;
3. Spark packets round-trip through the reference ``SparkHelloPacket``
   compact layout (Spark.thrift:113) with format sniffing against the
   framework codec.

Run:  python examples/thrift_interop_demo.py
"""

from __future__ import annotations

import time


def kvstore_leg() -> None:
    from openr_tpu.kvstore.thrift_peer import (
        KvStoreThriftPeerServer,
        ThriftPeerTransport,
    )
    from openr_tpu.kvstore.wrapper import KvStoreWrapper

    a, b = KvStoreWrapper("node-a"), KvStoreWrapper("node-b")
    a.start()
    b.start()
    server_a = KvStoreThriftPeerServer(a.store, host="127.0.0.1")
    server_b = KvStoreThriftPeerServer(b.store, host="127.0.0.1")
    server_a.start()
    server_b.start()
    try:
        a.set_key("demo:greeting", b"hello-over-thrift")
        a.store.add_peer(
            "0", "node-b", ThriftPeerTransport("127.0.0.1", server_b.port)
        )
        b.store.add_peer(
            "0", "node-a", ThriftPeerTransport("127.0.0.1", server_a.port)
        )
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            v = b.get_key("demo:greeting")
            if v is not None:
                print(
                    f"[kvstore] node-b learned demo:greeting = "
                    f"{v.value!r} over the thrift wire"
                )
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("sync never completed")
    finally:
        server_a.stop()
        server_b.stop()
        a.stop()
        b.stop()


def fib_leg() -> None:
    from openr_tpu.platform.netlink import MockNetlinkProtocolSocket
    from openr_tpu.platform.netlink_fib_handler import NetlinkFibHandler
    from openr_tpu.platform.thrift_fib import (
        FibThriftServer,
        ThriftFibAgent,
    )
    from openr_tpu.types import (
        BinaryAddress,
        IpPrefix,
        MplsAction,
        MplsActionCode,
        MplsRoute,
        NextHop,
        UnicastRoute,
    )

    kernel = MockNetlinkProtocolSocket()
    server = FibThriftServer(
        NetlinkFibHandler(kernel), host="127.0.0.1"
    )
    server.start()
    client = ThriftFibAgent("127.0.0.1", server.port)
    try:
        client.add_unicast_routes(
            786,
            [
                UnicastRoute(
                    dest=IpPrefix.from_str("fd00:de00::/64"),
                    next_hops=(
                        NextHop(
                            address=BinaryAddress.from_str(
                                "fe80::1", if_name="eth0"
                            ),
                            metric=2,
                        ),
                    ),
                )
            ],
        )
        client.add_mpls_routes(
            786,
            [
                MplsRoute(
                    top_label=10042,
                    next_hops=(
                        NextHop(
                            address=BinaryAddress.from_str("fe80::2"),
                            mpls_action=MplsAction(
                                action=MplsActionCode.SWAP,
                                swap_label=10043,
                            ),
                        ),
                    ),
                )
            ],
        )
        routes = client.get_route_table_by_client(786)
        labels = client.get_mpls_route_table_by_client(786)
        print(
            f"[fib] agent programmed {len(routes)} unicast route(s) and "
            f"{len(labels)} MPLS route(s); kernel table: "
            f"{[r.dest.to_str() for r in kernel.get_all_routes()]}"
        )
    finally:
        client.close()
        server.stop()


def spark_leg() -> None:
    from openr_tpu.spark import thrift_wire
    from openr_tpu.types.spark import SparkHeartbeatMsg, SparkPacket

    pkt = SparkPacket(
        heartbeat=SparkHeartbeatMsg(
            node_name="demo-node", if_name="eth0", seq_num=42
        )
    )
    data = thrift_wire.encode_packet(pkt)
    back = thrift_wire.decode_packet(data)
    print(
        f"[spark] heartbeat encoded to {len(data)} compact bytes "
        f"({data.hex(' ')}), decoded node={back.heartbeat.node_name!r} "
        f"seq={back.heartbeat.seq_num}"
    )


if __name__ == "__main__":
    kvstore_leg()
    fib_leg()
    spark_leg()
    print("thrift interop demo: all legs ok")
