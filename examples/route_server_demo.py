"""Route-server demo: the network-wide incremental route product
answering ANY node's route table from one resident engine.

The reference's Decision computes one node's routes per query
(``getRouteDbComputed`` re-runs SpfSolver for the asked node). The
destination-major engine (`ops/route_engine.py`) holds the WHOLE
network's route product device-resident instead: every node named as a
sample gets its complete route table assembled from the sweep, and a
churn event refreshes only the affected destinations in one fused
dispatch — the route-server shape (an external consumer watching a
fabric's LSDB and answering path queries for any pair), at a cost per
event that does not depend on how many nodes are being served.

The demo builds a fat-tree from synthetic adjacency databases, serves
three rack switches' full tables, applies a metric change and a link
failure, and shows per-event refresh + oracle parity.

Run:  python examples/route_server_demo.py [--nodes 336] [--grouped]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=336)
    p.add_argument("--grouped", action="store_true",
                   help="use the block-bipartite grouped backend")
    args = p.parse_args()

    from dataclasses import replace

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.models import topologies
    from openr_tpu.ops import route_engine

    topo = topologies.fat_tree_nodes(args.nodes)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    names = sorted(topo.adj_dbs)
    served = [n for n in names if n.startswith("rsw")][:3]

    cls = (
        route_engine.GroupedRouteSweepEngine
        if args.grouped
        else route_engine.RouteSweepEngine
    )
    t0 = time.perf_counter()
    engine = cls(ls, served)
    print(
        f"resident build: {len(names)} nodes, serving "
        f"{len(served)} full tables, "
        f"{(time.perf_counter() - t0) * 1000:.0f} ms "
        f"({'grouped' if args.grouped else 'ell'} backend)"
    )
    table = engine.result.routes_from(served[0])
    print(f"{served[0]}: {len(table)} destinations, e.g. "
          f"{next(iter(sorted(table.items())))}")

    # -- metric churn ----------------------------------------------------
    fsw = next(n for n in names if n.startswith("fsw"))
    db = ls.get_adjacency_databases()[fsw]
    adjs = list(db.adjacencies)
    adjs[0] = replace(adjs[0], metric=7)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    t0 = time.perf_counter()
    moved = engine.churn(ls, {fsw, adjs[0].other_node_name})
    dt = (time.perf_counter() - t0) * 1000
    if moved is None:
        print(f"metric event: cold rebuild in {dt:.1f} ms")
    else:
        print(f"metric event: {len(moved)} destinations refreshed in "
              f"{dt:.1f} ms (every served table current)")

    # -- link failure ----------------------------------------------------
    db = ls.get_adjacency_databases()[fsw]
    adjs = list(db.adjacencies)
    dropped = adjs.pop(0)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    odb = ls.get_adjacency_databases()[dropped.other_node_name]
    ls.update_adjacency_database(replace(
        odb,
        adjacencies=tuple(
            a for a in odb.adjacencies if a.other_node_name != fsw
        ),
    ))
    t0 = time.perf_counter()
    moved = engine.churn(ls, {fsw, dropped.other_node_name})
    dt = (time.perf_counter() - t0) * 1000
    if moved is None:
        print(f"link-down event: cold rebuild in {dt:.1f} ms")
    else:
        print(f"link-down event: {len(moved)} destinations refreshed "
              f"in {dt:.1f} ms (incremental — no cold rebuild: "
              f"{engine.cold_builds} build(s) total)")

    # -- oracle parity ---------------------------------------------------
    oracle = ls.run_spf(served[0])
    got = engine.result.routes_from(served[0])
    checked = 0
    for dst, (metric, nhs) in got.items():
        want = oracle.get(dst)
        assert want is not None and metric == want.metric, dst
        assert nhs == set(want.next_hops), dst
        checked += 1
    # completeness, not just subset parity: every reachable
    # destination (oracle includes the source itself) must be served
    assert checked == len(oracle) - 1, (checked, len(oracle))
    print(f"oracle parity: {checked} routes of {served[0]} exact "
          "(metrics + ECMP sets)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
