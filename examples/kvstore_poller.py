"""KvStorePoller: bulk-read LSDBs from many nodes' ctrl endpoints.

Example-parity with the reference ``examples/KvStorePoller.cpp``: connect
to a set of (host, port) ctrl endpoints and dump adjacency/prefix
databases from each.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from openr_tpu.ctrl.server import CtrlClient


class KvStorePoller:
    def __init__(self, endpoints: List[Tuple[str, int]]):
        self._endpoints = endpoints

    def get_adjacency_databases(self) -> Dict[str, dict]:
        """reference: KvStorePoller::getAdjacencyDatabases."""
        return self._poll("adj:")

    def get_prefix_databases(self) -> Dict[str, dict]:
        """reference: KvStorePoller::getPrefixDatabases."""
        return self._poll("prefix:")

    def _poll(self, prefix: str) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for host, port in self._endpoints:
            try:
                client = CtrlClient(host, port)
            except OSError:
                continue
            try:
                out[f"{host}:{port}"] = client.call(
                    "get_kvstore_keys_filtered", prefix=prefix
                )
            finally:
                client.close()
        return out


def main() -> None:
    import sys

    endpoints = []
    for arg in sys.argv[1:]:
        host, _, port = arg.rpartition(":")
        endpoints.append((host or "127.0.0.1", int(port)))
    if not endpoints:
        print("usage: kvstore_poller.py host:port [host:port ...]")
        return
    poller = KvStorePoller(endpoints)
    for endpoint, keys in poller.get_adjacency_databases().items():
        print(f"{endpoint}: {len(keys)} adjacency keys")
        for key in sorted(keys):
            print(f"  {key}")


if __name__ == "__main__":
    main()
