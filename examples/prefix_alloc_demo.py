"""Automatic prefix allocation demo: four nodes elect unique /64s out
of one seed prefix via RangeAllocator consensus over a shared KvStore
mesh, program them on a (mock) loopback, and re-elect when the seed
prefix changes — the openr-tpu analogue of the reference's
enable_prefix_alloc deployment flow (openr/allocators/PrefixAllocator).

Run:  python examples/prefix_alloc_demo.py
"""

from __future__ import annotations

import time

from openr_tpu.allocators.prefix_allocator import PrefixAllocator
from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.kvstore.wrapper import KvStoreWrapper, link_bidirectional
from openr_tpu.platform.netlink import MockNetlinkProtocolSocket
from openr_tpu.types import IpPrefix
from openr_tpu.utils.eventbase import OpenrEventBase

NODES = [f"rack-{i}" for i in range(4)]


class PrintingPrefixManager:
    def __init__(self, node):
        self.node = node

    def advertise_prefixes(self, entries):
        for e in entries:
            print(f"  {self.node}: advertise {e.prefix.to_str()}")

    def withdraw_prefixes(self, prefixes):
        for p in prefixes:
            print(f"  {self.node}: withdraw  {p.to_str()}")


def main() -> None:
    stores, evbs, allocs, netlinks = {}, {}, {}, {}
    for n in NODES:
        w = KvStoreWrapper(n)
        w.start()
        stores[n] = w
        evb = OpenrEventBase(f"alloc:{n}")
        evb.run_in_thread()
        evbs[n] = evb
    for i, a in enumerate(NODES):
        for b in NODES[i + 1 :]:
            link_bidirectional(stores[a], stores[b])

    seed = IpPrefix.from_str("fc00:cafe::/62")  # exactly 4 slots: contention!
    print(f"electing /64s from {seed.to_str()} ({len(NODES)} nodes, 4 slots)")
    for n in NODES:
        nl = MockNetlinkProtocolSocket()
        nl.add_link("lo", is_up=True)
        netlinks[n] = nl
        allocs[n] = PrefixAllocator(
            n,
            evbs[n],
            KvStoreClient(evbs[n], n, stores[n].store),
            PrintingPrefixManager(n),
            seed_prefix=seed,
            alloc_prefix_len=64,
            netlink=nl,
            loopback_if="lo",
        )

    deadline = time.time() + 20
    while time.time() < deadline:
        got = {n: a.allocated_prefix for n, a in allocs.items()}
        if all(got.values()) and len(set(got.values())) == len(NODES):
            break
        time.sleep(0.05)

    got = {n: a.allocated_prefix for n, a in allocs.items()}
    if not all(got.values()):
        raise SystemExit(
            f"did not converge within deadline: {got}"
        )
    print("\nconverged allocations:")
    for n in NODES:
        (link,) = netlinks[n].get_all_links()
        addrs = ", ".join(p.to_str() for p in link.addresses)
        print(f"  {n}: {allocs[n].allocated_prefix.to_str()}  (lo: {addrs})")
    assert len({a.allocated_prefix for a in allocs.values()}) == len(NODES)

    print("\nseed change -> re-election under fc00:beef::/62")
    for a in allocs.values():
        a.update_alloc_params(IpPrefix.from_str("fc00:beef::/62"), 64)
    deadline = time.time() + 20
    while time.time() < deadline:
        got = {n: a.allocated_prefix for n, a in allocs.items()}
        if (
            all(got.values())
            and len(set(got.values())) == len(NODES)
            and all(p.to_str().startswith("fc00:beef") for p in got.values())
        ):
            break
        time.sleep(0.05)
    got = {n: a.allocated_prefix for n, a in allocs.items()}
    if not all(got.values()) or not all(
        p.to_str().startswith("fc00:beef") for p in got.values()
    ):
        raise SystemExit(f"re-election did not converge: {got}")
    for n in NODES:
        print(f"  {n}: {allocs[n].allocated_prefix.to_str()}")

    for a in allocs.values():
        a.stop()
    for evb in evbs.values():
        evb.stop()
        evb.join()
    for w in stores.values():
        w.stop()
    print("\nok")


if __name__ == "__main__":
    main()
