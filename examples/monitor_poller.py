"""MonitorPoller: periodically scrape counters and event logs from a set
of nodes' ctrl endpoints.

Example-parity with the reference ``examples/ZmqMonitorPoller.cpp``
(which subscribed to each node's monitor socket): the thrift-era
equivalent polls ``get_counters`` / ``get_event_logs`` over the ctrl
API, keeping a last-seen high-water mark per node so each poll emits
only new log samples.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Tuple

from openr_tpu.ctrl.server import CtrlClient


class MonitorPoller:
    def __init__(self, endpoints: List[Tuple[str, int]]):
        self._endpoints = endpoints
        self._seen: Dict[Tuple[str, int], int] = {}

    def poll_counters(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for host, port in self._endpoints:
            try:
                out[f"{host}:{port}"] = CtrlClient(host, port).call(
                    "get_counters"
                )
            except Exception:
                continue  # node unreachable: skip this round
        return out

    def poll_new_logs(self) -> List[dict]:
        """Event-log samples not seen in a previous poll."""
        fresh: List[dict] = []
        for ep in self._endpoints:
            host, port = ep
            try:
                logs = CtrlClient(host, port).call(
                    "get_event_logs", limit=1000
                )
            except Exception:
                continue
            start = self._seen.get(ep, 0)
            for raw in logs[start:]:
                fresh.append(raw if isinstance(raw, dict) else json.loads(raw))
            self._seen[ep] = len(logs)
        return fresh

    def run(self, interval_s: float = 5.0) -> None:
        while True:
            for sample in self.poll_new_logs():
                print(json.dumps(sample))
            time.sleep(interval_s)


if __name__ == "__main__":
    import sys

    eps = [
        (h, int(p))
        for h, _, p in (arg.partition(":") for arg in sys.argv[1:])
    ] or [("127.0.0.1", 2018)]
    MonitorPoller(eps).run()
