"""KvStoreAgent: embed application state in the routing KvStore.

Example-parity with the reference ``examples/KvStoreAgent.cpp``: an
application running next to the daemon persists its own keys (with TTL
refresh handled by the client) and subscribes to keys published by the
same application on other nodes.

Run me standalone for a self-contained two-node demo:
    python examples/kvstore_agent.py
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.types import Value
from openr_tpu.utils.eventbase import OpenrEventBase

APP_PREFIX = "app-demo:"


class KvStoreAgent:
    """reference: examples/KvStoreAgent.cpp (kvStoreClient_->persistKey +
    subscribeKeyFilter on the app's key namespace)."""

    def __init__(self, node_name: str, kvstore, area: str = "0"):
        self.node_name = node_name
        self.area = area
        self.evb = OpenrEventBase(name=f"agent:{node_name}")
        self.client = KvStoreClient(self.evb, node_name, kvstore)
        self.peers_seen: Dict[str, bytes] = {}
        self.client.subscribe_key_filter(self._on_key)
        self.evb.run_in_thread()

    def advertise(self, payload: bytes, ttl_ms: int = 5000) -> None:
        """Own our per-node app key; the client keeps it alive."""
        self.client.persist_key(
            self.area, f"{APP_PREFIX}{self.node_name}", payload, ttl=ttl_ms
        )

    def _on_key(self, area: str, key: str, value: Optional[Value]) -> None:
        if not key.startswith(APP_PREFIX):
            return
        peer = key[len(APP_PREFIX):]
        if value is None:
            self.peers_seen.pop(peer, None)
        elif value.value is not None:
            self.peers_seen[peer] = value.value

    def stop(self) -> None:
        self.client.stop()
        self.evb.stop()
        self.evb.join()


def main() -> None:
    from openr_tpu.kvstore.wrapper import KvStoreWrapper, link_bidirectional

    a, b = KvStoreWrapper("node-a"), KvStoreWrapper("node-b")
    a.start()
    b.start()
    link_bidirectional(a, b)
    agent_a = KvStoreAgent("node-a", a.store)
    agent_b = KvStoreAgent("node-b", b.store)
    agent_a.advertise(b"hello from a")
    agent_b.advertise(b"hello from b")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if (
            agent_a.peers_seen.get("node-b") == b"hello from b"
            and agent_b.peers_seen.get("node-a") == b"hello from a"
        ):
            print("both agents see each other's app keys:")
            print("  node-a sees:", agent_a.peers_seen)
            print("  node-b sees:", agent_b.peers_seen)
            break
        time.sleep(0.05)
    agent_a.stop()
    agent_b.stop()
    a.stop()
    b.stop()


if __name__ == "__main__":
    main()
