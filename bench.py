"""Reconvergence benchmark: route-rebuild SPF after a topology change.

Scenario (mirrors the reference Decision benchmarks,
openr/decision/tests/DecisionBenchmark.cpp: BM_DecisionFabric, and its
<100 ms convergence design goal, openr/docs/Introduction/Overview.md:28):

  A ~1000-node 3-tier fat-tree is resident as a compiled snapshot on the
  device. One adjacency metric changes (link churn). Measured latency =
  incremental LinkState merge + ONE fused device dispatch (scatter the
  changed metric rows into the resident matrix + batched SPF from this
  node and every neighbor — exactly the rows a route rebuild consumes for
  best-path selection, ECMP first hops, and LFA; reference
  Decision.cpp:1124 getNextHopsWithMetric, :1192) + distance/first-hop
  readback to the host.

Prints exactly ONE JSON line:
  {"metric": ..., "value": ms, "unit": "ms", "vs_baseline": x,
   "device_only_ms": ms, "platform": "...", "error": null}
where vs_baseline is the speedup vs the reference's 100 ms convergence
design goal (>1.0 means faster than the goal). `value` is end-to-end
(dispatch + readback); `device_only_ms` isolates on-device compute by
timing K data-dependent chained dispatches against one (the fixed
relay/transport cost cancels in the difference).

Resilience: the TPU is reached through a relay that has been observed to
(a) fail backend init outright, (b) HANG indefinitely on the first
device op or even on jax.devices(), and (c) recover later the same day.
The top-level process therefore never imports jax: it probes the backend
in a subprocess under a hard timeout, RETRYING with escalating timeouts
across the bench budget (the relay has recovered mid-round before); runs
the benchmark in a TPU child if any probe passes; re-probes and retries
once if the TPU child dies mid-run; and degrades to a CPU-pinned child
otherwise — so a JSON line (with "probe_attempts" + "fallback" evidence
when degraded) is emitted no matter what the relay does.

Secondary legs folded into the same artifact:
- "bench_10k_churn": the 10k-node resident-ELL churn reconvergence
  (BASELINE.json config 4 axis), via benchmarks.bench_scale.churn_bench.
- "bench_link_churn": paired metric-vs-link churn at 10k through the
  resident route engine — link (structural) events overflow the bucket
  ladder and ride the frontier re-solve; reports the link-vs-metric
  median ratio (target: within ~2x) and the frontier-vs-full split.
- "minplus_ms": pallas-vs-jnp min-plus timing at the bench shape on real
  TPU; the main loop runs whichever measured faster (the losing number
  is kept in the artifact).
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time
import traceback

BASELINE_MS = 100.0  # reference convergence design goal
NORTHSTAR_MS = 10.0  # this repo's own target (BASELINE.json)
# error-path fallback only; successful runs name the real node count
METRIC_NAME = "spf_reconvergence_ms_fattree_1008"
# escalating probe schedule, spread across the bench budget: the relay
# has hung for >115s and recovered within the same round before
PROBE_TIMEOUTS_S = (60, 90, 120, 120)
PROBE_BUDGET_S = 320  # stop probing once this much wall time is spent
RETRY_PROBE_TIMEOUT_S = 120
TPU_CHILD_TIMEOUT_S = 270
# headline + 10k churn + ksp2 + route sweep + route-engine churn +
# sp-solver churn legs
TPU_CHILD_10K_TIMEOUT_S = 1000
CPU_CHILD_TIMEOUT_S = 150
CPU_CHILD_10K_TIMEOUT_S = 900
# soft wall-clock budget: optional legs (TPU retry, 10k CPU leg) are
# skipped once exceeded so a worst-case run still emits JSON promptly
BENCH_SOFT_BUDGET_S = 1200


def _run() -> dict:
    child_t0 = time.monotonic()

    # children only: the PARENT never imports jax (the relay-tunneled
    # plugin can hang at discovery; all jax work runs in probed,
    # timed-out subprocesses)
    from openr_tpu.utils.compile_cache import enable as _enable_cache

    _enable_cache()
    # jit compile count/time listeners: a compile-cache regression in
    # any leg shows up as jax.compile_count / jax.compile_ms in the
    # artifact instead of a silent latency cliff
    from openr_tpu.telemetry import jax_hooks as _jax_hooks

    _jax_hooks.install()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.graph.snapshot import INF, SnapshotCache, pad_patch_rows
    from openr_tpu.models import topologies
    from openr_tpu.ops import spf as spf_ops
    from openr_tpu.types import Adjacency, AdjacencyDatabase

    platform = jax.devices()[0].platform
    snapshots = SnapshotCache()

    topo = topologies.fat_tree_nodes(1000)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])

    churn_node = "fsw-0-0"
    my_node = "rsw-0-0"

    def churn(step: int) -> None:
        """Bump one adjacency metric on churn_node (incremental update)."""
        db = ls.get_adjacency_databases()[churn_node]
        adjs = list(db.adjacencies)
        a0 = adjs[0]
        adjs[0] = Adjacency(
            other_node_name=a0.other_node_name,
            if_name=a0.if_name,
            metric=2 + (step % 5),
            next_hop_v6=a0.next_hop_v6,
            next_hop_v4=a0.next_hop_v4,
            adj_label=a0.adj_label,
            is_overloaded=a0.is_overloaded,
            rtt=a0.rtt,
            timestamp=a0.timestamp,
            weight=a0.weight,
            other_if_name=a0.other_if_name,
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=db.this_node_name,
                is_overloaded=db.is_overloaded,
                adjacencies=tuple(adjs),
                node_label=db.node_label,
                area=db.area,
            )
        )

    # resident device state, owned by the bench loop
    snap0 = snapshots.get(ls)
    sid = snap0.node_index[my_node]
    batch, srcs_dev = spf_ops.source_batch(snap0, sid)
    bucket = srcs_dev.shape[0]
    state = {"metric_dev": jnp.asarray(snap0.metric)}
    noop_ids = np.asarray([sid] * 8, dtype=np.int32)

    def reconverge():
        snap = snapshots.get(ls)
        plan = snap.patch_plan()
        ids = pad_patch_rows(plan[0]) if plan is not None else None
        if ids is None:
            # full (re)compile or oversized change: upload the whole matrix
            state["metric_dev"] = jnp.asarray(snap.metric)
            ids = noop_ids
        vals = snap.metric[ids, :]
        # one fused dispatch: scatter + batched SPF + first hops. The
        # overloaded mask rides along on every step (patch_plan covers
        # metric rows only; this is an O(N) async upload).
        m2, packed = spf_ops.reconverge_step(
            state["metric_dev"],
            jnp.asarray(ids),
            jnp.asarray(vals),
            jnp.asarray(snap.overloaded),
            srcs_dev,
        )
        state["metric_dev"] = m2
        # Honest completion signal: read back the packed distance +
        # first-hop rows route selection consumes. On relay-backed
        # platforms a bare block_until_ready can ack before the device
        # round trip; a data-dependent readback cannot. One device->host
        # sync per reconvergence.
        packed_host = np.asarray(packed)
        d_host = packed_host[:bucket]
        fh_host = packed_host[bucket:].astype(bool)
        return d_host, fh_host

    def oracle_gate(d_host, fh_host) -> bool:
        """Device distances + ECMP first hops vs the host Dijkstra oracle
        (reference runSpf semantics), exact."""
        oracle = ls.run_spf(my_node)
        names = snap0.node_names
        for dst, res in oracle.items():
            did = snap0.node_index[dst]
            if d_host[0, did] != res.metric:
                return False
            if dst != my_node:
                got_nh = {
                    names[batch[i]]
                    for i in np.nonzero(fh_host[: len(batch), did])[0]
                }
                if got_nh != res.next_hops:
                    return False
        for dst in set(names) - set(oracle):
            if d_host[0, snap0.node_index[dst]] < INF:
                return False
        return True

    # warm-up (jit compile + first snapshot) on the always-available jnp
    # formulation, oracle-gated
    spf_ops.set_minplus_impl("jnp")
    d_host, fh_host = reconverge()
    assert oracle_gate(d_host, fh_host), "device SPF failed oracle gate"

    # one churn+reconverge outside the timed loop: the first patched
    # snapshot compiles the fused scatter+SPF program (one-time cost)
    churn(99)
    reconverge()

    # Device-only compute time for the CURRENT min-plus impl. A single
    # e2e sample is dominated by the relay transport (~fixed per
    # readback); chain K data-dependent dispatches (metric feeds back
    # into the next step) with ONE readback at the end, subtract the
    # 1-dispatch+readback time, and the fixed transport cost cancels:
    # per-dispatch device time = (T_K - T_1) / (K - 1).
    ov_dev = jnp.asarray(snap0.overloaded)
    ids_dev = jnp.asarray(noop_ids)
    # slice the 8 noop rows on-device: reading back the whole N x N
    # matrix just to re-upload 8 rows costs a full relay round trip
    vals_dev = state["metric_dev"][ids_dev, :]

    def chain_device_only() -> float:
        def time_chain(k: int) -> float:
            m = state["metric_dev"]
            t0 = time.perf_counter()
            packed = None
            for _ in range(k):
                m, packed = spf_ops.reconverge_step(
                    m, ids_dev, vals_dev, ov_dev, srcs_dev
                )
            np.asarray(packed)
            return (time.perf_counter() - t0) * 1000.0

        time_chain(1)  # warm any K=1 cache path
        t1 = statistics.median(time_chain(1) for _ in range(5))
        tk = statistics.median(time_chain(8) for _ in range(5))
        return round(max(0.0, (tk - t1) / 7.0), 3)

    # Min-plus impl CHOSEN BY MEASUREMENT on real TPU: time the jnp
    # (XLA-fused) and pallas (hand-tiled VMEM) kernels at the bench
    # shape, run the main loop on the winner, keep the loser's number in
    # the artifact. On host CPU the pallas path only runs in interpret
    # mode — stay on jnp and skip the ~90 extra full-SPF dispatches.
    device_only = None
    minplus_ms = None
    minplus_winner = spf_ops.get_minplus_impl()
    if platform != "cpu":
        minplus_ms = {"jnp": chain_device_only()}
        try:
            spf_ops.set_minplus_impl("pallas")
            d_host, fh_host = reconverge()  # compile the pallas programs
            if not oracle_gate(d_host, fh_host):
                raise RuntimeError("pallas min-plus failed the oracle gate")
            minplus_ms["pallas"] = chain_device_only()
        except Exception as e:
            minplus_ms["pallas"] = None
            minplus_ms["pallas_error"] = f"{type(e).__name__}: {e}"
            spf_ops.set_minplus_impl("jnp")
            snapshots.invalidate()  # rebuild resident state from scratch
            d_host, fh_host = reconverge()
            assert oracle_gate(d_host, fh_host), "jnp re-gate failed"
        if (
            minplus_ms.get("pallas") is not None
            and minplus_ms["pallas"] >= minplus_ms["jnp"]
        ):
            spf_ops.set_minplus_impl("jnp")
        device_only = minplus_ms[spf_ops.get_minplus_impl()]
        minplus_winner = spf_ops.get_minplus_impl()
        # persist the measured winner under the autotuner's
        # (platform, kernel, shape) key: impl="auto" resolutions in
        # later processes inherit this oracle-gated measurement
        # instead of re-timing a synthetic contraction
        try:
            from openr_tpu.ops.autotune import get_autotuner

            get_autotuner().record(
                "minplus",
                f"{bucket}x{state['metric_dev'].shape[-1]}",
                spf_ops.get_minplus_impl(),
                {k: v for k, v in minplus_ms.items()
                 if isinstance(v, (int, float))},
            )
            # arm the autotuner for every later leg: "auto" resolves
            # per shape to the just-recorded oracle-gated winner, so
            # the optional legs below run exactly the impl a
            # production process would pick up from the persist file
            spf_ops.set_minplus_impl("auto")
        except Exception:  # noqa: BLE001 - persistence is best-effort
            pass

    samples = []
    for step in range(10):
        churn(step)
        t0 = time.perf_counter()
        reconverge()
        samples.append((time.perf_counter() - t0) * 1000.0)
    value = statistics.median(samples)

    # Optional legs, each gated on the child's REMAINING time budget:
    # first-ever jit compiles ride a remote-compile tunnel that has
    # taken 30-200s when the relay degrades, and a leg that blows the
    # child timeout costs the HEADLINE number too (the parent kills the
    # whole child). A skipped leg records why.
    def leg_elapsed() -> float:
        return time.monotonic() - child_t0

    def annotate_ratios(leg: dict) -> dict:
        """Shared vs_baseline / vs_northstar / scale-note annotation
        for per-leg dicts (the north-star note keeps a CPU-fallback
        artifact from reading as 'north star met' at the wrong scale).
        The leg's node count is parsed from its bench name
        (scale.<shape>_<N>_<metric>) so the note stays honest at any
        scale."""
        v = max(leg["median_ms"], 1e-9)
        leg["vs_baseline"] = round(BASELINE_MS / v, 3)
        leg["vs_northstar"] = round(NORTHSTAR_MS / v, 3)
        digits = [
            p for p in leg.get("bench", "").split("_") if p.isdigit()
        ]
        n_desc = f"{digits[0]} nodes" if digits else "this scale"
        leg["northstar_scale_note"] = (
            "north-star target is 100k nodes / v4-32 mesh; this leg "
            f"is {n_desc} on one {leg.get('platform', '?')} device"
        )
        dev = leg.get("device_only_ms")
        if dev and "host_overhead_ratio" not in leg:
            # e2e-vs-device ratio (the committed-dispatch target is
            # this trending to ~1 as host turnarounds leave the path)
            leg["host_overhead_ratio"] = round(v / max(dev, 1e-3), 2)
        measured = _measured_overhead_ratio()
        if measured is not None:
            # the profiler's own wall-vs-device account over recent
            # dispatch windows — this is the headline; the derived
            # ratio above stays for comparison against old artifacts
            leg["host_overhead_ratio_measured"] = measured
        if "pipeline_depth_median" not in leg:
            # windows concurrently in flight when this leg's dispatches
            # pipelined (>= 2 means window N+1 submitted before window
            # N's reap landed); None for a leg that never pipelined
            leg["pipeline_depth_median"] = _pipeline_depth_median()
        return leg

    # second leg: 10k-node resident-ELL churn (the north-star scale
    # axis, BASELINE.json config 4) folded into the same artifact
    bench_10k = None
    if os.environ.get("OPENR_BENCH_10K") == "1":
        if leg_elapsed() > 240:
            bench_10k = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import churn_bench

                bench_10k = annotate_ratios(churn_bench(10000, 10))
            except Exception as e:
                bench_10k = {"error": f"{type(e).__name__}: {e}"}

    # link-churn leg: structural (link up/down) events at 10k through
    # the frontier re-solve path, paired with a metric-churn control
    # run on the same topology — the PR 6 perf target is the link
    # median landing within ~2x of the metric median
    bench_link = None
    if os.environ.get("OPENR_BENCH_10K") == "1":
        if leg_elapsed() > 330:
            bench_link = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import link_churn_bench

                bench_link = link_churn_bench(10000, 8)
            except Exception as e:
                bench_link = {"error": f"{type(e).__name__}: {e}"}

    # third leg: fabric-1008 KSP2 churn through the full SpfSolver —
    # the incremental KSP2 engine (BASELINE.json config 2)
    bench_ksp2 = None
    if os.environ.get("OPENR_BENCH_KSP2") == "1":
        if leg_elapsed() > 390:
            bench_ksp2 = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import ksp2_churn_bench

                bench_ksp2 = annotate_ratios(
                    ksp2_churn_bench(1000, 10)
                )
            except Exception as e:
                bench_ksp2 = {"error": f"{type(e).__name__}: {e}"}

    # fourth leg: the destination-major route sweep with ON-DEVICE
    # route selection (config 5 axis, transfer-fixed): all-sources
    # product consumed on device, digests + sampled route rows read
    # back. Runs the grouped (block-bipartite) backend with on-chip
    # jnp-vs-pallas impl probing; 1008 keeps the CPU fallback cheap
    # while the per-block device time is the scale-relevant number.
    bench_routes = None
    if os.environ.get("OPENR_BENCH_ROUTES") == "1":
        if leg_elapsed() > 420:
            bench_routes = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import route_sweep_bench

                bench_routes = route_sweep_bench(
                    1000, 256, backend="grouped"
                )
            except Exception as e:
                bench_routes = {"error": f"{type(e).__name__}: {e}"}

    # fifth leg: the incremental route engine on the GROUPED backend —
    # per churn event ONE fused dispatch re-solves only affected
    # destination rows of the resident network-wide route product
    bench_rchurn = None
    if os.environ.get("OPENR_BENCH_ROUTES") == "1":
        if leg_elapsed() > 480:
            bench_rchurn = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import (
                    route_engine_churn_bench,
                )

                bench_rchurn = route_engine_churn_bench(
                    1000, 8, backend="grouped"
                )
            except Exception as e:
                bench_rchurn = {"error": f"{type(e).__name__}: {e}"}

    # sixth leg: full-SPF RouteDb reconvergence at 10k with every
    # prefix SP_ECMP — the north star AS DEFINED (BASELINE.json: one
    # node's RouteDatabase, full solver) at the largest scale that
    # fits the child budget; SP route reuse bounds the host rebuild
    # to O(changed) prefixes (the 100k variant is the watcher's
    # solver_churn_100k_sp leg)
    bench_spsolver = None
    if os.environ.get("OPENR_BENCH_ROUTES") == "1":
        if leg_elapsed() > 540:
            bench_spsolver = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import ksp2_churn_bench

                bench_spsolver = annotate_ratios(
                    ksp2_churn_bench(10000, 6, sp_only=True)
                )
            except Exception as e:
                bench_spsolver = {"error": f"{type(e).__name__}: {e}"}

    # seventh leg: convergence tracing through the REAL module pipeline
    # (KvStore -> Decision -> Fib) with the telemetry spine on — the
    # per-event publication->FIB latency distribution plus the trace
    # artifact the north-star claim is audited against. Scale rides the
    # same env gate as the 10k churn leg; the artifact lands next to
    # this file so the watcher can collect it.
    bench_traces = None
    if os.environ.get("OPENR_BENCH_TRACES") == "1":
        if leg_elapsed() > 420:
            bench_traces = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import (
                    convergence_trace_bench,
                )

                trace_nodes = int(
                    os.environ.get("OPENR_BENCH_TRACE_NODES", "1000")
                )
                bench_traces = convergence_trace_bench(
                    trace_nodes,
                    6,
                    trace_path=os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "churn_traces.jsonl",
                    ),
                )
            except Exception as e:
                bench_traces = {"error": f"{type(e).__name__}: {e}"}

    # eighth leg: the resharding-free sharded dispatch contract —
    # sharded-vs-single resident churn with the registry deltas that
    # prove the sharded leg paid zero implicit XLA copies
    # (ops.reshard_events == 0) plus the per-shard overlapped-readback
    # account. On one chip the mesh is virtual and the ratio measures
    # sharded dispatch overhead, not scale-out.
    bench_shchurn = None
    if os.environ.get("OPENR_BENCH_SHARDED") == "1":
        if leg_elapsed() > 480:
            bench_shchurn = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import sharded_churn_bench

                bench_shchurn = sharded_churn_bench(1000, 8)
            except Exception as e:
                bench_shchurn = {"error": f"{type(e).__name__}: {e}"}

    # sliced-ELL kernel leg: paired jnp-vs-pallas relax timing on the
    # resident band structure with the bit-identity oracle gate; the
    # measured winner lands in the autotuner's family-keyed ell_relax
    # persistence (off-CPU), so impl="auto" sparse dispatches in later
    # processes inherit the oracle-gated number — the sparse twin of
    # the min-plus probe above
    bench_ellkern = None
    if os.environ.get("OPENR_BENCH_ELLKERN") == "1":
        if leg_elapsed() > 500:
            bench_ellkern = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import ell_kernel_bench

                bench_ellkern = ell_kernel_bench(1000, 256)
            except Exception as e:
                bench_ellkern = {"error": f"{type(e).__name__}: {e}"}

    # ninth leg: sustained-load service-plane run — the seeded
    # open-loop generator driving the REAL KvStore -> Decision -> Fib
    # pipeline at a fixed rate with admission control + pipelined emit,
    # plus a max-sustainable-rate estimate and the shed-by-coalescing
    # oracle-parity verdict (tools/load_report.py is the CI gate; this
    # leg folds the same numbers into the official bench artifact)
    bench_load = None
    if os.environ.get("OPENR_BENCH_LOAD") == "1":
        if leg_elapsed() > 540:
            bench_load = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import sustained_load_bench

                bench_load = sustained_load_bench(
                    int(os.environ.get("OPENR_BENCH_LOAD_NODES", "1000")),
                    rate=240,
                    duration_s=4.0,
                )
            except Exception as e:
                bench_load = {"error": f"{type(e).__name__}: {e}"}

    # tenth leg: multi-tenant batched worlds — B mixed-size tenant
    # graphs under per-round churn, solved as one bucket dispatch vs
    # one warm EllState reconverge per tenant; reports the
    # batched/sequential per-tenant cost ratio (the tenancy acceptance
    # gate is <= 0.5x at B=8), bucket compile counts, and the
    # tenancy.* counter deltas (make tenancy-smoke is the hard CI
    # gate; this leg folds the throughput numbers into the artifact)
    bench_tenancy = None
    if os.environ.get("OPENR_BENCH_TENANCY") == "1":
        if leg_elapsed() > 540:
            bench_tenancy = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import multi_tenant_bench

                bench_tenancy = multi_tenant_bench(
                    int(os.environ.get("OPENR_BENCH_TENANTS", "8"))
                )
            except Exception as e:
                bench_tenancy = {"error": f"{type(e).__name__}: {e}"}

    # eleventh leg: crash-recovery boot race — the state plane's cold
    # boot (replay every publication) vs warm boot (recover the
    # journaled checkpoint + rehydrate the resident engine from its
    # snapshot), parity-gated; the warm/cold ratio is the recovery
    # design's payoff number (make recovery-smoke is the hard CI gate;
    # this leg folds the timing into the official bench artifact)
    bench_recovery = None
    if os.environ.get("OPENR_BENCH_RECOVERY") == "1":
        if leg_elapsed() > 540:
            bench_recovery = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import recovery_bench

                bench_recovery = recovery_bench(
                    int(os.environ.get(
                        "OPENR_BENCH_RECOVERY_NODES", "200"
                    ))
                )
            except Exception as e:
                bench_recovery = {"error": f"{type(e).__name__}: {e}"}

    # twelfth leg: integrity-audit overhead — the same warm churn
    # loop with the audit plane armed every event (rate limit off,
    # the worst case) vs disarmed; the acceptance gate is an armed
    # e2e median within 5% of disarmed with zero violations on
    # healthy state (make integrity-smoke is the hard CI gate; this
    # leg folds the overhead number into the official artifact)
    bench_integrity = None
    if os.environ.get("OPENR_BENCH_INTEGRITY") == "1":
        if leg_elapsed() > 540:
            bench_integrity = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import integrity_audit_bench

                bench_integrity = integrity_audit_bench(
                    int(os.environ.get(
                        "OPENR_BENCH_INTEGRITY_NODES", "1000"
                    ))
                )
            except Exception as e:
                bench_integrity = {"error": f"{type(e).__name__}: {e}"}

    # thirteenth leg: digital-twin fleet reconvergence — N vantages
    # re-solved per topology event as ONE batched wave (the twin) vs
    # N sequential single-tenant dispatches (the pre-twin status quo),
    # parity-asserted on the final event; reports the per-event cost
    # ratio and dispatches/event (make twin-smoke is the hard CI
    # gate; this leg folds the fleet numbers into the artifact)
    bench_twin = None
    if os.environ.get("OPENR_BENCH_TWIN") == "1":
        if leg_elapsed() > 540:
            bench_twin = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import fleet_twin_bench

                bench_twin = fleet_twin_bench(
                    int(os.environ.get("OPENR_BENCH_TWIN_NODES", "16"))
                )
            except Exception as e:
                bench_twin = {"error": f"{type(e).__name__}: {e}"}

    # fourteenth leg: solver-as-a-service — B mixed-class tenants
    # driven through the live SolverService wave loop by concurrent
    # submitters; reports per-class latency percentiles, solves/s,
    # requests-per-wave, join/preemption deltas, and the scheduler
    # overhead vs a direct batched solve_views floor (make serve-smoke
    # is the hard CI gate; this leg folds the serving-throughput
    # numbers into the official artifact)
    bench_serve = None
    if os.environ.get("OPENR_BENCH_SERVE") == "1":
        if leg_elapsed() > 540:
            bench_serve = {
                "skipped": f"child budget ({leg_elapsed():.0f}s elapsed)"
            }
        else:
            try:
                from benchmarks.bench_scale import solver_service_bench

                bench_serve = solver_service_bench(
                    int(os.environ.get("OPENR_BENCH_SERVE_TENANTS", "64"))
                )
            except Exception as e:
                bench_serve = {"error": f"{type(e).__name__}: {e}"}

    # measured head-to-head: the committed same-host single-thread
    # solver runs (BASELINE_MEASURED.json — native C++ oracle + pure
    # Python host solver over the reference's DecisionBenchmark grid).
    # Unlike the 100 ms design-goal ratio, these divide by a MEASURED
    # number, so "matching-or-beating" is falsifiable.
    try:
        with open(
            os.path.join(os.path.dirname(__file__),
                         "BASELINE_MEASURED.json")
        ) as f:
            _measured_cases = json.load(f)["cases"]
    except (OSError, KeyError, ValueError):
        _measured_cases = {}

    def vs_measured_for(bench_name: str, v: float) -> dict:
        out = {}
        for backend, cases in _measured_cases.items():
            for case in cases:
                # rows marked with a non-default workload are not a
                # like-for-like single-node route build (e.g. the
                # native backend's all-sources sweep at 10k) and must
                # not feed a head-to-head ratio
                if case.get("workload") is not None:
                    continue
                if case.get("bench") == bench_name:
                    out[f"vs_measured_{backend}_solver"] = round(
                        case["churn_rebuild_ms"] / v, 3
                    )
        return out

    vs_measured = vs_measured_for(
        f"decision.fabric_{snap0.n}_sp_ecmp", value
    )
    if bench_spsolver is not None and "median_ms" in bench_spsolver:
        # baseline name derives from the leg's own node count so the
        # two cannot silently drift apart
        digits = [
            p
            for p in bench_spsolver.get("bench", "").split("_")
            if p.isdigit()
        ]
        if digits:
            bench_spsolver.update(
                vs_measured_for(
                    f"decision.fabric_{digits[0]}_sp_ecmp",
                    max(bench_spsolver["median_ms"], 1e-9),
                )
            )

    return {
        "metric": f"spf_reconvergence_ms_fattree_{snap0.n}",
        "value": round(value, 3),
        "unit": "ms",
        # two ratios, deliberately both: vs the reference's 100 ms
        # convergence goal AND vs this repo's own 10 ms north star
        "vs_baseline": round(BASELINE_MS / value, 3),
        "vs_northstar": round(NORTHSTAR_MS / value, 3),
        **vs_measured,
        "northstar_scale_note": (
            "north-star target is 100k nodes / v4-32 mesh; this metric "
            f"is {snap0.n} nodes on one {platform} device"
        ),
        "device_only_ms": device_only,
        "host_overhead_ratio": (
            round(value / max(device_only, 1e-3), 2)
            if device_only else None
        ),
        # headline measured ratio from the always-on profiling plane
        # (paired host/device timing per dispatch window) plus per-tag
        # host-touch distributions — the per-stage account that the
        # derived e2e/device ratio above can only approximate
        "host_overhead_ratio_measured": _measured_overhead_ratio(),
        "host_touches_by_tag": _host_touches_by_tag(),
        "pipeline_depth_median": _pipeline_depth_median(),
        "n_nodes": snap0.n,
        "platform": platform,
        # the oracle-gated measured winner (the session finishes with
        # impl="auto" armed so later legs resolve through the
        # autotuner; this field keeps the concrete winner readable)
        "minplus_impl": minplus_winner,
        "minplus_impl_armed": spf_ops.get_minplus_impl(),
        "minplus_ms": minplus_ms,
        "bench_10k_churn": bench_10k,
        "bench_link_churn": bench_link,
        "bench_ksp2_churn": bench_ksp2,
        "bench_route_sweep": bench_routes,
        "bench_route_engine_churn": bench_rchurn,
        "bench_sp_solver_churn": bench_spsolver,
        "bench_sharded_churn": bench_shchurn,
        "bench_ell_kernel": bench_ellkern,
        "bench_convergence_trace": bench_traces,
        "bench_sustained_load": bench_load,
        "bench_multi_tenant": bench_tenancy,
        "bench_recovery": bench_recovery,
        "bench_integrity_audit": bench_integrity,
        "bench_fleet_twin": bench_twin,
        "bench_solver_service": bench_serve,
        # per-event convergence-latency distribution from the telemetry
        # registry (convergence.e2e_ms feeds from every finished trace;
        # the solver-leg histograms ride along) — the artifact's
        # DeltaPath-style account next to the aggregate medians
        "latency_histograms": _histogram_snapshot(),
        # merged solver + resident-band counters accumulated across
        # every leg above — the churn-path health record (incremental
        # syncs, warm/cold solve split, widen and prewarm events)
        "spf_counters": _spf_counter_snapshot(),
        "error": None,
    }


def _pipeline_depth_median() -> "float | None":
    """Median ``ops.pipeline_depth`` observation — how many event
    windows were concurrently in flight at each pipelined submit —
    or None before any window pipelined."""
    try:
        from openr_tpu.telemetry import get_registry

        h = get_registry().histograms().get("ops.pipeline_depth")
        if h is None or not h.count:
            return None
        return round(h.percentile(0.50), 1)
    except Exception:
        return None


def _measured_overhead_ratio() -> "float | None":
    """Live ``ops.host_overhead_ratio`` from the profiling plane:
    sum(window wall) / sum(attributed device time) over the recent
    dispatch windows, or None before any sampled window landed."""
    try:
        from openr_tpu.telemetry import get_profiler

        ratio = get_profiler().host_overhead_ratio()
        return round(ratio, 3) if ratio is not None else None
    except Exception:
        return None


def _host_touches_by_tag() -> dict:
    """Per-tag ``ops.host_touches.<tag>`` snapshots (p50 + count) —
    which dispatch stages pay host turnarounds, and how often."""
    try:
        from openr_tpu.telemetry import get_registry

        reg = get_registry()
        out = {}
        for name, h in sorted(reg.histograms().items()):
            if not name.startswith("ops.host_touches.") or not h.count:
                continue
            tag = name[len("ops.host_touches."):]
            out[tag] = {
                "p50": round(h.percentile(0.50), 3),
                "count": h.count,
            }
        return out
    except Exception:
        return {}


def _histogram_snapshot() -> dict:
    """Every non-empty registry histogram, expanded to percentiles."""
    try:
        from openr_tpu.telemetry import get_registry

        out = {}
        for h in get_registry().histograms().values():
            if h.count:
                out.update(h.stats())
        return out
    except Exception:
        return {}


def _spf_counter_snapshot() -> dict:
    try:
        from openr_tpu.decision.spf_solver import get_spf_counters

        return {
            k: v for k, v in sorted(get_spf_counters().items()) if v
        }
    except Exception:
        return {}


def _child_main(mode: str) -> None:
    """Run the benchmark in a child process and print its JSON line."""
    out = {
        "metric": METRIC_NAME,
        "value": None,
        "unit": "ms",
        "vs_baseline": None,
        "vs_northstar": None,
        "error": None,
    }
    try:
        if mode == "cpu":
            from openr_tpu.testing import pin_host_cpu

            pin_host_cpu()
        out = _run()
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
        out["traceback_tail"] = traceback.format_exc().splitlines()[-4:]
    print(json.dumps(out))


def _spawn(mode: str, timeout_s: int, with_10k: bool = False):
    """Run this file in child mode; return (parsed json | None, note)."""
    env = dict(os.environ, OPENR_BENCH_CHILD=mode)
    if with_10k:
        # the optional legs share a fate: all ride the larger child
        # timeout and all are dropped together on the retry path
        env["OPENR_BENCH_10K"] = "1"
        env["OPENR_BENCH_KSP2"] = "1"
        env["OPENR_BENCH_ROUTES"] = "1"
        env["OPENR_BENCH_TRACES"] = "1"
        env["OPENR_BENCH_LOAD"] = "1"
        env["OPENR_BENCH_TENANCY"] = "1"
        env["OPENR_BENCH_RECOVERY"] = "1"
        env["OPENR_BENCH_INTEGRITY"] = "1"
        env["OPENR_BENCH_TWIN"] = "1"
        env["OPENR_BENCH_SERVE"] = "1"
        env["OPENR_BENCH_ELLKERN"] = "1"
    else:
        env.pop("OPENR_BENCH_10K", None)
        env.pop("OPENR_BENCH_KSP2", None)
        env.pop("OPENR_BENCH_ROUTES", None)
        env.pop("OPENR_BENCH_TRACES", None)
        env.pop("OPENR_BENCH_LOAD", None)
        env.pop("OPENR_BENCH_TENANCY", None)
        env.pop("OPENR_BENCH_RECOVERY", None)
        env.pop("OPENR_BENCH_INTEGRITY", None)
        env.pop("OPENR_BENCH_TWIN", None)
        env.pop("OPENR_BENCH_SERVE", None)
        env.pop("OPENR_BENCH_ELLKERN", None)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"{mode} child timed out after {timeout_s}s"
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    # a child that died before printing JSON (native abort, import error)
    # leaves its only diagnostic on stderr — surface the tail
    err_tail = " | ".join(
        proc.stderr.decode(errors="replace").splitlines()[-3:]
    )
    return None, (
        f"{mode} child rc={proc.returncode}, no JSON line"
        + (f"; stderr: {err_tail}" if err_tail else "")
    )


def _probe_tpu(timeout_s: int) -> tuple[bool, str]:
    """Check that the default (relay) backend initializes AND completes a
    trivial device round trip, under a hard timeout. jax.devices() itself
    has been observed to hang on the relay, hence the subprocess."""
    code = (
        "import jax, jax.numpy as jnp, numpy as np\n"
        "d = jax.devices()[0]\n"
        "x = jnp.ones((8, 8), jnp.float32)\n"
        "assert float(np.asarray(x @ x).sum()) == 512.0\n"
        "print('PLATFORM=' + d.platform)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend probe hung (> {timeout_s}s)"
    out = proc.stdout.decode(errors="replace")
    for line in out.splitlines():
        if line.startswith("PLATFORM="):
            plat = line.split("=", 1)[1].strip()
            if plat == "cpu":
                return False, "default backend is cpu"
            return True, plat
    return False, f"backend probe failed rc={proc.returncode}"


def main() -> None:
    child = os.environ.get("OPENR_BENCH_CHILD")
    if child:
        _child_main(child)
        return

    t_start = time.monotonic()

    def elapsed() -> float:
        return time.monotonic() - t_start

    notes = []
    attempts = []  # evidence trail: every probe, with timestamps

    def probe(timeout_s: int) -> bool:
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        ok, info = _probe_tpu(timeout_s)
        attempts.append(
            {
                "utc": stamp,
                "at_s": round(elapsed(), 1),
                "timeout_s": timeout_s,
                "ok": ok,
                "info": info,
            }
        )
        return ok

    def emit(result: dict) -> None:
        result["probe_attempts"] = attempts
        print(json.dumps(result))

    # escalating probe schedule: the relay has hung >115s and recovered
    # within the same round before — one 60s attempt is not evidence
    ok = False
    for timeout_s in PROBE_TIMEOUTS_S:
        ok = probe(timeout_s)
        if ok or elapsed() > PROBE_BUDGET_S:
            break

    if ok:
        result, note = _spawn(
            "tpu", TPU_CHILD_10K_TIMEOUT_S, with_10k=True
        )
        if result is not None and result.get("error") is None:
            emit(result)
            return
        notes.append(note or f"tpu child error: {result.get('error')}")
        # the relay can die mid-run: re-probe once and retry WITHOUT the
        # optional 10k leg before degrading to CPU
        if elapsed() < BENCH_SOFT_BUDGET_S and probe(RETRY_PROBE_TIMEOUT_S):
            result, note = _spawn("tpu", TPU_CHILD_TIMEOUT_S)
            if result is not None and result.get("error") is None:
                emit(result)
                return
            notes.append(note or f"tpu retry error: {result.get('error')}")
    else:
        notes.append(
            f"tpu unavailable after {len(attempts)} probes"
        )

    # Degraded path: a number on the host CPU is better than no number.
    with_10k = elapsed() < BENCH_SOFT_BUDGET_S
    result, note = _spawn(
        "cpu",
        CPU_CHILD_10K_TIMEOUT_S if with_10k else CPU_CHILD_TIMEOUT_S,
        with_10k=with_10k,
    )
    if result is None and with_10k:
        # the 10k leg blowing the child timeout must not cost the
        # headline number
        notes.append(note or "cpu+10k child failed")
        result, note = _spawn("cpu", CPU_CHILD_TIMEOUT_S)
    if result is not None:
        result["fallback"] = "; ".join(notes)
        # carry the most recent REAL-TPU capture of this same benchmark
        # (self-recorded mid-round when the relay was healthy) so a
        # relay outage does not erase the round's on-chip evidence from
        # the official artifact. Newest BENCH_r*_midround.json wins —
        # no per-round hand edit, and the round is read from the file.
        try:
            import glob

            candidates = sorted(
                glob.glob(
                    os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_r*_midround.json",
                    )
                )
            )
            with open(candidates[-1]) as f:
                preserved = json.load(f)
            result["last_known_tpu"] = {
                "captured_artifact": os.path.basename(candidates[-1]),
                "note": preserved.get("note"),
                "value": preserved["result"]["value"],
                "device_only_ms": preserved["result"]["device_only_ms"],
                "platform": preserved["result"]["platform"],
                "minplus_ms": preserved["result"]["minplus_ms"],
                "bench_10k_churn": preserved["result"][
                    "bench_10k_churn"
                ],
            }
        except (OSError, KeyError, IndexError, TypeError,
                json.JSONDecodeError):
            # best-effort enrichment must never break the emit
            # guarantee (a malformed/absent preserved file included)
            pass
        emit(result)
        return
    notes.append(note or "cpu child failed")
    emit(
        {
            "metric": METRIC_NAME,
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "vs_northstar": None,
            "error": "; ".join(n for n in notes if n),
        }
    )


if __name__ == "__main__":
    main()
