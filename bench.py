"""Reconvergence benchmark: route-rebuild SPF after a topology change.

Scenario (mirrors the reference Decision benchmarks,
openr/decision/tests/DecisionBenchmark.cpp: BM_DecisionFabric, and its
<100 ms convergence design goal, openr/docs/Introduction/Overview.md:28):

  A ~1000-node 3-tier fat-tree is resident as a compiled snapshot on the
  device. One adjacency metric changes (link churn). Measured latency =
  incremental LinkState merge + ONE fused device dispatch (scatter the
  changed metric rows into the resident matrix + batched SPF from this
  node and every neighbor — exactly the rows a route rebuild consumes for
  best-path selection, ECMP first hops, and LFA; reference
  Decision.cpp:1124 getNextHopsWithMetric, :1192) + distance/first-hop
  readback to the host.

Prints one JSON line:
  {"metric": ..., "value": ms, "unit": "ms", "vs_baseline": x}
where vs_baseline is the speedup vs the reference's 100 ms convergence
design goal (>1.0 means faster than the goal).
"""

from __future__ import annotations

import json
import statistics
import time

import numpy as np



def main() -> None:
    import jax.numpy as jnp

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.graph.snapshot import INF, SnapshotCache, pad_patch_rows
    from openr_tpu.models import topologies
    from openr_tpu.ops import spf as spf_ops
    from openr_tpu.types import Adjacency, AdjacencyDatabase

    snapshots = SnapshotCache()

    topo = topologies.fat_tree_nodes(1000)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])

    churn_node = "fsw-0-0"
    my_node = "rsw-0-0"

    def churn(step: int) -> None:
        """Bump one adjacency metric on churn_node (incremental update)."""
        db = ls.get_adjacency_databases()[churn_node]
        adjs = list(db.adjacencies)
        a0 = adjs[0]
        adjs[0] = Adjacency(
            other_node_name=a0.other_node_name,
            if_name=a0.if_name,
            metric=2 + (step % 5),
            next_hop_v6=a0.next_hop_v6,
            next_hop_v4=a0.next_hop_v4,
            adj_label=a0.adj_label,
            is_overloaded=a0.is_overloaded,
            rtt=a0.rtt,
            timestamp=a0.timestamp,
            weight=a0.weight,
            other_if_name=a0.other_if_name,
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=db.this_node_name,
                is_overloaded=db.is_overloaded,
                adjacencies=tuple(adjs),
                node_label=db.node_label,
                area=db.area,
            )
        )

    # resident device state, owned by the bench loop
    snap0 = snapshots.get(ls)
    sid = snap0.node_index[my_node]
    batch, srcs_dev = spf_ops.source_batch(snap0, sid)
    bucket = srcs_dev.shape[0]
    state = {"metric_dev": jnp.asarray(snap0.metric)}
    noop_ids = np.asarray([sid] * 8, dtype=np.int32)

    def reconverge():
        snap = snapshots.get(ls)
        plan = snap.patch_plan()
        ids = pad_patch_rows(plan[0]) if plan is not None else None
        if ids is None:
            # full (re)compile or oversized change: upload the whole matrix
            state["metric_dev"] = jnp.asarray(snap.metric)
            ids = noop_ids
        vals = snap.metric[ids, :]
        # one fused dispatch: scatter + batched SPF + first hops. The
        # overloaded mask rides along on every step (patch_plan covers
        # metric rows only; this is an O(N) async upload).
        m2, packed = spf_ops.reconverge_step(
            state["metric_dev"],
            jnp.asarray(ids),
            jnp.asarray(vals),
            jnp.asarray(snap.overloaded),
            srcs_dev,
        )
        state["metric_dev"] = m2
        # Honest completion signal: read back the packed distance +
        # first-hop rows route selection consumes. On relay-backed
        # platforms a bare block_until_ready can ack before the device
        # round trip; a data-dependent readback cannot. One device->host
        # sync per reconvergence.
        packed_host = np.asarray(packed)
        d_host = packed_host[:bucket]
        fh_host = packed_host[bucket:].astype(bool)
        return d_host, fh_host

    def oracle_gate(d_host, fh_host) -> bool:
        """Device distances + ECMP first hops vs the host Dijkstra oracle
        (reference runSpf semantics), exact."""
        oracle = ls.run_spf(my_node)
        names = snap0.node_names
        for dst, res in oracle.items():
            did = snap0.node_index[dst]
            if d_host[0, did] != res.metric:
                return False
            if dst != my_node:
                got_nh = {
                    names[batch[i]]
                    for i in np.nonzero(fh_host[: len(batch), did])[0]
                }
                if got_nh != res.next_hops:
                    return False
        for dst in set(names) - set(oracle):
            if d_host[0, snap0.node_index[dst]] < INF:
                return False
        return True

    # warm-up (jit compile + first snapshot). Probe the pallas min-plus
    # kernel; fall back to the fused-jnp formulation on any failure —
    # including a silent miscompile caught by the oracle gate.
    try:
        spf_ops.set_minplus_impl("pallas")
        d_host, fh_host = reconverge()
        if not oracle_gate(d_host, fh_host):
            raise RuntimeError("pallas min-plus failed the oracle gate")
    except Exception:
        spf_ops.set_minplus_impl("jnp")
        snapshots.invalidate()  # rebuild resident state from scratch
        d_host, fh_host = reconverge()
        assert oracle_gate(d_host, fh_host), "device SPF failed oracle gate"

    # one churn+reconverge outside the timed loop: the first patched
    # snapshot compiles the fused scatter+SPF program (one-time cost)
    churn(99)
    reconverge()

    samples = []
    for step in range(10):
        churn(step)
        t0 = time.perf_counter()
        reconverge()
        samples.append((time.perf_counter() - t0) * 1000.0)

    value = statistics.median(samples)
    baseline_ms = 100.0  # reference convergence design goal
    print(
        json.dumps(
            {
                "metric": f"spf_reconvergence_ms_fattree_{snap0.n}",
                "value": round(value, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / value, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
