"""Reconvergence benchmark: full all-sources SPF after a topology change.

Scenario (mirrors the reference Decision benchmarks,
openr/decision/tests/DecisionBenchmark.cpp: BM_DecisionFabric, and its
<100 ms convergence design goal, openr/docs/Introduction/Overview.md:28):

  A ~1000-node 3-tier fat-tree is resident as a compiled snapshot. One
  adjacency metric changes (link churn). Measured latency = incremental
  LinkState merge + snapshot recompile + device all-sources SPF (every
  node's distance vector; the reference computes *one* source per SPF
  call) + ECMP first-hop matrix for this node, result on host.

Prints one JSON line:
  {"metric": ..., "value": ms, "unit": "ms", "vs_baseline": x}
where vs_baseline is the speedup vs the reference's 100 ms convergence
design goal (>1.0 means faster than the goal).
"""

from __future__ import annotations

import json
import statistics
import time

import numpy as np


def main() -> None:
    import jax.numpy as jnp

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.graph.snapshot import SnapshotCache
    from openr_tpu.models import topologies
    from openr_tpu.ops import spf as spf_ops
    from openr_tpu.types import Adjacency, AdjacencyDatabase

    snapshots = SnapshotCache()

    topo = topologies.fat_tree_nodes(1000)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])

    churn_node = "fsw-0-0"
    my_node = "rsw-0-0"

    def churn(step: int) -> None:
        """Bump one adjacency metric on churn_node (incremental update)."""
        db = ls.get_adjacency_databases()[churn_node]
        adjs = list(db.adjacencies)
        a0 = adjs[0]
        adjs[0] = Adjacency(
            other_node_name=a0.other_node_name,
            if_name=a0.if_name,
            metric=2 + (step % 5),
            next_hop_v6=a0.next_hop_v6,
            next_hop_v4=a0.next_hop_v4,
            adj_label=a0.adj_label,
            is_overloaded=a0.is_overloaded,
            rtt=a0.rtt,
            timestamp=a0.timestamp,
            weight=a0.weight,
            other_if_name=a0.other_if_name,
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=db.this_node_name,
                is_overloaded=db.is_overloaded,
                adjacencies=tuple(adjs),
                node_label=db.node_label,
                area=db.area,
            )
        )

    def reconverge():
        snap = snapshots.get(ls)  # incremental patch on steady-state churn
        sid = snap.node_index[my_node]
        metric_dev, hop_dev, overloaded_dev = snap.device_arrays()
        d_src, d_all, fh = spf_ops.spf_from_source_with_first_hops(
            metric_dev, hop_dev, overloaded_dev, jnp.int32(sid)
        )
        # Honest completion signal: read this node's distance vector back
        # to the host (what route selection consumes). On relay-backed
        # platforms a bare block_until_ready can ack before the device
        # round trip; a data-dependent readback cannot. This is one
        # device->host sync per reconvergence.
        d_src_host = np.asarray(d_src)
        return snap, d_all, d_src_host

    # warm-up (jit compile + first snapshot; the readback inside
    # reconverge also arms true-sync mode on relay-backed platforms, so
    # every timed sample below measures a genuine device round trip).
    # Probe the pallas min-plus kernel first; fall back to the fused-jnp
    # formulation on any failure.
    try:
        spf_ops.set_minplus_impl("pallas")
        snap, d_all, _ = reconverge()
    except Exception:
        spf_ops.set_minplus_impl("jnp")
        snap, d_all, _ = reconverge()
    # whichever implementation survived, compare a reference row against
    # the jnp path once to guard against silent miscompiles
    if spf_ops.get_minplus_impl() == "pallas":
        spf_ops.set_minplus_impl("jnp")
        _, d_check, _ = reconverge()
        spf_ops.set_minplus_impl("pallas")
        if not np.array_equal(np.asarray(d_all), np.asarray(d_check)):
            spf_ops.set_minplus_impl("jnp")
        snap, d_all, _ = reconverge()
    n = snap.n

    # one churn+reconverge outside the timed loop: the first patched
    # snapshot compiles the row-scatter program (one-time cost)
    churn(99)
    reconverge()

    samples = []
    for step in range(10):
        churn(step)
        t0 = time.perf_counter()
        reconverge()
        samples.append((time.perf_counter() - t0) * 1000.0)

    value = statistics.median(samples)
    baseline_ms = 100.0  # reference convergence design goal
    print(
        json.dumps(
            {
                "metric": f"full_spf_reconvergence_ms_fattree_{n}",
                "value": round(value, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / value, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
