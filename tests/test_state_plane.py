"""Crash-safe state plane (openr_tpu.state): write-ahead journal +
checkpoint round trips through the PersistentStore, the KvStore merge
hook, the ``state.checkpoint_write`` fault seam, the config store's
no-silent-swallow corruption path, and the watchdog stall counters."""

import os
import time

from openr_tpu.config_store.persistent_store import PersistentStore
from openr_tpu.faults import FaultSchedule, get_injector
from openr_tpu.monitor.watchdog import Watchdog
from openr_tpu.state import LsdbCheckpoint, StatePlane
from openr_tpu.telemetry import get_registry
from openr_tpu.types import KeySetParams, Value
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import OpenrEventBase


def val(version=1, originator="node-a", value=b"v"):
    return Value(
        version=version,
        originator_id=originator,
        value=value,
        hash=wire.generate_hash(version, originator, value),
    )


def wait_until(pred, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def make_plane(tmp_path, name="state.bin", **kw):
    store = PersistentStore(str(tmp_path / name))
    return store, StatePlane(store, **kw)


class TestStatePlane:
    def test_journal_replay_roundtrip(self, tmp_path):
        store, plane = make_plane(tmp_path)
        plane.on_kvstore_merge("0", {"adj:a": val(1, "a")})
        plane.on_kvstore_merge("0", {"adj:b": val(1, "b")})
        plane.on_kvstore_merge("1", {"adj:c": val(2, "c")})
        # newer version of an earlier key: replay must keep the winner
        plane.on_kvstore_merge("0", {"adj:a": val(3, "a", b"v3")})
        store.stop()

        store2 = PersistentStore(str(tmp_path / "state.bin"))
        rec = StatePlane(store2).recover()
        assert not rec.had_checkpoint
        assert rec.journal_replayed == 4
        assert sorted(rec.key_vals_by_area) == ["0", "1"]
        assert rec.key_vals_by_area["0"]["adj:a"].version == 3
        assert rec.key_vals_by_area["0"]["adj:a"].value == b"v3"
        assert rec.key_vals_by_area["0"]["adj:b"].version == 1
        assert rec.key_vals_by_area["1"]["adj:c"].originator_id == "c"
        store2.stop()

    def test_checkpoint_collapses_journal(self, tmp_path):
        store, plane = make_plane(tmp_path)
        for i in range(5):
            plane.on_kvstore_merge("0", {f"k{i}": val(1, "a")})
        assert plane.journal_length() == 5
        plane.checkpoint()
        assert plane.journal_length() == 0
        # post-checkpoint appends journal again
        plane.on_kvstore_merge("0", {"k9": val(1, "a")})
        assert plane.journal_length() == 1
        store.stop()

        store2 = PersistentStore(str(tmp_path / "state.bin"))
        journal_keys = [
            k for k in store2.keys() if k.startswith("state:lsdb:journal:")
        ]
        assert len(journal_keys) == 1  # pre-checkpoint records erased
        rec = StatePlane(store2).recover()
        assert rec.had_checkpoint
        assert rec.journal_replayed == 1
        assert sorted(rec.key_vals_by_area["0"]) == [
            "k0", "k1", "k2", "k3", "k4", "k9",
        ]
        store2.stop()

    def test_auto_checkpoint_at_threshold(self, tmp_path):
        store, plane = make_plane(tmp_path, checkpoint_every=4)
        for i in range(4):
            plane.on_kvstore_merge("0", {f"k{i}": val(1, "a")})
        # the 4th append crossed the threshold and cut a checkpoint
        assert plane.journal_length() == 0
        assert store.load("state:lsdb:ckpt", LsdbCheckpoint) is not None
        store.stop()

    def test_checkpoint_write_seam_leaves_journal_intact(self, tmp_path):
        reg = get_registry()
        store, plane = make_plane(tmp_path)
        for i in range(3):
            plane.on_kvstore_merge("0", {f"k{i}": val(1, "a")})
        inj = get_injector()
        inj.reset()
        inj.arm("state.checkpoint_write", FaultSchedule.fail_once())
        before = reg.counter_get("state.checkpoint_failures")
        assert plane.maybe_checkpoint() is False
        assert reg.counter_get("state.checkpoint_failures") == before + 1
        # journal untouched: recovery replays everything
        assert plane.journal_length() == 3
        store.stop()
        store2 = PersistentStore(str(tmp_path / "state.bin"))
        rec = StatePlane(store2).recover()
        assert not rec.had_checkpoint
        assert rec.journal_replayed == 3
        assert sorted(rec.key_vals_by_area["0"]) == ["k0", "k1", "k2"]
        store2.stop()
        # the seam self-heals: next attempt commits
        store3, plane3 = make_plane(tmp_path, name="other.bin")
        plane3.on_kvstore_merge("0", {"k": val(1, "a")})
        assert plane3.maybe_checkpoint() is True
        store3.stop()
        inj.reset()

    def test_recovered_plane_continues_journaling(self, tmp_path):
        store, plane = make_plane(tmp_path)
        plane.on_kvstore_merge("0", {"a": val(1, "a")})
        plane.checkpoint()
        plane.on_kvstore_merge("0", {"b": val(1, "b")})
        store.stop()

        store2 = PersistentStore(str(tmp_path / "state.bin"))
        plane2 = StatePlane(store2)
        plane2.recover()
        # seq continues past the crashed process's journal
        plane2.on_kvstore_merge("0", {"c": val(1, "c")})
        store2.stop()

        store3 = PersistentStore(str(tmp_path / "state.bin"))
        rec = StatePlane(store3).recover()
        assert sorted(rec.key_vals_by_area["0"]) == ["a", "b", "c"]
        store3.stop()


class TestKvStoreJournalHook:
    def test_merge_hook_journals_accepted_updates(self, tmp_path):
        from openr_tpu.kvstore.store import KvStore

        store, plane = make_plane(tmp_path)
        kv = KvStore("node-a", areas=["0"], state_plane=plane)
        kv.start()
        try:
            kv.set_key_vals(
                "0", KeySetParams(key_vals={"adj:a": val(1, "a")})
            )
            # a re-merge of the SAME value is a no-op: no journal record
            kv.set_key_vals(
                "0", KeySetParams(key_vals={"adj:a": val(1, "a")})
            )
            kv.set_key_vals(
                "0", KeySetParams(key_vals={"adj:b": val(2, "b")})
            )
            assert wait_until(lambda: plane.journal_length() == 2)
        finally:
            kv.stop()
            store.stop()

        store2 = PersistentStore(str(tmp_path / "state.bin"))
        rec = StatePlane(store2).recover()
        assert sorted(rec.key_vals_by_area["0"]) == ["adj:a", "adj:b"]
        store2.stop()


class TestPersistentStoreCorruption:
    def test_truncated_file_counted_and_kept(self, tmp_path):
        reg = get_registry()
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        store.store("drain-state", {"is_overloaded": True})
        store.store("node-label", 42)
        store.stop()
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[: len(raw) // 2])

        before = reg.counter_get("config_store.load_errors")
        store2 = PersistentStore(path)
        # no silent swallow: counted, corrupt bytes kept for forensics,
        # store starts empty instead of crashing
        assert reg.counter_get("config_store.load_errors") == before + 1
        assert os.path.exists(path + ".tmp")
        with open(path + ".tmp", "rb") as f:
            assert f.read() == raw[: len(raw) // 2]
        assert store2.load("node-label") is None
        # the store still works: fresh writes land and reload
        store2.store("node-label", 7)
        store2.stop()
        store3 = PersistentStore(path)
        assert store3.load("node-label") == 7
        store3.stop()

    def test_missing_file_is_not_an_error(self, tmp_path):
        reg = get_registry()
        before = reg.counter_get("config_store.load_errors")
        store = PersistentStore(str(tmp_path / "absent.bin"))
        assert store.load("k") is None
        assert reg.counter_get("config_store.load_errors") == before
        store.stop()


class TestWatchdogStallCounters:
    def test_blocked_evb_bumps_stall_counters(self):
        reg = get_registry()
        crashes = []
        wd = Watchdog(
            interval_s=10.0,  # never fires on its own; we drive _check
            thread_timeout_s=0.05,
            crash_handler=crashes.append,
        )
        victim = OpenrEventBase(name="victim")
        victim.run_in_thread()
        victim.wait_until_running()
        healthy = OpenrEventBase(name="healthy")
        healthy.run_in_thread()
        healthy.wait_until_running()
        wd.add_evb("victim", victim)
        wd.add_evb("healthy", healthy)
        try:
            release = __import__("threading").Event()
            victim.run_in_event_base(lambda: release.wait(2.0))
            before = reg.counter_get("watchdog.stalls.victim")
            assert wait_until(
                lambda: time.monotonic() - victim.last_loop_ts > 0.1
            )
            healthy.run_in_event_base(lambda: None)  # keep it fresh
            wd._check()
            assert reg.counter_get("watchdog.stalls.victim") == before + 1
            assert reg.counter_get("watchdog.stalls.healthy") == 0
            assert reg.snapshot().get("watchdog.stalled") == 1
            assert crashes and "victim" in crashes[0]
            # the gauge clears once the loop unblocks
            release.set()
            assert wait_until(
                lambda: time.monotonic() - victim.last_loop_ts < 0.05
            )
            wd._check()
            assert reg.snapshot().get("watchdog.stalled") == 0
        finally:
            release.set()
            victim.stop()
            victim.join()
            healthy.stop()
            healthy.join()
