"""Config layer tests (reference analogue: openr/config/tests)."""

import json

import pytest

from openr_tpu.config.config import (
    AreaConfig,
    ConfigError,
    OpenrConfig,
    SparkConfig,
)
from openr_tpu.types.lsdb import PrefixForwardingAlgorithm, PrefixForwardingType


class TestValidation:
    def test_minimal_valid(self):
        cfg = OpenrConfig(node_name="node-1")
        assert cfg.area_ids() == ["0"]

    def test_node_name_required(self):
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="")

    def test_node_name_charset(self):
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="bad name")
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="bad:name")

    def test_duplicate_areas_rejected(self):
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="n",
                areas=[AreaConfig(area_id="a"), AreaConfig(area_id="a")],
            )

    def test_spark_hold_time_validation(self):
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="n",
                spark=SparkConfig(keepalive_time_s=5.0, hold_time_s=10.0),
            )

    def test_ksp2_requires_sr_mpls(self):
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="n",
                prefix_forwarding_algorithm=(
                    PrefixForwardingAlgorithm.KSP2_ED_ECMP
                ),
                prefix_forwarding_type=PrefixForwardingType.IP,
            )
        # valid combination passes
        OpenrConfig(
            node_name="n",
            prefix_forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            prefix_forwarding_type=PrefixForwardingType.SR_MPLS,
        )


class TestSerialization:
    def test_json_roundtrip(self, tmp_path):
        cfg = OpenrConfig(
            node_name="fc001",
            areas=[
                AreaConfig(
                    area_id="spine",
                    neighbor_regexes=["ssw.*"],
                    include_interface_regexes=["eth.*"],
                )
            ],
            enable_v4=True,
            prefix_forwarding_type=PrefixForwardingType.SR_MPLS,
        )
        path = tmp_path / "cfg.json"
        path.write_text(json.dumps(cfg.to_dict()))
        loaded = OpenrConfig.from_file(str(path))
        assert loaded.node_name == "fc001"
        assert loaded.enable_v4
        assert loaded.areas[0].area_id == "spine"
        assert loaded.prefix_forwarding_type == PrefixForwardingType.SR_MPLS

    def test_area_matching(self):
        cfg = OpenrConfig(
            node_name="n",
            areas=[
                AreaConfig(area_id="spine", neighbor_regexes=["ssw-.*"]),
                AreaConfig(area_id="pod", neighbor_regexes=["rsw-.*"]),
            ],
        )
        assert cfg.area_for_neighbor("ssw-1-2") == "spine"
        assert cfg.area_for_neighbor("rsw-0-1") == "pod"
        assert cfg.area_for_neighbor("other") is None

    def test_interface_matching(self):
        area = AreaConfig(
            include_interface_regexes=["eth[0-9]+"],
            exclude_interface_regexes=["eth99"],
        )
        assert area.matches_interface("eth0")
        assert not area.matches_interface("eth99")
        assert not area.matches_interface("lo")


def test_main_flag_config_builds():
    from openr_tpu.main import build_config, parse_args

    args = parse_args(
        ["--node-name", "fc001", "--areas", "0,1", "--enable-v4"]
    )
    cfg = build_config(args)
    assert cfg.node_name == "fc001"
    assert cfg.area_ids() == ["0", "1"]
    assert cfg.enable_v4


class TestGflagShim:
    """reference: openr/config/GflagConfig.h createConfigFromGflag +
    openr/common/Flags.cpp flag dialect."""

    def test_parse_dialect(self):
        from openr_tpu.config.gflags import parse_gflags

        r = parse_gflags(
            [
                "--node_name=fc42",
                "--openr_ctrl_port", "3018",
                "--dryrun",
                "--noenable_watchdog",
                "--enable_v4=true",
                "--enable_lfa=false",
                "--tls_ticket_seed_path=/x",  # outside the subset
            ]
        )
        assert r["node_name"] == "fc42"
        assert r["openr_ctrl_port"] == 3018
        assert r["dryrun"] is True
        assert r["enable_watchdog"] is False
        assert r["enable_v4"] is True
        assert r["enable_lfa"] is False
        assert "tls_ticket_seed_path" in r.unknown

    def test_config_translation(self):
        from openr_tpu.config.gflags import (
            config_from_gflags,
            parse_gflags,
        )
        from openr_tpu.types.lsdb import (
            PrefixForwardingAlgorithm,
            PrefixForwardingType,
        )

        cfg = config_from_gflags(
            parse_gflags(
                [
                    "--node_name=fc42",
                    "--areas=pod,spine",
                    "--listen_addr=*",
                    "--prefix_fwd_type_mpls",
                    "--prefix_algo_type_ksp2_ed_ecmp",
                    "--kvstore_key_ttl_ms=60000",
                    "--decision_debounce_max_ms=500",
                    "--link_flap_initial_backoff_ms=1000",
                    "--spark2_heartbeat_hold_time_s=30",
                    "--iface_regex_include=eth.*,po.*",
                    "--memory_limit_mb=450",
                ]
            )
        )
        assert cfg.node_name == "fc42"
        assert cfg.area_ids() == ["pod", "spine"]
        assert cfg.listen_addr == "::"
        assert cfg.prefix_forwarding_type == PrefixForwardingType.SR_MPLS
        assert (
            cfg.prefix_forwarding_algorithm
            == PrefixForwardingAlgorithm.KSP2_ED_ECMP
        )
        assert cfg.kvstore.key_ttl_ms == 60000
        assert cfg.decision.debounce_max_ms == 500
        assert cfg.link_monitor.linkflap_initial_backoff_ms == 1000
        assert cfg.spark.hold_time_s == 30.0
        assert cfg.watchdog.max_memory_mb == 450
        for area in cfg.areas:
            assert area.matches_interface("eth0")
            assert not area.matches_interface("lo")

    def test_invalid_combo_rejected(self):
        import pytest as _pytest

        from openr_tpu.config.config import ConfigError
        from openr_tpu.config.gflags import (
            config_from_gflags,
            parse_gflags,
        )

        # KSP2 without SR-MPLS is invalid in the typed config, exactly
        # like a hand-written JSON config
        with _pytest.raises(ConfigError):
            config_from_gflags(
                parse_gflags(
                    ["--node_name=x", "--prefix_algo_type_ksp2_ed_ecmp"]
                )
            )

    def test_config_file_wins(self, tmp_path):
        import json as _json

        from openr_tpu.config.gflags import load_config_from_argv

        path = tmp_path / "node.json"
        path.write_text(_json.dumps({"node_name": "from-file"}))
        cfg = load_config_from_argv(
            [f"--config={path}", "--node_name=from-flag"]
        )
        assert cfg.node_name == "from-file"

    def test_main_accepts_legacy_argv(self):
        from openr_tpu.main import build_config, parse_args

        args = parse_args(
            ["--node_name=fc9", "--areas=0", "--enable_v4"]
        )
        cfg = build_config(args)
        assert cfg.node_name == "fc9"
        assert cfg.enable_v4


class TestGflagShimRegressions:
    """Regressions from review: shared-spelling flags must reach the
    shim; native typos must fail fast; every accepted flag translates."""

    def test_shared_spelling_flags_reach_shim(self):
        # --areas/--dryrun exist in BOTH dialects; a legacy invocation
        # must not have them swallowed (and defaulted) by argparse
        from openr_tpu.main import build_config, parse_args

        args = parse_args(
            ["--node_name=fc42", "--areas=pod,spine", "--dryrun"]
        )
        cfg = build_config(args)
        assert cfg.node_name == "fc42"
        assert cfg.area_ids() == ["pod", "spine"]
        assert cfg.dryrun is True

    def test_native_typo_fails_fast(self):
        import pytest as _pytest

        from openr_tpu.main import parse_args

        with _pytest.raises(SystemExit):
            parse_args(["--node-name", "fc1", "--enable-v4x"])

    def test_prefix_alloc_flags_translate(self):
        from openr_tpu.config.gflags import (
            config_from_gflags,
            parse_gflags,
        )

        cfg = config_from_gflags(
            parse_gflags(
                [
                    "--node_name=fc1",
                    "--enable_prefix_alloc",
                    "--seed_prefix=fc00:cafe::/56",
                    "--alloc_prefix_len=64",
                    "--set_loopback_address",
                    "--loopback_iface=lo1",
                    "--spark_mcast_port=7777",
                    "--per_prefix_keys=false",
                ]
            )
        )
        assert cfg.prefix_alloc.enabled
        assert cfg.prefix_alloc.seed_prefix == "fc00:cafe::/56"
        assert cfg.prefix_alloc.alloc_prefix_len == 64
        assert cfg.prefix_alloc.set_loopback_addr
        assert cfg.prefix_alloc.loopback_iface == "lo1"
        assert cfg.spark.mcast_port == 7777
        assert cfg.per_prefix_keys is False

    def test_untranslated_flags_are_reported(self):
        from openr_tpu.config.gflags import parse_gflags

        r = parse_gflags(["--node_name=x", "--bgp_min_nexthop=2"])
        # flags with no config mapping are NOT silently accepted
        assert "bgp_min_nexthop" in r.unknown


def test_partial_flood_rate_rejected():
    import pytest as _pytest

    from openr_tpu.config.config import (
        ConfigError,
        KvStoreConfig,
        OpenrConfig,
    )

    with _pytest.raises(ConfigError):
        OpenrConfig(
            node_name="n",
            kvstore=KvStoreConfig(flood_msg_per_sec=100),
        )
    cfg = OpenrConfig(
        node_name="n",
        kvstore=KvStoreConfig(
            flood_msg_per_sec=100, flood_msg_burst_size=50
        ),
    )
    assert cfg.kvstore.flood_rate() == (100.0, 50)


def test_daemon_wires_decision_feature_flags():
    from openr_tpu.daemon import OpenrNode
    from openr_tpu.spark.io_provider import MockIoProvider

    node = OpenrNode(
        "flags-node",
        MockIoProvider(),
        enable_v4=True,
        enable_lfa=True,
        enable_ordered_fib=True,
        enable_bgp_route_programming=False,
        enable_rib_policy=False,
    )
    solver = node.decision.spf_solver
    assert solver.enable_v4
    assert solver.compute_lfa_paths
    assert solver.enable_ordered_fib
    assert solver.bgp_dry_run  # programming disabled -> dry run
    assert not node.decision._enable_rib_policy


class TestSolverMeshKnob:
    def test_gflag_maps_to_config(self):
        from openr_tpu.config.gflags import (
            config_from_gflags,
            parse_gflags,
        )

        cfg = config_from_gflags(parse_gflags(
            ["--node_name=x", "--enable_solver_mesh"]
        ))
        assert cfg.enable_solver_mesh is True
        cfg = config_from_gflags(parse_gflags(["--node_name=x"]))
        assert cfg.enable_solver_mesh is False

    def test_main_installs_engine_mesh(self, monkeypatch):
        """main() with enable_solver_mesh installs the process-global
        engine mesh before the daemon builds (checked by intercepting
        the daemon constructor — no full boot needed)."""
        from openr_tpu import main as main_mod
        from openr_tpu.decision import ksp2_engine

        ksp2_engine.set_engine_mesh(None)
        seen = {}

        class _Stop(Exception):
            pass

        def fake_node(*a, **kw):
            seen["mesh"] = ksp2_engine.get_engine_mesh()
            raise _Stop

        monkeypatch.setattr(main_mod, "OpenrNode", fake_node)

        # intercept BEFORE main() builds the persistent store: the
        # _Stop abort skips the normal shutdown path, so a real store
        # would leak its event-base thread and touch the machine-wide
        # default /tmp path
        class _NoStore:
            def __init__(self, *a, **kw):
                pass

            def stop(self):
                pass

        import openr_tpu.config_store.persistent_store as _ps

        monkeypatch.setattr(_ps, "PersistentStore", _NoStore)
        try:
            with pytest.raises(_Stop):
                main_mod.main([
                    "--node-name", "mesh-node",
                    "--enable_solver_mesh",
                ])
        finally:
            ksp2_engine.set_engine_mesh(None)
        assert seen["mesh"] is not None
        assert seen["mesh"].devices.size >= 1
