"""Config layer tests (reference analogue: openr/config/tests)."""

import json

import pytest

from openr_tpu.config.config import (
    AreaConfig,
    ConfigError,
    OpenrConfig,
    SparkConfig,
)
from openr_tpu.types.lsdb import PrefixForwardingAlgorithm, PrefixForwardingType


class TestValidation:
    def test_minimal_valid(self):
        cfg = OpenrConfig(node_name="node-1")
        assert cfg.area_ids() == ["0"]

    def test_node_name_required(self):
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="")

    def test_node_name_charset(self):
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="bad name")
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="bad:name")

    def test_duplicate_areas_rejected(self):
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="n",
                areas=[AreaConfig(area_id="a"), AreaConfig(area_id="a")],
            )

    def test_spark_hold_time_validation(self):
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="n",
                spark=SparkConfig(keepalive_time_s=5.0, hold_time_s=10.0),
            )

    def test_ksp2_requires_sr_mpls(self):
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="n",
                prefix_forwarding_algorithm=(
                    PrefixForwardingAlgorithm.KSP2_ED_ECMP
                ),
                prefix_forwarding_type=PrefixForwardingType.IP,
            )
        # valid combination passes
        OpenrConfig(
            node_name="n",
            prefix_forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            prefix_forwarding_type=PrefixForwardingType.SR_MPLS,
        )


class TestSerialization:
    def test_json_roundtrip(self, tmp_path):
        cfg = OpenrConfig(
            node_name="fc001",
            areas=[
                AreaConfig(
                    area_id="spine",
                    neighbor_regexes=["ssw.*"],
                    include_interface_regexes=["eth.*"],
                )
            ],
            enable_v4=True,
            prefix_forwarding_type=PrefixForwardingType.SR_MPLS,
        )
        path = tmp_path / "cfg.json"
        path.write_text(json.dumps(cfg.to_dict()))
        loaded = OpenrConfig.from_file(str(path))
        assert loaded.node_name == "fc001"
        assert loaded.enable_v4
        assert loaded.areas[0].area_id == "spine"
        assert loaded.prefix_forwarding_type == PrefixForwardingType.SR_MPLS

    def test_area_matching(self):
        cfg = OpenrConfig(
            node_name="n",
            areas=[
                AreaConfig(area_id="spine", neighbor_regexes=["ssw-.*"]),
                AreaConfig(area_id="pod", neighbor_regexes=["rsw-.*"]),
            ],
        )
        assert cfg.area_for_neighbor("ssw-1-2") == "spine"
        assert cfg.area_for_neighbor("rsw-0-1") == "pod"
        assert cfg.area_for_neighbor("other") is None

    def test_interface_matching(self):
        area = AreaConfig(
            include_interface_regexes=["eth[0-9]+"],
            exclude_interface_regexes=["eth99"],
        )
        assert area.matches_interface("eth0")
        assert not area.matches_interface("eth99")
        assert not area.matches_interface("lo")


def test_main_flag_config_builds():
    from openr_tpu.main import build_config, parse_args

    args = parse_args(
        ["--node-name", "fc001", "--areas", "0,1", "--enable-v4"]
    )
    cfg = build_config(args)
    assert cfg.node_name == "fc001"
    assert cfg.area_ids() == ["0", "1"]
    assert cfg.enable_v4
