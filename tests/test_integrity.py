"""Integrity plane: silent-corruption detection, quarantine, and warm
healing over every resident engine class.

The contract under test: a seeded bit flip in any resident device
state (ELL, grouped, sharded, world-batch) is detected within ONE
audit pass, healed bit-identical to a from-scratch cold build, and the
emitted route product never flaps — the host mirrors hold the last
verified-good bits throughout, so Fib-facing digests are unchanged
before, during, and after the quarantine. Plus the satellites: the
decorrelated backoff jitter, the disarmed-seam overhead bound, the
``decision.route_staleness_ms`` gauge, grouped snapshot/rehydrate
parity under the shared contract, and the ``mirror-coverage`` lint.
"""

import textwrap

import numpy as np
import pytest

from openr_tpu.faults import (
    DegradationSupervisor,
    FaultSchedule,
    consume_fault,
    fault_point,
    get_injector,
)
from openr_tpu.faults import injector as injector_mod
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.integrity import (
    ResidentEngineContract,
    get_auditor,
    quarantine_active,
    reset_auditor,
)
from openr_tpu.integrity import kernels as ik
from openr_tpu.integrity.auditor import IntegrityAuditor
from openr_tpu.models import topologies
from openr_tpu.ops import route_engine, route_sweep
from openr_tpu.ops import world_batch as wb
from openr_tpu.telemetry import get_registry
from openr_tpu.utils.eventbase import ExponentialBackoff

from tests.test_route_engine_delta import (
    KINDS,
    assert_bit_identical,
    engine_digests,
    load,
    make_engine,
    mutate_metric,
)


@pytest.fixture(autouse=True)
def _clean_plane():
    get_injector().reset()
    reset_auditor()
    yield
    get_injector().reset()
    reset_auditor()


def _topo():
    return topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )


def _fast_supervisor():
    return DegradationSupervisor(
        "route_engine", backoff_min_s=0.001, backoff_max_s=0.002
    )


# ---------------------------------------------------------------------
# digest kernels
# ---------------------------------------------------------------------


class TestDigestKernels:
    def test_device_host_parity(self):
        rng = np.random.default_rng(0)
        for shape in ((1, 1), (7, 3), (64, 33)):
            arr = rng.integers(
                -(2**31), 2**31, size=shape, dtype=np.int64
            ).astype(np.int32)
            assert int(ik.fnv_device(arr)) == ik.fnv_host(arr)

    def test_slots_parity(self):
        rng = np.random.default_rng(1)
        block = rng.integers(
            -(2**31), 2**31, size=(5, 8, 11), dtype=np.int64
        ).astype(np.int32)
        per_slot = np.asarray(ik.fnv_slots(block))
        for s in range(block.shape[0]):
            assert int(per_slot[s]) == ik.fnv_host(block[s])

    def test_row_order_independent(self):
        rng = np.random.default_rng(2)
        arr = rng.integers(
            -(2**31), 2**31, size=(16, 9), dtype=np.int64
        ).astype(np.int32)
        shuffled = arr[rng.permutation(16)]
        assert ik.fnv_host(arr) == ik.fnv_host(shuffled)

    def test_single_bit_sensitivity(self):
        arr = np.zeros((8, 8), dtype=np.int32)
        flipped = arr.copy()
        flipped[3, 5] ^= 1 << 17
        assert ik.fnv_host(arr) != ik.fnv_host(flipped)


# ---------------------------------------------------------------------
# detection + warm heal, all four engine classes
# ---------------------------------------------------------------------


@pytest.mark.parametrize("kind", KINDS)
def test_detect_quarantine_heal_bit_identical(kind):
    ls = load(_topo())
    engine = make_engine(kind, ls)
    engine.supervisor = _fast_supervisor()
    aud = get_auditor()
    assert engine.audit_ready()
    assert aud.audit_now()[-1]["verdict"] == "clean"

    before = engine_digests(engine)
    reg = get_registry()
    q0 = reg.counter_get("integrity.quarantines")
    engine.corrupt_resident(seed=7)
    report = aud.audit_now()[-1]
    # detected within ONE audit pass, healed within the same pass
    assert report["verdict"] == "healed"
    assert report["tier"] in ("residual", "digest", "oracle")
    assert reg.counter_get("integrity.quarantines") == q0 + 1
    assert not quarantine_active()

    # zero route flaps: the served product never changed at all, and
    # the healed residents are bit-identical to a cold build
    assert engine_digests(engine) == before
    assert engine.audit_ready()
    assert_bit_identical(engine, ls, kind)

    # the healed engine still churns warm
    rsw = next(
        n for n in engine.graph.node_names if n.startswith("rsw")
    )
    moved = engine.churn(ls, mutate_metric(ls, rsw, 0, 9))
    assert moved
    assert_bit_identical(engine, ls, kind)


def test_quarantine_poisons_warm_rung_without_heal():
    """Even if integrity_heal never runs, a quarantined engine must not
    serve another warm solve from the suspect residents: the next churn
    walks the ladder past the warm rung and rebuilds."""
    ls = load(_topo())
    engine = make_engine("ell", ls)
    engine.supervisor = _fast_supervisor()
    reg = get_registry()
    walks0 = reg.counter_get("route_engine.rung_failures.warm")
    engine.corrupt_resident(seed=3)
    engine.quarantine("test: manual quarantine")
    assert not engine.audit_ready()
    rsw = next(
        n for n in engine.graph.node_names if n.startswith("rsw")
    )
    moved = engine.churn(ls, mutate_metric(ls, rsw, 0, 13))
    # deeper rungs return None by the cold-rebuild contract — the point
    # is the warm rung REFUSED to serve from the poisoned residents
    assert moved is None
    assert reg.counter_get("route_engine.rung_failures.warm") == walks0 + 1
    assert engine._device_valid  # the rebuild un-poisoned it
    assert_bit_identical(engine, ls, "ell")


def test_oracle_tier_catches_residual_blind_spot(monkeypatch):
    """Tier 3 is the backstop for corruption tiers 1+2 can miss: blind
    them explicitly, raise one resident DR cell, and the sampled cold
    oracle (sampling every row here) must still convict."""
    ls = load(_topo())
    engine = make_engine("ell", ls)
    engine.supervisor = _fast_supervisor()
    monkeypatch.setattr(engine, "audit_residual", lambda: 0)
    monkeypatch.setattr(engine, "audit_digest_pair", lambda: (0, 0))
    aud = IntegrityAuditor(oracle_every=1, sample_rows=engine.graph.n)
    aud.register(engine)
    assert aud.audit_now()[-1]["verdict"] == "clean"
    engine._dr = engine._dr.at[1, 2].set(engine._dr[1, 2] + 1)
    report = aud.audit_now()[-1]
    assert report["tier"] == "oracle"
    # the heal rebuilt real state; the blinded tiers stay patched, so
    # the oracle itself re-audited the healed rows clean
    assert report["verdict"] == "healed"


# ---------------------------------------------------------------------
# world-batch plane
# ---------------------------------------------------------------------


def _world_items(n_tenants=2):
    items = []
    for i in range(n_tenants):
        topo = _topo()
        ls = LinkState(area=topo.area)
        for _name, db in sorted(topo.adj_dbs.items()):
            ls.update_adjacency_database(db)
        names = sorted(ls.get_adjacency_databases())
        items.append((f"tenant{i}", ls, names[i % len(names)]))
    return items


def test_world_batch_detect_quarantine_heal():
    m = wb.WorldManager(slots_per_bucket=4, max_resident=8)
    items = _world_items()
    views = m.solve_views(items)
    aud = get_auditor()
    assert m.audit_ready()
    assert aud.audit_now()[-1]["verdict"] == "clean"

    before = [np.array(v[2], copy=True) for v in views]
    reg = get_registry()
    q0 = reg.counter_get("tenancy.quarantines")
    h0 = reg.counter_get("tenancy.integrity_heals")
    m.corrupt_resident(seed=5)
    report = aud.audit_now()[-1]
    assert report["verdict"] == "healed"
    assert reg.counter_get("tenancy.quarantines") == q0 + 1
    assert reg.counter_get("tenancy.integrity_heals") == h0 + 1

    # the healed tenants serve bit-identical views with no re-solve
    warm0 = reg.counter_get("tenancy.warm_solves")
    cold0 = reg.counter_get("tenancy.cold_solves")
    views2 = m.solve_views(items)
    assert all(
        np.array_equal(a, v2[2]) for a, v2 in zip(before, views2)
    )
    assert reg.counter_get("tenancy.warm_solves") == warm0
    assert reg.counter_get("tenancy.cold_solves") == cold0


def test_world_batch_corruption_seam_on_solve_views():
    m = wb.WorldManager(slots_per_bucket=4, max_resident=8)
    items = _world_items()
    m.solve_views(items)
    reg = get_registry()
    c0 = reg.counter_get("faults.injected.device.corrupt_resident")
    get_injector().arm(
        route_engine.FAULT_CORRUPT, FaultSchedule.fail_once()
    )
    m.solve_views(items)
    assert (
        reg.counter_get("faults.injected.device.corrupt_resident")
        == c0 + 1
    )
    # the flip landed after the dispatches settled: the audit sees it
    assert get_auditor().audit_now()[-1]["verdict"] == "healed"


# ---------------------------------------------------------------------
# the seam + its disarmed cost
# ---------------------------------------------------------------------


def test_corrupt_seam_fires_on_engine_churn():
    ls = load(_topo())
    engine = make_engine("ell", ls)
    engine.supervisor = _fast_supervisor()
    before = engine_digests(engine)
    reg = get_registry()
    c0 = reg.counter_get("faults.injected.device.corrupt_resident")
    get_injector().arm(
        route_engine.FAULT_CORRUPT, FaultSchedule.fail_once()
    )
    rsw = next(
        n for n in engine.graph.node_names if n.startswith("rsw")
    )
    engine.churn(ls, mutate_metric(ls, rsw, 0, 21))
    assert (
        reg.counter_get("faults.injected.device.corrupt_resident")
        == c0 + 1
    )
    # detection within one cadence, heal bit-identical, zero flaps on
    # the UNTOUCHED routes (the churn itself legitimately moved some)
    report = get_auditor().audit_now()[-1]
    assert report["verdict"] == "healed"
    assert_bit_identical(engine, ls, "ell")
    after = engine_digests(engine)
    moved_names = {
        n for n in before if before[n] != after.get(n, before[n])
    }
    assert moved_names  # the metric change really moved routes
    assert set(after) == set(before)  # ...but deleted none


def test_disarmed_seam_never_reaches_injector(monkeypatch):
    """The churn-path overhead contract: a disarmed process pays ONE
    attribute read per seam crossing — the injector's locked paths must
    not even be entered."""
    inj = get_injector()
    inj.reset()

    def _boom(*a, **k):  # pragma: no cover - the assert is the test
        raise AssertionError("disarmed crossing entered the injector")

    monkeypatch.setattr(injector_mod.FaultInjector, "check", _boom)
    monkeypatch.setattr(injector_mod.FaultInjector, "consume", _boom)
    fault_point(route_engine.FAULT_CORRUPT)
    assert consume_fault(route_engine.FAULT_CORRUPT) is False


# ---------------------------------------------------------------------
# decorrelated backoff jitter
# ---------------------------------------------------------------------


class TestBackoffJitter:
    def test_spread_under_fixed_seeds(self):
        firsts = []
        for seed in range(8):
            b = ExponentialBackoff(0.05, 2.0, jitter=True, seed=seed)
            b.report_error()
            d = b.get_current_backoff()
            assert 0.05 <= d <= 2.0
            firsts.append(round(d, 9))
        # eight breakers opening on one event must NOT re-probe in
        # lockstep: the seeded streams spread
        assert len(set(firsts)) >= 6

    def test_bounds_and_determinism(self):
        a = ExponentialBackoff(0.05, 2.0, jitter=True, seed=42)
        b = ExponentialBackoff(0.05, 2.0, jitter=True, seed=42)
        seq_a, seq_b = [], []
        for _ in range(32):
            a.report_error()
            b.report_error()
            seq_a.append(a.get_current_backoff())
            seq_b.append(b.get_current_backoff())
        assert seq_a == seq_b  # replayable from the seed
        assert all(0.05 <= d <= 2.0 for d in seq_a)

    def test_default_off_keeps_reference_sequence(self):
        b = ExponentialBackoff(0.1, 0.4)
        got = []
        for _ in range(3):
            b.report_error()
            got.append(b.get_current_backoff())
        assert got == pytest.approx([0.1, 0.2, 0.4])

    def test_supervisor_defaults_jitter_on_with_name_seed(self):
        s1 = DegradationSupervisor("jitter_a", backoff_min_s=0.05,
                                   backoff_max_s=2.0)
        s2 = DegradationSupervisor("jitter_a", backoff_min_s=0.05,
                                   backoff_max_s=2.0)
        s3 = DegradationSupervisor("jitter_b", backoff_min_s=0.05,
                                   backoff_max_s=2.0)
        for s in (s1, s2, s3):
            s.breaker.report_error()
        # same name -> same replayable stream; distinct names diverge
        assert (
            s1.breaker.get_current_backoff()
            == s2.breaker.get_current_backoff()
        )
        assert (
            s1.breaker.get_current_backoff()
            != s3.breaker.get_current_backoff()
        )


# ---------------------------------------------------------------------
# auditor cadence + containment
# ---------------------------------------------------------------------


class _FakeEngine(ResidentEngineContract):
    audit_kind = "fake"

    def __init__(self):
        self.sample_calls = 0
        self.residual_calls = 0

    def audit_ready(self):
        return True

    def audit_residual(self):
        self.residual_calls += 1
        return 0

    def audit_digest_pair(self):
        return (0, 0)

    def audit_row_count(self):
        return 16

    def audit_sample_rows(self, rows):
        self.sample_calls += 1
        assert list(rows) == sorted(set(rows))
        assert all(0 <= r < 16 for r in rows)
        return 0

    def quarantine(self, reason):
        pass

    def integrity_heal(self):
        return True

    def corrupt_resident(self, seed):
        pass


def test_oracle_cadence_gating():
    aud = IntegrityAuditor(oracle_every=3, sample_rows=4,
                           min_interval_s=0.0)
    eng = _FakeEngine()
    aud.register(eng)
    for _ in range(6):
        aud.on_converge()
    assert eng.residual_calls == 6  # tiers 1+2 every converge
    assert eng.sample_calls == 2    # tier 3 on the 3rd and 6th only


def test_audit_errors_are_contained():
    aud = IntegrityAuditor()
    eng = _FakeEngine()
    eng.audit_residual = lambda: (_ for _ in ()).throw(RuntimeError("x"))
    aud.register(eng)
    reg = get_registry()
    e0 = reg.counter_get("integrity.audit_errors")
    aud.on_converge()  # must not raise: Decision's loop rides this
    assert reg.counter_get("integrity.audit_errors") == e0 + 1


# ---------------------------------------------------------------------
# snapshot / rehydrate under the shared contract (grouped backend)
# ---------------------------------------------------------------------


@pytest.mark.parametrize("kind", ("grouped", "ell"))
def test_snapshot_rehydrate_parity(kind):
    ls = load(_topo())
    engine = make_engine(kind, ls)
    engine.supervisor = _fast_supervisor()
    rsw = next(
        n for n in engine.graph.node_names if n.startswith("rsw")
    )
    engine.churn(ls, mutate_metric(ls, rsw, 0, 17))
    snap = engine.snapshot_resident_state()
    assert snap is not None and snap["kind"] == engine.audit_kind

    twin = make_engine(kind, ls)
    assert twin.rehydrate_resident_state(snap) is True
    np.testing.assert_array_equal(
        twin.result.digests, engine.result.digests
    )
    np.testing.assert_array_equal(
        np.asarray(twin._dr), np.asarray(engine._dr)
    )
    # the rehydrated residents audit clean and churn warm
    aud = IntegrityAuditor(oracle_every=1, sample_rows=4)
    aud.register(twin)
    assert aud.audit_now()[-1]["verdict"] == "clean"
    twin.supervisor = _fast_supervisor()
    # metric 1 yanks shortest paths ONTO the link: routes must move
    moved = twin.churn(ls, mutate_metric(ls, rsw, 0, 1))
    assert moved
    assert_bit_identical(twin, ls, kind)


def test_rehydrate_rejects_cross_class_and_stale():
    ls = load(_topo())
    ell = make_engine("ell", ls)
    grouped = make_engine("grouped", ls)
    snap = ell.snapshot_resident_state()
    assert snap is not None
    # cross-class: layouts differ, the gate must refuse
    assert grouped.rehydrate_resident_state(snap) is False
    # stale topology: mutate, re-sync the donor, old snap must refuse
    rsw = next(n for n in ell.graph.node_names if n.startswith("rsw"))
    ell.supervisor = _fast_supervisor()
    ell.churn(ls, mutate_metric(ls, rsw, 0, 29))
    fresh = make_engine("ell", ls)
    assert fresh.rehydrate_resident_state(snap) is False
    assert fresh.rehydrate_resident_state({"kind": "ell"}) is False
    assert fresh.rehydrate_resident_state(None) is False


# ---------------------------------------------------------------------
# decision.route_staleness_ms
# ---------------------------------------------------------------------


def test_route_staleness_gauge():
    from openr_tpu.decision.decision import Decision
    from openr_tpu.faults.supervisor import HealthState
    from openr_tpu.messaging.queue import ReplicateQueue

    d = Decision(
        "node1",
        kvstore_updates_queue=ReplicateQueue(name="kv"),
        route_updates_queue=ReplicateQueue(name="routes"),
        solver_backend="native",
    )
    gauge = d._route_staleness_ms
    assert gauge() == 0.0  # nothing installed yet
    import time as _time

    d._last_good_route_ts = _time.monotonic() - 0.25
    assert gauge() == 0.0  # healthy + no quarantine: not stale
    d.supervisor.state = HealthState.DEGRADED
    assert gauge() >= 250.0  # ages from the last verified-good install
    d.supervisor.state = HealthState.HEALTHY
    assert gauge() == 0.0  # self-heal zeroes it

    # an integrity quarantine makes the served routes stale too, even
    # with the ladder fully healthy
    aud = get_auditor()
    eng = _FakeEngine()
    aud.register(eng)
    aud._quarantined.add(eng)
    assert quarantine_active()
    assert gauge() >= 250.0
    aud._quarantined.discard(eng)
    assert gauge() == 0.0


# ---------------------------------------------------------------------
# mirror-coverage lint
# ---------------------------------------------------------------------

from tests.test_analysis_lint import lint, rule_hits  # noqa: E402

MIRROR_PREAMBLE = """\
    from openr_tpu.analysis.annotations import (
        mirrored_by, resident_buffers,
    )
"""


def test_mirror_coverage_flags_unmirrored_resident(tmp_path):
    report = lint(tmp_path, MIRROR_PREAMBLE + """
    @resident_buffers("_d_dev", "_packed_dev")
    class Engine:
        pass
    """)
    hits = rule_hits(report, "mirror-coverage")
    assert len(hits) == 2
    assert "_d_dev" in hits[0].message


def test_mirror_coverage_satisfied_by_mirrored_by(tmp_path):
    report = lint(tmp_path, MIRROR_PREAMBLE + """
    @mirrored_by(_d_dev="settled into _d_host on consume",
                 _packed_dev="rebuilt from the LinkState")
    @resident_buffers("_d_dev", "_packed_dev")
    class Engine:
        pass
    """)
    assert rule_hits(report, "mirror-coverage") == []


def test_mirror_coverage_partial_coverage_flags_the_gap(tmp_path):
    report = lint(tmp_path, MIRROR_PREAMBLE + """
    @mirrored_by(_d_dev="settled into _d_host on consume")
    @resident_buffers("_d_dev", "_packed_dev")
    class Engine:
        pass
    """)
    hits = rule_hits(report, "mirror-coverage")
    assert len(hits) == 1
    assert "_packed_dev" in hits[0].message


def test_mirror_coverage_suppressed_with_reason(tmp_path):
    report = lint(tmp_path, MIRROR_PREAMBLE + """
    # openr-lint: disable=mirror-coverage -- scratch block, cold build regenerates it wholesale
    @resident_buffers("_scratch_dev")
    class Engine:
        pass
    """)
    assert rule_hits(report, "mirror-coverage") == []


# ---------------------------------------------------------------------
# the contract itself
# ---------------------------------------------------------------------


def test_engines_implement_the_contract():
    ls = load(_topo())
    engine = make_engine("ell", ls)
    manager = wb.WorldManager(slots_per_bucket=2, max_resident=4)
    assert isinstance(engine, ResidentEngineContract)
    assert isinstance(manager, ResidentEngineContract)
    kinds = {engine.audit_kind, manager.audit_kind}
    assert kinds == {"ell", "world_batch"}
    # the defaulted half of the contract: worlds opt out of
    # snapshot/rehydrate (placement from the mirrors IS their warm
    # path), engines implement it
    assert manager.snapshot_resident_state() is None
    assert manager.rehydrate_resident_state({"kind": "world_batch"}) is False
