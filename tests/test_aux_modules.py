"""PersistentStore / Monitor / Watchdog tests (reference analogues:
config-store, monitor, watchdog test suites)."""

import os
import time

import pytest

from openr_tpu.config_store.persistent_store import PersistentStore
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.monitor.monitor import LogSample, Monitor, SystemMetrics
from openr_tpu.monitor.watchdog import Watchdog
from openr_tpu.types import Adjacency
from openr_tpu.utils.eventbase import OpenrEventBase


def wait_until(pred, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestPersistentStore:
    def test_store_load_roundtrip(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        store.store("drain-state", {"is_overloaded": True})
        assert store.load("drain-state") == {"is_overloaded": True}
        store.stop()

    def test_survives_restart(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        store.store("node-label", 42)
        store.store(
            "adj", Adjacency(other_node_name="x", if_name="if0")
        )
        store.stop()
        # new instance loads from disk
        store2 = PersistentStore(path)
        assert store2.load("node-label") == 42
        adj = store2.load("adj", Adjacency)
        assert adj.other_node_name == "x"
        store2.stop()

    def test_erase(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        store.store("k", 1)
        assert store.erase("k")
        assert not store.erase("k")
        assert store.load("k") is None
        store.stop()
        store2 = PersistentStore(path)
        assert store2.load("k") is None
        store2.stop()

    def test_batched_saves(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path, save_throttle_s=0.2)
        for i in range(50):
            store.store(f"k{i}", i)
        store.stop()
        # 50 writes coalesced into very few disk saves
        assert store.num_saves < 10
        store2 = PersistentStore(path)
        assert store2.load("k49") == 49
        store2.stop()


class TestMonitor:
    def test_event_log_drain_and_common_fields(self):
        q = ReplicateQueue(name="logs")
        mon = Monitor("node-a", q, max_history=16)
        mon.start()
        try:
            q.push(LogSample(event="NEIGHBOR_UP", neighbor="b"))
            q.push(LogSample(event="ROUTE_UPDATE").add_int("routes", 7))
            assert wait_until(lambda: mon.num_processed == 2)
            logs = mon.get_event_logs()
            assert logs[0].get("event") == "NEIGHBOR_UP"
            assert logs[0].get("node_name") == "node-a"  # merged common field
            assert logs[1].get("routes") == 7
        finally:
            mon.stop()

    def test_bounded_history(self):
        q = ReplicateQueue()
        mon = Monitor("node-a", q, max_history=4)
        mon.start()
        try:
            for i in range(10):
                q.push(LogSample(event=f"e{i}"))
            assert wait_until(lambda: mon.num_processed == 10)
            logs = mon.get_event_logs()
            assert len(logs) == 4
            assert logs[-1].get("event") == "e9"
        finally:
            mon.stop()

    def test_system_metrics(self):
        assert SystemMetrics.rss_bytes() > 0
        assert SystemMetrics.cpu_seconds() > 0


class TestWatchdog:
    def test_detects_stalled_evb(self):
        crashes = []
        wd = Watchdog(
            interval_s=0.05,
            thread_timeout_s=0.2,
            crash_handler=crashes.append,
        )
        evb = OpenrEventBase("victim")
        evb.run_in_thread()
        wd.add_evb("victim", evb)
        wd.start()
        try:
            # block the victim's loop
            evb.run_in_event_base(lambda: time.sleep(1.0))
            assert wait_until(lambda: crashes, timeout=2.0)
            assert "victim" in crashes[0]
        finally:
            wd.stop()
            evb.stop()
            evb.join()

    def test_quiet_evb_without_timers_stays_healthy(self):
        """An evb with NO timers and NO traffic (the Monitor on a quiet
        network) must not read as stalled: the run loop's idle wait is
        bounded so last_loop_ts keeps refreshing."""
        crashes = []
        wd = Watchdog(
            interval_s=0.05,
            thread_timeout_s=0.3,
            crash_handler=crashes.append,
        )
        evb = OpenrEventBase("quiet")  # no schedule_periodic anywhere
        evb.run_in_thread()
        wd.add_evb("quiet", evb)
        wd.start()
        try:
            time.sleep(1.0)  # >> thread_timeout_s of pure idleness
            assert crashes == []
        finally:
            wd.stop()
            evb.stop()
            evb.join()

    def test_healthy_evb_no_crash(self):
        crashes = []
        wd = Watchdog(
            interval_s=0.05,
            thread_timeout_s=0.5,
            crash_handler=crashes.append,
        )
        evb = OpenrEventBase("healthy")
        evb.run_in_thread()
        wd.add_evb("healthy", evb)
        wd.start()
        try:
            time.sleep(0.5)
            assert crashes == []
        finally:
            wd.stop()
            evb.stop()
            evb.join()

    def test_memory_limit(self):
        crashes = []
        wd = Watchdog(
            interval_s=0.05,
            max_memory_bytes=1,  # everything exceeds this
            crash_handler=crashes.append,
        )
        wd.start()
        try:
            assert wait_until(lambda: crashes, timeout=2.0)
            assert "memory" in crashes[0]
        finally:
            wd.stop()
