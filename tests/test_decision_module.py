"""End-to-end Decision pipeline tests: KvStore -> Decision -> route deltas.

The slice the reference exercises in
openr/decision/tests/DecisionTest.cpp by pushing synthetic Publications
into a real Decision and asserting on emitted DecisionRouteUpdates.
"""

import time

import pytest

from openr_tpu.decision.decision import Decision
from openr_tpu.kvstore.wrapper import KvStoreWrapper
from openr_tpu.messaging.queue import QueueTimeoutError, ReplicateQueue
from openr_tpu.models import topologies
from openr_tpu.types import (
    AdjacencyDatabase,
    PrefixDatabase,
    PrefixEntry,
    IpPrefix,
)
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire


class DecisionHarness:
    """KvStore + Decision wired through real queues."""

    def __init__(self, my_node, solver_backend="device"):
        self.store = KvStoreWrapper(f"store:{my_node}")
        self.route_q = ReplicateQueue(name="routeUpdates")
        self.route_reader = self.route_q.get_reader("test")
        self.decision = Decision(
            my_node,
            kvstore_updates_queue=self.store.store.updates_queue,
            route_updates_queue=self.route_q,
            debounce_min_s=0.01,
            debounce_max_s=0.05,
            solver_backend=solver_backend,
        )
        self.store.start()
        self.decision.start()
        self._versions = {}

    def stop(self):
        self.decision.stop()
        self.store.stop()

    def publish_adj(self, adj_db: AdjacencyDatabase):
        key = keyutil.adj_key(adj_db.this_node_name)
        v = self._versions[key] = self._versions.get(key, 0) + 1
        self.store.set_key(key, wire.dumps(adj_db), version=v,
                           originator=adj_db.this_node_name)

    def publish_prefixes(self, prefix_db: PrefixDatabase):
        key = keyutil.prefix_db_key(prefix_db.this_node_name)
        v = self._versions[key] = self._versions.get(key, 0) + 1
        self.store.set_key(key, wire.dumps(prefix_db), version=v,
                           originator=prefix_db.this_node_name)

    def publish_topology(self, topo):
        for db in topo.adj_dbs.values():
            self.publish_adj(db)
        for pdb in topo.prefix_dbs.values():
            self.publish_prefixes(pdb)

    def next_update(self, timeout=5.0):
        return self.route_reader.get(timeout=timeout)

    def drain_updates(self, timeout=0.3, first_timeout=10.0):
        """Collect updates until the queue goes quiet. The first wait is
        generous: the solver's first device compile happens lazily."""
        updates = []
        wait = first_timeout
        while True:
            try:
                updates.append(self.route_reader.get(timeout=wait))
                wait = timeout
            except QueueTimeoutError:
                return updates


@pytest.fixture
def harness():
    h = DecisionHarness("a")
    yield h
    h.stop()


def line_topology():
    return topologies.build_topology("line", [("a", "b", 1), ("b", "c", 2)])


class TestDecisionPipeline:
    def test_initial_convergence(self, harness):
        topo = line_topology()
        harness.publish_topology(topo)
        updates = harness.drain_updates()
        assert updates
        # after convergence the accumulated route db has routes to b and c
        routes = harness.decision.get_decision_route_db()
        b_pfx = topo.prefix_dbs["b"].prefix_entries[0].prefix
        c_pfx = topo.prefix_dbs["c"].prefix_entries[0].prefix
        assert b_pfx in routes.unicast_routes
        assert c_pfx in routes.unicast_routes
        # perf events ride the updates
        assert any(u.perf_events is not None for u in updates)

    def test_incremental_prefix_update(self, harness):
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        # now c advertises one more prefix: expect a delta with only it
        extra = IpPrefix.from_str("fd00:100::/64")
        pdb = topo.prefix_dbs["c"]
        harness.publish_prefixes(
            PrefixDatabase(
                this_node_name="c",
                prefix_entries=pdb.prefix_entries
                + (PrefixEntry(prefix=extra),),
                area=topo.area,
            )
        )
        updates = harness.drain_updates()
        touched = set()
        for u in updates:
            touched |= set(u.unicast_routes_to_update)
            touched |= set(u.unicast_routes_to_delete)
        assert extra in touched
        # the unrelated route to b must not be touched by the delta
        b_pfx = topo.prefix_dbs["b"].prefix_entries[0].prefix
        assert b_pfx not in touched

    def test_adjacency_change_triggers_full_rebuild(self, harness):
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        # metric change on b->c: route to c's prefix changes metric
        db = topo.adj_dbs["b"]
        from openr_tpu.types import Adjacency

        new_adjs = tuple(
            Adjacency(
                other_node_name=adj.other_node_name,
                if_name=adj.if_name,
                metric=40 if adj.other_node_name == "c" else adj.metric,
                next_hop_v6=adj.next_hop_v6,
                next_hop_v4=adj.next_hop_v4,
                other_if_name=adj.other_if_name,
                adj_label=adj.adj_label,
            )
            for adj in db.adjacencies
        )
        harness.publish_adj(
            AdjacencyDatabase(
                this_node_name="b",
                adjacencies=new_adjs,
                node_label=db.node_label,
                area=db.area,
            )
        )
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        c_pfx = topo.prefix_dbs["c"].prefix_entries[0].prefix
        (nh,) = routes.unicast_routes[c_pfx].nexthops
        assert nh.metric == 41

    def test_node_down_deletes_routes(self, harness):
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        c_pfx = topo.prefix_dbs["c"].prefix_entries[0].prefix
        # c's adjacency and prefix keys expire (ttl'd out)
        harness.store.set_key(
            keyutil.adj_key("c"), wire.dumps(AdjacencyDatabase(
                this_node_name="c", area=topo.area)), version=99,
            originator="c", ttl=120)
        harness.store.set_key(
            keyutil.prefix_db_key("c"),
            wire.dumps(PrefixDatabase(this_node_name="c", area=topo.area)),
            version=99, originator="c", ttl=120)
        time.sleep(0.5)
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        assert c_pfx not in routes.unicast_routes

    def test_any_source_route_computation(self, harness):
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        # compute routes from c's perspective (first-class API)
        routes_c = harness.decision.get_decision_route_db("c")
        a_pfx = topo.prefix_dbs["a"].prefix_entries[0].prefix
        assert a_pfx in routes_c.unicast_routes
        (nh,) = routes_c.unicast_routes[a_pfx].nexthops
        assert nh.neighbor_node_name == "b"
        assert nh.metric == 3

    def test_per_prefix_keys(self, harness):
        topo = line_topology()
        for db in topo.adj_dbs.values():
            harness.publish_adj(db)
        # advertise b's loopback via a per-prefix key
        b_pfx = topo.prefix_dbs["b"].prefix_entries[0].prefix
        key = keyutil.per_prefix_key("b", topo.area, b_pfx)
        pdb = PrefixDatabase(
            this_node_name="b",
            prefix_entries=(PrefixEntry(prefix=b_pfx),),
            area=topo.area,
        )
        harness.store.set_key(key, wire.dumps(pdb), version=1, originator="b")
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        assert b_pfx in routes.unicast_routes

    def test_debounce_coalesces_churn(self, harness):
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        runs_before = harness.decision.get_counters()[
            "decision.route_build_runs"
        ]
        # 10 rapid prefix updates
        extra = IpPrefix.from_str("fd00:200::/64")
        for i in range(10):
            harness.publish_prefixes(
                PrefixDatabase(
                    this_node_name="c",
                    prefix_entries=topo.prefix_dbs["c"].prefix_entries
                    + (PrefixEntry(prefix=extra),)[: i % 2 + 1],
                    area=topo.area,
                )
            )
        harness.drain_updates()
        runs_after = harness.decision.get_counters()[
            "decision.route_build_runs"
        ]
        assert runs_after - runs_before < 10  # debounced into fewer rebuilds


class TestDecisionSpReuse:
    def test_sp_reuse_active_through_daemon_path(self):
        """SP_ECMP per-prefix route reuse operates through the Decision
        module's publication-driven full rebuilds: remote churn events
        arriving as KvStore publications serve untouched prefixes from
        the cache (spf_solver._sp_dirty_nodes), with the accumulated
        route DB staying byte-identical to a fresh host solver."""
        from dataclasses import replace

        from openr_tpu.decision.prefix_state import PrefixState
        from openr_tpu.decision.spf_solver import (
            SPF_COUNTERS,
            SpfSolver,
        )
        from openr_tpu.graph.linkstate import LinkState
        from openr_tpu.types.lsdb import (
            PrefixForwardingAlgorithm,
            PrefixForwardingType,
        )

        topo = topologies.fat_tree_nodes(
            120,
            forwarding_algorithm=PrefixForwardingAlgorithm.SP_ECMP,
            forwarding_type=PrefixForwardingType.SR_MPLS,
        )
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        fsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("fsw")
        )
        h = DecisionHarness(rsw)
        try:
            h.publish_topology(topo)
            assert h.drain_updates(), "no initial routes"
            adj_dbs = dict(topo.adj_dbs)

            def churn(steps, base=0):
                for step in range(steps):
                    db = adj_dbs[fsw]
                    adjs = list(db.adjacencies)
                    adjs[0] = replace(
                        adjs[0], metric=2 + (base + step) % 5
                    )
                    adj_dbs[fsw] = replace(
                        db, adjacencies=tuple(adjs)
                    )
                    h.publish_adj(adj_dbs[fsw])
                    h.drain_updates(first_timeout=5.0)

            churn(2)  # warm: signature store + cache populate
            before = SPF_COUNTERS["decision.sp_route_reuses"]
            churn(3, base=2)
            assert (
                SPF_COUNTERS["decision.sp_route_reuses"] - before
                > 100
            ), "no SP route reuse through the daemon path"

            # parity: accumulated daemon route DB vs a fresh host
            # solver over the same final adjacency state
            ls = LinkState(area=topo.area)
            for name in sorted(adj_dbs):
                ls.update_adjacency_database(adj_dbs[name])
            ps = PrefixState()
            for pdb in topo.prefix_dbs.values():
                ps.update_prefix_database(pdb)
            want = SpfSolver(rsw, backend="host").build_route_db(
                rsw, {topo.area: ls}, ps
            )
            got = h.decision.get_decision_route_db()
            assert got.unicast_routes == want.unicast_routes
            assert got.mpls_routes == want.mpls_routes
        finally:
            h.stop()


class TestDecisionKsp2Engine:
    def test_engine_active_through_daemon_path(self, monkeypatch):
        """The incremental KSP2 engine operates through the Decision
        module's publication-driven rebuild: churn events arriving as
        KvStore publications run incremental syncs with route reuse,
        not cold rebuilds (reference rebuild driver:
        Decision.cpp:1860 rebuildRoutes)."""
        from dataclasses import replace

        from openr_tpu.decision import spf_solver as ss
        from openr_tpu.decision.spf_solver import SPF_COUNTERS
        from openr_tpu.types.lsdb import (
            PrefixForwardingAlgorithm,
            PrefixForwardingType,
        )

        monkeypatch.setattr(ss, "KSP2_DEVICE_MIN_DSTS", 1)
        topo = topologies.fat_tree_nodes(
            120,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            forwarding_type=PrefixForwardingType.SR_MPLS,
        )
        rsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("rsw"))
        fsw = next(k for k in sorted(topo.adj_dbs) if k.startswith("fsw"))
        h = DecisionHarness(rsw)
        try:
            h.publish_topology(topo)
            assert h.drain_updates(), "no initial routes"
            adj_dbs = dict(topo.adj_dbs)

            def churn(steps):
                for step in range(steps):
                    db = adj_dbs[fsw]
                    adjs = list(db.adjacencies)
                    adjs[0] = replace(adjs[0], metric=2 + step % 5)
                    adj_dbs[fsw] = replace(db, adjacencies=tuple(adjs))
                    h.publish_adj(adj_dbs[fsw])
                    h.drain_updates(first_timeout=5.0)

            churn(5)  # warm: cold build + tie transitions
            before = dict(SPF_COUNTERS)
            churn(3)
            syncs = (
                SPF_COUNTERS["decision.ksp2_incremental_syncs"]
                - before["decision.ksp2_incremental_syncs"]
            )
            reuses = (
                SPF_COUNTERS["decision.ksp2_route_reuses"]
                - before["decision.ksp2_route_reuses"]
            )
            assert syncs >= 3, "daemon-path rebuilds were not incremental"
            assert reuses > 0, "no routes reused through the daemon path"
        finally:
            h.stop()
