"""Solver-as-a-service: the serving plane over the tenant plane.

Covers the continuous-batching contract end to end: bucket-join bit
parity vs per-tenant sequential solves, SLO-class admission ordering
and preemption under a seeded mixed-class storm, client disconnect
mid-wave detaching the tenant WARM (no poisoned bucket), the
slow-client seam stalling only its own connection, occupancy-driven
bucket compaction/regrow round trips, the tenant plane's KSP2 view
parity vs the host oracle, the KSP2 committed-dispatch window
accounting (satellite of this PR), and a small multi-process client
smoke through the real ctrl wire.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace

import numpy as np
import pytest

from openr_tpu.ctrl.server import CtrlServer
from openr_tpu.ctrl.solver import SolverCtrlHandler
from openr_tpu.faults import FaultSchedule, get_injector
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.load import multi_client
from openr_tpu.models import topologies
from openr_tpu.ops.spf_sparse import (
    compile_ell,
    ell_source_batch,
    ell_view_batch_packed,
)
from openr_tpu.ops.world_batch import TENANCY_COUNTERS, WorldManager
from openr_tpu.serve.client import SolverClient
from openr_tpu.serve.service import SolverService
from openr_tpu.serve.slo import SLO_TABLE, order_requests
from openr_tpu.telemetry import get_registry


@pytest.fixture(autouse=True)
def _clean_faults():
    get_injector().reset()
    yield
    get_injector().reset()


def load(topo):
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    return ls


def _mutate_metric(ls, node, i, metric):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))


def _tenants(n=6, seed=0):
    """n mixed-size worlds (two shape buckets)."""
    topos = [
        topologies.grid(3),
        topologies.grid(4),
        topologies.ring(8),
        topologies.random_mesh(20, 3, seed=7 + seed),
        topologies.random_mesh(24, 3, seed=11 + seed),
        topologies.random_mesh(30, 4, seed=13 + seed),
    ][:n]
    lss = [load(t) for t in topos]
    return [
        (f"t{i}", ls, sorted(ls.get_adjacency_databases())[0])
        for i, ls in enumerate(lss)
    ]


def _oracle(ls, root):
    graph = compile_ell(ls)
    srcs = ell_source_batch(graph, ls, root)
    return np.asarray(ell_view_batch_packed(graph, srcs)).astype(
        np.int32
    )


def _assert_view_parity(view, ls, root, tag=""):
    graph, srcs, packed = view
    oracle = _oracle(ls, root)
    assert packed.shape == oracle.shape, tag
    assert np.array_equal(packed, oracle), tag


class TestWaveParity:
    def test_wave_join_bit_parity_vs_sequential(self):
        """Tenants submitted from many threads coalesce into waves;
        every served view must equal the sequential single-graph
        oracle byte for byte, across churn rounds."""
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4, max_resident=16)
        ).start()
        try:
            items = _tenants(6)
            for tid, _ls, _root in items:
                svc.register(tid)
            for rnd in range(3):
                if rnd:
                    for i, (tid, ls, root) in enumerate(items):
                        node = sorted(
                            ls.get_adjacency_databases()
                        )[rnd % 2]
                        _mutate_metric(
                            ls, node, 0, 2 + ((rnd + i) % 7)
                        )
                reqs = {}
                threads = []

                def _go(tid, ls, root):
                    reqs[tid] = svc.request_solve(tid, ls, root)

                for tid, ls, root in items:
                    th = threading.Thread(
                        target=_go, args=(tid, ls, root)
                    )
                    th.start()
                    threads.append(th)
                for th in threads:
                    th.join()
                for tid, ls, root in items:
                    view = reqs[tid].wait(60)
                    _assert_view_parity(
                        view, ls, root, f"round {rnd} {tid}"
                    )
        finally:
            svc.stop()

    def test_latest_wins_coalescing_serves_all_waiters(self):
        """Two requests for one tenant before its wave runs: the later
        supersedes the earlier, and BOTH waiters get the wave's view."""
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4)
        )
        items = _tenants(1)
        tid, ls, root = items[0]
        r1 = svc.request_solve(tid, ls, root)
        r2 = svc.request_solve(tid, ls, root)
        assert r1 in r2.superseded
        svc.start()
        try:
            v1 = r1.wait(60)
            v2 = r2.wait(60)
            assert np.array_equal(v1[2], v2[2])
            _assert_view_parity(v2, ls, root)
        finally:
            svc.stop()


class TestSloOrdering:
    def test_order_requests_class_then_arrival(self):
        """Seeded mixed-class storm: admission order is (class
        priority, arrival seq), and late premium arrivals preempt
        earlier bulk/standard ones (counted)."""
        import random

        rng = random.Random(20260806)
        storm = []
        for seq in range(64):
            storm.append(
                (rng.choice(list(SLO_TABLE)), seq)
            )
        before = TENANCY_COUNTERS["wave_preemptions"]
        ordered = order_requests(storm)
        # class blocks in priority order...
        pri = [SLO_TABLE[c].priority for c, _ in ordered]
        assert pri == sorted(pri)
        # ...and FIFO inside each class
        for cls in SLO_TABLE:
            seqs = [s for c, s in ordered if c == cls]
            assert seqs == sorted(seqs)
        # the storm interleaves classes, so preemptions must fire
        assert TENANCY_COUNTERS["wave_preemptions"] > before

    def test_wave_budget_prefers_premium(self):
        """With a wave budget of 2, a premium request entering the
        queue last still rides the first wave; surplus bulk rides the
        next wave (absorbing the vacancy) rather than being dropped."""
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4),
            wave_budget=2,
        )
        items = _tenants(3)
        (t0, ls0, r0), (t1, ls1, r1), (t2, ls2, r2) = items
        svc.register(t0, "bulk")
        svc.register(t1, "bulk")
        svc.register(t2, "premium")
        ra = svc.request_solve(t0, ls0, r0)
        rb = svc.request_solve(t1, ls1, r1)
        rc = svc.request_solve(t2, ls2, r2)
        with svc._cv:
            batch = svc._admit_locked()
            assert [r.tenant_id for r in batch] == [t2, t0]
            # leftovers stay pending for the next wave
            assert t1 in svc._pending
            # put the inspected batch back so the wave loop serves it
            for r in batch:
                svc._pending[r.tenant_id] = r
        svc.start()
        try:
            for r, (tid, ls, root) in zip(
                (ra, rb, rc), items
            ):
                _assert_view_parity(r.wait(60), ls, root, tid)
        finally:
            svc.stop()


class TestFaultSeams:
    def test_disconnect_mid_wave_detaches_warm(self):
        """serve.client_disconnect at delivery: the hit tenant is
        parked WARM (slot freed, mirror kept), its waiter gets a
        ConnectionError, the co-bucketed tenant's view stays
        bit-correct, and the re-solve after reconnect rehydrates."""
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4)
        ).start()
        try:
            items = _tenants(2)
            (t0, ls0, r0), (t1, ls1, r1) = items
            for tid, ls, root in items:
                svc.register(tid)
                _assert_view_parity(
                    svc.solve(tid, ls, root), ls, root
                )
            get_injector().arm(
                "serve.client_disconnect", FaultSchedule.fail_once()
            )
            # same wave: one delivery trips the seam, the other — and
            # the shared bucket — must be unharmed
            ra = svc.request_solve(t0, ls0, r0)
            rb = svc.request_solve(t1, ls1, r1)
            errors = 0
            for r, ls, root in ((ra, ls0, r0), (rb, ls1, r1)):
                try:
                    _assert_view_parity(r.wait(60), ls, root)
                except ConnectionError:
                    errors += 1
            assert errors == 1
            hit = t0 if ra.error is not None else t1
            t = svc.manager._tenants[hit]
            assert t.slot is None  # detached...
            assert t.packed_host is not None and t.solved  # ...warm
            rehyd0 = TENANCY_COUNTERS["rehydrations"]
            ls, root = (ls0, r0) if hit == t0 else (ls1, r1)
            # churn + re-solve: the parked tenant re-places WARM from
            # its host mirror (rehydration, not a cold solve)
            _mutate_metric(
                ls, sorted(ls.get_adjacency_databases())[0], 0, 11
            )
            _assert_view_parity(svc.solve(hit, ls, root), ls, root)
            assert TENANCY_COUNTERS["rehydrations"] > rehyd0
        finally:
            svc.stop()

    def test_slow_client_stalls_only_its_connection(self):
        """serve.slow_client (delay schedule) on the ctrl reply path:
        the slow client's reply is late; a second client served by the
        same service completes while the first is still stalled."""
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4)
        ).start()
        srv = CtrlServer(SolverCtrlHandler(svc))
        srv.start()
        try:
            spec = multi_client.TenantSpec("slow", "grid", 3)
            dbs = spec.build_dbs()
            c_slow = SolverClient("127.0.0.1", srv.port)
            c_fast = SolverClient("127.0.0.1", srv.port)
            for c, tid in ((c_slow, "slow"), (c_fast, "fast")):
                c.register(tid)
                c.update_world(
                    tid, [dbs[k] for k in sorted(dbs)],
                    root=spec.root_of(dbs),
                )
                c.solve(tid)  # warmup (compiles out of the way)
            get_injector().arm(
                "serve.slow_client",
                FaultSchedule.delay(1.0, n=1),
            )
            t0 = time.perf_counter()
            done = {}

            def _slow():
                c_slow.solve("slow")
                done["slow"] = time.perf_counter() - t0

            th = threading.Thread(target=_slow)
            th.start()
            time.sleep(0.1)
            c_fast.solve("fast")
            done["fast"] = time.perf_counter() - t0
            th.join(30)
            assert done["slow"] >= 1.0
            assert done["fast"] < done["slow"]
            c_slow.close()
            c_fast.close()
        finally:
            srv.stop()
            svc.stop()


class TestCompaction:
    def test_occupancy_compaction_and_regrow_roundtrip(self):
        """8 same-shape tenants -> park 6 -> compaction shrinks the
        bucket to the occupancy's pow2 (counted) -> remaining tenants
        still solve bit-correct -> re-admitting all 8 regrows the
        bucket, parity throughout."""
        mgr = WorldManager(slots_per_bucket=8, max_resident=64)
        items = [
            (f"g{i}", load(topologies.grid(3)), "node-0")
            for i in range(8)
        ]
        mgr.solve_views(items)
        (bucket,) = mgr._buckets.values()
        assert bucket.slots == 8 and bucket.occupancy() == 8
        for tid, _ls, _root in items[2:]:
            mgr.park(tid)
        before = TENANCY_COUNTERS["bucket_compactions"]
        assert mgr.compact_buckets(vacancy=0.5) == 1
        assert TENANCY_COUNTERS["bucket_compactions"] == before + 1
        (bucket,) = mgr._buckets.values()
        assert bucket.slots == 2 and bucket.occupancy() == 2
        for tid, ls, root in items[:2]:
            _assert_view_parity(
                mgr.solve_view(tid, ls, root), ls, root, tid
            )
        # churn + full re-admission: the compacted bucket regrows
        for i, (tid, ls, _root) in enumerate(items):
            _mutate_metric(ls, "node-0", 0, 3 + i % 5)
        views = mgr.solve_views(items)
        for view, (tid, ls, root) in zip(views, items):
            _assert_view_parity(view, ls, root, tid)
        (bucket,) = mgr._buckets.values()
        assert bucket.slots == 8 and bucket.occupancy() == 8

    def test_compaction_drops_empty_buckets(self):
        mgr = WorldManager(slots_per_bucket=4)
        items = _tenants(2)
        mgr.solve_views(items)
        for tid, _ls, _root in items:
            mgr.drop(tid)
        assert mgr.bucket_count() >= 1
        mgr.compact_buckets()
        assert mgr.bucket_count() == 0


class TestKsp2View:
    def test_ksp2_view_parity_vs_host_oracle(self):
        """The tenant plane's second-path view must trace byte-equal
        to ls.get_kth_paths(root, dst, 1) + (…, 2) for every
        destination."""
        mgr = WorldManager(slots_per_bucket=4)
        for topo in (
            topologies.grid(4),
            topologies.random_mesh(24, 3, seed=11),
        ):
            ls = load(topo)
            root = sorted(ls.get_adjacency_databases())[0]
            tid = f"k-{topo.name}"
            mgr.solve_view(tid, ls, root)
            dsts = [
                n
                for n in sorted(ls.get_adjacency_databases())
                if n != root
            ]
            before = TENANCY_COUNTERS["ksp2_views"]
            got = mgr.ksp2_view(tid, dsts)
            assert TENANCY_COUNTERS["ksp2_views"] == before + 1
            for dst in dsts:
                want = ls.get_kth_paths(root, dst, 1) + \
                    ls.get_kth_paths(root, dst, 2)
                assert got[dst] == want, (topo.name, dst)

    def test_ksp2_view_requires_settled_solve(self):
        mgr = WorldManager(slots_per_bucket=4)
        ls = load(topologies.grid(3))
        mgr.solve_view("a", ls, "node-0")
        _mutate_metric(ls, "node-0", 0, 5)
        mgr._sync("a", ls, "node-0")  # dirty, not solved
        with pytest.raises(RuntimeError):
            mgr.ksp2_view("a", ["node-1"])


class TestKsp2CommittedChain:
    def test_ksp2_window_accounting(self, monkeypatch):
        """Satellite: the KSP2 relay round trip rides the committed
        chain — each sync() runs inside the ksp2_window accounting
        window (one histogram observation per event) and warm syncs
        hit the AOT executable cache instead of re-deriving jit
        signatures."""
        from openr_tpu.decision import ksp2_engine

        monkeypatch.setenv("OPENR_KSP2_FAST", "1")
        ls = load(topologies.grid(4))
        names = sorted(ls.get_adjacency_databases())
        root, dsts = names[0], names[1:]
        eng = ksp2_engine.Ksp2Engine(root)
        assert eng.sync(ls, dsts) is None  # cold build
        _mutate_metric(ls, names[1], 0, 9)
        # first warm sync: the incremental dispatch's AOT executable
        # compiles and lands in the cache
        assert eng.sync(ls, dsts) is not None
        reg = get_registry()
        h = reg.histogram("ops.host_touches.ksp2_window")
        c0 = h.count
        hits0 = reg.counter_get("ops.aot_hits")
        # same churn shape again: one window observation, zero new
        # executables — the relay round trip rides the committed cache
        _mutate_metric(ls, names[1], 0, 4)
        affected = eng.sync(ls, dsts)
        assert affected is not None  # warm incremental path ran
        assert h.count == c0 + 1
        assert reg.counter_get("ops.aot_hits") > hits0


class TestCtrlWire:
    def test_ctrl_round_trip_parity_and_disconnect(self):
        """Full wire round trip: register/update/solve digests match
        the jax-free oracle replay; closing the client connection
        parks its tenants warm via the transport teardown hook."""
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4)
        ).start()
        srv = CtrlServer(SolverCtrlHandler(svc))
        srv.start()
        try:
            specs = [
                multi_client.TenantSpec("w0", "grid", 3, seed=1),
                multi_client.TenantSpec(
                    "w1", "mesh", 20, seed=3, slo="premium"
                ),
            ]
            oracle = multi_client.oracle_digests(specs, 2)
            client = SolverClient("127.0.0.1", srv.port)
            worlds = {}
            for spec in specs:
                dbs = spec.build_dbs()
                worlds[spec.tenant_id] = (spec, dbs)
                client.register(spec.tenant_id, slo=spec.slo)
                client.update_world(
                    spec.tenant_id,
                    [dbs[k] for k in sorted(dbs)],
                    root=spec.root_of(dbs),
                )
            for i in range(2):
                for tid, (spec, dbs) in worlds.items():
                    if i > 0:
                        node = multi_client.apply_mutation(
                            dbs, spec, i
                        )
                        client.update_world(tid, [dbs[node]])
                    view = client.solve(tid)
                    assert view.digest() == oracle[tid][i], (tid, i)
            client.close()
            deadline = time.time() + 5
            while (
                svc.manager.resident_count() > 0
                and time.time() < deadline
            ):
                time.sleep(0.05)
            assert svc.manager.resident_count() == 0
            # warm records survive the disconnect
            for spec in specs:
                t = svc.manager._tenants[spec.tenant_id]
                assert t.solved and t.packed_host is not None
        finally:
            srv.stop()
            svc.stop()


@pytest.mark.slow
class TestMultiProcess:
    def test_multi_process_client_smoke(self, tmp_path):
        """Two OS-process jax-free clients drive disjoint tenants
        through one service over the real wire; digests match the
        oracle replay and no child reports errors. (The >=3-process
        B>=64 version is the serve-smoke gate.)"""
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4)
        ).start()
        srv = CtrlServer(SolverCtrlHandler(svc))
        srv.start()
        try:
            client_specs = {
                "c0": [
                    multi_client.TenantSpec("p0", "grid", 3, seed=1),
                    multi_client.TenantSpec(
                        "p1", "ring", 8, seed=2, slo="bulk"
                    ),
                ],
                "c1": [
                    multi_client.TenantSpec(
                        "p2", "mesh", 20, seed=3, slo="premium"
                    ),
                ],
            }
            rounds = 2
            procs = multi_client.spawn_clients(
                "127.0.0.1", srv.port, client_specs, rounds,
                str(tmp_path),
            )
            results = multi_client.harvest(procs)
            all_specs = [
                s for specs in client_specs.values() for s in specs
            ]
            oracle = multi_client.oracle_digests(all_specs, rounds)
            for res in results:
                assert not res["errors"], res
                assert res["rounds"] == rounds
                for tid, digs in res["digests"].items():
                    assert digs == oracle[tid], tid
        finally:
            srv.stop()
            svc.stop()


class TestTelemetrySurface:
    def test_histogram_percentile_accessor(self):
        reg = get_registry()
        h = reg.histogram("test.serve.pctl", window=16)
        for v in range(1, 11):
            h.observe(float(v))
        assert h.percentile(0.5) == 5.0 or h.percentile(0.5) == 6.0
        assert reg.percentile("test.serve.pctl", 0.99) == 10.0
        assert reg.percentile("test.serve.empty", 0.99) == 0.0

    def test_serve_counters_exist_after_wave(self):
        svc = SolverService(
            manager=WorldManager(slots_per_bucket=4)
        ).start()
        try:
            tid, ls, root = _tenants(1)[0]
            svc.register(tid, "premium")
            svc.solve(tid, ls, root)
            snap = svc.counters()
            assert snap["serve.waves"] >= 1
            assert snap["serve.requests"] >= 1
            assert "tenancy.wave_occupancy" in snap
            assert svc.class_p99("premium") > 0.0
        finally:
            svc.stop()
