"""Ctrl API over TLS with secure-then-plain client fallback
(reference: the thrift ctrl server's optional TLS and the py client
factory's secure->plain fallback, openr/py/openr/clients/
openr_client.py:27-140). Gated on the openssl binary for self-signed
cert generation."""

import shutil
import ssl
import subprocess

import pytest

from openr_tpu.ctrl.server import CtrlClient, CtrlServer

pytestmark = pytest.mark.skipif(
    shutil.which("openssl") is None, reason="openssl unavailable"
)


class _EchoHandler:
    """Minimal handler shape: any public method is callable."""

    def get_counters(self):
        return {"ok": 1}


@pytest.fixture
def cert(tmp_path):
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", str(key), "-out", str(cert),
            "-days", "1", "-nodes", "-subj", "/CN=localhost",
        ],
        check=True,
        capture_output=True,
    )
    return str(cert), str(key)


class TestCtrlTls:
    def test_tls_server_plain_fallback_clients(self, cert):
        cert_path, key_path = cert
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert_path, key_path)
        server = CtrlServer(_EchoHandler(), ssl_context=ctx)
        server.start()
        try:
            # the fallback client lands on TLS (self-signed accepted,
            # like the reference's onbox mode)
            client = CtrlClient("127.0.0.1", server.port)
            assert client.call("get_counters") == {"ok": 1}
            assert isinstance(client._sock, ssl.SSLSocket)
            client.close()
        finally:
            server.stop()

    def test_plain_server_still_served(self):
        server = CtrlServer(_EchoHandler())
        server.start()
        try:
            client = CtrlClient("127.0.0.1", server.port)
            assert client.call("get_counters") == {"ok": 1}
            assert not isinstance(client._sock, ssl.SSLSocket)
            client.close()
        finally:
            server.stop()

    def test_rpc_layer_tls_fallback_factory(self, cert):
        from openr_tpu.utils.rpc import RpcServer, connect_with_tls_fallback

        cert_path, key_path = cert
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert_path, key_path)
        server = RpcServer(ssl_context=ctx)
        server.register("ping", lambda: "pong", [], str)
        server.start()
        try:
            client = connect_with_tls_fallback("127.0.0.1", server.port)
            assert client.call("ping", [], str) == "pong"
            client.close()
        finally:
            server.stop()

        # and against a plain server the same factory falls back
        plain = RpcServer()
        plain.register("ping", lambda: "pong", [], str)
        plain.start()
        try:
            client = connect_with_tls_fallback("127.0.0.1", plain.port)
            assert client.call("ping", [], str) == "pong"
            client.close()
        finally:
            plain.stop()
