"""Incremental KSP2 engine: byte-exact parity with the host solver
under every churn class the invalidation logic models.

The engine (openr_tpu/decision/ksp2_engine.py) persists first/second
paths across topology changes and re-solves only destinations its
distance-algebra test marks affected; these tests drive the SAME
mutation stream through a device solver (engine on) and a fresh host
solver and require identical RouteDatabases every step — an unsound
invalidation (a destination wrongly kept) shows up as a parity break.
Reference semantics: LinkState.cpp:763 getKthPaths, Decision.cpp:908
selectBestPathsKsp2.
"""

from __future__ import annotations

import random
from dataclasses import replace

import pytest

from openr_tpu.decision import ksp2_engine
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SPF_COUNTERS, SpfSolver
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.types import AdjacencyDatabase
from openr_tpu.types.lsdb import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


@pytest.fixture(autouse=True)
def _engine_everywhere(monkeypatch):
    from openr_tpu.decision import spf_solver as ss

    monkeypatch.setattr(ss, "KSP2_DEVICE_MIN_DSTS", 1)
    # force the accelerator-only fast path on under the CPU test mesh
    # (the slow 2-dispatch path keeps coverage via the parity-ring
    # churn suite, which does not set the override)
    monkeypatch.setenv("OPENR_KSP2_FAST", "1")


def _ksp2_network(kind: str, n: int):
    kwargs = dict(
        forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        forwarding_type=PrefixForwardingType.SR_MPLS,
    )
    topo = (
        topologies.grid(n, **kwargs)
        if kind == "grid"
        else topologies.fat_tree_nodes(n, **kwargs)
    )
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    ps = PrefixState()
    for pdb in topo.prefix_dbs.values():
        ps.update_prefix_database(pdb)
    return topo, {topo.area: ls}, ps


def _mutate_metric(ls, node, i, metric):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))


def _drop_adj(ls, node, i):
    """Remove one adjacency (link down: the reverse side still
    advertises, so the Link disappears — bidirectional check)."""
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    dropped = adjs.pop(i)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return dropped


def _restore_adj(ls, node, adj):
    db = ls.get_adjacency_databases()[node]
    ls.update_adjacency_database(
        replace(db, adjacencies=tuple(list(db.adjacencies) + [adj]))
    )


def _set_overload(ls, node, overloaded):
    db = ls.get_adjacency_databases()[node]
    ls.update_adjacency_database(replace(db, is_overloaded=overloaded))


def _set_label(ls, node, label):
    db = ls.get_adjacency_databases()[node]
    ls.update_adjacency_database(replace(db, node_label=label))


class TestEngineChurnParity:
    def _stream(self, kind, n, root, mutations):
        """Apply each mutation to twin graphs; device (engine) and host
        route DBs must match after every step."""
        topo, area_d, ps = _ksp2_network(kind, n)
        _topo, area_h, ps_h = _ksp2_network(kind, n)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        dev = SpfSolver(root, backend="device")
        host = SpfSolver(root, backend="host")
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root), "cold"
        for step, fn in enumerate(mutations):
            fn(ls_d)
            fn(ls_h)
            d = dev.build_route_db(root, area_d, ps)
            h = host.build_route_db(root, area_h, ps_h)
            assert d.to_route_db(root) == h.to_route_db(root), step
        return dev

    def test_single_link_metric_cycle_fabric(self):
        """The decision-bench scenario: one fsw adjacency metric
        cycling through ECMP-tie and non-tie values."""
        topo, _, _ = _ksp2_network("fabric", 120)
        fsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("fsw")
        )
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        before = dict(SPF_COUNTERS)
        self._stream(
            "fabric",
            120,
            rsw,
            [
                (lambda s: (lambda ls: _mutate_metric(ls, fsw, 0, s)))(
                    2 + step % 5
                )
                for step in range(8)
            ],
        )
        syncs = (
            SPF_COUNTERS["decision.ksp2_incremental_syncs"]
            - before["decision.ksp2_incremental_syncs"]
        )
        assert syncs >= 4  # steady-state events ran incrementally

    def test_random_metric_churn_grid(self):
        rng = random.Random(13)
        topo, _, _ = _ksp2_network("grid", 5)
        nodes = sorted(topo.adj_dbs)

        def mk(step):
            victim = rng.choice(nodes)
            metric = rng.randint(1, 9)

            def m(ls):
                db = ls.get_adjacency_databases()[victim]
                if db.adjacencies:
                    _mutate_metric(
                        ls, victim, step % len(db.adjacencies), metric
                    )

            return m

        self._stream("grid", 5, "node-0", [mk(s) for s in range(15)])

    def test_link_down_up(self):
        topo, _, _ = _ksp2_network("fabric", 120)
        fsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("fsw")
        )
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        dropped = {}

        def down(ls):
            dropped[id(ls)] = _drop_adj(ls, fsw, 0)

        def up(ls):
            _restore_adj(ls, fsw, dropped[id(ls)])

        def metric(ls):
            _mutate_metric(ls, fsw, 0, 4)

        self._stream("fabric", 120, rsw, [metric, down, metric, up])

    def test_overload_flip_transit_node(self):
        """Draining a transit fsw must dirty every destination routed
        through it (node_users index + distance tests)."""
        topo, _, _ = _ksp2_network("fabric", 120)
        fsws = [k for k in sorted(topo.adj_dbs) if k.startswith("fsw")]
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        self._stream(
            "fabric",
            120,
            rsw,
            [
                lambda ls: _set_overload(ls, fsws[0], True),
                lambda ls: _mutate_metric(ls, fsws[1], 0, 3),
                lambda ls: _set_overload(ls, fsws[0], False),
            ],
        )

    def test_overloaded_advertiser_drain_filter(self):
        """Draining a DESTINATION (advertiser) changes best-route
        filtering even when no path through it changes."""
        topo, _, _ = _ksp2_network("fabric", 120)
        rsws = [k for k in sorted(topo.adj_dbs) if k.startswith("rsw")]
        self._stream(
            "fabric",
            120,
            rsws[0],
            [
                lambda ls: _set_overload(ls, rsws[5], True),
                lambda ls: _set_overload(ls, rsws[5], False),
            ],
        )

    def test_node_label_change_transit(self):
        """A transit node's SR label is embedded in KSP2 label stacks;
        flipping it must dirty the routes through that node."""
        topo, _, _ = _ksp2_network("fabric", 120)
        fsws = [k for k in sorted(topo.adj_dbs) if k.startswith("fsw")]
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        self._stream(
            "fabric",
            120,
            rsw,
            [lambda ls: _set_label(ls, fsws[0], 60000)],
        )

    def test_fast_path_dispatch_economy(self):
        """Steady-state metric churn with unchanged first paths must
        not issue the follow-up masked dispatch: the speculative
        resident-mask solve inside the fused dispatch covers it (the
        1-round-trip property)."""
        topo, area_d, ps = _ksp2_network("fabric", 120)
        (ls,) = area_d.values()
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        fsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("fsw")
        )
        dev = SpfSolver(rsw, backend="device")
        dev.build_route_db(rsw, area_d, ps)
        from openr_tpu.decision import spf_solver as ss

        engine = next(iter(dev._ksp2_engines.values()))
        assert engine.masks_t is not None  # fast path active
        # warm one full metric cycle (covers cold/tie transitions)
        for step in range(5):
            _mutate_metric(ls, fsw, 0, 2 + step % 5)
            dev.build_route_db(rsw, area_d, ps)
        # steady state: metric cycles where the churned link stays off
        # every first path (3 -> 4 -> 5: strictly worse than the
        # metric-1 siblings) must cost zero masked dispatches
        quiet = 0
        for metric in (4, 5):
            _mutate_metric(ls, fsw, 0, metric)
            before = dict(SPF_COUNTERS)
            dev.build_route_db(rsw, area_d, ps)
            batches = (
                SPF_COUNTERS["decision.ksp2_device_batches"]
                - before["decision.ksp2_device_batches"]
            )
            syncs = (
                SPF_COUNTERS["decision.ksp2_incremental_syncs"]
                - before["decision.ksp2_incremental_syncs"]
            )
            assert syncs == 1, "event did not run incrementally"
            if batches == 0:
                quiet += 1
        assert quiet == 2, "fast path issued masked dispatches"

    def test_route_reuse_counts(self):
        """Steady-state no-op rebuild reuses every cached route."""
        topo, area_d, ps = _ksp2_network("fabric", 120)
        (ls_d,) = area_d.values()
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        dev = SpfSolver(rsw, backend="device")
        dev.build_route_db(rsw, area_d, ps)
        before = dict(SPF_COUNTERS)
        dev.build_route_db(rsw, area_d, ps)
        reuses = (
            SPF_COUNTERS["decision.ksp2_route_reuses"]
            - before["decision.ksp2_route_reuses"]
        )
        assert reuses > 100  # nearly every prefix reused

    def test_undrain_reconnects_masked_second_path(self):
        """Draining then undraining the ONLY transit node of a
        destination's second path: the masked graph disconnects and
        must RECONNECT on undrain (code-review regression: the
        link-appeared guard must use effective weights, or the stale
        empty second path survives the undrain)."""
        topo, _, _ = _ksp2_network("fabric", 120)
        fsws = [k for k in sorted(topo.adj_dbs) if k.startswith("fsw")]
        rsw = next(
            k for k in sorted(topo.adj_dbs) if k.startswith("rsw")
        )
        # drain every fsw except two: first paths ride one, the only
        # second path rides the other — draining it disconnects the
        # masked graph for many destinations
        keep = fsws[:2]
        muts = []
        for f in fsws[2:]:
            muts.append(
                (lambda node: lambda ls: _set_overload(ls, node, True))(f)
            )
        muts.append(lambda ls: _set_overload(ls, keep[1], True))
        muts.append(lambda ls: _set_overload(ls, keep[1], False))
        self._stream("fabric", 120, rsw, muts)

    def test_mixed_sp_ecmp_advertiser_not_reused_stale(self):
        """An SP_ECMP-only advertiser is OUTSIDE the engine's tracked
        destination set: its routes must be re-derived every build, not
        reused from a cache the affected set cannot speak for
        (code-review regression: stale ECMP next-hops after churn)."""
        topo, area_d, ps = _ksp2_network("grid", 5)
        _t, area_h, ps_h = _ksp2_network("grid", 5)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        # flip node-12's prefixes to SP_ECMP/IP in both worlds
        for p, world_ls in ((ps, ls_d), (ps_h, ls_h)):
            pdb = topo.prefix_dbs["node-12"]
            p.update_prefix_database(
                replace(
                    pdb,
                    prefix_entries=tuple(
                        replace(
                            e,
                            forwarding_type=PrefixForwardingType.IP,
                            forwarding_algorithm=(
                                PrefixForwardingAlgorithm.SP_ECMP
                            ),
                        )
                        for e in pdb.prefix_entries
                    ),
                )
            )
        dev = SpfSolver("node-0", backend="device")
        host = SpfSolver("node-0", backend="host")
        dev.build_route_db("node-0", area_d, ps)
        host.build_route_db("node-0", area_h, ps_h)
        # churn a link on the shortest path toward node-12
        for ls in (ls_d, ls_h):
            _mutate_metric(ls, "node-7", 0, 9)
            _mutate_metric(ls, "node-11", 0, 9)
        d = dev.build_route_db("node-0", area_d, ps)
        h = host.build_route_db("node-0", area_h, ps_h)
        assert d.to_route_db("node-0") == h.to_route_db("node-0")

    def test_multi_area_ksp2_device_parity(self):
        """Two areas, each KSP2-rich, a border root in both: the
        per-area engines batch both graphs and stay byte-exact with the
        host solver under churn in either area (previously multi-area
        KSP2 was host-only)."""
        from openr_tpu.types import PrefixDatabase

        def build_world():
            area_ls = {}
            ps = PrefixState()
            for area, kind, n in (("a", "grid", 4), ("b", "fabric", 120)):
                topo = (
                    topologies.grid(
                        n,
                        area=area,
                        forwarding_algorithm=(
                            PrefixForwardingAlgorithm.KSP2_ED_ECMP
                        ),
                        forwarding_type=PrefixForwardingType.SR_MPLS,
                    )
                    if kind == "grid"
                    else topologies.fat_tree_nodes(
                        n,
                        area=area,
                        forwarding_algorithm=(
                            PrefixForwardingAlgorithm.KSP2_ED_ECMP
                        ),
                        forwarding_type=PrefixForwardingType.SR_MPLS,
                    )
                )
                ls = LinkState(area=area)
                for name in sorted(topo.adj_dbs):
                    ls.update_adjacency_database(topo.adj_dbs[name])
                area_ls[area] = ls
                for pdb in topo.prefix_dbs.values():
                    ps.update_prefix_database(pdb)
            # border root: present in area a's grid as node-0 and in
            # area b via an adjacency to a rack switch
            rsw = sorted(
                k
                for k in area_ls["b"].get_adjacency_databases()
                if k.startswith("rsw")
            )[0]
            from openr_tpu.types import Adjacency, AdjacencyDatabase

            def border_adj(other, metric=1):
                return Adjacency(
                    other_node_name=other,
                    if_name=f"if_node-0_{other}",
                    other_if_name=f"if_{other}_node-0",
                    metric=metric,
                )

            area_ls["b"].update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name="node-0",
                    adjacencies=(border_adj(rsw),),
                    node_label=9000,
                    area="b",
                )
            )
            bdb = area_ls["b"].get_adjacency_databases()[rsw]
            area_ls["b"].update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name=rsw,
                    adjacencies=tuple(bdb.adjacencies)
                    + (border_adj("node-0"),),
                    node_label=bdb.node_label,
                    area="b",
                )
            )
            return area_ls, ps, rsw

        area_d, ps, rsw = build_world()
        area_h, ps_h, _ = build_world()
        dev = SpfSolver("node-0", backend="device")
        host = SpfSolver("node-0", backend="host")

        def check(step):
            d = dev.build_route_db("node-0", area_d, ps)
            h = host.build_route_db("node-0", area_h, ps_h)
            assert d.to_route_db("node-0") == h.to_route_db("node-0"), step

        check("cold")
        fsw = sorted(
            k
            for k in area_d["b"].get_adjacency_databases()
            if k.startswith("fsw")
        )[0]
        before = dict(SPF_COUNTERS)
        for step in range(3):  # churn area b
            for ls in (area_d["b"], area_h["b"]):
                _mutate_metric(ls, fsw, 0, 2 + step)
            check(f"b-{step}")
        for step in range(3):  # churn area a
            for ls in (area_d["a"], area_h["a"]):
                _mutate_metric(ls, "node-2", 0, 3 + step)
            check(f"a-{step}")
        # the multi-area engine path actually engaged: both area
        # engines synced incrementally and untouched routes were reused
        # (MIN_DSTS is 1 via the fixture, so both areas signal)
        assert (
            SPF_COUNTERS["decision.ksp2_incremental_syncs"]
            - before["decision.ksp2_incremental_syncs"]
            >= 6
        )
        assert (
            SPF_COUNTERS["decision.ksp2_route_reuses"]
            - before["decision.ksp2_route_reuses"]
            > 0
        )

    def test_soak_seed_9013_stale_mask_regression(self):
        """Soak-found regression: under compound churn (overload flips
        + link drops), a destination's resident masks drifted, the
        speculative masked row went bogus (total 6 vs true 8), the
        re-trace silently dropped its second path, and the destination
        never entered the affected set — stale reused routes diverged
        from the host 12 steps later. The fix recomputes
        unrealizable-row destinations and invalidates every
        moved-row destination."""
        from tools.soak_ksp2 import soak_one

        out = soak_one(9013, "fabric", 120, 60)
        assert out["parity"] == "ok", out

    def test_soak_seed_40018_slot_map_drift_regression(self):
        """The root cause behind both soak breaks: a band patch that
        changes a node's in-edge SET re-packs its slot assignments,
        re-aiming every resident mask bit for that row — a dropped
        link shifted two slots and the masked solve excluded the
        wrong edges (metric-15 second path where the truth was 8).
        The engine now snapshots per-node slot maps and sends
        re-slotted nodes' path users through the fresh-mask aff1
        bucket."""
        from tools.soak_ksp2 import soak_one

        out = soak_one(40018, "grid", 5, 60)
        assert out["parity"] == "ok", out

    def test_soak_tool_slice(self):
        """CI slice of tools/soak_ksp2: randomized mixed churn with
        byte-exact device-vs-host parity, engine + fast path active."""
        from tools.soak_ksp2 import soak_one

        for seed, kind, n in ((0, "grid", 5), (1, "fabric", 120)):
            out = soak_one(seed, kind, n, 20)
            assert out["parity"] == "ok", out
            assert out["incremental_syncs"] > 0

    def test_fuzz_mixed_churn_random_mesh(self):
        """Adversarial soundness net: a random weighted mesh under a
        random stream of MIXED churn (metric changes, link drops and
        restores, drain/undrain, label flips) must keep the
        engine-backed device solver byte-exact with the host solver at
        every step. Any unsound invalidation (a destination wrongly
        kept cached) breaks parity here."""

        from openr_tpu.models import topologies

        rng = random.Random(0xF00D)
        topo = topologies.random_mesh(30, seed=7)
        area_d = {topo.area: LinkState(area=topo.area)}
        area_h = {topo.area: LinkState(area=topo.area)}
        ps = PrefixState()
        ps_h = PrefixState()
        for name in sorted(topo.adj_dbs):
            area_d[topo.area].update_adjacency_database(
                topo.adj_dbs[name]
            )
            area_h[topo.area].update_adjacency_database(
                topo.adj_dbs[name]
            )
        for pdb in topo.prefix_dbs.values():
            pdb2 = replace(
                pdb,
                prefix_entries=tuple(
                    replace(
                        e,
                        forwarding_type=PrefixForwardingType.SR_MPLS,
                        forwarding_algorithm=(
                            PrefixForwardingAlgorithm.KSP2_ED_ECMP
                        ),
                    )
                    for e in pdb.prefix_entries
                ),
            )
            ps.update_prefix_database(pdb2)
            ps_h.update_prefix_database(pdb2)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        nodes = sorted(topo.adj_dbs)
        root = nodes[0]
        dev = SpfSolver(root, backend="device")
        host = SpfSolver(root, backend="host")
        dropped = {}

        def mutate(step):
            kind = rng.choice(
                ["metric", "metric", "metric", "drop", "restore",
                 "drain", "undrain", "label"]
            )
            victim = rng.choice(nodes[1:])
            for ls in (ls_d, ls_h):
                db = ls.get_adjacency_databases()[victim]
                if kind == "metric" and db.adjacencies:
                    # deterministic picks inside the twin loop: an rng
                    # draw here would advance the stream differently
                    # for each twin and desynchronize the graphs
                    i = step % len(db.adjacencies)
                    m = (step * 7 + i) % 90 + 1
                    adjs = list(db.adjacencies)
                    adjs[i] = replace(adjs[i], metric=m)
                    ls.update_adjacency_database(
                        replace(db, adjacencies=tuple(adjs))
                    )
                elif kind == "drop" and len(db.adjacencies) > 1:
                    adjs = list(db.adjacencies)
                    gone = adjs.pop(step % len(adjs))
                    dropped[(id(ls), victim)] = gone
                    ls.update_adjacency_database(
                        replace(db, adjacencies=tuple(adjs))
                    )
                elif kind == "restore":
                    gone = dropped.pop((id(ls), victim), None)
                    if gone is not None:
                        ls.update_adjacency_database(
                            replace(
                                db,
                                adjacencies=tuple(
                                    list(db.adjacencies) + [gone]
                                ),
                            )
                        )
                elif kind == "drain":
                    ls.update_adjacency_database(
                        replace(db, is_overloaded=True)
                    )
                elif kind == "undrain":
                    ls.update_adjacency_database(
                        replace(db, is_overloaded=False)
                    )
                elif kind == "label":
                    ls.update_adjacency_database(
                        replace(db, node_label=50000 + step)
                    )

        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root), "cold"
        for step in range(25):
            mutate(step)
            d = dev.build_route_db(root, area_d, ps)
            h = host.build_route_db(root, area_h, ps_h)
            assert d.to_route_db(root) == h.to_route_db(root), step

    def test_prefix_change_invalidates_route_cache(self):
        """A changed prefix advertisement must not serve stale routes."""
        topo, area_d, ps = _ksp2_network("fabric", 120)
        _t, area_h, ps_h = _ksp2_network("fabric", 120)
        rsws = [k for k in sorted(topo.adj_dbs) if k.startswith("rsw")]
        root = rsws[0]
        dev = SpfSolver(root, backend="device")
        host = SpfSolver(root, backend="host")
        dev.build_route_db(root, area_d, ps)
        host.build_route_db(root, area_h, ps_h)
        # withdraw one node's prefixes in both worlds
        for p in (ps, ps_h):
            p.delete_prefix_database(rsws[3], topo.area)
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root)


def _lag_network(metric2: int = 2):
    """2-tier leaf/spine where every leaf-spine pair is a 2-member LAG
    (parallel links, metrics 1 and ``metric2``) — the shape that used
    to force host fallbacks + engine cold rebuilds."""
    kwargs = dict(
        forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        forwarding_type=PrefixForwardingType.SR_MPLS,
    )
    edges = []
    for leaf in range(4):
        for spine in range(2):
            edges.append((f"leaf-{leaf}", f"spine-{spine}", 1))
            edges.append((f"leaf-{leaf}", f"spine-{spine}", metric2))
    topo = topologies.build_topology("lag-fabric", edges, **kwargs)
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    ps = PrefixState()
    for pdb in topo.prefix_dbs.values():
        ps.update_prefix_database(pdb)
    return topo, {topo.area: ls}, ps


class TestParallelLinksFirstClass:
    """VERDICT item 6: LAG members are individually maskable, so the
    incremental engine stays warm and no destination falls back to the
    host path on parallel-link fabrics (reference: LinkState.h:82)."""

    def test_lag_fabric_device_host_parity_under_churn(self):
        topo, area_d, ps = _lag_network()
        _t, area_h, ps_h = _lag_network()
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        root = "leaf-0"
        before = dict(SPF_COUNTERS)
        dev = SpfSolver(root, backend="device")
        host = SpfSolver(root, backend="host")
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root), "cold"

        # churn BOTH LAG members on leaf-1<->spine-0: the min member
        # (adjacency 0) and its sibling (adjacency 1); each step must
        # stay in device/host parity
        steps = []
        for s in range(6):
            steps.append(
                (lambda m: lambda ls: _mutate_metric(
                    ls, "leaf-1", 0, m
                ))(1 + s % 3)
            )
            steps.append(
                (lambda m: lambda ls: _mutate_metric(
                    ls, "leaf-1", 1, m
                ))(2 + s % 4)
            )
        for step, fn in enumerate(steps):
            fn(ls_d)
            fn(ls_h)
            d = dev.build_route_db(root, area_d, ps)
            h = host.build_route_db(root, area_h, ps_h)
            assert d.to_route_db(root) == h.to_route_db(root), step

        fallbacks = (
            SPF_COUNTERS["decision.ksp2_host_fallbacks"]
            - before["decision.ksp2_host_fallbacks"]
        )
        assert fallbacks == 0, fallbacks
        syncs = (
            SPF_COUNTERS["decision.ksp2_incremental_syncs"]
            - before["decision.ksp2_incremental_syncs"]
        )
        assert syncs >= 6  # the engine stayed warm through LAG churn

    def test_lag_member_down_up_parity(self):
        topo, area_d, ps = _lag_network()
        _t, area_h, ps_h = _lag_network()
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        root = "leaf-0"
        dev = SpfSolver(root, backend="device")
        host = SpfSolver(root, backend="host")
        dev.build_route_db(root, area_d, ps)
        host.build_route_db(root, area_h, ps_h)

        dropped_d = _drop_adj(ls_d, "leaf-0", 0)
        dropped_h = _drop_adj(ls_h, "leaf-0", 0)
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root), "down"

        _restore_adj(ls_d, "leaf-0", dropped_d)
        _restore_adj(ls_h, "leaf-0", dropped_h)
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root), "up"

    def test_equal_cost_lag_members_both_excluded(self):
        """Equal-cost parallel members are BOTH on the first-path ECMP
        set; the second path must avoid the whole group."""
        topo, area_d, ps = _lag_network(metric2=1)
        _t, area_h, ps_h = _lag_network(metric2=1)
        root = "leaf-0"
        dev = SpfSolver(root, backend="device")
        host = SpfSolver(root, backend="host")
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root)


class TestEngineBeyondLegacyBound:
    @pytest.mark.slow
    def test_engine_active_above_4096_nodes(self):
        """VERDICT item 8: the incremental engine runs with the
        all-pairs matrix resident at >4096 nodes (the old
        ENGINE_MAX_NODES). Realistic shape: KSP2 is a per-prefix
        opt-in, so destinations are a subset while the graph is big.
        (~15 s on CPU: each event is one [4224, 4224] all-pairs
        dispatch — single-digit ms on a real accelerator.)"""
        from openr_tpu.decision import ksp2_engine
        from benchmarks.bench_scale import ksp2_churn_bench

        assert ksp2_engine.ENGINE_MAX_NODES > 4096
        result = ksp2_churn_bench(4200, 1, ksp2_dst_count=128)
        assert result["ksp2_host_fallbacks"] == 0
        assert result["incremental_syncs"] >= 1, result


class TestBandWideningOnSolverPath:
    """ell_patch(widen=True) on the Decision/KSP2 path: a node at
    exactly its slot-class capacity gaining a NEW adjacency widens the
    resident band in place (no full recompile of the graph), the
    reconverge dispatch re-uploads the widened band wholesale, and the
    KSP2 engine — whose resident masks were shaped for the old band —
    re-seeds cleanly instead of shape-mismatching."""

    def test_new_adjacency_widens_and_stays_correct(self):
        from openr_tpu.types import Adjacency

        topo, area_d, ps = _ksp2_network("fabric", 120)
        _t2, area_h, ps_h = _ksp2_network("fabric", 120)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        rsws = [k for k in sorted(topo.adj_dbs)
                if k.startswith("rsw")]
        a, b = rsws[0], rsws[-1]
        root = rsws[1]
        dev = SpfSolver(root, backend="device")
        host = SpfSolver(root, backend="host")
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root), "cold"

        before = dict(SPF_COUNTERS)
        from openr_tpu.decision import spf_solver as _ss

        state = _ss._ELL_RESIDENT.state_for(ls_d)
        bands_before = tuple(state.graph.bands)
        # enough NEW adjacencies from `a` to overflow its slot class:
        # per-link "in" graphs give every link its own slot, so +len
        # targets pushes a's in-slot count past any pow2 bound below
        targets = [r for r in rsws if r not in (a, root)][:9]
        assert len(targets) >= 6

        def add_links(ls):
            for v in targets:
                for u, w in ((a, v), (v, a)):
                    db = ls.get_adjacency_databases()[u]
                    link = Adjacency(
                        other_node_name=w, if_name=f"xw-{u}-{w}",
                        metric=2, other_if_name=f"xw-{w}-{u}",
                    )
                    ls.update_adjacency_database(
                        replace(
                            db,
                            adjacencies=tuple(
                                list(db.adjacencies) + [link]
                            ),
                        )
                    )

        add_links(ls_d)
        add_links(ls_h)
        d = dev.build_route_db(root, area_d, ps)
        h = host.build_route_db(root, area_h, ps_h)
        assert d.to_route_db(root) == h.to_route_db(root), "widened"
        # the widening GENUINELY happened: some band's k grew in place
        # while the band partition (starts/rows) stayed fixed
        state = _ss._ELL_RESIDENT.state_for(ls_d)
        bands_after = tuple(state.graph.bands)
        assert [
            (x.start, x.rows) for x in bands_after
        ] == [(x.start, x.rows) for x in bands_before]
        assert any(
            x.k > y.k for x, y in zip(bands_after, bands_before)
        ), (bands_before, bands_after)
        # the resident bands took the PATCH path (widening), not a
        # full recompile
        assert (
            SPF_COUNTERS["decision.ell_patches"]
            > before["decision.ell_patches"]
        )
        assert (
            SPF_COUNTERS["decision.ell_full_compiles"]
            == before["decision.ell_full_compiles"]
        )
        # follow-up metric churn on the widened graph still works
        fsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("fsw"))
        for step in range(3):
            _mutate_metric(ls_d, fsw, 0, 3 + step)
            _mutate_metric(ls_h, fsw, 0, 3 + step)
            d = dev.build_route_db(root, area_d, ps)
            h = host.build_route_db(root, area_h, ps_h)
            assert d.to_route_db(root) == h.to_route_db(root), step


class TestMeshShardedEngine:
    """The engine's all-pairs residency sharded over the device mesh
    (set_engine_mesh): per-device footprint n^2/ndev, activation bound
    scaled by sqrt(ndev) — the path past the single-chip 12k ceiling.
    The speculative resident-masks fast path runs mesh-wide too: the
    destination batch pads to a device multiple and the mask stack /
    dm residents stripe over the batch axis; when it cannot engage the
    drop is typed (decision.ksp2.spec_mesh_fallbacks), never silent."""

    @pytest.fixture()
    def engine_mesh(self):
        import jax

        from openr_tpu.parallel.mesh import make_mesh

        ksp2_engine.set_engine_mesh(make_mesh(jax.devices()))
        try:
            yield ksp2_engine.get_engine_mesh()
        finally:
            ksp2_engine.set_engine_mesh(None)

    def test_bound_scales_with_mesh(self, engine_mesh):
        ndev = engine_mesh.devices.size
        assert ksp2_engine.engine_max_nodes() == int(
            ksp2_engine.ENGINE_MAX_NODES * ndev ** 0.5
        )
        ksp2_engine.set_engine_mesh(None)
        assert (
            ksp2_engine.engine_max_nodes()
            == ksp2_engine.ENGINE_MAX_NODES
        )

    def test_sharded_churn_parity(self, engine_mesh):
        """Twin graphs through the device (sharded engine) and host
        solvers across metric churn: identical RouteDbs, incremental
        syncs engaged, zero host fallbacks."""
        topo, area_d, ps = _ksp2_network("fabric", 120)
        _t2, area_h, ps_h = _ksp2_network("fabric", 120)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        fsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("fsw"))
        rsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("rsw"))
        dev = SpfSolver(rsw, backend="device")
        host = SpfSolver(rsw, backend="host")
        before = dict(SPF_COUNTERS)
        d = dev.build_route_db(rsw, area_d, ps)
        h = host.build_route_db(rsw, area_h, ps_h)
        assert d.to_route_db(rsw) == h.to_route_db(rsw), "cold"
        for step in range(4):
            _mutate_metric(ls_d, fsw, 0, 2 + step % 3)
            _mutate_metric(ls_h, fsw, 0, 2 + step % 3)
            d = dev.build_route_db(rsw, area_d, ps)
            h = host.build_route_db(rsw, area_h, ps_h)
            assert d.to_route_db(rsw) == h.to_route_db(rsw), step
        assert (
            SPF_COUNTERS["decision.ksp2_incremental_syncs"]
            > before["decision.ksp2_incremental_syncs"]
        )
        assert (
            SPF_COUNTERS["decision.ksp2_host_fallbacks"]
            == before["decision.ksp2_host_fallbacks"]
        )

    def test_mesh_fast_path_engages(self, engine_mesh):
        """The speculative resident-masks fast path must run ON the
        mesh: mask/dm residents padded to a device multiple and
        batch-striped, warm dispatches counted, zero typed fallbacks —
        and routes stay host-exact through churn (no silent drop to
        the plain dispatch, let alone single-chip)."""
        topo, area_d, ps = _ksp2_network("fabric", 120)
        _t2, area_h, ps_h = _ksp2_network("fabric", 120)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        fsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("fsw"))
        rsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("rsw"))
        dev = SpfSolver(rsw, backend="device")
        host = SpfSolver(rsw, backend="host")
        before = dict(SPF_COUNTERS)
        d = dev.build_route_db(rsw, area_d, ps)
        h = host.build_route_db(rsw, area_h, ps_h)
        assert d.to_route_db(rsw) == h.to_route_db(rsw), "cold"
        engine = next(iter(dev._ksp2_engines.values()))
        assert engine._mesh is not None
        assert engine.masks_t is not None, (
            "speculative fast path must engage on-mesh"
        )
        ndev = engine_mesh.devices.size
        assert engine.masks_t[0].shape[0] % ndev == 0, "padded batch"
        assert engine.dm_dev.shape[0] == engine.masks_t[0].shape[0]
        for step in range(4):
            _mutate_metric(ls_d, fsw, 0, 2 + step % 3)
            _mutate_metric(ls_h, fsw, 0, 2 + step % 3)
            d = dev.build_route_db(rsw, area_d, ps)
            h = host.build_route_db(rsw, area_h, ps_h)
            assert d.to_route_db(rsw) == h.to_route_db(rsw), step
        assert (
            SPF_COUNTERS["decision.ksp2_warm_dispatches"]
            > before["decision.ksp2_warm_dispatches"]
        ), "sharded metric churn must count warm speculative dispatches"
        assert (
            SPF_COUNTERS["decision.ksp2.spec_mesh_fallbacks"]
            == before["decision.ksp2.spec_mesh_fallbacks"]
        ), "the fast path engaged: no fallback may be recorded"

    def test_mesh_fallback_is_typed(self, engine_mesh, monkeypatch):
        """When the padded mask stack exceeds the device budget the
        mesh fast path refuses LOUDLY — typed counter bumped — while
        the plain sharded dispatch keeps routes host-exact."""
        from openr_tpu.decision import spf_solver as ss

        monkeypatch.setattr(ss, "KSP2_DEVICE_MASK_BUDGET", 1)
        topo, area_d, ps = _ksp2_network("fabric", 120)
        _t2, area_h, ps_h = _ksp2_network("fabric", 120)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        fsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("fsw"))
        rsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("rsw"))
        dev = SpfSolver(rsw, backend="device")
        host = SpfSolver(rsw, backend="host")
        before = dict(SPF_COUNTERS)
        d = dev.build_route_db(rsw, area_d, ps)
        h = host.build_route_db(rsw, area_h, ps_h)
        assert d.to_route_db(rsw) == h.to_route_db(rsw), "cold"
        engine = next(iter(dev._ksp2_engines.values()))
        assert engine.masks_t is None
        assert (
            SPF_COUNTERS["decision.ksp2.spec_mesh_fallbacks"]
            > before["decision.ksp2.spec_mesh_fallbacks"]
        ), "budget refusal must be typed, not silent"
        _mutate_metric(ls_d, fsw, 0, 7)
        _mutate_metric(ls_h, fsw, 0, 7)
        d = dev.build_route_db(rsw, area_d, ps)
        h = host.build_route_db(rsw, area_h, ps_h)
        assert d.to_route_db(rsw) == h.to_route_db(rsw), "churn"

    def test_activates_past_single_chip_bound(self, engine_mesh,
                                              monkeypatch):
        """With the single-chip bound shrunk below the graph size, the
        mesh-scaled bound still activates the engine — the composition
        that breaks the ceiling — and routes stay host-exact."""
        monkeypatch.setattr(ksp2_engine, "ENGINE_MAX_NODES", 64)
        assert ksp2_engine.engine_max_nodes() >= 120
        topo, area_d, ps = _ksp2_network("fabric", 120)
        _t2, area_h, ps_h = _ksp2_network("fabric", 120)
        (ls_d,) = area_d.values()
        (ls_h,) = area_h.values()
        fsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("fsw"))
        rsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("rsw"))
        dev = SpfSolver(rsw, backend="device")
        host = SpfSolver(rsw, backend="host")
        before = dict(SPF_COUNTERS)
        d = dev.build_route_db(rsw, area_d, ps)
        h = host.build_route_db(rsw, area_h, ps_h)
        assert d.to_route_db(rsw) == h.to_route_db(rsw), "cold"
        # several small wiggles: a big first delta legitimately trips
        # the most-destinations-affected cold-rebuild heuristic
        for step in range(4):
            _mutate_metric(ls_d, fsw, 0, 2 + step % 3)
            _mutate_metric(ls_h, fsw, 0, 2 + step % 3)
            d = dev.build_route_db(rsw, area_d, ps)
            h = host.build_route_db(rsw, area_h, ps_h)
            assert d.to_route_db(rsw) == h.to_route_db(rsw), step
        assert (
            SPF_COUNTERS["decision.ksp2_incremental_syncs"]
            > before["decision.ksp2_incremental_syncs"]
        ), "engine must be ACTIVE past the single-chip bound"

    def test_mesh_knob_change_reseeds(self, engine_mesh):
        """Flipping the mesh knob mid-life cold-rebuilds instead of
        mixing shardings in the resident state."""
        topo, area_d, ps = _ksp2_network("fabric", 120)
        (ls_d,) = area_d.values()
        rsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("rsw"))
        fsw = next(k for k in sorted(topo.adj_dbs)
                   if k.startswith("fsw"))
        dev = SpfSolver(rsw, backend="device")
        dev.build_route_db(rsw, area_d, ps)
        ksp2_engine.set_engine_mesh(None)  # knob change
        _mutate_metric(ls_d, fsw, 0, 9)
        before = dict(SPF_COUNTERS)
        dev.build_route_db(rsw, area_d, ps)
        assert (
            SPF_COUNTERS["decision.ksp2_cold_builds"]
            > before["decision.ksp2_cold_builds"]
        )


class TestNativeTraceBatch:
    """Differential gate for the native batch tracer (spfcore.cpp
    ksp2_trace_batch): over randomized topologies with exclusions,
    overloaded transit nodes and unreachable destinations, the native
    paths must be BYTE-IDENTICAL (content and order) to the Python
    tracer it replaces."""

    def _graphs(self):
        import numpy as np

        from openr_tpu.decision import spf_solver as ss

        for seed, kind in ((3, "mesh"), (5, "mesh"), (1, "fabric")):
            if kind == "mesh":
                topo = topologies.random_mesh(
                    28, degree=4, seed=seed, max_metric=9
                )
            else:
                topo = topologies.fat_tree(
                    pods=2, ssw_per_plane=2, fsw_per_pod=2,
                    rsw_per_pod=3,
                )
            ls = LinkState(area=topo.area)
            for name in sorted(topo.adj_dbs):
                ls.update_adjacency_database(topo.adj_dbs[name])
            # drain one transit node so blocked filtering is exercised
            names = sorted(topo.adj_dbs)
            drained = names[len(names) // 2]
            db = ls.get_adjacency_databases()[drained]
            ls.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name=db.this_node_name,
                    is_overloaded=True,
                    adjacencies=db.adjacencies,
                    node_label=db.node_label,
                    area=db.area,
                )
            )
            state = ss._ELL_RESIDENT.state_for(ls)
            yield ls, state.graph, np.random.default_rng(seed)

    def test_matches_python_tracer(self):
        import numpy as np

        from openr_tpu.graph import native_spf

        if not native_spf.is_available():
            pytest.skip("native core unavailable")
        for ls, graph, rng in self._graphs():
            names = list(graph.node_names)
            src = names[0]
            sid = graph.node_index[src]
            cands_of = ksp2_engine.make_cands_of(ls, graph.node_index)
            transit_blocked = {
                nm for nm in names
                if ls.is_node_overloaded(nm) and nm != src
            }
            arrays = ksp2_engine._TraceArrays(
                graph, cands_of, transit_blocked
            )
            # a distance row from the HOST oracle
            spf = ls.get_spf_result(src)
            row = np.full(graph.n_pad, ksp2_engine.INF, np.int32)
            for nm, res in spf.items():
                row[graph.node_index[nm]] = res.metric
            dsts = [nm for nm in names if nm != src]
            # shared-row, no exclusions (first-path shape)
            got = arrays.trace(
                sid,
                np.asarray(
                    [graph.node_index[d] for d in dsts], np.int32
                ),
                row, True, [set()] * len(dsts),
            )
            want = [
                ksp2_engine.trace_paths_from_row(
                    src, d, graph.node_index, row.tolist(), set(),
                    cands_of, transit_blocked,
                )
                for d in dsts
            ]
            assert got == want, "shared-row trace diverged"
            # per-dst rows with first-path exclusions (second-path
            # shape). Every destination gets a DISTINCT perturbed row
            # (random entries bumped) so a row-indexing bug in the
            # shared_row=0 stride arithmetic cannot hide behind
            # identical rows; expectations re-derive from the same
            # perturbed row through the Python tracer.
            excls = [
                {l for p in w for l in p} for w in want
            ]
            rows = np.tile(row, (len(dsts), 1))
            for i in range(len(dsts)):
                bump = rng.integers(0, graph.n_pad, size=3)
                rows[i, bump] = np.minimum(
                    rows[i, bump].astype(np.int64) + 1 + i,
                    int(ksp2_engine.INF),
                ).astype(np.int32)
            got2 = arrays.trace(
                sid,
                np.asarray(
                    [graph.node_index[d] for d in dsts], np.int32
                ),
                rows, False, excls,
            )
            want2 = [
                ksp2_engine.trace_paths_from_row(
                    src, d, graph.node_index, rows[i].tolist(), excl,
                    cands_of, transit_blocked,
                )
                for i, (d, excl) in enumerate(zip(dsts, excls))
            ]
            assert got2 == want2, "per-dst excluded trace diverged"
