"""SP_ECMP per-prefix route reuse: byte-exact parity with the host
solver under every churn class the column-wise dirty test models.

The device solver caches per-prefix routes across builds and reuses a
cached route only when the SP dirty test (spf_solver._sp_dirty_nodes)
proves every advertiser's route inputs unchanged: distance + first-hop
columns, first-hop neighbors' own columns, overload bits, node labels,
and the local link signature. These tests drive the SAME mutation
stream through a device solver (reuse on) and a fresh host solver and
require identical RouteDatabases every step — an unsound dirty test (a
changed input not modeled) shows up as a parity break.
Reference semantics: Decision.cpp:1896-1917 (per-prefix incremental
rebuild), Decision.cpp:847/:1124/:1211 (SP route derivation).
"""

from __future__ import annotations

from dataclasses import replace

from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SPF_COUNTERS, SpfSolver
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.types.lsdb import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


def _sp_network(kind: str, n: int,
                ftype=PrefixForwardingType.SR_MPLS):
    kwargs = dict(
        forwarding_algorithm=PrefixForwardingAlgorithm.SP_ECMP,
        forwarding_type=ftype,
    )
    topo = (
        topologies.grid(n, **kwargs)
        if kind == "grid"
        else topologies.fat_tree_nodes(n, **kwargs)
    )
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    ps = PrefixState()
    for pdb in topo.prefix_dbs.values():
        ps.update_prefix_database(pdb)
    return topo, {topo.area: ls}, ps


def _mutate_metric(ls, node, i, metric):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))


def _drop_adj(ls, node, i):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    dropped = adjs.pop(i)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return dropped


def _restore_adj(ls, node, adj):
    db = ls.get_adjacency_databases()[node]
    ls.update_adjacency_database(
        replace(db, adjacencies=tuple(list(db.adjacencies) + [adj]))
    )


def _set_overload(ls, node, overloaded):
    db = ls.get_adjacency_databases()[node]
    ls.update_adjacency_database(
        replace(db, is_overloaded=overloaded)
    )


def _set_node_label(ls, node, label):
    db = ls.get_adjacency_databases()[node]
    ls.update_adjacency_database(replace(db, node_label=label))


class _Worlds:
    """Device solver (reuse on) + host oracle over twin LinkStates."""

    def __init__(self, kind: str, n: int,
                 ftype=PrefixForwardingType.SR_MPLS):
        topo, self.area_d, self.ps = _sp_network(kind, n, ftype)
        _t, self.area_h, self.ps_h = _sp_network(kind, n, ftype)
        (self.ls_d,) = self.area_d.values()
        (self.ls_h,) = self.area_h.values()
        names = sorted(topo.adj_dbs)
        # fabrics: root at a leaf (RSW) so remote-churn tests mutate
        # nodes that are genuinely remote from the root
        self.root = next(
            (k for k in names if k.startswith("rsw")), names[0]
        )
        self.topo = topo
        self.dev = SpfSolver(self.root, backend="device")
        self.host = SpfSolver(self.root, backend="host")

    def step(self, mutate=None):
        if mutate is not None:
            mutate(self.ls_d)
            mutate(self.ls_h)
        d = self.dev.build_route_db(self.root, self.area_d, self.ps)
        h = self.host.build_route_db(
            self.root, self.area_h, self.ps_h
        )
        assert d.to_route_db(self.root) == h.to_route_db(self.root)

    def reuses(self, mutate=None):
        before = SPF_COUNTERS["decision.sp_route_reuses"]
        self.step(mutate)
        return SPF_COUNTERS["decision.sp_route_reuses"] - before


class TestSpRouteReuse:
    def test_noop_rebuild_reuses_everything(self):
        w = _Worlds("fabric", 120)
        w.step()
        w.step()  # second build stores + populates
        assert w.reuses() > 100  # steady state: nearly every prefix

    def test_remote_metric_churn_parity(self):
        w = _Worlds("fabric", 120)
        fsw = next(
            k for k in sorted(w.topo.adj_dbs) if k.startswith("fsw")
        )
        w.step()
        w.step()
        total = 0
        for step in range(6):
            total += w.reuses(
                lambda ls: _mutate_metric(ls, fsw, 0, 2 + step % 5)
            )
        # remote churn must not disable reuse for untouched advertisers
        assert total > 0

    def test_overload_flip_not_reused_stale(self):
        """Draining an advertiser changes its routes via
        maybeFilterDrainedNodes even when distances are unchanged —
        the ov vector must catch it (Decision.cpp:783)."""
        w = _Worlds("fabric", 120)
        rsws = [
            k for k in sorted(w.topo.adj_dbs) if k.startswith("rsw")
        ]
        target = rsws[-1]
        w.step()
        w.step()
        w.step(lambda ls: _set_overload(ls, target, True))
        w.step(lambda ls: _set_overload(ls, target, False))

    def test_node_label_change_not_reused_stale(self):
        """An SR PUSH route embeds the advertiser's node label; a label
        change with unchanged distances must invalidate it."""
        w = _Worlds("fabric", 120)
        rsws = [
            k for k in sorted(w.topo.adj_dbs) if k.startswith("rsw")
        ]
        target = rsws[-1]
        w.step()
        w.step()
        w.step(lambda ls: _set_node_label(ls, target, 60123))
        w.step(lambda ls: _set_node_label(ls, target, 60124))

    def test_local_link_churn_parity(self):
        """Local link metric changes alter every next hop's
        materialized weight — the links signature must invalidate."""
        w = _Worlds("fabric", 120)
        w.step()
        w.step()
        for m in (3, 4, 1):
            w.step(
                lambda ls, m=m: _mutate_metric(ls, w.root, 0, m)
            )

    def test_link_down_up_parity(self):
        w = _Worlds("fabric", 120)
        fsw = next(
            k for k in sorted(w.topo.adj_dbs) if k.startswith("fsw")
        )
        w.step()
        w.step()
        slot = {}

        def down(ls):
            slot[id(ls)] = _drop_adj(ls, fsw, 0)

        def up(ls):
            _restore_adj(ls, fsw, slot[id(ls)])

        w.step(down)
        w.step(up)

    def test_prefix_version_change_invalidates(self):
        """A prefix DB update bumps the version meta: the whole cache
        is rebuilt (no stale routes for changed entries)."""
        w = _Worlds("grid", 5)
        w.step()
        w.step()
        node = sorted(w.topo.prefix_dbs)[-1]
        pdb = w.topo.prefix_dbs[node]
        new_pdb = replace(
            pdb,
            prefix_entries=tuple(
                replace(e, forwarding_type=PrefixForwardingType.IP)
                for e in pdb.prefix_entries
            ),
        )
        w.ps.update_prefix_database(new_pdb)
        w.ps_h.update_prefix_database(new_pdb)
        w.step()

    def test_ip_forwarding_grid_parity(self):
        w = _Worlds("grid", 6, ftype=PrefixForwardingType.IP)
        w.step()
        w.step()
        assert w.reuses() > 20
        for step in range(4):
            w.step(
                lambda ls, s=step: _mutate_metric(
                    ls, "node-21", 0, 2 + s
                )
            )

    def test_static_mpls_update_invalidates(self):
        """_add_best_paths merges static MPLS next hops into
        self-advertised anycast routes (prepend label); a static-route
        update with unchanged graph + prefix state must not serve the
        stale cached route (code-review regression)."""
        from openr_tpu.types import BinaryAddress
        from openr_tpu.decision.spf_solver import make_next_hop

        w = _Worlds("grid", 5)
        # make the root advertise an anycast prefix with a prepend
        # label in both worlds
        pdb = w.topo.prefix_dbs[w.root]
        new_pdb = replace(
            pdb,
            prefix_entries=tuple(
                replace(e, prepend_label=70001)
                for e in pdb.prefix_entries
            ),
        )
        w.ps.update_prefix_database(new_pdb)
        w.ps_h.update_prefix_database(new_pdb)
        w.step()
        w.step()
        nh = make_next_hop(
            BinaryAddress.from_str("fe80::99"), None, 0, None
        )
        for solver in (w.dev, w.host):
            solver.update_static_mpls_routes({70001: [nh]}, [])
        w.step()
        for solver in (w.dev, w.host):
            solver.update_static_mpls_routes({}, [70001])
        w.step()

    def test_multi_area_parity_and_reuse(self):
        """Two areas with a border root: per-area dirty signatures
        union, churn in either area invalidates only that area's dirty
        columns, and untouched prefixes reuse (cross-area min
        semantics: Decision.cpp:1124 loops areas)."""
        from openr_tpu.decision.prefix_state import PrefixState
        from openr_tpu.types import Adjacency, AdjacencyDatabase

        def build_world():
            area_ls = {}
            ps = PrefixState()
            for area, kind, n in (
                ("a", "grid", 4),
                ("b", "fabric", 120),
            ):
                kwargs = dict(
                    area=area,
                    forwarding_algorithm=(
                        PrefixForwardingAlgorithm.SP_ECMP
                    ),
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                )
                topo = (
                    topologies.grid(n, **kwargs)
                    if kind == "grid"
                    else topologies.fat_tree_nodes(n, **kwargs)
                )
                ls = LinkState(area=area)
                for name in sorted(topo.adj_dbs):
                    ls.update_adjacency_database(topo.adj_dbs[name])
                area_ls[area] = ls
                for pdb in topo.prefix_dbs.values():
                    ps.update_prefix_database(pdb)
            rsw = sorted(
                k
                for k in area_ls["b"].get_adjacency_databases()
                if k.startswith("rsw")
            )[0]

            def border_adj(other, metric=1):
                return Adjacency(
                    other_node_name=other,
                    if_name=f"if_node-0_{other}",
                    other_if_name=f"if_{other}_node-0",
                    metric=metric,
                )

            area_ls["b"].update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name="node-0",
                    adjacencies=(border_adj(rsw),),
                    node_label=9000,
                    area="b",
                )
            )
            bdb = area_ls["b"].get_adjacency_databases()[rsw]
            area_ls["b"].update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name=rsw,
                    adjacencies=tuple(bdb.adjacencies)
                    + (border_adj("node-0"),),
                    node_label=bdb.node_label,
                    area="b",
                )
            )
            return area_ls, ps

        area_d, ps = build_world()
        area_h, ps_h = build_world()
        dev = SpfSolver("node-0", backend="device")
        host = SpfSolver("node-0", backend="host")

        def check(step):
            d = dev.build_route_db("node-0", area_d, ps)
            h = host.build_route_db("node-0", area_h, ps_h)
            assert d.to_route_db("node-0") == h.to_route_db(
                "node-0"
            ), step

        check("cold")
        check("warm")
        fsw = sorted(
            k
            for k in area_d["b"].get_adjacency_databases()
            if k.startswith("fsw")
        )[0]
        before = SPF_COUNTERS["decision.sp_route_reuses"]
        for step in range(3):  # churn area b: area-a prefixes reuse
            for ls in (area_d["b"], area_h["b"]):
                _mutate_metric(ls, fsw, 0, 2 + step)
            check(f"b-{step}")
        for step in range(3):  # churn area a: area-b prefixes reuse
            for ls in (area_d["a"], area_h["a"]):
                _mutate_metric(ls, "node-2", 0, 3 + step)
            check(f"a-{step}")
        assert (
            SPF_COUNTERS["decision.sp_route_reuses"] - before > 0
        )

    def test_rib_policy_does_not_pollute_reuse_cache(self):
        """Decision applies RibPolicy to the dict build_route_db
        returned; the entries are shared with the solver's reuse
        caches, so policy application must be NON-mutating — an
        in-place transform would survive policy expiry on every reused
        route (code-review regression)."""
        from openr_tpu.decision.rib_policy import (
            RibPolicy,
            RibPolicyStatement,
            RibRouteAction,
            RibRouteActionWeight,
        )

        w = _Worlds("grid", 5)
        db1 = w.dev.build_route_db(w.root, w.area_d, w.ps)
        db2 = w.dev.build_route_db(w.root, w.area_d, w.ps)
        prefix = next(iter(db2.unicast_routes))
        before = {
            nh.weight for nh in db2.unicast_routes[prefix].nexthops
        }
        policy = RibPolicy(
            [
                RibPolicyStatement(
                    name="w9",
                    prefixes=(prefix,),
                    action=RibRouteAction(
                        set_weight=RibRouteActionWeight(
                            default_weight=9
                        )
                    ),
                )
            ],
            ttl_secs=300,
        )
        policy.apply_policy(db2.unicast_routes)
        assert {
            nh.weight for nh in db2.unicast_routes[prefix].nexthops
        } == {9}
        # steady-state rebuild: the reused route must be the RAW one
        db3 = w.dev.build_route_db(w.root, w.area_d, w.ps)
        assert {
            nh.weight for nh in db3.unicast_routes[prefix].nexthops
        } == before
        assert db3.unicast_routes == db1.unicast_routes

    def test_label_collision_churn_parity(self):
        """Node-label collisions through the patched label-route map:
        two nodes claim one label (smaller name wins,
        Decision.cpp:620-633); churn then moves the label around —
        winner relabeled (handover to the losing claimant), loser
        relabeled, collision created and dissolved — and every step
        must match the host solver byte-exactly (contested removals
        take the full-loop fallback)."""
        w = _Worlds("grid", 5)
        nodes = sorted(w.topo.adj_dbs)
        a, b, c = nodes[2], nodes[7], nodes[11]

        def set_label(node, label):
            def fn(ls):
                _set_node_label(ls, node, label)

            return fn

        w.step()
        w.step()
        # create a collision: b takes a's label (a < b: a keeps it)
        a_label = w.ls_d.get_adjacency_databases()[a].node_label
        w.step(set_label(b, a_label))
        w.step()  # steady state with the collision live
        # winner churn: relabel a — the label must hand over to b
        w.step(set_label(a, 61001))
        w.step()
        # loser churn while contested: c joins the collision
        w.step(set_label(c, a_label))
        w.step()
        # dissolve: everyone unique again
        w.step(set_label(b, 61002))
        w.step(set_label(c, 61003))
        w.step()
        # and metric churn right after collision churn still reuses
        assert w.reuses(
            lambda ls: _mutate_metric(ls, nodes[-1], 0, 7)
        ) >= 0

    def test_soak_mixed_churn_parity(self):
        """CI slice of tools/soak_sp_reuse: randomized interleaved
        churn (metric, overload, label, link drop/restore, prefix
        updates, static MPLS) with byte-exact device-vs-host parity at
        every step. The full soak (60 seeds x 120 steps, 392k reuses)
        ran clean during round 5."""
        from tools.soak_sp_reuse import soak_one

        for seed, kind, n in (
            (0, "grid", 6),
            (1, "fabric", 120),
            (2, "mesh", 40),
            (3, "multi", 120),
        ):
            out = soak_one(seed, kind, n, 30)
            assert out["parity"] == "ok", out
            assert out["sp_route_reuses"] > 0

    def test_lfa_disables_sp_reuse(self):
        """LFA-enabled solvers must never take the reuse path (the
        dirty test is gated off: Decision.cpp:1192 LFA reads rows the
        per-column contract does not promise to keep stable)."""
        topo, area_d, ps = _sp_network("grid", 5)
        root = sorted(topo.adj_dbs)[0]
        dev = SpfSolver(root, backend="device",
                        compute_lfa_paths=True)
        dev.build_route_db(root, area_d, ps)
        before = SPF_COUNTERS["decision.sp_route_reuses"]
        dev.build_route_db(root, area_d, ps)
        dev.build_route_db(root, area_d, ps)
        assert SPF_COUNTERS["decision.sp_route_reuses"] == before
