"""Incident-replay plane tests: bundle schema, journal-ring bounds,
deterministic twin replay, cross-wire trace continuity, and the
checked-in ``tests/scenarios/`` regression fixtures.

Every test resets the flight-recorder singleton with its own dump dir
(the conftest autouse fixture restores defaults after)."""

import base64
import glob
import json
import os

import pytest

from openr_tpu.telemetry import (
    BUNDLE_SCHEMA,
    get_registry,
    load_bundle,
    reset_flight_recorder,
)
from openr_tpu.telemetry.flight import _lsdb_digest


def _recorder(tmp_path, **kw):
    kw.setdefault("dump_dir", str(tmp_path / "flight"))
    kw.setdefault("min_dump_interval_s", 0.0)
    kw.setdefault("max_dumps", 64)
    return reset_flight_recorder(**kw)


def _b64(text: str) -> str:
    return base64.b64encode(text.encode()).decode()


def _feed(fr, n, keys=4, area="0"):
    for i in range(n):
        fr.journal_note(
            area, f"adj:node-{i % keys}",
            value_b64=_b64(f"v{i}"), version=i + 1,
            originator=f"node-{i % keys}",
        )


class TestBundleSchema:
    def test_round_trip_compact_json(self, tmp_path):
        fr = _recorder(tmp_path)
        fr.note("engine", i=1)
        _feed(fr, 6)
        fr.journal_mark("wave", window="test", vantages=["node-0"])
        path = fr.dump_postmortem(trigger="manual", reason="schema")
        assert path and path.endswith(".json")
        with open(path, "rb") as fh:
            raw = fh.read()
        # compact separators: no indent whitespace after a comma-newline
        assert b",\n" not in raw and b": " not in raw
        bundle = load_bundle(path)
        assert bundle["schema"] == BUNDLE_SCHEMA
        for key in ("trigger", "reason", "ts", "records", "counters",
                    "counters_delta", "journal", "attribution",
                    "host_overhead_ratio"):
            assert key in bundle, key
        journal = bundle["journal"]
        assert journal["base_seq"] == 0
        assert len(journal["records"]) == 7
        anchor = journal["anchor"]
        assert set(anchor) >= {"checkpoint_seq", "graph_digest", "lsdb"}
        assert anchor["graph_digest"] == _lsdb_digest(anchor["lsdb"])

    def test_gzip_dump_loads_transparently(self, tmp_path):
        fr = _recorder(tmp_path, gzip_dumps=True)
        _feed(fr, 3)
        path = fr.dump_postmortem(trigger="manual", reason="gz")
        assert path.endswith(".json.gz")
        bundle = load_bundle(path)
        assert bundle["schema"] == BUNDLE_SCHEMA
        assert len(bundle["journal"]["records"]) == 3

    def test_counters_delta_since_previous_dump(self, tmp_path):
        fr = _recorder(tmp_path)
        reg = get_registry()
        first = load_bundle(
            fr.dump_postmortem(trigger="manual", reason="baseline")
        )
        # absolute snapshot always present; the delta keys on the
        # SECOND bundle must reflect only what moved since the first
        assert "counters" in first
        reg.counter_bump("test.replay_delta", 5)
        second = load_bundle(
            fr.dump_postmortem(trigger="manual", reason="delta")
        )
        assert second["counters_delta"]["test.replay_delta"] == 5
        assert second["counters"]["test.replay_delta"] >= 5

    def test_dump_bytes_histogram_fed(self, tmp_path):
        fr = _recorder(tmp_path)
        snap0 = get_registry().snapshot().get(
            "ops.flight.dump_bytes.count", 0
        )
        path = fr.dump_postmortem(trigger="manual", reason="bytes")
        snap = get_registry().snapshot()
        assert snap.get("ops.flight.dump_bytes.count", 0) == snap0 + 1
        assert snap.get("ops.flight.dump_bytes.max", 0) > 0
        assert os.path.getsize(path) > 0


class TestJournalRing:
    def test_bounded_under_churn_storm(self, tmp_path):
        fr = _recorder(tmp_path, journal=64)
        ev0 = get_registry().counter_get("flight.journal_evictions")
        _feed(fr, 500, keys=8)
        assert fr.journal_len() == 64
        assert get_registry().counter_get(
            "flight.journal_evictions"
        ) - ev0 == 500 - 64

    def test_eviction_folds_into_base_keeps_completeness(self, tmp_path):
        fr = _recorder(tmp_path, journal=64)
        _feed(fr, 300, keys=8)
        # base + slice must reconstruct exactly the last write per key
        state = {
            k: dict(v) for k, v in fr.journal_base().get("0", {}).items()
        }
        for rec in fr.journal_records():
            if "mark" in rec:
                continue
            state[rec["key"]] = {
                "value_b64": rec["value_b64"],
                "version": rec["version"],
                "originator": rec["originator"],
            }
        expect = {
            f"adj:node-{i % 8}": {
                "value_b64": _b64(f"v{i}"),
                "version": i + 1,
                "originator": f"node-{i % 8}",
            }
            for i in range(300)
        }
        assert state == expect

    def test_evicted_marks_drop_and_move_base_seq(self, tmp_path):
        fr = _recorder(tmp_path, journal=64)
        for i in range(70):
            fr.journal_mark("wave", window=f"w{i}")
        assert fr.journal_len() == 64
        assert fr.journal_base() == {}  # marks never fold into base
        bundle = load_bundle(
            fr.dump_postmortem(trigger="manual", reason="marks")
        )
        assert bundle["journal"]["base_seq"] == 6

    def test_journal_appends_while_frozen(self, tmp_path):
        fr = _recorder(tmp_path)
        fr.freeze()
        try:
            _feed(fr, 3)
            fr.note("engine", i=1)  # activity ring DOES drop frozen
        finally:
            fr.unfreeze()
        assert fr.journal_len() == 3
        assert fr.records() == []

    def test_size_ceiling_truncates_but_stays_replayable(self, tmp_path):
        # the counters/attribution snapshot is irreducible and grows
        # with whatever ran earlier in this process, so measure it and
        # set the ceiling just above that floor
        probe = _recorder(tmp_path)
        base = os.path.getsize(
            probe.dump_postmortem(trigger="manual", reason="probe")
        )
        ceiling = max(4096, base + 2048)
        fr = _recorder(tmp_path, max_dump_bytes=ceiling)
        tr0 = get_registry().counter_get("flight.dump_truncations")
        _feed(fr, 120, keys=6)
        path = fr.dump_postmortem(trigger="manual", reason="ceiling")
        assert os.path.getsize(path) <= ceiling
        assert get_registry().counter_get(
            "flight.dump_truncations"
        ) > tr0
        bundle = load_bundle(path)
        assert bundle["truncated"] is True
        anchor = bundle["journal"]["anchor"]
        # dropped pubs folded into the bundle's own anchor: the digest
        # must still verify against the (grown) anchor LSDB
        assert anchor["graph_digest"] == _lsdb_digest(anchor["lsdb"])


class TestReplayDeterminism:
    @pytest.fixture()
    def incident(self, tmp_path):
        _recorder(tmp_path)
        from openr_tpu.models.topologies import ring
        from openr_tpu.twin import FabricTwin, ScenarioDriver

        twin = FabricTwin(ring(8), record_journal=True)
        drv = ScenarioDriver(twin, seed=20)
        twin.converge()
        drv.inject_micro_loop("node-0", "node-1")
        assert twin.analyze().loops()
        from openr_tpu.telemetry import get_flight_recorder

        path = get_flight_recorder().dump_postmortem(
            trigger="manual", reason="determinism"
        )
        live = {str(k): v for k, v in twin.route_digests().items()}
        twin.close()
        return path, live

    def test_same_bundle_bit_identical_twice(self, incident):
        from openr_tpu.twin.replay import ScenarioReplayer, replay_digest

        path, live = incident
        v1 = ScenarioReplayer.from_path(path).replay()
        v2 = ScenarioReplayer.from_path(path).replay()
        assert v1.reproduced and v2.reproduced
        assert not v1.errors and not v1.divergence
        assert replay_digest(v1) == replay_digest(v2)
        assert v1.route_digests == live
        assert v1.digests_match_recorded is True

    def test_corrupt_anchor_detected(self, incident, tmp_path):
        from openr_tpu.twin.replay import ScenarioReplayer

        path, _live = incident
        bundle = load_bundle(path)
        area = next(iter(bundle["journal"]["anchor"]["lsdb"]))
        key = next(iter(bundle["journal"]["anchor"]["lsdb"][area]))
        bundle["journal"]["anchor"]["lsdb"][area][key]["version"] += 1
        with pytest.raises(ValueError, match="anchor digest"):
            ScenarioReplayer(bundle).replay()


class TestTraceContinuity:
    def test_client_span_reaches_service_wave_records(self, tmp_path):
        fr = _recorder(tmp_path)
        from openr_tpu.ctrl.server import CtrlServer
        from openr_tpu.ctrl.solver import SolverCtrlHandler
        from openr_tpu.models.topologies import ring
        from openr_tpu.serve.client import SolverClient
        from openr_tpu.serve.service import SolverService

        svc = SolverService().start()
        srv = CtrlServer(SolverCtrlHandler(svc), port=0)
        srv.start()
        try:
            client = SolverClient(port=srv.port)
            client.register("t0")
            topo = ring(6)
            client.update_world(
                "t0", topo.adj_dbs.values(), root="node-0"
            )
            client.solve("t0")
            client.solve("t0")
            wave_spans = {
                s
                for r in fr.records()
                if r.get("kind") == "wave"
                for s in r.get("client_spans", [])
            }
            hits = [s for s in client.span_ids if s in wave_spans]
            assert hits, (
                "no client span id surfaced in service wave records"
            )
            assert all(
                s.startswith(client.trace_id + ".") for s in hits
            )
            # a dump requested over the wire pairs with the client span
            out = client.dump_postmortem(
                trigger="manual", reason="continuity"
            )
            bundle = load_bundle(out["path"])
            assert f"client span {client.last_span_id}" in bundle["reason"]
            client.close()
        finally:
            srv.stop()
            svc.stop()


class TestScenarioFixtures:
    FIXTURES = sorted(
        glob.glob(os.path.join(
            os.path.dirname(__file__), "scenarios", "*.json"
        ))
        + glob.glob(os.path.join(
            os.path.dirname(__file__), "scenarios", "*.json.gz"
        ))
    )

    def test_fixtures_exist(self):
        assert self.FIXTURES, "tests/scenarios/ holds no bundles"

    @pytest.mark.parametrize(
        "path", FIXTURES, ids=[os.path.basename(p) for p in FIXTURES]
    )
    def test_fixture_replays_deterministically(self, path, tmp_path):
        _recorder(tmp_path)
        from openr_tpu.twin.replay import ScenarioReplayer, replay_digest

        v1 = ScenarioReplayer.from_path(path).replay()
        v2 = ScenarioReplayer.from_path(path).replay()
        assert not v1.errors, v1.errors
        assert not v1.divergence, v1.divergence
        if v1.recorded_classes:
            assert v1.reproduced, (
                f"recorded {v1.recorded_classes} did not reproduce "
                f"(replayed {v1.replayed_classes})"
            )
        assert v1.digests_match_recorded is True
        assert replay_digest(v1) == replay_digest(v2)
