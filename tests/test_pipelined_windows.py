"""Pipelined event windows (PR 16): multi-event bursts whose committed
dispatches submit back to back under one ``pipeline_drain`` (window
N+1 on the stream before window N's reap lands), speculative dispatch
of the debounce backlog's most-likely composition, and their
interaction with the chaos seams.

Four claims, each with its own class:

- Burst parity: ``churn_burst`` leaves digests bit-identical to the
  same events applied one sequential ``churn()`` at a time, across
  the ELL, grouped, and mesh-sharded backends — with the pipelining
  witnessed (``ops.pipelined_dispatches``) and the whole burst costing
  at most 2 host touches per drain.
- Speculation parity: a matching speculation ADOPTS
  (``ops.spec_hits``) and a mismatched one CANCELS
  (``ops.spec_cancels``, never silent); both end bit-identical to the
  sequential oracle, and sample-band compositions refuse to speculate
  (``ops.spec_skips``).
- Chaos-seam interaction: a fault mid-burst or mid-speculation
  degrades WITHIN the ladder (burst cancel -> supervised replay;
  speculation abandoned -> committed path), never up it — and the
  decision-layer speculation stands down entirely while any fault is
  armed so chaos charges are consumed only by the committed path.
- Compile flatness: warm bursts at pipeline depths 1..3 cost zero AOT
  compiles and zero backend jit compiles; the world-batch pipelined
  entry point solves batches bit-identically to per-batch
  ``solve_views`` while overlapping disjoint-bucket launches.
"""

import numpy as np
import pytest
from dataclasses import replace

from openr_tpu.faults.injector import (
    FaultSchedule,
    get_injector,
)
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.ops import dispatch_accounting as da
from openr_tpu.ops import route_engine, route_sweep
from openr_tpu.telemetry import get_registry


def load(topo):
    ls = LinkState(area=topo.area)
    for name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    return ls


def make_topo():
    return topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )


def mutate_metric(ls, node, i, metric):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {node, adjs[i].other_node_name}


def make_engine(kind, ls):
    names = sorted(ls.get_adjacency_databases().keys())
    if kind in ("ell_sharded", "grouped_sharded"):
        import jax

        from openr_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(jax.devices())
        cls = (
            route_engine.RouteSweepEngine
            if kind == "ell_sharded"
            else route_engine.GroupedRouteSweepEngine
        )
        return cls(ls, [names[0]], align=16, mesh=mesh)
    cls = (
        route_engine.RouteSweepEngine
        if kind == "ell"
        else route_engine.GroupedRouteSweepEngine
    )
    return cls(ls, [names[0]])


def digests(engine):
    return route_sweep.digests_by_name(engine.result)


def safe_edges(ls, sample_names, count):
    """(node, slot) churn pairs whose BOTH endpoints avoid the sample
    band — a window touching a sample node's adjacencies refuses to
    speculate/burst by design."""
    out = []
    sample = set(sample_names)
    for node in sorted(ls.get_adjacency_databases().keys()):
        if node in sample:
            continue
        for i, a in enumerate(
            ls.get_adjacency_databases()[node].adjacencies
        ):
            if a.other_node_name in sample:
                continue
            out.append((node, i))
            break
        if len(out) == count:
            return out
    raise RuntimeError("topology too small for sample-free churn set")


KINDS = ("ell", "grouped", "ell_sharded", "grouped_sharded")
EVENTS = ((0, 7), (1, 5), (2, 9))  # (edge index, metric)


@pytest.fixture(autouse=True)
def _clean_injector():
    get_injector().reset()
    yield
    get_injector().reset()


@pytest.mark.parametrize("kind", KINDS)
class TestBurstParity:
    def test_burst_matches_sequential(self, kind):
        """A 3-event burst leaves the same digests as the same events
        applied one supervised churn() at a time."""
        topo = make_topo()
        ls_a, ls_b = load(topo), load(topo)
        seq = make_engine(kind, ls_a)
        bst = make_engine(kind, ls_b)
        edges = safe_edges(ls_a, seq.sample_names, 3)
        for ei, metric in EVENTS:
            n, s = edges[ei]
            seq.churn(ls_a, mutate_metric(ls_a, n, s, metric))
        bst.churn_burst(ls_b, [
            (lambda n=edges[ei][0], s=edges[ei][1], m=metric:
             mutate_metric(ls_b, n, s, m))
            for ei, metric in EVENTS
        ])
        assert digests(seq) == digests(bst)

    def test_burst_submits_ahead_of_reap(self, kind):
        """The acceptance-criterion witness: a warm multi-event burst
        dispatches window N+1 before window N's reap lands
        (ops.pipelined_dispatches), folds every window into one drain
        (ops.windows_per_drain), and the whole drain costs at most 2
        host touches."""
        topo = make_topo()
        ls = load(topo)
        eng = make_engine(kind, ls)
        edges = safe_edges(ls, eng.sample_names, 3)
        # warm the chain and the burst bucket
        for ei, metric in EVENTS:
            n, s = edges[ei]
            eng.churn(ls, mutate_metric(ls, n, s, metric))
        reg = get_registry()
        piped0 = reg.counter_get("ops.pipelined_dispatches")
        cancels0 = reg.counter_get("ops.burst_cancels")
        with da.pipeline_drain("test_drain") as w:
            eng.churn_burst(ls, [
                (lambda n=edges[ei][0], s=edges[ei][1], m=metric + 1:
                 mutate_metric(ls, n, s, m))
                for ei, metric in EVENTS
            ])
        assert reg.counter_get("ops.burst_cancels") == cancels0
        assert reg.counter_get("ops.pipelined_dispatches") >= piped0 + 2
        assert w.windows == len(EVENTS)
        assert w.touches <= 2, (
            f"burst cost {w.touches} touches; the drain budget is 2"
        )
        assert w.blocking_syncs == 0


class TestSpeculationParity:
    def _warm_pair(self):
        topo = make_topo()
        ls_a, ls_b = load(topo), load(topo)
        seq = make_engine("ell", ls_a)
        spc = make_engine("ell", ls_b)
        edges = safe_edges(ls_a, seq.sample_names, 3)
        for ei, metric in EVENTS:
            n, s = edges[ei]
            seq.churn(ls_a, mutate_metric(ls_a, n, s, metric))
            spc.churn(ls_b, mutate_metric(ls_b, n, s, metric))
        return ls_a, ls_b, seq, spc, edges

    def test_spec_hit_adopts_bit_identical(self):
        ls_a, ls_b, seq, spc, edges = self._warm_pair()
        reg = get_registry()
        h0 = reg.counter_get("ops.spec_hits")
        n, s = edges[0]
        aff_b = mutate_metric(ls_b, n, s, 21)
        assert spc.speculate_churn(ls_b, [aff_b])
        spc.churn_window(ls_b, [aff_b])
        seq.churn(ls_a, mutate_metric(ls_a, n, s, 21))
        assert reg.counter_get("ops.spec_hits") == h0 + 1
        assert digests(seq) == digests(spc)

    def test_spec_mismatch_cancels_bit_identical(self):
        """Deliver a LARGER backlog than was speculated: the stale
        speculation cancels (counted, never silent) and the committed
        replay equals the sequential chain."""
        ls_a, ls_b, seq, spc, edges = self._warm_pair()
        reg = get_registry()
        c0 = reg.counter_get("ops.spec_cancels")
        (n0, s0), (n1, s1) = edges[0], edges[1]
        aff_b1 = mutate_metric(ls_b, n0, s0, 23)
        assert spc.speculate_churn(ls_b, [aff_b1])
        aff_b2 = mutate_metric(ls_b, n1, s1, 6)
        spc.churn_window(ls_b, [aff_b1, aff_b2])
        seq.churn_window(ls_a, [
            mutate_metric(ls_a, n0, s0, 23),
            mutate_metric(ls_a, n1, s1, 6),
        ])
        assert reg.counter_get("ops.spec_cancels") == c0 + 1
        assert digests(seq) == digests(spc)

    def test_sample_band_composition_refuses_to_speculate(self):
        """A backlog touching a sample node's adjacencies skips
        speculation (the sample-band refresh mutates sweeper state
        before dispatch — not cancellable) and the committed window
        still lands bit-identically."""
        ls_a, ls_b, seq, spc, edges = self._warm_pair()
        reg = get_registry()
        k0 = reg.counter_get("ops.spec_skips")
        sample = spc.sample_names[0]
        aff_b = mutate_metric(ls_b, sample, 0, 15)
        assert not spc.speculate_churn(ls_b, [aff_b])
        assert reg.counter_get("ops.spec_skips") == k0 + 1
        spc.churn_window(ls_b, [aff_b])
        seq.churn(ls_a, mutate_metric(ls_a, sample, 0, 15))
        assert digests(seq) == digests(spc)


class TestChaosSeamInteraction:
    def test_fault_mid_burst_cancels_and_replays_within_ladder(self):
        """A dispatch fault inside a burst window cancels the burst
        (ops.burst_cancels) and replays the coalesced union through
        the SUPERVISED path — the ladder degrades warm->...), never
        exhausting, and the result still matches the sequential
        oracle run without any fault."""
        topo = make_topo()
        ls_a, ls_b = load(topo), load(topo)
        seq = make_engine("ell", ls_a)
        bst = make_engine("ell", ls_b)
        edges = safe_edges(ls_a, seq.sample_names, 3)
        for ei, metric in EVENTS:
            n, s = edges[ei]
            seq.churn(ls_a, mutate_metric(ls_a, n, s, metric))
            bst.churn(ls_b, mutate_metric(ls_b, n, s, metric))
        reg = get_registry()
        c0 = reg.counter_get("ops.burst_cancels")
        lost0 = reg.counter_get("recovery.device_lost")
        get_injector().arm(
            "route_engine.dispatch", FaultSchedule.fail_once()
        )
        bst.churn_burst(ls_b, [
            (lambda n=edges[ei][0], s=edges[ei][1], m=metric + 2:
             mutate_metric(ls_b, n, s, m))
            for ei, metric in EVENTS
        ])
        for ei, metric in EVENTS:
            n, s = edges[ei]
            seq.churn(ls_a, mutate_metric(ls_a, n, s, metric + 2))
        assert reg.counter_get("ops.burst_cancels") == c0 + 1
        # degraded WITHIN the ladder: no device-loss escalation
        assert reg.counter_get("recovery.device_lost") == lost0
        assert digests(seq) == digests(bst)

    def test_fault_mid_speculation_abandons_not_escalates(self):
        """A fault during the speculative solve abandons the attempt
        (ops.spec_cancels) OUTSIDE the supervisor — the later
        committed window runs clean and bit-identical; the ladder
        never sees the speculative failure."""
        topo = make_topo()
        ls_a, ls_b = load(topo), load(topo)
        seq = make_engine("ell", ls_a)
        spc = make_engine("ell", ls_b)
        edges = safe_edges(ls_a, seq.sample_names, 2)
        for ei, metric in EVENTS[:2]:
            n, s = edges[ei]
            seq.churn(ls_a, mutate_metric(ls_a, n, s, metric))
            spc.churn(ls_b, mutate_metric(ls_b, n, s, metric))
        reg = get_registry()
        c0 = reg.counter_get("ops.spec_cancels")
        n, s = edges[0]
        aff_b = mutate_metric(ls_b, n, s, 31)
        get_injector().arm(
            "route_engine.dispatch", FaultSchedule.fail_once()
        )
        assert not spc.speculate_churn(ls_b, [aff_b])
        assert reg.counter_get("ops.spec_cancels") == c0 + 1
        spc.churn_window(ls_b, [aff_b])
        seq.churn(ls_a, mutate_metric(ls_a, n, s, 31))
        assert digests(seq) == digests(spc)

    def test_decision_speculation_stands_down_while_armed(self):
        """The decision-layer speculation gate: while ANY chaos charge
        is armed, speculate_views refuses (ops.spec_skips) WITHOUT
        consuming the charge — the committed rebuild owns every fault
        seam, so a chaos test's armed fault can never be eaten by a
        speculative solve outside the ladder."""
        from openr_tpu.decision.spf_solver import SpfSolver

        topo = topologies.grid(4)
        ls = load(topo)
        root = sorted(ls.get_adjacency_databases())[0]
        solver = SpfSolver(root, backend="device")
        area_ls = {topo.area: ls}
        reg = get_registry()
        k0 = reg.counter_get("ops.spec_skips")
        inj = get_injector()
        inj.arm("decision.spf_solve", FaultSchedule.fail_once())
        assert solver.speculate_views(root, area_ls) == 0
        assert reg.counter_get("ops.spec_skips") == k0 + 1
        assert inj.any_armed, "stand-down must not consume the charge"

    def test_decision_speculation_stages_when_clear(self):
        """With no charge armed the same call stages warm views
        (ops.spec_dispatches) and the next build consumes them
        (ops.spec_hits)."""
        from openr_tpu.decision.prefix_state import PrefixState
        from openr_tpu.decision.spf_solver import SpfSolver

        topo = topologies.grid(4)
        ls = load(topo)
        root = sorted(ls.get_adjacency_databases())[0]
        solver = SpfSolver(root, backend="device")
        area_ls = {topo.area: ls}
        ps = PrefixState()
        reg = get_registry()
        d0 = reg.counter_get("ops.spec_dispatches")
        h0 = reg.counter_get("ops.spec_hits")
        assert solver.speculate_views(root, area_ls) == 1
        assert reg.counter_get("ops.spec_dispatches") == d0 + 1
        solver.build_route_db(root, area_ls, ps)
        assert reg.counter_get("ops.spec_hits") == h0 + 1


class TestCompileFlatnessAndWorldBatch:
    def test_zero_retraces_across_pipeline_depths(self):
        """After warmup, bursts at depths 1, 2 and 3 compile NOTHING:
        pipelining reuses the eager path's per-(tag, bucket)
        executables."""
        topo = make_topo()
        ls = load(topo)
        eng = make_engine("ell", ls)
        edges = safe_edges(ls, eng.sample_names, 3)
        for ei, metric in EVENTS:
            n, s = edges[ei]
            eng.churn(ls, mutate_metric(ls, n, s, metric))
        eng.churn_burst(ls, [
            lambda: mutate_metric(ls, edges[0][0], edges[0][1], 4),
            lambda: mutate_metric(ls, edges[1][0], edges[1][1], 6),
        ])
        reg = get_registry()
        aot0 = reg.counter_get("ops.aot_compiles")
        jax0 = reg.counter_get("jax.compile_count")
        for metrics in ((8,), (9, 12), (13, 5, 7)):
            eng.churn_burst(ls, [
                (lambda n=edges[k][0], s=edges[k][1], m=m:
                 mutate_metric(ls, n, s, m))
                for k, m in enumerate(metrics)
            ])
        assert reg.counter_get("ops.aot_compiles") == aot0
        assert reg.counter_get("jax.compile_count") == jax0

    def test_world_batch_pipelined_matches_sequential(self):
        """solve_views_pipelined over disjoint-shape batches returns
        per-batch views bit-identical to per-batch solve_views, while
        overlapping the launches (ops.pipelined_dispatches) and
        folding the batches into one drain."""
        from openr_tpu.ops.world_batch import WorldManager

        topos_a = [topologies.grid(3), topologies.grid(4)]
        topos_b = [
            topologies.random_mesh(24, 3, seed=7),
            topologies.random_mesh(30, 4, seed=11),
        ]
        batch_a = [
            (f"a{i}", load(t), sorted(load(t).get_adjacency_databases())[0])
            for i, t in enumerate(topos_a)
        ]
        batch_b = [
            (f"b{i}", load(t), sorted(load(t).get_adjacency_databases())[0])
            for i, t in enumerate(topos_b)
        ]
        ref_mgr = WorldManager(slots_per_bucket=8)
        ref_views = [
            ref_mgr.solve_views(batch_a),
            ref_mgr.solve_views(batch_b),
        ]
        reg = get_registry()
        drains0 = reg.counter_get("ops.pipeline_drains")
        pip_mgr = WorldManager(slots_per_bucket=8)
        got_views = pip_mgr.solve_views_pipelined([batch_a, batch_b])
        assert reg.counter_get("ops.pipeline_drains") == drains0 + 1
        for ref_batch, got_batch in zip(ref_views, got_views):
            for (rg, rs, rp), (gg, gs, gp) in zip(ref_batch, got_batch):
                assert rs == gs
                np.testing.assert_array_equal(np.asarray(rp),
                                              np.asarray(gp))
