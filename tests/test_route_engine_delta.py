"""Delta-compacted, double-buffered churn readback: after ANY event
class (bucketed incremental, full-width refresh, cold rebuild) the
delta-applied resident host result must be bit-identical to a
from-scratch cold build of the same engine class — digests, nh_totals,
sample metrics AND sample masks — for the ELL, grouped, and
mesh-sharded engines. Plus the pipelining contract: defer_consume
leaves the host result stale behind a PendingDelta, coalesced windows
fold to one dispatch, and readback accounting scales with changed rows
rather than the product width."""

import numpy as np
import pytest
from dataclasses import replace

from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.ops import route_engine, route_sweep


def load(topo):
    ls = LinkState(area=topo.area)
    for name, db in sorted(topo.adj_dbs.items()):
        ls.update_adjacency_database(db)
    return ls


def full_digests(ls):
    names = sorted(ls.get_adjacency_databases().keys())
    result = route_sweep.all_sources_route_sweep(
        ls, [names[0]], block=64
    )
    return route_sweep.digests_by_name(result)


def engine_digests(engine):
    return route_sweep.digests_by_name(engine.result)


def mutate_metric(ls, node, i, metric):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {node, adjs[i].other_node_name}


def make_engine(kind, ls):
    """One of the four engine configurations under test."""
    names = sorted(ls.get_adjacency_databases().keys())
    if kind in ("ell_sharded", "grouped_sharded"):
        import jax

        from openr_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(jax.devices())
        cls = (
            route_engine.RouteSweepEngine
            if kind == "ell_sharded"
            else route_engine.GroupedRouteSweepEngine
        )
        return cls(ls, [names[0]], align=16, mesh=mesh)
    cls = (
        route_engine.RouteSweepEngine
        if kind == "ell"
        else route_engine.GroupedRouteSweepEngine
    )
    return cls(ls, [names[0]])


def assert_bit_identical(engine, ls, kind):
    """The delta-applied resident result vs a from-scratch cold build
    of the SAME engine class: every assembled field must match bit for
    bit (same class + same ls ordering => identical layout, so the
    engine-local mask bit assignment is directly comparable)."""
    twin = make_engine(kind, ls)
    a, b = engine.result, twin.result
    assert engine.graph.node_names == twin.graph.node_names
    np.testing.assert_array_equal(a.digests, b.digests)
    np.testing.assert_array_equal(a.nh_totals, b.nh_totals)
    np.testing.assert_array_equal(a.sample_metrics, b.sample_metrics)
    np.testing.assert_array_equal(a.sample_masks, b.sample_masks)


KINDS = ("ell", "grouped", "ell_sharded", "grouped_sharded")


@pytest.mark.parametrize("kind", KINDS)
class TestDeltaApplyParity:
    def _topo(self):
        return topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )

    def test_incremental_delta_apply(self, kind):
        ls = load(self._topo())
        engine = make_engine(kind, ls)
        rsw = next(n for n in engine.graph.node_names
                   if n.startswith("rsw"))
        for metric in (7, 3, 11):
            moved = engine.churn(ls, mutate_metric(ls, rsw, 0, metric))
            assert moved is not None and moved != []
            # moved names come from the DEVICE diff: every row the
            # delta touched, nothing else
            assert engine.last_delta_rows == len(moved)
        assert engine.incremental_events == 3
        assert engine.full_refreshes == 0
        assert_bit_identical(engine, ls, kind)
        # the delta-applied sample rows answer route queries correctly
        sample = engine.sample_names[0]
        got = engine.result.routes_from(sample)
        for dst, res in ls.run_spf(sample).items():
            if dst == sample:
                continue
            m, nhs = got[dst]
            assert m == res.metric and nhs == set(res.next_hops), dst

    def test_full_width_refresh_delta_apply(self, kind, monkeypatch):
        monkeypatch.setattr(route_engine, "_ROW_BUCKETS", (8,))
        ls = load(self._topo())
        engine = make_engine(kind, ls)
        engine._k_hint = 8
        # this test targets the FULL-WIDTH rung of the overflow policy;
        # a zero budget makes every converged frontier fall back
        # (tests/test_frontier_parity.py owns the frontier rung)
        engine.frontier_threshold = 0.0
        ssw = next(n for n in engine.graph.node_names
                   if n.startswith("ssw"))
        moved = engine.churn(ls, mutate_metric(ls, ssw, 0, 9))
        assert moved is not None and len(moved) > 8
        assert engine.full_refreshes == 1
        assert engine.cold_builds == 1
        # full-width DISPATCH, delta READBACK: the moved names are the
        # device diff and the accounting matches it
        assert engine.last_delta_rows == len(moved)
        assert_bit_identical(engine, ls, kind)

    def test_cold_rebuild_after_deltas(self, kind):
        """A cold rebuild layered on top of delta-applied state (the
        third event class) must leave the same bit-identical result —
        and drain any pending delta first."""
        ls = load(self._topo())
        engine = make_engine(kind, ls)
        rsw = next(n for n in engine.graph.node_names
                   if n.startswith("rsw"))
        pending = engine.churn(
            ls, mutate_metric(ls, rsw, 0, 7), defer_consume=True
        )
        assert isinstance(pending, route_engine.PendingDelta)
        engine._build(ls)  # the cold path every fallback funnels into
        assert pending.consumed, "cold rebuild must drain the delta"
        assert engine.cold_builds == 2
        assert_bit_identical(engine, ls, kind)


class TestDoubleBuffer:
    def _setup(self):
        topo = topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls = load(topo)
        return ls, make_engine("ell", ls)

    def test_defer_returns_pending_and_result_lags(self):
        ls, engine = self._setup()
        rsw = next(n for n in engine.graph.node_names
                   if n.startswith("rsw"))
        before = dict(engine_digests(engine))
        pending = engine.churn(
            ls, mutate_metric(ls, rsw, 0, 7), defer_consume=True
        )
        assert isinstance(pending, route_engine.PendingDelta)
        assert not pending.consumed
        # device state committed, HOST result intentionally stale
        assert engine.version == ls.topology_version
        assert engine_digests(engine) == before
        names = pending.wait()
        assert pending.consumed and names
        assert engine_digests(engine) == full_digests(ls)
        assert_bit_identical(engine, ls, "ell")
        # wait() is idempotent
        assert pending.wait() == names

    def test_next_event_consumes_prior_delta_in_overlap(self):
        ls, engine = self._setup()
        rsws = [n for n in engine.graph.node_names
                if n.startswith("rsw")]
        p1 = engine.churn(
            ls, mutate_metric(ls, rsws[0], 0, 7), defer_consume=True
        )
        assert not p1.consumed
        p2 = engine.churn(
            ls, mutate_metric(ls, rsws[1], 0, 9), defer_consume=True
        )
        # event 2's dispatch window consumed event 1's delta on host
        assert p1.consumed and p1.names
        assert not p2.consumed
        assert engine._pending is p2
        engine.flush()
        assert p2.consumed
        assert engine._pending is None
        assert engine_digests(engine) == full_digests(ls)
        assert_bit_identical(engine, ls, "ell")
        # flush with nothing pending is a no-op
        assert engine.flush() is None

    def test_pipelined_sequence_matches_eager(self):
        """A fully pipelined churn sequence (every event deferred, one
        flush at the end) lands on the same result as the eager
        engine, event names included."""
        topo = topologies.fat_tree(
            pods=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls_a, ls_b = load(topo), load(topo)
        eager = make_engine("ell", ls_a)
        piped = make_engine("ell", ls_b)
        rsw = next(n for n in eager.graph.node_names
                   if n.startswith("rsw"))
        eager_names = []
        piped_handles = []
        for metric in (5, 9, 2, 12):
            eager_names.append(
                eager.churn(ls_a, mutate_metric(ls_a, rsw, 0, metric))
            )
            piped_handles.append(piped.churn(
                ls_b, mutate_metric(ls_b, rsw, 0, metric),
                defer_consume=True,
            ))
        piped.flush()
        assert [p.names for p in piped_handles] == eager_names
        assert engine_digests(piped) == engine_digests(eager)
        assert engine_digests(piped) == full_digests(ls_b)


class TestCoalescing:
    def test_window_folds_to_one_dispatch(self):
        """N patches inside one debounce window through
        churn_coalesced: ONE incremental event, same digests as N
        sequential churns."""
        topo = topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls_a, ls_b = load(topo), load(topo)
        seq = make_engine("ell", ls_a)
        fused = make_engine("ell", ls_b)
        rsws = [n for n in seq.graph.node_names
                if n.startswith("rsw")][:4]
        sets_b = []
        for i, rsw in enumerate(rsws):
            assert seq.churn(
                ls_a, mutate_metric(ls_a, rsw, 0, 3 + i)
            ) is not None
            sets_b.append(mutate_metric(ls_b, rsw, 0, 3 + i))
        moved = fused.churn_coalesced(ls_b, sets_b)
        assert moved is not None
        assert seq.incremental_events == 4
        assert fused.incremental_events == 1
        assert fused.coalesced_events == 1
        assert engine_digests(fused) == engine_digests(seq)
        assert engine_digests(fused) == full_digests(ls_b)
        assert_bit_identical(fused, ls_b, "ell")

    def test_self_cancelling_window_is_noop(self):
        """A patch and its exact inverse inside one window diff to
        nothing against the resident mirrors: zero rows re-solved."""
        topo = topologies.fat_tree(
            pods=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls = load(topo)
        engine = make_engine("ell", ls)
        rsw = next(n for n in engine.graph.node_names
                   if n.startswith("rsw"))
        old = ls.get_adjacency_databases()[rsw].adjacencies[0].metric
        s1 = mutate_metric(ls, rsw, 0, old + 5)
        s2 = mutate_metric(ls, rsw, 0, old)
        assert engine.churn_coalesced(ls, [s1, s2]) == []
        assert engine.incremental_events == 0
        assert engine.coalesced_events == 1
        assert engine_digests(engine) == full_digests(ls)

    def test_single_set_window_not_counted(self):
        topo = topologies.fat_tree(
            pods=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls = load(topo)
        engine = make_engine("ell", ls)
        rsw = next(n for n in engine.graph.node_names
                   if n.startswith("rsw"))
        assert engine.churn_coalesced(
            ls, [mutate_metric(ls, rsw, 0, 7)]
        ) is not None
        assert engine.coalesced_events == 0
        assert engine.incremental_events == 1


class TestMeshPipelining:
    """The pipelining contract ON the 8-way virtual mesh: delta
    segments are read back per shard (addressable shards, async
    host copies) and consumed inside the next event's solve window —
    pipelined must stay bit-identical to eager even when a deferred
    window spans a shard-boundary event (changed rows landing in more
    than one device's row stripe)."""

    def test_pipelined_matches_eager_across_shard_boundary(self):
        topo = topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls_a, ls_b = load(topo), load(topo)
        eager = make_engine("ell_sharded", ls_a)
        piped = make_engine("ell_sharded", ls_b)
        # churn targets in DIFFERENT row stripes of the sharded
        # residents, so consecutive deferred windows cross shards
        ndev = piped.mesh.devices.size
        block = piped.graph.n_pad // ndev
        rsws = [n for n in piped.graph.node_names
                if n.startswith("rsw")]
        by_shard = {}
        for n in rsws:
            by_shard.setdefault(
                piped.graph.node_index[n] // block, n
            )
        targets = list(by_shard.values())[:2]
        assert len(targets) == 2, "need churn in two distinct shards"
        eager_names = []
        handles = []
        for step, metric in enumerate((5, 9, 2, 12)):
            node = targets[step % 2]
            eager_names.append(eager.churn(
                ls_a, mutate_metric(ls_a, node, 0, metric)
            ))
            handles.append(piped.churn(
                ls_b, mutate_metric(ls_b, node, 0, metric),
                defer_consume=True,
            ))
        piped.flush()
        # the deferred deltas really were multi-shard: some window's
        # changed rows landed in more than one per-shard segment
        multi = any(
            sum(1 for c in p.ch_counts if c) >= 2 for p in handles
        )
        assert multi, "no deferred window spanned a shard boundary"
        assert [p.names for p in handles] == eager_names
        assert engine_digests(piped) == engine_digests(eager)
        assert engine_digests(piped) == full_digests(ls_b)
        assert_bit_identical(piped, ls_b, "ell_sharded")


class TestShardedNoReshard:
    """The resharding-free acceptance gate: a 5-event metric-churn run
    on the virtual mesh completes under jax.transfer_guard("disallow")
    (zero implicit host transfers) with ops.reshard_events unmoved
    (zero placement corrections — the tripwire in ShardingPlan.ensure
    counts device-side resharding the guard cannot see)."""

    def test_five_event_churn_zero_reshards_zero_transfers(self):
        import jax

        from openr_tpu.telemetry import get_registry

        topo = topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls = load(topo)
        engine = make_engine("ell_sharded", ls)
        rsw = next(n for n in engine.graph.node_names
                   if n.startswith("rsw"))
        # one eager warm-up event compiles the churn dispatches (cold
        # compilation is not the steady state the gate measures)
        assert engine.churn(ls, mutate_metric(ls, rsw, 0, 3))
        reg = get_registry()
        before = reg.counter_get("ops.reshard_events")
        with jax.transfer_guard("disallow"):
            for metric in (5, 9, 2, 12, 7):
                pending = engine.churn(
                    ls, mutate_metric(ls, rsw, 0, metric),
                    defer_consume=True,
                )
                assert isinstance(pending, route_engine.PendingDelta)
            engine.flush()
        assert reg.counter_get("ops.reshard_events") == before, (
            "churn run forced a placement correction (reshard)"
        )
        assert engine.incremental_events >= 6
        assert engine_digests(engine) == full_digests(ls)
        assert_bit_identical(engine, ls, "ell_sharded")


@pytest.mark.parametrize("kind", ("ell", "ell_sharded"))
class TestReadbackAccounting:
    def test_bytes_scale_with_delta_rows_not_width(self, kind):
        """The readback accounting identity: bytes == one meta row per
        shard segment + changed rows × row width — and a leaf-local
        event's readback is far below the full packed product."""
        topo = topologies.fat_tree(
            pods=4, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=6
        )
        ls = load(topo)
        engine = make_engine(kind, ls)
        full_bytes = (
            engine._packed_dev.shape[0]
            * engine._packed_dev.shape[1] * 4
        )
        rsw = next(n for n in engine.graph.node_names
                   if n.startswith("rsw"))
        pending = engine.churn(
            ls, mutate_metric(ls, rsw, 0, 7), defer_consume=True
        )
        row_bytes = pending.segs[0].shape[1] * 4
        n_segs = len(pending.segs)
        engine.flush()
        assert pending.delta_rows == sum(pending.ch_counts)
        assert pending.readback_bytes == (
            n_segs * row_bytes + pending.delta_rows * row_bytes
        )
        assert engine.last_readback_bytes == pending.readback_bytes
        assert engine.last_delta_rows == pending.delta_rows
        # compaction never reads padding rows (at toy scale a leaf
        # metric event legitimately moves every REAL row — the leaf's
        # distance to every destination changed — so the bench, not
        # this test, demonstrates the orders-of-magnitude gap; here we
        # pin the bound and the exact identity above)
        assert pending.delta_rows <= engine.graph.n
        assert pending.readback_bytes < full_bytes
        assert engine_digests(engine) == full_digests(ls)

    def test_changed_subset_of_affected(self, kind):
        """Compaction drops re-solved-but-identical rows: changed
        counts never exceed the detection's affected counts."""
        topo = topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls = load(topo)
        engine = make_engine(kind, ls)
        fsw = next(n for n in engine.graph.node_names
                   if n.startswith("fsw"))
        pending = engine.churn(
            ls, mutate_metric(ls, fsw, 0, 9), defer_consume=True
        )
        for cnt, ch in zip(pending.counts, pending.ch_counts):
            assert 0 <= ch <= cnt
        assert pending.wait()
        assert engine_digests(engine) == full_digests(ls)
