"""Decision parity corpus, part 2: scenarios from the reference golden
suite (openr/decision/tests/DecisionTest.cpp) not covered by
test_spf_solver / test_decision_module / test_bgp_lfa / test_multiarea.

All written fresh against our API; the reference citations mark which
case each test mirrors.
"""

import time

import pytest

from openr_tpu.decision.decision import Decision, DecisionPendingUpdates
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.graph.linkstate import LinkState, LinkStateChange
from openr_tpu.models import topologies
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    IpPrefix,
    MplsActionCode,
    PerfEvents,
    PrefixDatabase,
    PrefixEntry,
)
from tests.test_decision_module import DecisionHarness, line_topology
from tests.test_linkstate import adj, db


def prefix_db(node, prefixes, area="0"):
    return PrefixDatabase(
        this_node_name=node,
        prefix_entries=tuple(
            PrefixEntry(prefix=IpPrefix.from_str(p)) for p in prefixes
        ),
        area=area,
    )


def network(adj_dbs, prefix_dbs, area="0"):
    ls = LinkState(area=area)
    for a in adj_dbs:
        ls.update_adjacency_database(a)
    ps = PrefixState()
    for p in prefix_dbs:
        ps.update_prefix_database(p)
    return {area: ls}, ps


class TestShortestPathEdgeCases:
    """reference: DecisionTest.cpp:404-530 (ShortestPathTest group)."""

    def test_unreachable_nodes(self):
        # two nodes with no adjacencies at all: no routes, no labels
        area_ls, ps = network(
            [db("1", [], node_label=1), db("2", [], node_label=2)],
            [prefix_db("1", ["fd00:1::/64"]), prefix_db("2", ["fd00:2::/64"])],
        )
        for node in ("1", "2"):
            rdb = SpfSolver(node).build_route_db(node, area_ls, ps)
            assert rdb is not None
            assert len(rdb.unicast_routes) == 0
            # own POP label still programmed
            assert all(
                next(iter(e.nexthops)).mpls_action.action
                == MplsActionCode.POP_AND_LOOKUP
                for e in rdb.mpls_routes.values()
            )

    def test_missing_neighbor_adjacency_db(self):
        # R1 declares adj to R2, but R2's AdjDb was never received:
        # the link is not bidirectional, R2 unreachable
        area_ls, ps = network(
            [db("1", [adj("2", "if_12", "if_21")])],
            [prefix_db("1", ["fd00:1::/64"]), prefix_db("2", ["fd00:2::/64"])],
        )
        rdb = SpfSolver("1").build_route_db("1", area_ls, ps)
        assert rdb is not None
        assert len(rdb.unicast_routes) == 0

    def test_empty_neighbor_adjacency_db(self):
        # R2's AdjDb exists but lists no adjacency back to R1
        area_ls, ps = network(
            [db("1", [adj("2", "if_12", "if_21")]), db("2", [])],
            [prefix_db("1", ["fd00:1::/64"]), prefix_db("2", ["fd00:2::/64"])],
        )
        for node in ("1", "2"):
            rdb = SpfSolver(node).build_route_db(node, area_ls, ps)
            assert rdb is not None
            assert len(rdb.unicast_routes) == 0

    def test_unknown_node_returns_none(self):
        # empty link state: buildRouteDb has no graph for the node
        area_ls, ps = network([], [])
        assert SpfSolver("1").build_route_db("1", area_ls, ps) is None
        assert SpfSolver("2").build_route_db("2", area_ls, ps) is None


class TestAdjacencyUpdate:
    """reference: DecisionTest.cpp:531 SpfSolver.AdjacencyUpdate —
    change-flag classification drives full-rebuild decisions."""

    def test_change_flag_sequence(self):
        ls = LinkState(area="0")
        db1 = db("1", [adj("2", "if_12", "if_21", metric=10)], node_label=1)
        db2 = db("2", [adj("1", "if_21", "if_12", metric=10)], node_label=2)

        c = ls.update_adjacency_database(db1)
        assert not c.topology_changed
        assert c.node_label_changed
        c = ls.update_adjacency_database(db2)
        assert c.topology_changed  # link came up (bidirectional now)
        assert c.node_label_changed

        # identical resend: nothing changed
        c = ls.update_adjacency_database(db2)
        assert c == LinkStateChange(False, False, False)

        # nexthop address change: link attributes only, no topology change
        db1_nh = db(
            "1",
            [
                Adjacency(
                    other_node_name="2",
                    if_name="if_12",
                    other_if_name="if_21",
                    metric=10,
                    next_hop_v6=b"\xfe\x80" + b"\x00" * 12 + b"\xb0\x0c",
                )
            ],
            node_label=1,
        )
        c = ls.update_adjacency_database(db1_nh)
        assert not c.topology_changed
        assert c.link_attributes_changed

        # adj label change: link attributes only
        db1_lbl = db(
            "1",
            [
                Adjacency(
                    other_node_name="2",
                    if_name="if_12",
                    other_if_name="if_21",
                    metric=10,
                    next_hop_v6=b"\xfe\x80" + b"\x00" * 12 + b"\xb0\x0c",
                    adj_label=111,
                )
            ],
            node_label=1,
        )
        c = ls.update_adjacency_database(db1_lbl)
        assert not c.topology_changed
        assert c.link_attributes_changed

        # node label change alone
        db1_node_lbl = db(
            "1",
            [
                Adjacency(
                    other_node_name="2",
                    if_name="if_12",
                    other_if_name="if_21",
                    metric=10,
                    next_hop_v6=b"\xfe\x80" + b"\x00" * 12 + b"\xb0\x0c",
                    adj_label=111,
                )
            ],
            node_label=11,
        )
        c = ls.update_adjacency_database(db1_node_lbl)
        assert not c.topology_changed
        assert not c.link_attributes_changed
        assert c.node_label_changed

    def test_route_counts_both_perspectives(self):
        # 1 unicast (peer prefix) + 3 mpls (own POP, peer node, adj) each
        area_ls, ps = network(
            [
                db("1", [adj("2", "if_12", "if_21", adj_label=9001)],
                   node_label=1),
                db("2", [adj("1", "if_21", "if_12", adj_label=9002)],
                   node_label=2),
            ],
            [prefix_db("1", ["fd00:1::/64"]), prefix_db("2", ["fd00:2::/64"])],
        )
        for node in ("1", "2"):
            rdb = SpfSolver(node).build_route_db(node, area_ls, ps)
            assert len(rdb.unicast_routes) == 1
            assert len(rdb.mpls_routes) == 3


class TestMplsOneSided:
    """reference: DecisionTest.cpp:670 MplsRoutes.BasicTest — label
    routes across a mix of one-sided and bidirectional links."""

    def test_label_routes(self):
        # 1 -> 2 one-sided (2 never declares 1); 2 <-> 3 bidirectional.
        # Node 2 has no node label.
        area_ls, ps = network(
            [
                db("1", [adj("2", "if_12", "if_21")], node_label=1),
                db("2", [adj("3", "if_23", "if_32", adj_label=9023)],
                   node_label=0),
                db("3", [adj("2", "if_32", "if_23", adj_label=9032)],
                   node_label=3),
            ],
            [],
        )
        total = 0
        per_node = {}
        for node in ("1", "2", "3"):
            rdb = SpfSolver(node).build_route_db(node, area_ls, ps)
            per_node[node] = rdb.mpls_routes
            total += len(rdb.mpls_routes)
        assert total == 5
        # 1: own POP only (its link is not bidirectional)
        assert set(per_node["1"]) == {1}
        # 2: adj label + swap/php toward 3's node label
        assert set(per_node["2"]) == {9023, 3}
        # 3: own POP + adj label (2 has no node label to route toward)
        assert set(per_node["3"]) == {3, 9032}


class TestDuplicateNodeLabels:
    """reference: DecisionTest.cpp:1946 DuplicateMplsRoutes — when two
    nodes claim the same node label, the smaller node name wins."""

    def test_smaller_name_wins(self):
        area_ls, ps = network(
            [
                db("1", [adj("2", "if_12", "if_21")], node_label=7),
                db(
                    "2",
                    [
                        adj("1", "if_21", "if_12"),
                        adj("3", "if_23", "if_32"),
                    ],
                    node_label=2,
                ),
                db("3", [adj("2", "if_32", "if_23")], node_label=7),
            ],
            [],
        )
        rdb = SpfSolver("2").build_route_db("2", area_ls, ps)
        entry = rdb.mpls_routes[7]
        # label 7 belongs to node "1" (smaller name), so 2's route for it
        # points at 1, not 3
        (nh,) = entry.nexthops
        assert nh.neighbor_node_name == "1"


class TestConnectivity:
    """reference: DecisionTest.cpp:1214 GraphConnectedOrPartitioned."""

    def test_partition_and_heal(self):
        p1 = prefix_db("1", ["fd00:1::/64"])
        p2 = prefix_db("2", ["fd00:2::/64"])
        # partitioned: no adjacency between 1 and 2
        area_ls, ps = network([db("1", []), db("2", [])], [p1, p2])
        rdb = SpfSolver("1").build_route_db("1", area_ls, ps)
        assert len(rdb.unicast_routes) == 0

        # heal: both declare the adjacency
        ls = area_ls["0"]
        ls.update_adjacency_database(db("1", [adj("2", "if_12", "if_21")]))
        change = ls.update_adjacency_database(
            db("2", [adj("1", "if_21", "if_12")])
        )
        assert change.topology_changed
        rdb = SpfSolver("1").build_route_db("1", area_ls, ps)
        assert IpPrefix.from_str("fd00:2::/64") in rdb.unicast_routes


class TestOverloadedLink:
    """reference: DecisionTest.cpp:2936 OverloadLinkTest — an adjacency
    marked overloaded (hard-drained link) carries no transit traffic."""

    def test_overloaded_link_takes_detour(self):
        # triangle: 1-2 direct (metric 1, but overloaded), 1-3-2 (cost 20)
        area_ls, ps = network(
            [
                db(
                    "1",
                    [
                        adj("2", "if_12", "if_21", metric=1, overloaded=True),
                        adj("3", "if_13", "if_31", metric=10),
                    ],
                ),
                db(
                    "2",
                    [
                        adj("1", "if_21", "if_12", metric=1, overloaded=True),
                        adj("3", "if_23", "if_32", metric=10),
                    ],
                ),
                db(
                    "3",
                    [
                        adj("1", "if_31", "if_13", metric=10),
                        adj("2", "if_32", "if_23", metric=10),
                    ],
                ),
            ],
            [prefix_db("2", ["fd00:2::/64"])],
        )
        rdb = SpfSolver("1").build_route_db("1", area_ls, ps)
        entry = rdb.unicast_routes[IpPrefix.from_str("fd00:2::/64")]
        (nh,) = entry.nexthops
        assert nh.neighbor_node_name == "3"
        assert nh.metric == 20

    def test_link_overload_one_direction_suffices(self):
        # overload declared by only one endpoint still drains the link
        # (reference: Link::isOverloaded is an OR of both directions)
        area_ls, ps = network(
            [
                db("1", [adj("2", "if_12", "if_21", overloaded=True)]),
                db("2", [adj("1", "if_21", "if_12")]),
            ],
            [prefix_db("2", ["fd00:2::/64"])],
        )
        rdb = SpfSolver("1").build_route_db("1", area_ls, ps)
        assert len(rdb.unicast_routes) == 0


class TestParallelAdjacencies:
    """reference: DecisionTest.cpp:3374 ParallelAdjRing MultiPathTest —
    ECMP across parallel links between the same node pair."""

    def test_equal_cost_parallel_links_both_used(self):
        area_ls, ps = network(
            [
                db(
                    "1",
                    [
                        adj("2", "if1_12", "if1_21", metric=5),
                        adj("2", "if2_12", "if2_21", metric=5),
                    ],
                ),
                db(
                    "2",
                    [
                        adj("1", "if1_21", "if1_12", metric=5),
                        adj("1", "if2_21", "if2_12", metric=5),
                    ],
                ),
            ],
            [prefix_db("2", ["fd00:2::/64"])],
        )
        rdb = SpfSolver("1").build_route_db("1", area_ls, ps)
        entry = rdb.unicast_routes[IpPrefix.from_str("fd00:2::/64")]
        ifaces = {nh.address.if_name for nh in entry.nexthops}
        assert ifaces == {"if1_12", "if2_12"}
        assert all(nh.metric == 5 for nh in entry.nexthops)

    def test_unequal_parallel_links_min_only(self):
        area_ls, ps = network(
            [
                db(
                    "1",
                    [
                        adj("2", "if1_12", "if1_21", metric=5),
                        adj("2", "if2_12", "if2_21", metric=9),
                    ],
                ),
                db(
                    "2",
                    [
                        adj("1", "if1_21", "if1_12", metric=5),
                        adj("1", "if2_21", "if2_12", metric=9),
                    ],
                ),
            ],
            [prefix_db("2", ["fd00:2::/64"])],
        )
        rdb = SpfSolver("1").build_route_db("1", area_ls, ps)
        entry = rdb.unicast_routes[IpPrefix.from_str("fd00:2::/64")]
        (nh,) = entry.nexthops
        assert nh.address.if_name == "if1_12"
        assert nh.metric == 5


class TestGridStress:
    """reference: DecisionTest.cpp:4358 GridTopology.StressTest."""

    def test_grid_100_full_routes(self):
        topo = topologies.grid(10)
        ls = LinkState(area=topo.area)
        for name in sorted(topo.adj_dbs):
            ls.update_adjacency_database(topo.adj_dbs[name])
        ps = PrefixState()
        for pdb in topo.prefix_dbs.values():
            ps.update_prefix_database(pdb)
        rdb = SpfSolver("node-0").build_route_db(
            "node-0", {topo.area: ls}, ps
        )
        # a route to every other node's loopback
        assert len(rdb.unicast_routes) == 99
        # corner-to-corner distance in a 10x10 grid is 18 hops
        far = topo.prefix_dbs["node-99"].prefix_entries[0].prefix
        assert min(
            nh.metric for nh in rdb.unicast_routes[far].nexthops
        ) == 18


class TestDecisionModuleBehaviors:
    """reference: DecisionTest.cpp DecisionTestFixture cases."""

    @pytest.fixture
    def harness(self):
        h = DecisionHarness("a")
        yield h
        h.stop()

    def test_no_spf_on_irrelevant_publication(self, harness):
        # reference: :5621 NoSpfOnIrrelevantPublication
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        runs = harness.decision.get_counters()["decision.route_build_runs"]
        harness.store.set_key("unrelated:xyz", b"junk", version=1,
                              originator="x")
        time.sleep(0.3)
        assert harness.decision.get_counters()[
            "decision.route_build_runs"
        ] == runs

    def test_no_spf_on_duplicate_publication(self, harness):
        # reference: :5654 NoSpfOnDuplicatePublication — re-announcing
        # identical LSDB content (bumped version, same value) is a no-op
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        runs = harness.decision.get_counters()["decision.route_build_runs"]
        harness.publish_adj(topo.adj_dbs["b"])  # identical content
        harness.publish_prefixes(topo.prefix_dbs["c"])
        time.sleep(0.3)
        assert harness.decision.get_counters()[
            "decision.route_build_runs"
        ] == runs

    def test_duplicate_prefixes_failover(self, harness):
        # reference: :5854 DuplicatePrefixes — anycast advertised by two
        # nodes; when one disappears, traffic shifts to the survivor
        topo = line_topology()
        harness.publish_topology(topo)
        anycast = IpPrefix.from_str("fd00:aaaa::/64")
        harness.publish_prefixes(
            PrefixDatabase(
                this_node_name="b",
                prefix_entries=topo.prefix_dbs["b"].prefix_entries
                + (PrefixEntry(prefix=anycast),),
                area=topo.area,
            )
        )
        harness.publish_prefixes(
            PrefixDatabase(
                this_node_name="c",
                prefix_entries=topo.prefix_dbs["c"].prefix_entries
                + (PrefixEntry(prefix=anycast),),
                area=topo.area,
            )
        )
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        # b is closer (metric 1) than c (metric 3): b wins
        assert {
            nh.neighbor_node_name
            for nh in routes.unicast_routes[anycast].nexthops
        } == {"b"}

        # b withdraws: failover to c
        harness.publish_prefixes(topo.prefix_dbs["b"])
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        assert {
            nh.neighbor_node_name
            for nh in routes.unicast_routes[anycast].nexthops
        } == {"b"}  # still via b: b is the first hop toward c
        assert routes.unicast_routes[anycast].nexthops == {
            nh
            for nh in routes.unicast_routes[
                topo.prefix_dbs["c"].prefix_entries[0].prefix
            ].nexthops
        }

    def test_counters_gauges(self, harness):
        # reference: :6252 Counters + :1964 updateGlobalCounters
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        counters = harness.decision.get_counters()
        assert counters["decision.adj_db_update"] >= 3
        assert counters["decision.prefix_db_update"] >= 3
        assert counters["decision.route_build_runs"] >= 1
        assert counters["decision.publications"] >= 1
        # global gauges
        assert counters["decision.num_nodes"] == 3
        assert counters["decision.num_complete_adjacencies"] == 2
        assert counters["decision.num_partial_adjacencies"] == 0
        assert counters["decision.num_prefixes"] == 3
        assert counters["decision.num_conflicting_prefixes"] == 0


class TestDecisionFixtureMore:
    """reference: DecisionTest.cpp DecisionTestFixture cases — round 2
    additions (:4878 InitialRouteUpdate, :5353 ParallelLinks, :6166
    PerPrefixKeyExpiry, :6361 ExceedMaxBackoff, :5073
    SelfReditributePrefixPublication, :6048 DecisionSubReliability)."""

    @pytest.fixture
    def harness(self):
        h = DecisionHarness("a")
        yield h
        h.stop()

    def test_initial_route_update(self, harness):
        # reference: :4878 — the first emitted delta carries the full
        # initial RIB as updates, nothing as deletes
        topo = line_topology()
        harness.publish_topology(topo)
        updates = harness.drain_updates()
        assert updates
        first = updates[0]
        assert not first.unicast_routes_to_delete
        got = set()
        for u in updates:
            got |= set(u.unicast_routes_to_update)
        for node in ("b", "c"):
            assert topo.prefix_dbs[node].prefix_entries[0].prefix in got

    def test_parallel_links_decision(self, harness):
        # reference: :5353 ParallelLinks — ECMP over equal parallel
        # adjacencies; metric bump collapses to the cheaper link
        from openr_tpu.types import Adjacency

        def adj_db(metric2):
            return AdjacencyDatabase(
                this_node_name="a",
                adjacencies=(
                    Adjacency(
                        other_node_name="b",
                        if_name="if1_ab",
                        other_if_name="if1_ba",
                        metric=10,
                    ),
                    Adjacency(
                        other_node_name="b",
                        if_name="if2_ab",
                        other_if_name="if2_ba",
                        metric=metric2,
                    ),
                ),
                area="0",
            )

        b_side = AdjacencyDatabase(
            this_node_name="b",
            adjacencies=(
                Adjacency(
                    other_node_name="a",
                    if_name="if1_ba",
                    other_if_name="if1_ab",
                    metric=10,
                ),
                Adjacency(
                    other_node_name="a",
                    if_name="if2_ba",
                    other_if_name="if2_ab",
                    metric=10,
                ),
            ),
            area="0",
        )
        harness.publish_adj(adj_db(10))
        harness.publish_adj(b_side)
        b_pfx = IpPrefix.from_str("fd00:b::/64")
        harness.publish_prefixes(prefix_db("b", ["fd00:b::/64"]))
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        ifaces = {
            nh.address.if_name
            for nh in routes.unicast_routes[b_pfx].nexthops
        }
        assert ifaces == {"if1_ab", "if2_ab"}

        # bump one link's metric: single next-hop remains
        harness.publish_adj(adj_db(20))
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        ifaces = {
            nh.address.if_name
            for nh in routes.unicast_routes[b_pfx].nexthops
        }
        assert ifaces == {"if1_ab"}

    def test_per_prefix_key_expiry(self, harness):
        # reference: :6166 PerPrefixKeyExpiry — a TTL'd per-prefix key
        # expires in KvStore and Decision withdraws the route
        from openr_tpu.utils import keys as keyutil
        from openr_tpu.utils import wire

        topo = line_topology()
        for adb in topo.adj_dbs.values():
            harness.publish_adj(adb)
        extra = IpPrefix.from_str("fd00:e0e::/64")
        key = keyutil.per_prefix_key("b", topo.area, extra)
        pdb = PrefixDatabase(
            this_node_name="b",
            prefix_entries=(PrefixEntry(prefix=extra),),
            area=topo.area,
        )
        harness.store.set_key(
            key, wire.dumps(pdb), version=1, originator="b", ttl=500
        )
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        assert extra in routes.unicast_routes

        # wait past the TTL: the key expires, the route is withdrawn
        deadline = time.time() + 5.0
        while time.time() < deadline:
            routes = harness.decision.get_decision_route_db()
            if extra not in routes.unicast_routes:
                break
            time.sleep(0.1)
        assert extra not in routes.unicast_routes

    def test_exceed_max_backoff(self, harness):
        # reference: :6361 ExceedMaxBackoff — a continuous update stream
        # cannot starve route builds past the debounce ceiling
        topo = line_topology()
        harness.publish_topology(topo)
        harness.drain_updates()
        runs_before = harness.decision.get_counters()[
            "decision.route_build_runs"
        ]
        # stream updates for ~8x the max debounce (0.05s in the harness)
        extra = IpPrefix.from_str("fd00:7e7::/64")
        end = time.time() + 0.4
        i = 0
        while time.time() < end:
            i += 1
            harness.publish_prefixes(
                PrefixDatabase(
                    this_node_name="c",
                    prefix_entries=topo.prefix_dbs["c"].prefix_entries
                    + (PrefixEntry(prefix=extra),) * (i % 2),
                    area=topo.area,
                )
            )
            time.sleep(0.01)
        harness.drain_updates()
        runs_after = harness.decision.get_counters()[
            "decision.route_build_runs"
        ]
        # at least one build happened DURING the stream (max-backoff fired),
        # and far fewer builds than publications (min-backoff coalesced)
        assert runs_after > runs_before
        assert runs_after - runs_before < i

    def test_self_advertised_anycast_no_local_route(self, harness):
        # reference: :5073 flavor — a prefix we advertise ourselves is
        # never programmed locally, even when others advertise it too
        topo = line_topology()
        harness.publish_topology(topo)
        anycast = IpPrefix.from_str("fd00:5e1f::/64")
        for node in ("a", "c"):
            harness.publish_prefixes(
                PrefixDatabase(
                    this_node_name=node,
                    prefix_entries=topo.prefix_dbs[node].prefix_entries
                    + (PrefixEntry(prefix=anycast),),
                    area=topo.area,
                )
            )
        harness.drain_updates()
        routes = harness.decision.get_decision_route_db()
        assert anycast not in routes.unicast_routes

    def test_decision_sub_reliability(self):
        # reference: :6048 DecisionSubReliability — a burst of hundreds of
        # publications is fully absorbed; the final RIB matches a clean
        # solver run over the final state
        import random

        rng = random.Random(7)
        topo = topologies.grid(4)
        harness = DecisionHarness("node-0")
        try:
            self._run_sub_reliability(harness, rng, topo)
        finally:
            harness.stop()

    def _run_sub_reliability(self, harness, rng, topo):
        harness.publish_topology(topo)
        nodes = sorted(topo.adj_dbs)
        # churn: random metric changes across the grid
        for step in range(200):
            node = rng.choice(nodes)
            adb = topo.adj_dbs[node]
            adjs = tuple(
                Adjacency(
                    other_node_name=a.other_node_name,
                    if_name=a.if_name,
                    other_if_name=a.other_if_name,
                    metric=rng.randint(1, 10),
                    next_hop_v6=a.next_hop_v6,
                    next_hop_v4=a.next_hop_v4,
                    adj_label=a.adj_label,
                )
                for a in adb.adjacencies
            )
            topo.adj_dbs[node] = AdjacencyDatabase(
                this_node_name=node,
                adjacencies=adjs,
                node_label=adb.node_label,
                area=adb.area,
            )
            harness.publish_adj(topo.adj_dbs[node])
        harness.drain_updates()

        # clean-room reference: fresh LinkState + solver over final state
        ls = LinkState(area=topo.area)
        for n in nodes:
            ls.update_adjacency_database(topo.adj_dbs[n])
        ps = PrefixState()
        for pdb in topo.prefix_dbs.values():
            ps.update_prefix_database(pdb)
        expected = SpfSolver("node-0").build_route_db(
            "node-0", {topo.area: ls}, ps
        )
        got = harness.decision.get_decision_route_db()
        assert got.unicast_routes == expected.unicast_routes


class TestBgpIgpTieBreak:
    """reference: DecisionTest.cpp:907 BGPRedistribution.IgpMetric — metric
    vectors tie on a tie-breaker entity, so the IGP distance decides; link
    drains and metric bumps shift the winner and it all heals."""

    def test_igp_metric_walk(self):
        from openr_tpu.decision.metric_vector import (
            CompareType,
            MetricEntity,
            MetricVector,
        )
        from openr_tpu.types import PrefixType

        def bgp_mv(tie_metric):
            # 5 entities, identical except the lowest-priority tie-breaker
            ents = [
                MetricEntity(
                    type=i,
                    priority=i,
                    op=CompareType.WIN_IF_PRESENT,
                    is_best_path_tie_breaker=(i == 4),
                    metric=(tie_metric if i == 4 else i,),
                )
                for i in range(5)
            ]
            return MetricVector(metrics=tuple(ents))

        anycast = IpPrefix.from_str("fd00:b9c::/64")

        def adj_db_1(m2=10, m3=10, drain2=False):
            return db(
                "1",
                [
                    adj("2", "if_12", "if_21", metric=m2,
                        overloaded=drain2),
                    adj("3", "if_13", "if_31", metric=m3),
                ],
            )

        ls = LinkState(area="0")
        ls.update_adjacency_database(adj_db_1())
        ls.update_adjacency_database(db("2", [adj("1", "if_21", "if_12",
                                                  metric=10)]))
        ls.update_adjacency_database(db("3", [adj("1", "if_31", "if_13",
                                                  metric=10)]))
        ps = PrefixState()
        for node, tie in (("2", 4), ("3", 100)):
            ps.update_prefix_database(
                PrefixDatabase(
                    this_node_name=node,
                    prefix_entries=(
                        PrefixEntry(
                            prefix=IpPrefix.from_str(f"fd00:{node}::/64")
                        ),
                        PrefixEntry(
                            prefix=anycast,
                            type=PrefixType.BGP,
                            mv=bgp_mv(tie),
                        ),
                    ),
                    area="0",
                )
            )
        solver = SpfSolver("1", enable_best_route_selection=False)
        area_ls = {"0": ls}

        def anycast_hops():
            rdb = solver.build_route_db("1", area_ls, ps)
            entry = rdb.unicast_routes.get(anycast)
            if entry is None:
                return None
            return {(nh.neighbor_node_name, nh.metric)
                    for nh in entry.nexthops}

        # step 1: equidistant tie-broken advertisers -> ECMP
        assert anycast_hops() == {("2", 10), ("3", 10)}
        # step 2: node 3 farther -> node 2 only
        ls.update_adjacency_database(adj_db_1(m3=20))
        assert anycast_hops() == {("2", 10)}
        # step 3: drain the 1-2 link -> node 3 takes over
        ls.update_adjacency_database(adj_db_1(m3=20, drain2=True))
        assert anycast_hops() == {("3", 20)}
        # step 4: bump drained link metric, still node 3
        ls.update_adjacency_database(adj_db_1(m2=20, m3=20, drain2=True))
        assert anycast_hops() == {("3", 20)}
        # step 5: undrain -> equidistant ECMP again
        ls.update_adjacency_database(adj_db_1(m2=20, m3=20))
        assert anycast_hops() == {("2", 20), ("3", 20)}


class TestDecisionPendingUpdates:
    """reference: DecisionTest.cpp:6485-6545 DecisionPendingUpdates unit
    group."""

    def test_needs_full_rebuild_on_topology_change(self):
        p = DecisionPendingUpdates("me")
        assert not p.needs_full_rebuild()
        assert not p.needs_route_update()
        p.apply_link_state_change(
            "other", LinkStateChange(topology_changed=True)
        )
        assert p.needs_full_rebuild()
        assert p.needs_route_update()
        p.reset()
        assert not p.needs_full_rebuild()

    def test_link_attributes_only_matter_for_self(self):
        p = DecisionPendingUpdates("me")
        p.apply_link_state_change(
            "other", LinkStateChange(link_attributes_changed=True)
        )
        assert not p.needs_full_rebuild()
        p.apply_link_state_change(
            "me", LinkStateChange(link_attributes_changed=True)
        )
        assert p.needs_full_rebuild()

    def test_updated_prefixes_accumulate_without_full_rebuild(self):
        p = DecisionPendingUpdates("me")
        pfx1 = IpPrefix.from_str("fd00:1::/64")
        pfx2 = IpPrefix.from_str("fd00:2::/64")
        p.apply_prefix_state_change({pfx1})
        p.apply_prefix_state_change({pfx2})
        assert not p.needs_full_rebuild()
        assert p.needs_route_update()
        assert p.updated_prefixes == {pfx1, pfx2}
        p.reset()
        assert p.updated_prefixes == set()

    def test_perf_events_keep_oldest_chain(self):
        p = DecisionPendingUpdates("me")
        old = PerfEvents()
        old.add("n1", "FIRST")
        time.sleep(0.01)
        new = PerfEvents()
        new.add("n2", "SECOND")
        p.apply_prefix_state_change(
            {IpPrefix.from_str("fd00:1::/64")}, new
        )
        p.apply_prefix_state_change(
            {IpPrefix.from_str("fd00:2::/64")}, old
        )
        events = p.move_out_events()
        assert events is not None
        names = [e.event_descr for e in events.events]
        assert "FIRST" in names  # oldest chain won
        assert p.move_out_events() is None


class TestMultiAreaBestPath:
    """reference: DecisionTest.cpp:4930 MultiAreaBestPathCalculation —
    node 1 and node 4 straddle areas A and B; routes resolve per area,
    and a prefix reachable through both areas at equal cost ECMPs across
    the area boundary."""

    @pytest.mark.parametrize("backend", ["host", "device"])
    def test_cross_area_ecmp(self, backend):
        ls_a = LinkState(area="A")
        ls_a.update_adjacency_database(
            db("1", [adj("2", "if_12", "if_21", metric=10)], area="A")
        )
        ls_a.update_adjacency_database(
            db(
                "2",
                [
                    adj("1", "if_21", "if_12", metric=10),
                    adj("4", "if_24", "if_42", metric=10),
                ],
                area="A",
            )
        )
        ls_a.update_adjacency_database(
            db("4", [adj("2", "if_42", "if_24", metric=10)], area="A")
        )
        ls_b = LinkState(area="B")
        ls_b.update_adjacency_database(
            db("1", [adj("3", "if_13", "if_31", metric=10)], area="B")
        )
        ls_b.update_adjacency_database(
            db(
                "3",
                [
                    adj("1", "if_31", "if_13", metric=10),
                    adj("4", "if_34", "if_43", metric=10),
                ],
                area="B",
            )
        )
        ls_b.update_adjacency_database(
            db("4", [adj("3", "if_43", "if_34", metric=10)], area="B")
        )
        ps = PrefixState()
        ps.update_prefix_database(prefix_db("1", ["fd00:1::/64"], area="A"))
        ps.update_prefix_database(prefix_db("2", ["fd00:2::/64"], area="A"))
        ps.update_prefix_database(prefix_db("3", ["fd00:3::/64"], area="B"))
        ps.update_prefix_database(prefix_db("4", ["fd00:4::/64"], area="B"))
        area_ls = {"A": ls_a, "B": ls_b}

        def hops(node, pfx):
            rdb = SpfSolver(node, backend=backend).build_route_db(
                node, area_ls, ps
            )
            entry = rdb.unicast_routes.get(IpPrefix.from_str(pfx))
            if entry is None:
                return None
            return {
                (nh.neighbor_node_name, nh.metric, nh.area)
                for nh in entry.nexthops
            }

        # node 1: addr2 via area A, addr3 via area B, addr4 (originated
        # only into B) ECMP across BOTH areas at cost 20
        assert hops("1", "fd00:2::/64") == {("2", 10, "A")}
        assert hops("1", "fd00:3::/64") == {("3", 10, "B")}
        assert hops("1", "fd00:4::/64") == {
            ("2", 20, "A"),
            ("3", 20, "B"),
        }
        # node 2 only participates in A: sees addr1 (and addr4 via the
        # area-A path through 4's area-A membership)
        assert hops("2", "fd00:1::/64") == {("1", 10, "A")}
        assert hops("2", "fd00:3::/64") is None
        # node 3 only in B
        assert hops("3", "fd00:4::/64") == {("4", 10, "B")}
        assert hops("3", "fd00:2::/64") is None


class TestCompatibilityNode:
    """reference: DecisionTest.cpp:1377 ConnectivityTest.CompatibilityNodeTest
    — nodes whose adjacencies carry a DIFFERENT (older) adjacency-label
    numbering still form bidirectional links and route correctly,
    including the ECMP case where an asymmetric metric makes the direct
    and transit paths equal-cost."""

    def test_old_label_space_routes(self):
        ls = LinkState(area="0")
        ps = PrefixState()
        # "old" adjacencies: same links, different adj-label space
        # (1000021-style labels vs 10000x) — labels are opaque to the
        # topology; only (node, iface) pairs identify a link
        ls.update_adjacency_database(db("2", [
            adj("1", "2/1", "1/2", metric=10, adj_label=1000011),
            adj("3", "2/3", "3/2", metric=10, adj_label=100003),
        ], node_label=2))
        ls.update_adjacency_database(db("3", [
            adj("2", "3/2", "2/3", metric=10, adj_label=100002),
            adj("1", "3/1", "1/3", metric=10, adj_label=1000012),
        ], node_label=3))
        ls.update_adjacency_database(db("1", [
            adj("2", "1/2", "2/1", metric=10, adj_label=1000021),
        ], node_label=1))
        # node 1 re-announces with BOTH adjacencies, then bumps the
        # metric toward 2 (adj12_old_2): exercises versioned updates
        # on a mixed-label-space fabric
        ls.update_adjacency_database(db("1", [
            adj("2", "1/2", "2/1", metric=10, adj_label=1000021),
            adj("3", "1/3", "3/1", metric=10, adj_label=1000031),
        ], node_label=1))
        ls.update_adjacency_database(db("1", [
            adj("2", "1/2", "2/1", metric=20, adj_label=1000022),
            adj("3", "1/3", "3/1", metric=10, adj_label=1000031),
        ], node_label=1))
        for n in ("1", "2", "3"):
            ps.update_prefix_database(
                prefix_db(n, [f"fd00:{n}::/64"])
            )
        area_ls = {"0": ls}

        from tests.test_spf_solver import nh_neighbors

        # node 1 -> addr2: direct (metric 20) ties the transit path
        # via 3 (10 + 10) -> ECMP over both neighbors
        rdb1 = SpfSolver("1").build_route_db("1", area_ls, ps)
        e2 = rdb1.unicast_routes[IpPrefix.from_str("fd00:2::/64")]
        assert nh_neighbors(e2) == {"2", "3"}
        assert all(nh.metric == 20 for nh in e2.nexthops)
        e3 = rdb1.unicast_routes[IpPrefix.from_str("fd00:3::/64")]
        assert nh_neighbors(e3) == {"3"}
        # node 2 routes to both others directly
        rdb2 = SpfSolver("2").build_route_db("2", area_ls, ps)
        assert nh_neighbors(
            rdb2.unicast_routes[IpPrefix.from_str("fd00:1::/64")]
        ) == {"1"}
        assert nh_neighbors(
            rdb2.unicast_routes[IpPrefix.from_str("fd00:3::/64")]
        ) == {"3"}
        # the reference's 21-route shape is 6 unicast + 9 node-label +
        # 6 adj-label across the three perspectives; per perspective
        # that is 2 unicast + (own POP + 2 peer node labels) + 2
        # adj-labels — assert node 1's exact MPLS shape (labels here:
        # adj labels 1000022/1000031 + node labels 1/2/3)
        assert len(rdb1.unicast_routes) == 2
        mpls1 = rdb1.mpls_routes
        assert len(mpls1) == 5, sorted(mpls1)
        pop = mpls1[1]  # own node label: POP_AND_LOOKUP
        assert all(
            nh.mpls_action.action == MplsActionCode.POP_AND_LOOKUP
            for nh in pop.nexthops
        )
        # peer node label 3: direct neighbor -> PHP
        assert all(
            nh.mpls_action.action == MplsActionCode.PHP
            for nh in mpls1[3].nexthops
        )
        # node label 2 ties direct (20) with transit via 3 (10+10):
        # the direct leg PHPs, the transit leg SWAPs
        acts = {
            (nh.neighbor_node_name, nh.mpls_action.action)
            for nh in mpls1[2].nexthops
        }
        assert acts == {
            ("2", MplsActionCode.PHP), ("3", MplsActionCode.SWAP),
        }
        # the old-space adj labels program as-is
        assert {1000022, 1000031} <= set(mpls1)


class TestPrefixWithMixedTypeRoutes:
    """reference: DecisionTest.cpp:6412
    EnableBestRouteSelectionFixture.PrefixWithMixedTypeRoutes — one
    prefix announced by node2 as BGP type and node3 as RIB type; best
    route selection picks across the types by metrics (NOT by
    announcing type), falling back to the full candidate set on ties."""

    def test_mixed_bgp_rib_same_prefix(self):
        from openr_tpu.types import PrefixType

        ls = LinkState(area="0")
        ps = PrefixState()
        ls.update_adjacency_database(db("1", [
            adj("2", "1/2", "2/1", metric=10),
            adj("3", "1/3", "3/1", metric=10),
        ], node_label=1))
        ls.update_adjacency_database(db("2", [
            adj("1", "2/1", "1/2", metric=10),
        ], node_label=2))
        ls.update_adjacency_database(db("3", [
            adj("1", "3/1", "1/3", metric=10),
        ], node_label=3))
        shared = IpPrefix.from_str("fd00:10::/64")
        from openr_tpu.types.lsdb import MetricVector

        # the reference's BGP entry carries an EMPTY MetricVector (not
        # absent — an absent MV on a BGP advertiser blocks the route)
        ps.update_prefix_database(PrefixDatabase(
            this_node_name="2",
            prefix_entries=(
                PrefixEntry(
                    prefix=shared, type=PrefixType.BGP,
                    mv=MetricVector(),
                ),
            ),
            area="0",
        ))
        ps.update_prefix_database(PrefixDatabase(
            this_node_name="3",
            prefix_entries=(
                PrefixEntry(prefix=shared, type=PrefixType.RIB),
            ),
            area="0",
        ))
        area_ls = {"0": ls}

        from tests.test_spf_solver import nh_neighbors

        # best-route-selection ON (the fixture's enabled leg): equal
        # metrics on both announcements -> ECMP across the two
        # announcing nodes regardless of their differing types
        rdb = SpfSolver(
            "1", enable_best_route_selection=True
        ).build_route_db("1", area_ls, ps)
        assert nh_neighbors(rdb.unicast_routes[shared]) == {"2", "3"}
        # a higher path preference on the RIB announcement wins the
        # selection outright (metrics dominate type)
        from openr_tpu.types import PrefixMetrics

        ps.update_prefix_database(PrefixDatabase(
            this_node_name="3",
            prefix_entries=(
                PrefixEntry(
                    prefix=shared, type=PrefixType.RIB,
                    metrics=PrefixMetrics(
                        version=1, path_preference=2000,
                        source_preference=100, distance=0,
                    ),
                ),
            ),
            area="0",
        ))
        rdb = SpfSolver(
            "1", enable_best_route_selection=True
        ).build_route_db("1", area_ls, ps)
        assert nh_neighbors(rdb.unicast_routes[shared]) == {"3"}
