"""Fib module tests (reference analogue: openr/fib/tests/FibTest.cpp)."""

import time

import pytest

from openr_tpu.decision.rib import (
    DecisionRouteUpdate,
    RibMplsEntry,
    RibUnicastEntry,
)
from openr_tpu.fib.fib import OPENR_CLIENT_ID, Fib
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.fib_service import MockFibAgent
from openr_tpu.types import BinaryAddress, IpPrefix, NextHop


def wait_until(pred, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def rib_entry(prefix_str, nh="fe80::1", metric=1):
    return RibUnicastEntry(
        prefix=IpPrefix.from_str(prefix_str),
        nexthops={
            NextHop(
                address=BinaryAddress.from_str(nh, if_name="if0"),
                metric=metric,
            )
        },
    )


@pytest.fixture
def fib_setup():
    agent = MockFibAgent()
    route_q = ReplicateQueue(name="routes")
    fib = Fib(
        "node-a",
        agent,
        route_q,
        keepalive_interval_s=0.1,
        retry_min_s=0.02,
        retry_max_s=0.2,
    )
    fib.start()
    yield agent, route_q, fib
    fib.stop()


def push_update(route_q, entries=(), deletes=(), mpls=(), mpls_deletes=()):
    update = DecisionRouteUpdate()
    for e in entries:
        update.unicast_routes_to_update[e.prefix] = e
    update.unicast_routes_to_delete.extend(deletes)
    update.mpls_routes_to_update.extend(mpls)
    update.mpls_routes_to_delete.extend(mpls_deletes)
    route_q.push(update)


class TestFib:
    def test_programs_routes(self, fib_setup):
        agent, route_q, fib = fib_setup
        push_update(route_q, entries=[rib_entry("fd00::/64")])
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 1
        )
        # first programming is a full sync (cold start)
        assert agent.counters["sync_fib"] >= 1

    def test_incremental_add_delete(self, fib_setup):
        agent, route_q, fib = fib_setup
        push_update(route_q, entries=[rib_entry("fd00:1::/64")])
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 1
        )
        push_update(
            route_q,
            entries=[rib_entry("fd00:2::/64")],
            deletes=[IpPrefix.from_str("fd00:1::/64")],
        )
        assert wait_until(
            lambda: [
                r.dest.to_str()
                for r in agent.get_route_table_by_client(OPENR_CLIENT_ID)
            ]
            == ["fd00:2::/64"]
        )
        assert agent.counters["delete_unicast"] == 1

    def test_mpls_routes(self, fib_setup):
        agent, route_q, fib = fib_setup
        push_update(
            route_q,
            mpls=[
                RibMplsEntry(
                    100101,
                    {
                        NextHop(
                            address=BinaryAddress.from_str("fe80::2"),
                            metric=1,
                        )
                    },
                )
            ],
        )
        assert wait_until(
            lambda: len(agent.get_mpls_route_table_by_client(OPENR_CLIENT_ID))
            == 1
        )
        push_update(route_q, mpls_deletes=[100101])
        assert wait_until(
            lambda: len(agent.get_mpls_route_table_by_client(OPENR_CLIENT_ID))
            == 0
        )

    def test_retry_after_agent_failure(self, fib_setup):
        agent, route_q, fib = fib_setup
        push_update(route_q, entries=[rib_entry("fd00:1::/64")])
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 1
        )
        agent.set_fail(True)
        push_update(route_q, entries=[rib_entry("fd00:2::/64")])
        assert wait_until(
            lambda: fib.get_counters()["fib.route_programming_failures"] >= 1
        )
        agent.set_fail(False)
        # retry with backoff resyncs the full table
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 2
        )

    def test_agent_restart_triggers_resync(self, fib_setup):
        agent, route_q, fib = fib_setup
        push_update(route_q, entries=[rib_entry("fd00:1::/64")])
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 1
        )
        agent.restart()
        # keepalive detects the restart and resyncs
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 1
        )

    def test_do_not_install_not_programmed(self, fib_setup):
        agent, route_q, fib = fib_setup
        entry = rib_entry("fd00:9::/64")
        entry.do_not_install = True
        push_update(route_q, entries=[entry, rib_entry("fd00:8::/64")])
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID)) == 1
        )
        # but it is tracked in Fib's own route db
        db = fib.get_route_db()
        assert len(db.unicast_routes) == 2

    def test_longest_prefix_match(self, fib_setup):
        agent, route_q, fib = fib_setup
        push_update(
            route_q,
            entries=[rib_entry("fd00::/16"), rib_entry("fd00:1::/64")],
        )
        assert wait_until(lambda: len(fib.get_route_db().unicast_routes) == 2)
        r = fib.longest_prefix_match("fd00:1::5")
        assert r is not None and r.dest.to_str() == "fd00:1::/64"
        r = fib.longest_prefix_match("fd00:2::5")
        assert r is not None and r.dest.to_str() == "fd00::/16"

    def test_dry_run_programs_nothing(self):
        agent = MockFibAgent()
        route_q = ReplicateQueue()
        fib = Fib("node-a", agent, route_q, dry_run=True)
        fib.start()
        try:
            push_update(route_q, entries=[rib_entry("fd00::/64")])
            assert wait_until(
                lambda: len(fib.get_route_db().unicast_routes) == 1
            )
            assert agent.get_route_table_by_client(OPENR_CLIENT_ID) == []
        finally:
            fib.stop()


class TestFibSyncSemantics:
    """The remaining reference FibTest surface: full-sync stray
    removal, the fib-updates publication, and mixed-family updates
    (reference: fib/tests/FibTest.cpp, 13 cases)."""

    def test_resync_removes_stray_routes(self, fib_setup):
        """syncFib is full-state reconciliation: routes the agent holds
        that Decision no longer wants are withdrawn (reference:
        Fib.cpp:674 syncRouteDb)."""
        from openr_tpu.types import UnicastRoute

        agent, route_q, fib = fib_setup
        push_update(route_q, entries=[rib_entry("fd00:1::/64")])
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID))
            == 1
        )
        # a stray present in the agent table AT RESYNC TIME must be
        # reconciled away (restart() would wipe it before the resync
        # ever saw it — force the resync through the failure/retry
        # path instead, which leaves the stray in place)
        stray = UnicastRoute(dest=IpPrefix.from_str("fd00:bad::/64"))
        agent.add_unicast_routes(OPENR_CLIENT_ID, [stray])
        assert any(
            r.dest == stray.dest
            for r in agent.get_route_table_by_client(OPENR_CLIENT_ID)
        )
        agent.set_fail(True)
        push_update(route_q, entries=[rib_entry("fd00:2::/64")])
        assert wait_until(
            lambda: fib.get_counters()[
                "fib.route_programming_failures"
            ]
            >= 1
        )
        agent.set_fail(False)  # recovery resync = full syncFib
        assert wait_until(
            lambda: sorted(
                r.dest.to_str()
                for r in agent.get_route_table_by_client(OPENR_CLIENT_ID)
            )
            == ["fd00:1::/64", "fd00:2::/64"]
        )

    def test_fib_updates_queue_publishes_programmed_routes(self):
        """Programmed updates are re-published on the fibUpdatesQueue
        for downstream consumers (reference: Main.cpp fibUpdatesQueue,
        Fib.cpp publication after successful programming)."""
        agent = MockFibAgent()
        route_q = ReplicateQueue(name="routes2")
        fib_updates = ReplicateQueue(name="fibUpdates")
        reader = fib_updates.get_reader("test")
        fib = Fib(
            "node-a",
            agent,
            route_q,
            fib_updates_queue=fib_updates,
            keepalive_interval_s=0.1,
        )
        fib.start()
        try:
            push_update(route_q, entries=[rib_entry("fd00:2::/64")])

            def got_update():
                from openr_tpu.messaging.queue import QueueTimeoutError

                try:
                    update = reader.get(timeout=0.2)
                except QueueTimeoutError:
                    return False
                return (
                    IpPrefix.from_str("fd00:2::/64")
                    in update.unicast_routes_to_update
                )

            assert wait_until(got_update)
        finally:
            fib.stop()

    def test_mixed_unicast_mpls_single_update(self, fib_setup):
        from openr_tpu.types import BinaryAddress, MplsAction, MplsActionCode

        agent, route_q, fib = fib_setup
        mpls = RibMplsEntry(
            20007,
            {
                NextHop(
                    address=BinaryAddress.from_str("fe80::7", if_name="if0"),
                    mpls_action=MplsAction(action=MplsActionCode.PHP),
                )
            },
        )
        push_update(
            route_q, entries=[rib_entry("fd00:7::/64")], mpls=[mpls]
        )
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID))
            == 1
            and len(
                agent.get_mpls_route_table_by_client(OPENR_CLIENT_ID)
            )
            == 1
        )
        # withdraw both in one update
        push_update(
            route_q,
            deletes=[IpPrefix.from_str("fd00:7::/64")],
            mpls_deletes=[20007],
        )
        assert wait_until(
            lambda: agent.get_route_table_by_client(OPENR_CLIENT_ID) == []
            and agent.get_mpls_route_table_by_client(OPENR_CLIENT_ID)
            == []
        )
