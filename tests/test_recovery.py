"""Crash-safe recovery: device-loss ladder rung (single-chip, grouped,
sharded-mesh shrink), the tenant plane's torn-dispatch rebuild,
Decision's checkpointed warm boot (bit-identical to the cold oracle),
and Fib graceful restart (hold -> one reconciling sync, routes never
flap)."""

import time

import numpy as np
import pytest

from openr_tpu.config_store.persistent_store import PersistentStore
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.spf_solver import reset_device_caches
from openr_tpu.faults import (
    DegradationSupervisor,
    FaultSchedule,
    HealthState,
    get_injector,
)
from openr_tpu.fib.fib import OPENR_CLIENT_ID, Fib
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.models import topologies
from openr_tpu.ops.world_batch import TENANCY_COUNTERS, WorldManager
from openr_tpu.platform.fib_service import MockFibAgent
from openr_tpu.state import StatePlane
from openr_tpu.telemetry import get_registry
from openr_tpu.types import Publication, Value
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire
from tests.test_fib import push_update, rib_entry, wait_until
from tests.test_route_engine_delta import (
    assert_bit_identical,
    engine_digests,
    full_digests,
    load,
    make_engine,
    mutate_metric,
)


@pytest.fixture(autouse=True)
def _clean_injector():
    get_injector().reset()
    yield
    get_injector().reset()


def _fat_tree_ls():
    return load(
        topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
    )


def _engine_setup(kind):
    ls = _fat_tree_ls()
    engine = make_engine(kind, ls)
    engine.supervisor = DegradationSupervisor(
        "route_engine", backoff_min_s=0.001, backoff_max_s=0.002
    )
    return ls, engine


class TestEngineDeviceLoss:
    @pytest.mark.parametrize("kind", ["ell", "grouped"])
    def test_device_loss_recovers_within_ladder(self, kind):
        reg = get_registry()
        ls, engine = _engine_setup(kind)
        rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))
        lost0 = reg.counter_get("recovery.device_lost")
        rebuilds0 = reg.counter_get("recovery.device_rebuilds")

        get_injector().arm("device.lost", FaultSchedule.fail_once())
        affected = mutate_metric(ls, rsw, 0, 41)
        engine.churn(ls, affected)

        # recover is a middle rung: the walk lands DEGRADED, never host
        assert engine.supervisor.state is HealthState.DEGRADED
        assert engine.device_rebuilds == 1
        assert reg.counter_get("recovery.device_lost") == lost0 + 1
        assert reg.counter_get("recovery.device_rebuilds") == rebuilds0 + 1
        # the event that observed the loss still landed, bit-identical
        assert_bit_identical(engine, ls, kind)

        # next churn goes straight through warm: self-heal to HEALTHY
        engine.churn(ls, mutate_metric(ls, rsw, 0, 42))
        assert engine.supervisor.state is HealthState.HEALTHY
        assert engine.device_rebuilds == 1
        assert_bit_identical(engine, ls, kind)

    def test_non_loss_failure_skips_recover_rung(self):
        reg = get_registry()
        ls, engine = _engine_setup("ell")
        rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))
        idle0 = reg.counter_get("route_engine.rung_failures.recover")
        get_injector().arm(
            "route_engine.dispatch", FaultSchedule.fail_once()
        )
        engine.churn(ls, mutate_metric(ls, rsw, 0, 17))
        # a plain dispatch fault is NOT a device loss: the recover rung
        # stays inert and the walk lands on the cold rung as before
        assert engine.supervisor.state is HealthState.DEGRADED
        assert engine.device_rebuilds == 0
        assert (
            reg.counter_get("route_engine.rung_failures.recover")
            == idle0 + 1
        )
        assert_bit_identical(engine, ls, "ell")

    def test_sharded_mesh_shrinks_to_survivors(self):
        reg = get_registry()
        ls, engine = _engine_setup("ell_sharded")
        assert engine.mesh is not None
        size0 = int(engine.mesh.devices.size)
        assert size0 >= 2
        dead = engine.mesh.devices.flat[0]
        engine._probe_device = lambda dev: dev.id != dead.id

        rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))
        shrinks0 = reg.counter_get("recovery.mesh_shrinks")
        get_injector().arm("device.lost", FaultSchedule.fail_once())
        engine.churn(ls, mutate_metric(ls, rsw, 0, 23))

        # never silent: the shrink is typed and the gauge moves
        assert engine.supervisor.state is HealthState.DEGRADED
        assert engine.mesh_shrinks == 1
        assert reg.counter_get("recovery.mesh_shrinks") == shrinks0 + 1
        assert int(engine.mesh.devices.size) == size0 - 1
        assert reg.snapshot().get("recovery.mesh_size") == size0 - 1
        # route product on the survivor mesh matches the host oracle
        assert engine_digests(engine) == full_digests(ls)

        engine.churn(ls, mutate_metric(ls, rsw, 0, 24))
        assert engine.supervisor.state is HealthState.HEALTHY
        assert engine_digests(engine) == full_digests(ls)

    def test_all_devices_lost_falls_to_host(self):
        ls, engine = _engine_setup("ell_sharded")
        engine._probe_device = lambda dev: False
        rsw = next(n for n in engine.graph.node_names if n.startswith("rsw"))
        get_injector().arm("device.lost", FaultSchedule.fail_once())
        # cold rebuild on a dead mesh also observes the loss; keep the
        # seam armed so every device rung fails and host serves
        get_injector().arm(
            "route_engine.cold_build", FaultSchedule.fail_n(4)
        )
        engine.churn(ls, mutate_metric(ls, rsw, 0, 29))
        assert engine.supervisor.state is HealthState.FALLBACK
        assert engine.host_fallbacks >= 1
        assert engine_digests(engine) == full_digests(ls)


class TestWorldBatchDeviceLoss:
    def test_torn_dispatch_rebuilds_from_host(self):
        reg = get_registry()
        ls1 = load(topologies.grid(4))
        ls2 = load(topologies.grid(4))
        wm = WorldManager(slots_per_bucket=4, max_resident=8)
        root = sorted(ls1.get_adjacency_databases())[0]
        wm.solve_views([("a", ls1, root), ("b", ls2, root)])

        mutate_metric(ls1, root, 0, 55)
        mutate_metric(ls2, root, 1, 77)
        recov0 = TENANCY_COUNTERS["device_loss_recoveries"]
        rehyd0 = TENANCY_COUNTERS["rehydrations"]
        lost0 = reg.counter_get("recovery.device_lost")
        get_injector().arm("device.lost", FaultSchedule.fail_once())
        views = wm.solve_views([("a", ls1, root), ("b", ls2, root)])

        assert TENANCY_COUNTERS["device_loss_recoveries"] == recov0 + 1
        assert reg.counter_get("recovery.device_lost") == lost0 + 1
        # the re-placement after the loss is a WARM rehydration from
        # the host snapshots, not a cold re-admit
        assert TENANCY_COUNTERS["rehydrations"] >= rehyd0 + 2

        oracle = WorldManager(slots_per_bucket=4, max_resident=8)
        ovs = oracle.solve_views([("a", ls1, root), ("b", ls2, root)])
        for got, want in zip(views, ovs):
            np.testing.assert_array_equal(
                np.asarray(got[2]), np.asarray(want[2])
            )

    def test_repeated_loss_raises(self):
        ls = load(topologies.grid(3))
        wm = WorldManager(slots_per_bucket=2, max_resident=4)
        root = sorted(ls.get_adjacency_databases())[0]
        get_injector().arm("device.lost", FaultSchedule.fail_n(10))
        with pytest.raises(Exception):
            # more consecutive losses than the recovery bound: loud
            wm.solve_views([("t", ls, root)])


def _publish_topo(decision, topo, versions):
    kv = {}
    for db in topo.adj_dbs.values():
        k = keyutil.adj_key(db.this_node_name)
        versions[k] = versions.get(k, 0) + 1
        kv[k] = Value(
            version=versions[k],
            originator_id=db.this_node_name,
            value=wire.dumps(db),
        )
    for pdb in topo.prefix_dbs.values():
        k = keyutil.prefix_db_key(pdb.this_node_name)
        versions[k] = versions.get(k, 0) + 1
        kv[k] = Value(
            version=versions[k],
            originator_id=pdb.this_node_name,
            value=wire.dumps(pdb),
        )
    pub = Publication(key_vals=kv, area=topo.area)
    decision.process_publication(pub)
    return kv


class TestDecisionWarmBoot:
    def test_warm_boot_bit_identical_and_warm(self, tmp_path, monkeypatch):
        from openr_tpu.decision import spf_solver
        from openr_tpu.ops.spf_sparse import ELL_COUNTERS

        # route the small test area through the resident sliced-ELL
        # path (the one the state plane snapshots)
        monkeypatch.setattr(spf_solver, "SPARSE_NODE_THRESHOLD", 2)
        reg = get_registry()
        topo = topologies.build_topology(
            "grid",
            [("a", "b", 1), ("b", "c", 2), ("a", "c", 5), ("c", "d", 1)],
        )
        store = PersistentStore(str(tmp_path / "state.bin"))
        plane = StatePlane(store)
        d1 = Decision(
            "a",
            kvstore_updates_queue=ReplicateQueue(name="kv1"),
            route_updates_queue=ReplicateQueue(name="routes1"),
            state_plane=plane,
        )
        versions = {}
        kv = _publish_topo(d1, topo, versions)
        # mirror what the KvStore merge hook would have journaled
        plane.on_kvstore_merge(topo.area, kv)
        d1.rebuild_routes("TEST")
        d1.checkpoint_state()
        routes_before = dict(d1.route_db.unicast_routes)
        assert reg.counter_get("state.engine_snapshots") >= 1
        store.stop()

        # crash: resident device state and process memory are gone
        reset_device_caches()

        store2 = PersistentStore(str(tmp_path / "state.bin"))
        plane2 = StatePlane(store2)
        rec = plane2.recover()
        assert rec.key_vals_by_area[topo.area]
        assert topo.area in rec.engine_snapshots
        d2 = Decision(
            "a",
            kvstore_updates_queue=ReplicateQueue(name="kv2"),
            route_updates_queue=ReplicateQueue(name="routes2"),
            state_plane=plane2,
        )
        warm0 = reg.counter_get("state.warm_seeds")
        cold_solves0 = ELL_COUNTERS["ell_cold_solves"]
        warm = d2.warm_boot(rec)
        assert warm == 1
        assert reg.counter_get("state.warm_seeds") == warm0 + 1
        # the warm-boot rebuild reconverges WARM: zero cold ELL solves
        assert ELL_COUNTERS["ell_cold_solves"] == cold_solves0
        assert dict(d2.route_db.unicast_routes) == routes_before
        store2.stop()

    def test_warm_boot_digest_mismatch_seeds_cold(self, tmp_path, monkeypatch):
        from dataclasses import replace

        from openr_tpu.decision import spf_solver

        monkeypatch.setattr(spf_solver, "SPARSE_NODE_THRESHOLD", 2)
        reg = get_registry()
        topo = topologies.build_topology(
            "grid", [("a", "b", 1), ("b", "c", 2), ("a", "c", 5)]
        )
        store = PersistentStore(str(tmp_path / "state.bin"))
        plane = StatePlane(store)
        d1 = Decision(
            "a",
            kvstore_updates_queue=ReplicateQueue(name="kv1"),
            route_updates_queue=ReplicateQueue(name="routes1"),
            state_plane=plane,
        )
        versions = {}
        kv = _publish_topo(d1, topo, versions)
        plane.on_kvstore_merge(topo.area, kv)
        d1.rebuild_routes("TEST")
        d1.checkpoint_state()
        # the journal advances past the snapshot: a metric changes
        db = dict(topo.adj_dbs)["b"]
        adjs = list(db.adjacencies)
        adjs[0] = replace(adjs[0], metric=adjs[0].metric + 7)
        newer = replace(db, adjacencies=tuple(adjs))
        k = keyutil.adj_key("b")
        versions[k] += 1
        newer_kv = {
            k: Value(
                version=versions[k],
                originator_id="b",
                value=wire.dumps(newer),
            )
        }
        plane.on_kvstore_merge(topo.area, newer_kv)
        d1.process_publication(
            Publication(key_vals=newer_kv, area=topo.area)
        )
        d1.rebuild_routes("TEST")
        routes_after = dict(d1.route_db.unicast_routes)
        store.stop()

        reset_device_caches()
        store2 = PersistentStore(str(tmp_path / "state.bin"))
        rec = StatePlane(store2).recover()
        d2 = Decision(
            "a",
            kvstore_updates_queue=ReplicateQueue(name="kv2"),
            route_updates_queue=ReplicateQueue(name="routes2"),
        )
        cold0 = reg.counter_get("state.cold_seeds")
        warm = d2.warm_boot(rec)
        # stale snapshot: digest-gated rehydration seeds cold — slower,
        # never wrong
        assert warm == 0
        assert reg.counter_get("state.cold_seeds") == cold0 + 1
        assert dict(d2.route_db.unicast_routes) == routes_after
        store2.stop()


class _RestartDuringSyncAgent(MockFibAgent):
    """Agent that restarts itself as the first sync_fib completes —
    the restart lands between Fib.start() and the first keepalive, so
    the just-synced table is wiped before the keepalive can observe a
    steady baseline."""

    def __init__(self):
        super().__init__()
        self.restart_after_syncs = 0

    def sync_fib(self, client_id, routes):
        super().sync_fib(client_id, routes)
        if self.restart_after_syncs:
            self.restart_after_syncs -= 1
            self.restart()


class TestFibGracefulRestart:
    def _previous_life(self, agent, entries):
        """Run one Fib life to program routes and capture its
        RouteDatabase — the material a warm boot would recover."""
        q = ReplicateQueue(name="gr-prev")
        fib = Fib("node-a", agent, q, keepalive_interval_s=5.0)
        fib.start()
        push_update(q, entries=entries)
        assert wait_until(
            lambda: len(agent.get_route_table_by_client(OPENR_CLIENT_ID))
            == len(entries)
        )
        rdb = fib.get_route_db()
        fib.stop()
        return rdb

    def test_hold_then_reconcile_no_flap(self):
        agent = MockFibAgent()
        entries = [rib_entry("fd00:1::/64"), rib_entry("fd00:2::/64")]
        rdb = self._previous_life(agent, entries)
        syncs0 = agent.counters["sync_fib"]
        deletes0 = agent.counters["delete_unicast"]

        q = ReplicateQueue(name="gr-routes")
        fib = Fib(
            "node-a", agent, q,
            keepalive_interval_s=5.0,
            graceful_restart_hold_s=30.0,
        )
        fib.start_graceful_restart(rdb)
        fib.start()
        try:
            assert fib.counters["fib.graceful_restarts"] == 1
            # the hold: recovered routes served, agent untouched
            assert fib.longest_prefix_match("fd00:1::1") is not None
            time.sleep(0.1)
            assert agent.counters["sync_fib"] == syncs0
            assert agent.counters["delete_unicast"] == deletes0

            # Decision re-converges: same routes plus one new — ONE
            # reconciling sync, zero deletes, nothing flaps
            push_update(
                q, entries=entries + [rib_entry("fd00:3::/64")]
            )
            assert wait_until(
                lambda: fib.counters["fib.gr_reconciles"] == 1
            )
            assert agent.counters["sync_fib"] == syncs0 + 1
            assert agent.counters["delete_unicast"] == deletes0
            table = agent.get_route_table_by_client(OPENR_CLIENT_ID)
            assert len(table) == 3
            # GR is over: the next update programs as a plain delta
            push_update(q, entries=[rib_entry("fd00:4::/64")])
            assert wait_until(
                lambda: len(
                    agent.get_route_table_by_client(OPENR_CLIENT_ID)
                ) == 4
            )
            assert agent.counters["sync_fib"] == syncs0 + 1
        finally:
            fib.stop()

    def test_hold_expiry_reconciles(self):
        agent = MockFibAgent()
        entries = [rib_entry("fd00:a::/64")]
        rdb = self._previous_life(agent, entries)
        syncs0 = agent.counters["sync_fib"]

        q = ReplicateQueue(name="gr-exp")
        fib = Fib(
            "node-a", agent, q,
            keepalive_interval_s=5.0,
            graceful_restart_hold_s=0.1,
        )
        fib.start_graceful_restart(rdb)
        fib.start()
        try:
            # Decision never re-converges: the hold timer fires and the
            # journal-recovered state reconciles on its own
            assert wait_until(
                lambda: fib.counters["fib.gr_hold_expirations"] == 1
            )
            assert wait_until(
                lambda: agent.counters["sync_fib"] == syncs0 + 1
            )
            assert fib.counters["fib.gr_reconciles"] == 1
            assert len(
                agent.get_route_table_by_client(OPENR_CLIENT_ID)
            ) == 1
        finally:
            fib.stop()

    def test_agent_restart_during_hold_ends_gr(self):
        agent = MockFibAgent()
        rdb = self._previous_life(agent, [rib_entry("fd00:b::/64")])

        q = ReplicateQueue(name="gr-agent")
        fib = Fib(
            "node-a", agent, q,
            keepalive_interval_s=0.05,
            graceful_restart_hold_s=30.0,
        )
        fib.start_graceful_restart(rdb)
        fib.start()
        try:
            agent.restart()  # wipes the held table: GR's premise gone
            assert wait_until(
                lambda: fib.counters["fib.agent_restarts"] == 1
            )
            # the restart resync re-programs the recovered routes now
            # instead of waiting out the 30s hold
            assert wait_until(
                lambda: len(
                    agent.get_route_table_by_client(OPENR_CLIENT_ID)
                ) == 1
            )
            assert fib.counters["fib.gr_hold_expirations"] == 0
        finally:
            fib.stop()

    def test_agent_restart_during_inflight_sync(self):
        # satellite: the agent restarts while the first sync_fib is in
        # flight — between start() and the first keepalive. start()'s
        # aliveSince baseline predates the restart, so the keepalive
        # detects it and re-programs the routes the restart wiped.
        agent = _RestartDuringSyncAgent()
        agent.restart_after_syncs = 1
        q = ReplicateQueue(name="gr-inflight")
        fib = Fib("node-a", agent, q, keepalive_interval_s=0.05)
        fib.start()
        try:
            push_update(q, entries=[rib_entry("fd00:c::/64")])
            # first sync landed, then the agent dumped it; the resync
            # triggered by the keepalive restores the route
            assert wait_until(
                lambda: fib.counters["fib.agent_restarts"] == 1
            )
            assert wait_until(
                lambda: len(
                    agent.get_route_table_by_client(OPENR_CLIENT_ID)
                ) == 1
            )
            assert agent.counters["sync_fib"] >= 2
        finally:
            fib.stop()
