"""Wire robustness: garbage on any listener must never wedge it.

The byte-sniffing dual-stack listeners accept frames from untrusted
peers; a malformed frame may at worst produce a TApplicationException
reply or a hangup for THAT connection — the listener must keep serving
well-formed clients afterwards. Fuzzed over random bytes and
truncations of valid frames."""

import socket
import struct

import numpy as np
import pytest

from openr_tpu.kvstore.dualstack import DualStackPeerServer
from openr_tpu.kvstore.wrapper import KvStoreWrapper
from openr_tpu.utils import theader
from openr_tpu.utils import thrift_binary as tb
from openr_tpu.utils import thrift_compact as tc
from openr_tpu.utils.thrift_rpc import FramedCompactClient


class TestDecoderFuzz:
    def test_theader_unwrap_contract(self):
        """unwrap either succeeds or raises ValueError — never an
        uncaught IndexError/struct.error (the dispatch loop catches
        exactly ValueError to hang up cleanly)."""
        rng = np.random.default_rng(99)
        for _ in range(400):
            n = int(rng.integers(0, 64))
            blob = bytes(rng.integers(0, 256, n, dtype="uint8"))
            # bias half the cases toward the magic so header parsing
            # actually runs
            if rng.integers(2):
                blob = b"\x0f\xff" + blob
            try:
                theader.unwrap(blob)
            except ValueError:
                pass

    def test_theader_truncations_of_valid_frame(self):
        msg = b"\x82\x21\x01\x04ping\x00"
        frame = theader.wrap(msg, seqid=9, info={"k": "v"})
        for cut in range(len(frame)):
            try:
                theader.unwrap(frame[:cut])
            except ValueError:
                pass

    def test_binary_message_header_contract(self):
        rng = np.random.default_rng(7)
        for _ in range(400):
            n = int(rng.integers(0, 48))
            blob = bytes(rng.integers(0, 256, n, dtype="uint8"))
            if rng.integers(2):
                blob = b"\x80\x01\x00\x01" + blob
            try:
                name, _mt, _sq, off = tb.decode_message_header(blob)
                tb.decode(
                    tc.StructSchema("Any", ()), blob[off:]
                )
            except (ValueError, UnicodeDecodeError):
                pass


class TestListenerSurvivesGarbage:
    def test_garbage_then_valid_calls(self):
        """Random garbage frames (and raw unframed noise) on the
        dual-stack peer port, then a well-formed client of EVERY stock
        shape: the listener must still answer all of them."""
        from openr_tpu.kvstore.thrift_peer import (
            _GET_ARGS,
            _GET_RESULT,
        )

        hub = KvStoreWrapper("fuzz-hub")
        hub.start()
        server = DualStackPeerServer(hub.store, host="127.0.0.1")
        server.start()
        try:
            hub.set_key("adj:x", b"v", version=1)
            rng = np.random.default_rng(3)
            for case in range(30):
                sock = socket.create_connection(
                    ("127.0.0.1", server.port), timeout=5
                )
                try:
                    n = int(rng.integers(1, 200))
                    payload = bytes(
                        rng.integers(0, 256, n, dtype="uint8")
                    )
                    if case % 3 == 0:
                        # framed garbage (sniffable length prefix)
                        sock.sendall(
                            struct.pack(">I", len(payload)) + payload
                        )
                    elif case % 3 == 1:
                        # framed garbage dressed as thrift (0x82 lead)
                        sock.sendall(
                            struct.pack(">I", len(payload) + 1)
                            + b"\x82" + payload
                        )
                    else:
                        # raw unframed noise
                        sock.sendall(payload)
                    sock.settimeout(1)
                    try:
                        sock.recv(64)
                    except (TimeoutError, OSError):
                        pass
                finally:
                    sock.close()
            # every stock client shape still gets service
            for th, binary in (
                (False, False), (True, False),
                (False, True), (True, True),
            ):
                client = FramedCompactClient(
                    "127.0.0.1", server.port,
                    theader=th, binary=binary,
                )
                result = client.call(
                    "getKvStoreKeyValsFilteredArea",
                    _GET_ARGS,
                    {"filter": {"prefix": "adj:",
                                "originatorIds": [],
                                "ignoreTtl": False,
                                "doNotPublishValue": False},
                     "area": "0"},
                    _GET_RESULT,
                )
                assert "adj:x" in result["success"]["keyVals"]
                client.close()
        finally:
            server.stop()
            hub.stop()


class TestNewSchemaGoldens:
    """Hand-derived byte vectors for round-5 ctrl schemas — the wire
    contract pinned independently of the codec (the same discipline as
    the KvStore goldens in test_thrift_compact.py)."""

    def test_rib_policy_golden(self):
        value = {
            "statements": [{
                "name": "s1",
                "matcher": {"prefixes": []},
                "action": {"set_weight": {
                    "default_weight": 1,
                    "area_to_weight": {},
                    "neighbor_to_weight": {"n": 3},
                }},
            }],
            "ttl_secs": 60,
        }
        got = tc.encode(tc.RIB_POLICY, value)
        golden = bytes([
            0x19,        # field 1 (delta 1): list
            0x1C,        # list header: size 1, elem struct
            0x18, 0x02, 0x73, 0x31,   # stmt field 1 string "s1"
            0x1C,        # stmt field 2 struct (matcher)
            0x19, 0x0C,  # matcher field 1: empty STRUCT-elem list
            0x00,        # matcher STOP
            0x1C,        # stmt field 3 struct (action)
            0x1C,        # action field 1 struct (set_weight)
            0x25, 0x02,  # weight field 2 (delta 2): i32 zigzag(1)=2
            0x1B, 0x00,  # field 3: empty map
            0x1B,        # field 4: map, size...
            0x01, 0x85,  # varint size 1, (string key << 4) | i32 val
            0x01, 0x6E,  # key "n"
            0x06,        # zigzag(3) = 6
            0x00,        # weight STOP
            0x00,        # action STOP
            0x00,        # stmt STOP
            0x15, 0x78,  # policy field 2 (delta 1): i32 zigzag(60)
            0x00,        # policy STOP
        ])
        assert got == golden, got.hex(" ")
        assert tc.decode(tc.RIB_POLICY, got) == value

    def test_spt_infos_golden(self):
        value = {
            "infos": {"r": {
                "passive": True, "cost": 2, "children": set(),
            }},
            "counters": {"neighborCounters": {},
                         "rootCounters": {}},
            "floodPeers": set(),
        }
        got = tc.encode(tc.SPT_INFOS, value)
        golden = bytes([
            0x1B, 0x01,  # field 1 (delta 1): map, size 1
            0x8C,        # (string key << 4) | struct value
            0x01, 0x72,  # key "r"
            0x11,        # SptInfo field 1: BOOL TRUE in the header
            0x16, 0x04,  # field 2 (delta 1): i64 zigzag(2) = 4
            0x2A, 0x08,  # field 4 (delta 2): set, empty, elem binary
            0x00,        # SptInfo STOP
            0x1C,        # field 2 (delta 1): counters struct
            0x1B, 0x00,  # neighborCounters: empty map
            0x1B, 0x00,  # rootCounters: empty map
            0x00,        # counters STOP
            0x2A, 0x08,  # field 4 (delta 2): floodPeers empty set
            0x00,        # STOP
        ])
        assert got == golden, got.hex(" ")
        assert tc.decode(tc.SPT_INFOS, got) == value
