"""DUAL algorithm tests (reference analogue: openr/dual/tests/DualTest.cpp):
message-bus simulation over topologies, SPT ground-truth comparison,
link-failure diffusing reconvergence."""

import heapq
from collections import deque

import pytest

from openr_tpu.dual.dual import (
    INFINITY,
    DualNode,
    DualState,
)


class DualNetwork:
    """Synchronous message bus running DualNodes over an edge list."""

    def __init__(self, edges, roots):
        self.nodes = {}
        self.edges = {}  # (a, b) -> cost
        names = sorted({n for e in edges for n in e[:2]})
        for name in names:
            self.nodes[name] = DualNode(name, is_root=name in roots)
        self.queue = deque()
        for a, b, cost in edges:
            self.edges[(a, b)] = cost
            self.edges[(b, a)] = cost
        for a, b, cost in edges:
            self._enqueue(a, self.nodes[a].peer_up(b, cost))
            self._enqueue(b, self.nodes[b].peer_up(a, cost))
        self.drain()

    def _enqueue(self, sender, msgs):
        for neighbor, batch in msgs.items():
            for msg in batch:
                self.queue.append((sender, neighbor, msg))

    def drain(self, limit=100_000):
        count = 0
        while self.queue:
            count += 1
            assert count < limit, "dual message storm: no convergence"
            sender, receiver, msg = self.queue.popleft()
            if (sender, receiver) not in self.edges:
                continue  # link vanished while in flight
            out = self.nodes[receiver].process_message(sender, msg)
            self._enqueue(receiver, out)
        return count

    def cut(self, a, b):
        self.edges.pop((a, b), None)
        self.edges.pop((b, a), None)
        self._enqueue(a, self.nodes[a].peer_down(b))
        self._enqueue(b, self.nodes[b].peer_down(a))
        self.drain()

    def change_cost(self, a, b, cost):
        self.edges[(a, b)] = cost
        self.edges[(b, a)] = cost
        self._enqueue(a, self.nodes[a].peer_cost_change(b, cost))
        self._enqueue(b, self.nodes[b].peer_cost_change(a, cost))
        self.drain()

    def ground_truth(self, root):
        """Dijkstra over the current edge set."""
        dist = {root: 0}
        heap = [(0, root)]
        seen = set()
        while heap:
            d, u = heapq.heappop(heap)
            if u in seen:
                continue
            seen.add(u)
            for (a, b), cost in self.edges.items():
                if a != u:
                    continue
                nd = d + cost
                if nd < dist.get(b, INFINITY):
                    dist[b] = nd
                    heapq.heappush(heap, (nd, b))
        return dist

    def assert_converged(self, root):
        truth = self.ground_truth(root)
        for name, node in self.nodes.items():
            dual = node.get_dual(root)
            assert dual is not None, f"{name} has no dual for {root}"
            assert dual.state == DualState.PASSIVE, f"{name} still ACTIVE"
            expected = truth.get(name, INFINITY)
            assert dual.distance == expected, (
                f"{name}: distance {dual.distance} != {expected}"
            )
            if name != root and expected < INFINITY:
                # nexthop must be on a shortest path
                nh = dual.nexthop
                assert nh is not None
                link = self.edges.get((name, nh))
                assert link is not None
                assert link + truth[nh] == expected, (
                    f"{name}: nexthop {nh} not on shortest path"
                )


class TestDualConvergence:
    def test_line(self):
        net = DualNetwork(
            [("r", "a", 1), ("a", "b", 1), ("b", "c", 1)], roots={"r"}
        )
        net.assert_converged("r")

    def test_weighted_mesh(self):
        net = DualNetwork(
            [
                ("r", "a", 4),
                ("r", "b", 1),
                ("a", "b", 1),
                ("a", "c", 2),
                ("b", "c", 6),
                ("c", "d", 1),
            ],
            roots={"r"},
        )
        net.assert_converged("r")
        # a's shortest path to r is via b (1+1=2), not direct (4)
        assert net.nodes["a"].get_dual("r").nexthop == "b"

    def test_ring(self):
        edges = [(f"n{i}", f"n{(i + 1) % 6}", 1) for i in range(6)]
        net = DualNetwork(edges, roots={"n0"})
        net.assert_converged("n0")

    def test_multi_root(self):
        net = DualNetwork(
            [("r1", "a", 1), ("a", "r2", 1), ("r2", "b", 1)],
            roots={"r1", "r2"},
        )
        net.assert_converged("r1")
        net.assert_converged("r2")
        # flood root election: smallest ready root everywhere
        for node in net.nodes.values():
            assert node.pick_flood_root() == "r1"


class TestDualReconvergence:
    def test_link_cut_reroutes(self):
        # square: r-a, r-b, a-c, b-c
        net = DualNetwork(
            [("r", "a", 1), ("r", "b", 1), ("a", "c", 1), ("b", "c", 1)],
            roots={"r"},
        )
        net.assert_converged("r")
        # cut c's shortest link; it must reconverge through the other side
        first_nh = net.nodes["c"].get_dual("r").nexthop
        other = "b" if first_nh == "a" else "a"
        net.cut("c", first_nh)
        net.assert_converged("r")
        assert net.nodes["c"].get_dual("r").nexthop == other

    def test_cost_increase_triggers_diffusion(self):
        net = DualNetwork(
            [("r", "a", 1), ("a", "b", 1), ("r", "b", 10)], roots={"r"}
        )
        net.assert_converged("r")
        assert net.nodes["b"].get_dual("r").distance == 2
        net.change_cost("a", "b", 20)
        net.assert_converged("r")
        assert net.nodes["b"].get_dual("r").distance == 10
        assert net.nodes["b"].get_dual("r").nexthop == "r"

    def test_cost_decrease_local_computation(self):
        net = DualNetwork(
            [("r", "a", 5), ("a", "b", 1)], roots={"r"}
        )
        net.assert_converged("r")
        net.change_cost("r", "a", 1)
        net.assert_converged("r")
        assert net.nodes["b"].get_dual("r").distance == 2

    def test_partition_distances_infinite(self):
        net = DualNetwork(
            [("r", "a", 1), ("a", "b", 1), ("b", "c", 1)], roots={"r"}
        )
        net.assert_converged("r")
        net.cut("a", "b")
        net.assert_converged("r")
        assert net.nodes["b"].get_dual("r").distance >= INFINITY
        assert net.nodes["c"].get_dual("r").distance >= INFINITY
        assert net.nodes["a"].get_dual("r").distance == 1

    def test_heal_after_partition(self):
        net = DualNetwork(
            [("r", "a", 1), ("a", "b", 1)], roots={"r"}
        )
        net.cut("a", "b")
        net.assert_converged("r")
        # heal
        net.edges[("a", "b")] = 1
        net.edges[("b", "a")] = 1
        net._enqueue("a", net.nodes["a"].peer_up("b", 1))
        net._enqueue("b", net.nodes["b"].peer_up("a", 1))
        net.drain()
        net.assert_converged("r")
        assert net.nodes["b"].get_dual("r").distance == 2


class TestDualFuzz:
    def test_random_topologies_with_churn(self):
        """Random graphs + random cut/cost events, validated against
        Dijkstra ground truth after every event."""
        import random

        for seed in range(15):
            rng = random.Random(seed)
            n = rng.randint(4, 9)
            names = [f"n{i}" for i in range(n)]
            edges = []
            seen = set()
            for i in range(1, n):
                j = rng.randrange(i)
                edges.append((names[i], names[j], rng.randint(1, 9)))
                seen.add((min(i, j), max(i, j)))
            for _ in range(n):
                i, j = rng.randrange(n), rng.randrange(n)
                if i != j and (min(i, j), max(i, j)) not in seen:
                    seen.add((min(i, j), max(i, j)))
                    edges.append((names[i], names[j], rng.randint(1, 9)))
            net = DualNetwork(edges, roots={"n0"})
            net.assert_converged("n0")
            for _ in range(5):
                live = [e for e in net.edges if e[0] < e[1]]
                if not live:
                    break
                a, b = rng.choice(live)
                if rng.random() < 0.5:
                    net.cut(a, b)
                else:
                    net.change_cost(a, b, rng.randint(1, 9))
                net.assert_converged("n0")
