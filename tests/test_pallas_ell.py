"""Pallas sliced-ELL relax kernel (ops.pallas_ell): bit-exact parity
with the jnp formulation, the autotuner's family-keyed persistence,
and the zero-retrace / no-transfer contracts with the kernel armed.

The kernel runs in interpret mode on CPU (``_interpret`` defaults to
non-TPU platforms), so every parity assertion here is exact int32
equality — the relaxation is a monotone min-plus contraction with a
unique fixed point, and the padding/overload-masking contract promises
the tiled kernel computes the SAME lattice values, not approximately
close ones. Oracles are independent numpy re-derivations of the band
algebra, not calls back into the jnp impl under test.
"""

import json
import os
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.ops import autotune, route_engine, route_sweep, spf_sparse
from openr_tpu.ops.pallas_ell import (
    INF,
    TILE_N,
    TILE_S,
    ell_band_relax,
    ell_band_relax_masked,
    rev_band_relax,
    vmem_bytes,
)
from openr_tpu.types import AdjacencyDatabase


def load(topo, overloaded_nodes=()):
    ls = LinkState(area=topo.area)
    for name, db in sorted(topo.adj_dbs.items()):
        if name in overloaded_nodes:
            db = AdjacencyDatabase(
                this_node_name=db.this_node_name,
                is_overloaded=True,
                adjacencies=db.adjacencies,
                node_label=db.node_label,
                area=db.area,
            )
        ls.update_adjacency_database(db)
    return ls


def mutate_metric(ls, node, i, metric):
    db = ls.get_adjacency_databases()[node]
    adjs = list(db.adjacencies)
    adjs[i] = replace(adjs[i], metric=metric)
    ls.update_adjacency_database(replace(db, adjacencies=tuple(adjs)))
    return {node, adjs[i].other_node_name}


class ForcedTuner(autotune.Autotuner):
    """Deterministic winner for every key — no timing, no disk."""

    def __init__(self, winner: str):
        super().__init__(persist=False)
        self.forced = winner

    def pick(self, kernel, shape_key, candidates):
        return self.forced if self.forced in candidates else next(
            iter(candidates)
        )


@pytest.fixture(autouse=True)
def _restore_impl_and_tuner():
    prev = spf_sparse.get_ell_relax_impl()
    prev_tuner = autotune.get_autotuner()
    yield
    spf_sparse.set_ell_relax_impl(prev)
    autotune.set_autotuner(prev_tuner)


# ---------------------------------------------------------------------
# numpy oracles: independent re-derivation of the band algebra
# ---------------------------------------------------------------------


def np_band_relax(d, src, w, overloaded, pos):
    d = np.asarray(d).astype(np.int64)
    src = np.asarray(src)
    w_eff = np.where(np.asarray(overloaded)[src], int(INF),
                     np.asarray(w)).astype(np.int64)
    relaxed = np.minimum(
        d[:, src] + w_eff[None, :, :], int(INF)
    ).min(axis=2)
    rows = src.shape[0]
    return np.minimum(d[:, pos:pos + rows], relaxed).astype(np.int32)


def np_band_relax_masked(d, src, w, mask, overloaded, pos):
    d = np.asarray(d).astype(np.int64)
    src = np.asarray(src)
    w_eff = np.where(np.asarray(overloaded)[src], int(INF),
                     np.asarray(w))
    w_b = np.where(np.asarray(mask), int(INF),
                   w_eff[None, :, :]).astype(np.int64)
    relaxed = np.minimum(d[:, src] + w_b, int(INF)).min(axis=2)
    rows = src.shape[0]
    return np.minimum(d[:, pos:pos + rows], relaxed).astype(np.int32)


def np_rev_relax(d, v, w, t_ids, overloaded, pos):
    d = np.asarray(d).astype(np.int64)
    v = np.asarray(v)
    ov = np.asarray(overloaded)
    blocked = ov[v][None, :, :] & (
        v[None, :, :] != np.asarray(t_ids)[:, None, None]
    )
    w_eff = np.where(blocked, int(INF),
                     np.asarray(w)[None, :, :]).astype(np.int64)
    relaxed = np.minimum(d[:, v] + w_eff, int(INF)).min(axis=2)
    rows = v.shape[0]
    return np.minimum(d[:, pos:pos + rows], relaxed).astype(np.int32)


def synth_band(rng, s, n_pad, rows, k, pos, inf_frac=0.2,
               ov_frac=0.2, inf_w_frac=0.15):
    """Random operands with the hazards the padding contract must keep
    inert: INF distance cells, whole all-INF rows, INF weights, and
    overloaded sources."""
    d = rng.integers(0, INF // 4, size=(s, n_pad), dtype=np.int32)
    d[rng.random((s, n_pad)) < inf_frac] = INF
    d[0, :] = INF  # an all-INF source row stays all-INF-or-relaxed
    src = rng.integers(0, n_pad, size=(rows, k), dtype=np.int32)
    w = rng.integers(1, 1000, size=(rows, k), dtype=np.int32)
    w[rng.random((rows, k)) < inf_w_frac] = INF
    ov = rng.random(n_pad) < ov_frac
    return d, src, w, ov


BAND_SHAPES = [
    # (s, n_pad, rows, k, pos): tile-exact, off-tile, and edge extents
    (8, 256, 128, 4, 0),  # exact (TILE_S, TILE_N) multiples
    (8, 256, 128, 4, 64),  # band offset inside the padded axis
    (5, 256, 100, 3, 64),  # s and rows both off-tile
    (1, 384, 1, 1, 200),  # degenerate 1-row band, k = 1
    (9, 256, 127, 2, 0),  # rows one short of a lane tile
    (16, 512, 129, 6, 128),  # rows one past a lane tile
    (3, 128, 128, 9, 0),  # k past the slot-class nominal sizes
]


class TestBandKernelParity:
    @pytest.mark.parametrize("s,n_pad,rows,k,pos", BAND_SHAPES)
    def test_plain_band_matches_oracle(self, s, n_pad, rows, k, pos):
        rng = np.random.default_rng(seed=s * 1000 + rows + k)
        d, src, w, ov = synth_band(rng, s, n_pad, rows, k, pos)
        got = np.asarray(ell_band_relax(
            jnp.asarray(d), jnp.asarray(src), jnp.asarray(w),
            jnp.asarray(ov), pos,
        ))
        want = np_band_relax(d, src, w, ov, pos)
        assert got.dtype == np.int32
        assert np.array_equal(got, want)

    @pytest.mark.parametrize("s,n_pad,rows,k,pos", BAND_SHAPES)
    def test_masked_band_matches_oracle(self, s, n_pad, rows, k, pos):
        rng = np.random.default_rng(seed=s * 77 + rows * 3 + k)
        d, src, w, ov = synth_band(rng, s, n_pad, rows, k, pos)
        mask = rng.random((s, rows, k)) < 0.3
        got = np.asarray(ell_band_relax_masked(
            jnp.asarray(d), jnp.asarray(src), jnp.asarray(w),
            jnp.asarray(mask), jnp.asarray(ov), pos,
        ))
        want = np_band_relax_masked(d, src, w, mask, ov, pos)
        assert np.array_equal(got, want)

    @pytest.mark.parametrize("s,n_pad,rows,k,pos", BAND_SHAPES)
    def test_rev_band_matches_oracle(self, s, n_pad, rows, k, pos):
        rng = np.random.default_rng(seed=s * 13 + rows * 7 + k)
        d, v, w, ov = synth_band(rng, s, n_pad, rows, k, pos)
        t_ids = rng.integers(0, n_pad, size=(s,), dtype=np.int32)
        got = np.asarray(rev_band_relax(
            jnp.asarray(d), jnp.asarray(v), jnp.asarray(w),
            jnp.asarray(t_ids), jnp.asarray(ov), pos,
        ))
        want = np_rev_relax(d, v, w, t_ids, ov, pos)
        assert np.array_equal(got, want)

    def test_all_overloaded_only_direct_mins_survive(self):
        """Every source overloaded => the relax degenerates to the
        identity on d's band slice (no edge may extend a path)."""
        rng = np.random.default_rng(seed=42)
        d, src, w, _ = synth_band(rng, 6, 256, 120, 3, 32, ov_frac=0.0)
        ov = np.ones(256, bool)
        got = np.asarray(ell_band_relax(
            jnp.asarray(d), jnp.asarray(src), jnp.asarray(w),
            jnp.asarray(ov), 32,
        ))
        assert np.array_equal(got, d[:, 32:152])

    def test_vmap_over_batch_axis(self):
        """pallas_call's batching rule must carry the kernel under
        vmap — the world-model solves are jit(vmap(...)) chains."""
        rng = np.random.default_rng(seed=3)
        batch_d = []
        want = []
        src = rng.integers(0, 128, size=(64, 3), dtype=np.int32)
        w = rng.integers(1, 50, size=(64, 3), dtype=np.int32)
        ov = rng.random(128) < 0.2
        for _ in range(4):
            d, _, _, _ = synth_band(rng, 8, 128, 64, 3, 0)
            batch_d.append(d)
            want.append(np_band_relax(d, src, w, ov, 0))
        got = np.asarray(jax.vmap(
            lambda dd: ell_band_relax(
                dd, jnp.asarray(src), jnp.asarray(w), jnp.asarray(ov), 0
            )
        )(jnp.asarray(np.stack(batch_d))))
        assert np.array_equal(got, np.stack(want))

    def test_vmem_budget_is_positive_and_tile_scaled(self):
        base = vmem_bytes(256, 4)
        assert base > 0
        assert vmem_bytes(512, 4) > base  # d panel scales with n_pad
        assert vmem_bytes(256, 8) > base  # slot panels scale with k
        assert vmem_bytes(256, 4, masked=True) > base
        # the budget is tile-bounded: independent of S entirely, and
        # the d panel term is TILE_S rows regardless of source count
        assert TILE_S * 256 * 4 <= base


# ---------------------------------------------------------------------
# whole-solve parity on real topologies
# ---------------------------------------------------------------------


def topo_cases():
    return [
        ("ring", topologies.ring(17), ()),
        ("fat_tree", topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        ), ()),
        ("random", topologies.random_mesh(
            40, degree=5, seed=3, max_metric=30
        ), ()),
        ("random_overloaded", topologies.random_mesh(
            30, degree=4, seed=9, max_metric=20
        ), ("node-12", "node-3")),
    ]


class TestTopologyParity:
    @pytest.mark.parametrize(
        "name,topo,ov", topo_cases(), ids=lambda c: str(c)[:14]
    )
    def test_all_pairs_bit_identical(self, name, topo, ov):
        ls = load(topo, overloaded_nodes=ov)
        graph = spf_sparse.compile_ell(ls)
        srcs = np.arange(graph.n, dtype=np.int32)
        spf_sparse.set_ell_relax_impl("jnp")
        d_jnp = np.asarray(
            spf_sparse.ell_distances_from_sources(graph, srcs)
        )
        spf_sparse.set_ell_relax_impl("pallas")
        d_pl = np.asarray(
            spf_sparse.ell_distances_from_sources(graph, srcs)
        )
        assert np.array_equal(d_jnp, d_pl)

    def test_masked_relax_bit_identical_on_real_bands(self):
        """The KSP2 per-batch edge-exclusion variant, on the real band
        structure of a fat-tree (multiple slot classes)."""
        ls = load(topo_cases()[1][1])
        graph = spf_sparse.compile_ell(ls)
        rng = np.random.default_rng(seed=11)
        b = 4
        d = rng.integers(
            0, INF // 4, size=(b, graph.n_pad), dtype=np.int32
        )
        d[rng.random(d.shape) < 0.25] = INF
        masks = tuple(
            jnp.asarray(rng.random((b,) + s.shape) < 0.3)
            for s in graph.src
        )
        args = (
            jnp.asarray(d), graph.bands,
            tuple(jnp.asarray(s) for s in graph.src),
            tuple(jnp.asarray(w) for w in graph.w),
            masks, jnp.asarray(graph.overloaded),
        )
        got_j = np.asarray(spf_sparse._ell_relax_masked(*args, impl="jnp"))
        got_p = np.asarray(
            spf_sparse._ell_relax_masked(*args, impl="pallas")
        )
        assert np.array_equal(got_j, got_p)

    def test_route_sweep_digests_bit_identical(self):
        """Destination-major sweep (the rev kernel) end to end."""
        topo = topo_cases()[1][1]
        ls_a, ls_b = load(topo), load(topo)
        names = sorted(ls_a.get_adjacency_databases().keys())
        spf_sparse.set_ell_relax_impl("jnp")
        eng_j = route_engine.RouteSweepEngine(ls_a, [names[0]])
        spf_sparse.set_ell_relax_impl("pallas")
        eng_p = route_engine.RouteSweepEngine(ls_b, [names[0]])
        assert route_sweep.digests_by_name(eng_j.result) == \
            route_sweep.digests_by_name(eng_p.result)


# ---------------------------------------------------------------------
# autotuner: family-keyed persistence
# ---------------------------------------------------------------------


class TestAutotunePersistence:
    def _cache(self, tmp_path, monkeypatch):
        monkeypatch.setenv("OPENR_CACHE_DIR", str(tmp_path))
        return os.path.join(str(tmp_path), "autotune.json")

    def test_round_trip_same_winner_without_remeasure(
        self, tmp_path, monkeypatch
    ):
        path = self._cache(tmp_path, monkeypatch)
        calls = []

        def measure(thunk, reps=3):
            calls.append(1)
            thunk()
            return float(len(calls))  # first candidate measured wins

        t1 = autotune.Autotuner(measure=measure)
        w1 = t1.pick("ell_relax", "256x4", {
            "jnp": lambda: None, "pallas": lambda: None,
        })
        assert w1 == "jnp" and len(calls) == 2
        data = json.load(open(path))
        assert data["version"] == 2
        key = f"{jax.devices()[0].platform}:ell_relax:256x4"
        assert data["winners"][key]["winner"] == "jnp"
        assert data["winners"][key]["family"] == "ell_relax"
        # a fresh process (new tuner) adopts the persisted winner and
        # never measures
        t2 = autotune.Autotuner(measure=measure)
        calls.clear()
        w2 = t2.pick("ell_relax", "256x4", {
            "jnp": lambda: None, "pallas": lambda: None,
        })
        assert w2 == "jnp" and calls == []

    def test_legacy_flat_schema_migrates(self, tmp_path, monkeypatch):
        path = self._cache(tmp_path, monkeypatch)
        platform = jax.devices()[0].platform
        legacy = {
            f"{platform}:minplus:8x256": {"winner": "pallas"},
            # out-of-family winner: a dense pallas_t must never arm
            # the sparse relax dispatch
            f"{platform}:ell_relax:256x4": {"winner": "pallas_t"},
            f"{platform}:nonsense": {"winner": "jnp"},  # malformed key
            f"{platform}:unknown_family:1x1": {"winner": "jnp"},
        }
        with open(path, "w") as f:
            json.dump(legacy, f)
        t = autotune.Autotuner(measure=lambda th, reps=3: 1.0)
        assert t.pick("minplus", "8x256", {
            "jnp": lambda: None, "pallas": lambda: None,
        }) == "pallas"  # valid legacy entry adopted
        # the invalid ell_relax entry was dropped -> re-measured
        assert t.pick("ell_relax", "256x4", {
            "jnp": lambda: None, "pallas": lambda: None,
        }) in ("jnp", "pallas")
        # any save rewrites the surviving entries under the v2 schema
        data = json.load(open(path))
        assert data["version"] == 2
        keys = set(data["winners"])
        assert f"{platform}:minplus:8x256" in keys
        assert f"{platform}:nonsense" not in keys
        assert f"{platform}:unknown_family:1x1" not in keys
        for entry in data["winners"].values():
            assert entry["winner"] in \
                autotune._FAMILY_CANDIDATES[entry["family"]]

    def test_record_rejects_out_of_family_winner(self):
        t = autotune.Autotuner(persist=False)
        with pytest.raises(AssertionError):
            t.record("ell_relax", "256x4", "pallas_t")
        with pytest.raises(AssertionError):
            t.record("not_a_family", "256x4", "jnp")

    def test_resolve_ell_relax_adopts_recorded_winner(self):
        t = autotune.Autotuner(persist=False)
        autotune.set_autotuner(t)
        t.record("ell_relax", "256x3", "pallas")
        assert autotune.resolve_ell_relax((256, 3)) == "pallas"


# ---------------------------------------------------------------------
# compile-flatness, burst parity, sharded transfer guard — kernel armed
# ---------------------------------------------------------------------


def _warm_engine_auto():
    """Fat-tree engine built with impl='auto' resolving to pallas for
    every shape (forced tuner), warmed through one churn event."""
    autotune.set_autotuner(ForcedTuner("pallas"))
    spf_sparse.set_ell_relax_impl("auto")
    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    ls = load(topo)
    names = sorted(ls.get_adjacency_databases().keys())
    eng = route_engine.RouteSweepEngine(ls, [names[0]])
    rsw = next(n for n in eng.graph.node_names if n.startswith("rsw"))
    assert eng.churn(ls, mutate_metric(ls, rsw, 0, 3))
    return eng, ls, rsw


class TestArmedContracts:
    def test_zero_retrace_across_churn_under_auto(self):
        """Warm metric churn with the kernel armed through the
        autotuner costs zero new compiles: the @pallas-suffixed AOT
        tags and the ell_impl statics were all built during warm-up,
        and nothing about a metric flip re-keys them."""
        from openr_tpu.telemetry import get_registry

        eng, ls, rsw = _warm_engine_auto()
        # first cycle warms every row bucket these events land in
        for metric in (5, 9, 2, 12):
            eng.churn(ls, mutate_metric(ls, rsw, 0, metric))
        reg = get_registry()
        aot0 = reg.counter_get("ops.aot_compiles")
        jax0 = reg.counter_get("jax.compile_count")
        for metric in (5, 9, 2, 12):
            eng.churn(ls, mutate_metric(ls, rsw, 0, metric))
        assert reg.counter_get("ops.aot_compiles") == aot0
        assert reg.counter_get("jax.compile_count") == jax0

    def test_warm_churn_two_touch_contract_holds_armed(self):
        """Arming the kernel must not change the dispatch cadence: a
        warm event window still costs <= 2 host touches and zero
        blocking syncs."""
        from openr_tpu.ops import dispatch_accounting as da

        eng, ls, rsw = _warm_engine_auto()
        with da.event_window("test_armed") as w:
            assert eng.churn(ls, mutate_metric(ls, rsw, 0, 8))
        assert w.touches <= 2, f"armed churn cost {w.touches} touches"
        assert w.blocking_syncs == 0

    def test_pipelined_burst_digest_parity_armed(self):
        """A 3-event pipelined burst with the kernel armed leaves
        digests bit-identical to the jnp engine fed the same events."""
        topo = topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls_j, ls_p = load(topo), load(topo)
        names = sorted(ls_j.get_adjacency_databases().keys())
        spf_sparse.set_ell_relax_impl("jnp")
        eng_j = route_engine.RouteSweepEngine(ls_j, [names[0]])
        autotune.set_autotuner(ForcedTuner("pallas"))
        spf_sparse.set_ell_relax_impl("auto")
        eng_p = route_engine.RouteSweepEngine(ls_p, [names[0]])
        edges = []
        sample = set(eng_j.sample_names)
        for node in names:
            if node in sample:
                continue
            adjs = ls_j.get_adjacency_databases()[node].adjacencies
            for i, a in enumerate(adjs):
                if a.other_node_name not in sample:
                    edges.append((node, i))
                    break
            if len(edges) == 3:
                break
        # warm both engines through one sequential round
        for (node, slot), metric in zip(edges, (7, 5, 9)):
            eng_j.churn(ls_j, mutate_metric(ls_j, node, slot, metric))
            eng_p.churn(ls_p, mutate_metric(ls_p, node, slot, metric))
        # second round: sequential on the jnp engine, one pipelined
        # burst on the armed engine
        final = list(zip(edges, (11, 4, 13)))
        for (node, slot), metric in final:
            eng_j.churn(ls_j, mutate_metric(ls_j, node, slot, metric))
        eng_p.churn_burst(ls_p, [
            (lambda n=node, s=slot, m=metric:
             mutate_metric(ls_p, n, s, m))
            for (node, slot), metric in final
        ])
        assert route_sweep.digests_by_name(eng_j.result) == \
            route_sweep.digests_by_name(eng_p.result)

    def test_sharded_churn_no_implicit_transfers_armed(self):
        """The sharded twin runs the kernel per shard: warm churn with
        pallas armed completes under jax.transfer_guard('disallow')
        with zero placement corrections — shard_map hands the kernel
        its local rows, nothing reshards."""
        from openr_tpu.parallel.mesh import make_mesh
        from openr_tpu.telemetry import get_registry

        autotune.set_autotuner(ForcedTuner("pallas"))
        spf_sparse.set_ell_relax_impl("auto")
        topo = topologies.fat_tree(
            pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        ls = load(topo)
        names = sorted(ls.get_adjacency_databases().keys())
        mesh = make_mesh(jax.devices())
        eng = route_engine.RouteSweepEngine(
            ls, [names[0]], align=16, mesh=mesh
        )
        rsw = next(n for n in eng.graph.node_names
                   if n.startswith("rsw"))
        assert eng.churn(ls, mutate_metric(ls, rsw, 0, 3))
        reg = get_registry()
        before = reg.counter_get("ops.reshard_events")
        with jax.transfer_guard("disallow"):
            for metric in (5, 9, 2):
                eng.churn(ls, mutate_metric(ls, rsw, 0, metric))
        assert reg.counter_get("ops.reshard_events") == before
