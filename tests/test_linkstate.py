"""LinkState graph-engine tests (reference analogue:
openr/decision/tests/LinkStateTest.cpp)."""

import pytest

from openr_tpu.graph.linkstate import HoldableValue, LinkState
from openr_tpu.models import topologies
from openr_tpu.types import Adjacency, AdjacencyDatabase


def load(topo):
    ls = LinkState(area=topo.area)
    for db in topo.adj_dbs.values():
        ls.update_adjacency_database(db)
    return ls


def adj(other, if_name, other_if, metric=1, overloaded=False, adj_label=0):
    return Adjacency(
        other_node_name=other,
        if_name=if_name,
        other_if_name=other_if,
        metric=metric,
        is_overloaded=overloaded,
        adj_label=adj_label,
    )


def db(node, adjs, overloaded=False, node_label=0, area="0"):
    return AdjacencyDatabase(
        this_node_name=node,
        adjacencies=tuple(adjs),
        is_overloaded=overloaded,
        node_label=node_label,
        area=area,
    )


class TestBidirectionalLinks:
    def test_unidirectional_adjacency_creates_no_link(self):
        ls = LinkState()
        change = ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba")])
        )
        assert not change.topology_changed
        assert ls.num_links == 0

    def test_bidirectional_adjacency_creates_link(self):
        ls = LinkState()
        ls.update_adjacency_database(db("a", [adj("b", "if_ab", "if_ba")]))
        change = ls.update_adjacency_database(
            db("b", [adj("a", "if_ba", "if_ab")])
        )
        assert change.topology_changed
        assert ls.num_links == 1
        assert ls.get_metric_from_a_to_b("a", "b") == 1

    def test_mismatched_ifaces_no_link(self):
        ls = LinkState()
        ls.update_adjacency_database(db("a", [adj("b", "if_ab", "WRONG")]))
        ls.update_adjacency_database(db("b", [adj("a", "if_ba", "if_ab")]))
        assert ls.num_links == 0

    def test_link_removal_on_adj_withdrawal(self):
        ls = LinkState()
        ls.update_adjacency_database(db("a", [adj("b", "if_ab", "if_ba")]))
        ls.update_adjacency_database(db("b", [adj("a", "if_ba", "if_ab")]))
        change = ls.update_adjacency_database(db("a", []))
        assert change.topology_changed
        assert ls.num_links == 0

    def test_delete_adjacency_database(self):
        ls = load(topologies.ring(4))
        change = ls.delete_adjacency_database("node-0")
        assert change.topology_changed
        assert not ls.has_node("node-0")
        # node-1..3 remain connected in a line
        assert ls.get_metric_from_a_to_b("node-1", "node-3") == 2


class TestMetricsAndOverloads:
    def _pair(self, metric_ab=1, metric_ba=1):
        ls = LinkState()
        ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=metric_ab)])
        )
        ls.update_adjacency_database(
            db("b", [adj("a", "if_ba", "if_ab", metric=metric_ba)])
        )
        return ls

    def test_asymmetric_metrics(self):
        ls = self._pair(metric_ab=5, metric_ba=9)
        assert ls.get_metric_from_a_to_b("a", "b") == 5
        assert ls.get_metric_from_a_to_b("b", "a") == 9

    def test_metric_change_invalidates_memo(self):
        ls = self._pair()
        v0 = ls.topology_version
        change = ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=7)])
        )
        assert change.topology_changed
        assert ls.topology_version > v0
        assert ls.get_metric_from_a_to_b("a", "b") == 7

    def test_link_overload_takes_link_down(self):
        ls = self._pair()
        change = ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", overloaded=True)])
        )
        assert change.topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") is None

    def test_node_overload_blocks_transit_only(self):
        # line a - b - c with b overloaded: a can reach b but not c
        ls = LinkState()
        ls.update_adjacency_database(db("a", [adj("b", "if_ab", "if_ba")]))
        ls.update_adjacency_database(
            db(
                "b",
                [adj("a", "if_ba", "if_ab"), adj("c", "if_bc", "if_cb")],
                overloaded=True,
            )
        )
        ls.update_adjacency_database(db("c", [adj("b", "if_cb", "if_bc")]))
        assert ls.is_node_overloaded("b")
        assert ls.get_metric_from_a_to_b("a", "b") == 1
        assert ls.get_metric_from_a_to_b("a", "c") is None
        # b itself can still reach everything (source exemption)
        assert ls.get_metric_from_a_to_b("b", "c") == 1

    def test_no_change_is_no_change(self):
        topo = topologies.grid(3)
        ls = load(topo)
        v0 = ls.topology_version
        change = ls.update_adjacency_database(topo.adj_dbs["node-0"])
        assert not change.topology_changed
        assert ls.topology_version == v0


class TestEcmpAndPaths:
    def test_ecmp_next_hops_square(self):
        # a-b-d and a-c-d equal cost: a's next hops toward d are {b, c}
        ls = LinkState()
        edges = [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
        topo = topologies.build_topology("sq", edges)
        for adj_db in topo.adj_dbs.values():
            ls.update_adjacency_database(adj_db)
        res = ls.get_spf_result("a")
        assert res["d"].metric == 2
        assert res["d"].next_hops == {"b", "c"}
        assert res["b"].next_hops == {"b"}

    def test_unequal_paths_single_next_hop(self):
        edges = [("a", "b", 1), ("a", "c", 5), ("b", "d", 1), ("c", "d", 1)]
        topo = topologies.build_topology("sq2", edges)
        ls = load(topo)
        res = ls.get_spf_result("a")
        assert res["d"].metric == 2
        assert res["d"].next_hops == {"b"}

    def test_hop_count_mode(self):
        edges = [("a", "b", 10), ("b", "c", 10), ("a", "c", 100)]
        topo = topologies.build_topology("tri", edges)
        ls = load(topo)
        assert ls.get_metric_from_a_to_b("a", "c") == 20
        assert ls.get_hops_from_a_to_b("a", "c") == 1
        assert ls.get_max_hops_to_node("a") == 1

    def test_kth_paths_edge_disjoint(self):
        # square: two edge-disjoint paths a->d
        edges = [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
        ls = load(topologies.build_topology("sq3", edges))
        p1 = ls.get_kth_paths("a", "d", 1)
        assert len(p1) == 2  # both equal-cost shortest paths traced
        used = {l for p in p1 for l in p}
        p2 = ls.get_kth_paths("a", "d", 2)
        assert all(l not in used for p in p2 for l in p)
        assert p2 == []  # square is exhausted after the two shortest

    def test_kth_paths_second_shortest(self):
        # triangle with a longer detour: k=1 direct, k=2 via c
        edges = [("a", "b", 1), ("a", "c", 2), ("c", "b", 2)]
        ls = load(topologies.build_topology("tri2", edges))
        p1 = ls.get_kth_paths("a", "b", 1)
        assert len(p1) == 1 and len(p1[0]) == 1
        p2 = ls.get_kth_paths("a", "b", 2)
        assert len(p2) == 1 and len(p2[0]) == 2

    def test_path_a_in_path_b(self):
        edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]
        ls = load(topologies.build_topology("line", edges))
        res = ls.get_spf_result("a")
        full = ls._trace_one_path("a", "d", res, set())
        sub = full[1:3]
        assert LinkState.path_a_in_path_b(sub, full)
        assert not LinkState.path_a_in_path_b(full, sub)


class TestHolds:
    def test_holdable_value_basics(self):
        hv = HoldableValue(10)
        # degrading change (increase) held for hold_down ttl
        assert not hv.update_value(20, 2, 3)  # no observable change yet
        assert hv.value == 10 and hv.has_hold()
        assert not hv.decrement_ttl()
        assert not hv.decrement_ttl()
        assert hv.decrement_ttl()  # third tick expires the hold
        assert hv.value == 20 and not hv.has_hold()

    def test_holdable_bool_false_hold(self):
        # hold of value False must still count as a hold
        hv = HoldableValue(False)
        assert not hv.update_value(True, 5, 5)
        assert hv.value is False and hv.has_hold()

    def test_second_change_clears_hold(self):
        hv = HoldableValue(10)
        hv.update_value(20, 5, 5)
        assert hv.has_hold()
        # second change while held: applied immediately
        assert hv.update_value(30, 5, 5)
        assert hv.value == 30 and not hv.has_hold()

    def test_same_value_noop(self):
        hv = HoldableValue(10)
        assert not hv.update_value(10, 5, 5)
        assert not hv.has_hold()

    def test_link_up_hold(self):
        ls = LinkState()
        ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba")]), hold_up_ttl=2
        )
        change = ls.update_adjacency_database(
            db("b", [adj("a", "if_ba", "if_ab")]), hold_up_ttl=2
        )
        # link held down: not yet a topology change
        assert not change.topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") is None
        assert ls.has_holds()
        assert not ls.decrement_holds().topology_changed
        assert ls.decrement_holds().topology_changed  # hold expired
        assert ls.get_metric_from_a_to_b("a", "b") == 1

    def test_metric_update_during_link_up_hold(self):
        """A link added under a hold mutates membership WITHOUT an
        invalidation; the ordered-links memo must still see it, or the
        next merge misreads the link as brand new and silently drops
        the metric update (code-review regression)."""
        ls = LinkState()
        ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=10)]),
            hold_up_ttl=3,
        )
        # warm the memo for "a" BEFORE the held link lands
        assert ls.ordered_links_from_node("a") == []
        ls.update_adjacency_database(
            db("b", [adj("a", "if_ba", "if_ab")]), hold_up_ttl=3
        )
        # metric update while the link is still held down
        ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=99)]),
            hold_up_ttl=3,
        )
        for _ in range(4):
            ls.decrement_holds()
        assert ls.get_metric_from_a_to_b("a", "b") == 99

    def test_held_metric_revert_converges_to_advertised(self):
        """Metric change under hold, then a revert advertisement before
        expiry: the link must converge to the ADVERTISED value, not the
        held-away one (code-review repro: the merge guard compared the
        new metric against the observable value, so the revert never
        reached the HoldableValue and the stale raw value became
        visible at expiry)."""
        ls = LinkState()
        ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=10)])
        )
        ls.update_adjacency_database(
            db("b", [adj("a", "if_ba", "if_ab")])
        )
        assert ls.get_metric_from_a_to_b("a", "b") == 10
        # degrade under a hold: observable stays 10, raw goes 20
        ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=20)]),
            hold_up_ttl=1,
            hold_down_ttl=3,
        )
        assert ls.get_metric_from_a_to_b("a", "b") == 10
        # revert BEFORE expiry: advertised value is 10 again
        ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=10)]),
            hold_up_ttl=1,
            hold_down_ttl=3,
        )
        for _ in range(4):
            ls.decrement_holds()
        assert ls.get_metric_from_a_to_b("a", "b") == 10

    def test_metric_hold_down(self):
        ls = LinkState()
        ls.update_adjacency_database(db("a", [adj("b", "if_ab", "if_ba", metric=5)]))
        ls.update_adjacency_database(db("b", [adj("a", "if_ba", "if_ab")]))
        # metric increase (degrading) held for hold_down ttl
        change = ls.update_adjacency_database(
            db("a", [adj("b", "if_ab", "if_ba", metric=9)]),
            hold_up_ttl=1,
            hold_down_ttl=2,
        )
        assert not change.topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") == 5
        ls.decrement_holds()
        assert ls.decrement_holds().topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") == 9
