"""KvStore tests: merge semantics, flooding topologies, sync FSM, TTLs.

Scenario coverage mirrors the reference suites
(openr/kvstore/tests/KvStoreTest.cpp, KvStoreThriftTest.cpp,
KvStoreClientInternalTest.cpp) — written fresh against our API.
"""

import time

import pytest

from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.kvstore.store import (
    KvStoreFilters,
    compare_values,
    merge_key_values,
)
from openr_tpu.kvstore.wrapper import KvStoreWrapper, link_bidirectional
from openr_tpu.types import TTL_INFINITY, KvStorePeerState, Value
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import OpenrEventBase


def val(version=1, originator="node-a", value=b"v", ttl=TTL_INFINITY, ttl_version=0):
    return Value(
        version=version,
        originator_id=originator,
        value=value,
        ttl=ttl,
        ttl_version=ttl_version,
        hash=wire.generate_hash(version, originator, value),
    )


def wait_until(pred, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestMergeSemantics:
    def test_new_key_accepted(self):
        store = {}
        updates = merge_key_values(store, {"k": val()})
        assert set(updates) == {"k"}
        assert store["k"].value == b"v"

    def test_higher_version_wins(self):
        store = {"k": val(version=2, value=b"old")}
        updates = merge_key_values(store, {"k": val(version=3, value=b"new")})
        assert set(updates) == {"k"}
        assert store["k"].value == b"new"

    def test_lower_version_rejected(self):
        store = {"k": val(version=3, value=b"cur")}
        updates = merge_key_values(store, {"k": val(version=2, value=b"old")})
        assert not updates
        assert store["k"].value == b"cur"

    def test_same_version_higher_originator_wins(self):
        store = {"k": val(originator="node-a", value=b"a")}
        updates = merge_key_values(
            store, {"k": val(originator="node-b", value=b"b")}
        )
        assert set(updates) == {"k"}
        assert store["k"].originator_id == "node-b"

    def test_same_version_same_originator_value_tiebreak(self):
        store = {"k": val(value=b"aaa")}
        updates = merge_key_values(store, {"k": val(value=b"bbb")})
        assert set(updates) == {"k"}  # higher value wins deterministically
        assert store["k"].value == b"bbb"
        # and the lower value loses
        updates = merge_key_values(store, {"k": val(value=b"aaa")})
        assert not updates

    def test_identical_value_no_update(self):
        store = {"k": val()}
        assert not merge_key_values(store, {"k": val()})

    def test_ttl_only_update(self):
        store = {"k": val(ttl=1000)}
        ttl_update = Value(
            version=1,
            originator_id="node-a",
            value=None,
            ttl=5000,
            ttl_version=1,
        )
        updates = merge_key_values(store, {"k": ttl_update})
        assert set(updates) == {"k"}
        assert store["k"].ttl == 5000
        assert store["k"].ttl_version == 1
        assert store["k"].value == b"v"  # value untouched

    def test_invalid_ttl_rejected(self):
        store = {}
        assert not merge_key_values(store, {"k": val(ttl=0)})
        assert not merge_key_values(store, {"k": val(ttl=-5)})

    def test_filters_applied(self):
        store = {}
        filters = KvStoreFilters(key_prefixes=["adj:"])
        updates = merge_key_values(
            store, {"adj:n1": val(), "prefix:n1": val()}, filters
        )
        assert set(updates) == {"adj:n1"}

    def test_compare_values_orderings(self):
        assert compare_values(val(version=2), val(version=1)) == 1
        assert compare_values(val(version=1), val(version=2)) == -1
        assert (
            compare_values(val(originator="b"), val(originator="a")) == 1
        )
        assert compare_values(val(), val()) == 0
        v_no_hash = Value(version=1, originator_id="node-a", value=None)
        assert compare_values(val(), v_no_hash) == -2
        assert (
            compare_values(
                val(ttl_version=2), val(ttl_version=1)
            )
            == 1
        )


class TestFlooding:
    def setup_method(self):
        self.stores = []

    def teardown_method(self):
        for s in self.stores:
            s.stop()

    def mk(self, name, **kwargs):
        s = KvStoreWrapper(name, **kwargs)
        s.start()
        self.stores.append(s)
        return s

    def test_two_stores_sync_and_flood(self):
        a, b = self.mk("node-a"), self.mk("node-b")
        a.set_key("pre-sync", b"from-a")
        link_bidirectional(a, b)
        # initial full sync carries pre-link keys
        assert wait_until(lambda: b.get_key("pre-sync") is not None)
        assert b.get_key("pre-sync").value == b"from-a"
        # live flood after sync
        b.set_key("live", b"from-b")
        assert wait_until(lambda: a.get_key("live") is not None)
        states = a.peer_states()
        assert states["node-b"] == KvStorePeerState.INITIALIZED

    def test_star_topology_flood(self):
        hub = self.mk("hub")
        leaves = [self.mk(f"leaf-{i}") for i in range(4)]
        for leaf in leaves:
            link_bidirectional(hub, leaf)
        leaves[0].set_key("k0", b"x")
        for s in [hub] + leaves:
            assert wait_until(lambda s=s: s.get_key("k0") is not None), s.node_id

    def test_ring_topology_flood(self):
        ring = [self.mk(f"r{i}") for i in range(5)]
        for i in range(5):
            link_bidirectional(ring[i], ring[(i + 1) % 5])
        ring[2].set_key("rk", b"ring")
        for s in ring:
            assert wait_until(lambda s=s: s.get_key("rk") is not None), s.node_id

    def test_conflict_resolution_converges(self):
        a, b = self.mk("node-a"), self.mk("node-b")
        # both write the same key at the same version before linking
        a.set_key("k", b"alpha", version=1)
        b.set_key("k", b"beta", version=1)
        link_bidirectional(a, b)
        # (version, originator, value) ordering: same version+different
        # originators -> higher originator ("node-b") wins everywhere
        assert wait_until(
            lambda: a.get_key("k") is not None
            and a.get_key("k").originator_id == "node-b"
        )
        assert b.get_key("k").originator_id == "node-b"

    def test_three_way_sync_pushes_back(self):
        a, b = self.mk("node-a"), self.mk("node-b")
        a.set_key("only-a", b"a")
        b.set_key("only-b", b"b")
        link_bidirectional(a, b)
        assert wait_until(lambda: b.get_key("only-a") is not None)
        assert wait_until(lambda: a.get_key("only-b") is not None)

    def test_ttl_expiry(self):
        a = self.mk("node-a")
        a.set_key("mortal", b"x", ttl=150)
        assert a.get_key("mortal") is not None
        assert wait_until(lambda: a.get_key("mortal") is None, timeout=3.0)

    def test_ttl_decrement_on_flood(self):
        a, b = self.mk("node-a"), self.mk("node-b")
        link_bidirectional(a, b)
        assert wait_until(
            lambda: a.peer_states()["node-b"] == KvStorePeerState.INITIALIZED
        )
        a.set_key("mortal", b"x", ttl=5000)
        assert wait_until(lambda: b.get_key("mortal") is not None)
        assert b.get_key("mortal").ttl < 5000


class TestKvStoreClient:
    def setup_method(self):
        self.stores = []
        self.evbs = []

    def teardown_method(self):
        for e in self.evbs:
            e.stop()
            e.join()
        for s in self.stores:
            s.stop()

    def mk_client(self, name):
        s = KvStoreWrapper(name)
        s.start()
        self.stores.append(s)
        evb = OpenrEventBase(f"client-evb:{name}")
        evb.run_in_thread()
        self.evbs.append(evb)
        client = KvStoreClient(evb, name, s.store, ttl_refresh_interval_s=0.1)
        return s, client

    def test_persist_and_get(self):
        s, client = self.mk_client("node-a")
        client.persist_key("0", "my-key", b"mine")
        v = client.get_key("0", "my-key")
        assert v is not None and v.value == b"mine" and v.version == 1

    def test_persist_wins_back_ownership(self):
        s, client = self.mk_client("node-a")
        client.persist_key("0", "contested", b"mine")
        # someone else overrides with a higher version
        s.set_key("contested", b"theirs", version=5, originator="node-z")
        assert wait_until(
            lambda: (v := s.get_key("contested")) is not None
            and v.originator_id == "node-a"
            and v.version > 5
        )

    def test_ttl_refresh_keeps_key_alive(self):
        s, client = self.mk_client("node-a")
        client.persist_key("0", "heartbeat", b"alive", ttl=400)
        time.sleep(1.5)  # several ttl periods
        v = s.get_key("heartbeat")
        assert v is not None and v.ttl_version > 0

    def test_subscribe_key_callback(self):
        s, client = self.mk_client("node-a")
        hits = []
        client.subscribe_key("0", "watched", lambda k, v: hits.append((k, v)))
        s.set_key("watched", b"1")
        assert wait_until(lambda: len(hits) >= 1)
        assert hits[0][0] == "watched" and hits[0][1].value == b"1"
