"""SpfSolver route-computation tests.

Scenario coverage mirrors the reference golden corpus
(openr/decision/tests/DecisionTest.cpp, 51 cases): SP-ECMP, anycast,
best-metrics selection, drained advertisers, min-nexthop, SR-MPLS label
routes, KSP2 edge-disjoint multipath — all written fresh against our API.
"""

import pytest

from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.models import topologies
from openr_tpu.types import (
    IpPrefix,
    MplsActionCode,
    PrefixDatabase,
    PrefixEntry,
    PrefixMetrics,
)
from openr_tpu.types.lsdb import PrefixForwardingAlgorithm, PrefixForwardingType


def setup_network(topo, prefix_dbs=None):
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    prefix_state = PrefixState()
    for db in (prefix_dbs or topo.prefix_dbs).values():
        prefix_state.update_prefix_database(db)
    return {topo.area: ls}, prefix_state


def overload_node(topo, name):
    from openr_tpu.types import AdjacencyDatabase

    db = topo.adj_dbs[name]
    topo.adj_dbs[name] = AdjacencyDatabase(
        this_node_name=db.this_node_name,
        is_overloaded=True,
        adjacencies=db.adjacencies,
        node_label=db.node_label,
        area=db.area,
    )


def route_map(route_db):
    return {e.prefix: e for e in (route_db.unicast_routes.values())}


def nh_neighbors(entry):
    return {nh.neighbor_node_name for nh in entry.nexthops}


class TestSpEcmp:
    def test_line_routes(self):
        topo = topologies.build_topology(
            "line", [("a", "b", 10), ("b", "c", 20)]
        )
        area_ls, prefix_state = setup_network(topo)
        solver = SpfSolver("a")
        db = solver.build_route_db("a", area_ls, prefix_state)
        routes = db.unicast_routes
        b_pfx = topo.prefix_dbs["b"].prefix_entries[0].prefix
        c_pfx = topo.prefix_dbs["c"].prefix_entries[0].prefix
        # no route to own prefix
        a_pfx = topo.prefix_dbs["a"].prefix_entries[0].prefix
        assert a_pfx not in routes
        rb, rc = routes[b_pfx], routes[c_pfx]
        assert nh_neighbors(rb) == {"b"}
        assert nh_neighbors(rc) == {"b"}
        (nb,) = rb.nexthops
        assert nb.metric == 10
        assert nb.address.if_name == "if_a_b"
        (nc,) = rc.nexthops
        assert nc.metric == 30

    def test_ecmp_two_paths(self):
        topo = topologies.build_topology(
            "sq", [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
        )
        area_ls, prefix_state = setup_network(topo)
        solver = SpfSolver("a")
        db = solver.build_route_db("a", area_ls, prefix_state)
        d_pfx = topo.prefix_dbs["d"].prefix_entries[0].prefix
        rd = db.unicast_routes[d_pfx]
        assert nh_neighbors(rd) == {"b", "c"}
        assert all(nh.metric == 2 for nh in rd.nexthops)

    def test_unequal_cost_single_path(self):
        topo = topologies.build_topology(
            "sq", [("a", "b", 1), ("a", "c", 9), ("b", "d", 1), ("c", "d", 1)]
        )
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        d_pfx = topo.prefix_dbs["d"].prefix_entries[0].prefix
        assert nh_neighbors(db.unicast_routes[d_pfx]) == {"b"}

    def test_anycast_closest_wins(self):
        # b and d both advertise P; a is 1 hop from b, 2 from d
        topo = topologies.build_topology(
            "line", [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]
        )
        anycast = IpPrefix.from_str("fd00:a::/64")
        pdbs = dict(topo.prefix_dbs)
        for node in ("b", "d"):
            pdbs[node] = PrefixDatabase(
                this_node_name=node,
                prefix_entries=pdbs[node].prefix_entries
                + (PrefixEntry(prefix=anycast),),
                area=topo.area,
            )
        area_ls, prefix_state = setup_network(topo, prefix_dbs=pdbs)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        r = db.unicast_routes[anycast]
        assert nh_neighbors(r) == {"b"}
        (nh,) = r.nexthops
        assert nh.metric == 1

    def test_anycast_equidistant_ecmp(self):
        topo = topologies.build_topology(
            "sq", [("a", "b", 1), ("a", "c", 1)]
        )
        anycast = IpPrefix.from_str("fd00:a::/64")
        pdbs = dict(topo.prefix_dbs)
        for node in ("b", "c"):
            pdbs[node] = PrefixDatabase(
                this_node_name=node,
                prefix_entries=pdbs[node].prefix_entries
                + (PrefixEntry(prefix=anycast),),
                area=topo.area,
            )
        area_ls, prefix_state = setup_network(topo, prefix_dbs=pdbs)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        assert nh_neighbors(db.unicast_routes[anycast]) == {"b", "c"}

    def test_unreachable_advertiser_no_route(self):
        topo = topologies.build_topology(
            "disc", [("a", "b", 1), ("c", "d", 1)]
        )
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        c_pfx = topo.prefix_dbs["c"].prefix_entries[0].prefix
        assert c_pfx not in db.unicast_routes

    def test_node_not_in_graph_returns_none(self):
        topo = topologies.build_topology("pair", [("a", "b", 1)])
        area_ls, prefix_state = setup_network(topo)
        assert SpfSolver("zz").build_route_db("zz", area_ls, prefix_state) is None


class TestBestRouteSelection:
    def _anycast_with_metrics(self, metrics_by_node):
        topo = topologies.build_topology(
            "tri", [("a", "b", 1), ("a", "c", 1)]
        )
        anycast = IpPrefix.from_str("fd00:a::/64")
        pdbs = dict(topo.prefix_dbs)
        for node, metrics in metrics_by_node.items():
            pdbs[node] = PrefixDatabase(
                this_node_name=node,
                prefix_entries=pdbs[node].prefix_entries
                + (PrefixEntry(prefix=anycast, metrics=metrics),),
                area=topo.area,
            )
        area_ls, prefix_state = setup_network(topo, prefix_dbs=pdbs)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        return anycast, db

    def test_higher_path_preference_wins(self):
        anycast, db = self._anycast_with_metrics(
            {
                "b": PrefixMetrics(path_preference=100),
                "c": PrefixMetrics(path_preference=50),
            }
        )
        assert nh_neighbors(db.unicast_routes[anycast]) == {"b"}

    def test_source_preference_tiebreak(self):
        anycast, db = self._anycast_with_metrics(
            {
                "b": PrefixMetrics(path_preference=100, source_preference=10),
                "c": PrefixMetrics(path_preference=100, source_preference=90),
            }
        )
        assert nh_neighbors(db.unicast_routes[anycast]) == {"c"}

    def test_lower_distance_tiebreak(self):
        anycast, db = self._anycast_with_metrics(
            {
                "b": PrefixMetrics(path_preference=1, distance=4),
                "c": PrefixMetrics(path_preference=1, distance=2),
            }
        )
        assert nh_neighbors(db.unicast_routes[anycast]) == {"c"}

    def test_equal_metrics_multipath(self):
        anycast, db = self._anycast_with_metrics(
            {
                "b": PrefixMetrics(path_preference=7),
                "c": PrefixMetrics(path_preference=7),
            }
        )
        assert nh_neighbors(db.unicast_routes[anycast]) == {"b", "c"}

    def test_negative_metrics_select_nothing(self):
        # worse than the (0,0,0) initial best: no route (reference quirk)
        anycast, db = self._anycast_with_metrics(
            {
                "b": PrefixMetrics(path_preference=0, distance=5),
                "c": PrefixMetrics(path_preference=0, distance=9),
            }
        )
        assert anycast not in db.unicast_routes


class TestDrainedNodes:
    def _topo_with_anycast(self):
        topo = topologies.build_topology(
            "tri", [("a", "b", 1), ("a", "c", 1)]
        )
        anycast = IpPrefix.from_str("fd00:a::/64")
        pdbs = dict(topo.prefix_dbs)
        for node in ("b", "c"):
            pdbs[node] = PrefixDatabase(
                this_node_name=node,
                prefix_entries=pdbs[node].prefix_entries
                + (PrefixEntry(prefix=anycast),),
                area=topo.area,
            )
        return topo, anycast, pdbs

    def test_drained_advertiser_filtered(self):
        topo, anycast, pdbs = self._topo_with_anycast()
        overload_node(topo, "b")
        area_ls, prefix_state = setup_network(topo, prefix_dbs=pdbs)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        assert nh_neighbors(db.unicast_routes[anycast]) == {"c"}

    def test_all_drained_falls_back_unfiltered(self):
        topo, anycast, pdbs = self._topo_with_anycast()
        overload_node(topo, "b")
        overload_node(topo, "c")
        area_ls, prefix_state = setup_network(topo, prefix_dbs=pdbs)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        assert nh_neighbors(db.unicast_routes[anycast]) == {"b", "c"}


class TestRouteConstraints:
    def test_min_nexthop_drops_route(self):
        topo = topologies.build_topology(
            "sq", [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
        )
        d_pfx = topo.prefix_dbs["d"].prefix_entries[0].prefix
        pdbs = dict(topo.prefix_dbs)
        pdbs["d"] = PrefixDatabase(
            this_node_name="d",
            prefix_entries=(PrefixEntry(prefix=d_pfx, min_nexthop=3),),
            area=topo.area,
        )
        area_ls, prefix_state = setup_network(topo, prefix_dbs=pdbs)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        # only 2 ECMP nexthops < 3 required: dropped
        assert d_pfx not in db.unicast_routes

        pdbs["d"] = PrefixDatabase(
            this_node_name="d",
            prefix_entries=(PrefixEntry(prefix=d_pfx, min_nexthop=2),),
            area=topo.area,
        )
        area_ls, prefix_state = setup_network(topo, prefix_dbs=pdbs)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        assert len(db.unicast_routes[d_pfx].nexthops) == 2

    def test_v4_gated_by_flag(self):
        topo = topologies.build_topology(
            "pair", [("a", "b", 1)], v4_prefixes=True
        )
        area_ls, prefix_state = setup_network(topo)
        b_pfx = topo.prefix_dbs["b"].prefix_entries[0].prefix
        assert b_pfx.is_v4
        db = SpfSolver("a", enable_v4=False).build_route_db(
            "a", area_ls, prefix_state
        )
        assert b_pfx not in db.unicast_routes
        db = SpfSolver("a", enable_v4=True).build_route_db(
            "a", area_ls, prefix_state
        )
        r = db.unicast_routes[b_pfx]
        (nh,) = r.nexthops
        assert len(nh.address.addr) == 4  # v4 nexthop for v4 prefix


class TestMplsRoutes:
    def test_node_label_routes(self):
        topo = topologies.build_topology(
            "line", [("a", "b", 1), ("b", "c", 1)]
        )
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        labels = {
            n: topo.adj_dbs[n].node_label for n in ("a", "b", "c")
        }
        # own label: POP_AND_LOOKUP
        own = db.mpls_routes[labels["a"]]
        (nh,) = own.nexthops
        assert nh.mpls_action.action == MplsActionCode.POP_AND_LOOKUP
        # neighbor label: PHP (penultimate hop pop)
        rb = db.mpls_routes[labels["b"]]
        (nhb,) = rb.nexthops
        assert nhb.mpls_action.action == MplsActionCode.PHP
        assert nhb.neighbor_node_name == "b"
        # remote label: SWAP via b
        rc = db.mpls_routes[labels["c"]]
        (nhc,) = rc.nexthops
        assert nhc.mpls_action.action == MplsActionCode.SWAP
        assert nhc.mpls_action.swap_label == labels["c"]
        assert nhc.neighbor_node_name == "b"

    def test_adjacency_label_routes(self):
        topo = topologies.build_topology("pair", [("a", "b", 1)])
        # add adjacency labels
        from openr_tpu.types import Adjacency, AdjacencyDatabase

        def with_adj_label(db, label):
            adjs = tuple(
                Adjacency(
                    other_node_name=adj.other_node_name,
                    if_name=adj.if_name,
                    metric=adj.metric,
                    next_hop_v6=adj.next_hop_v6,
                    next_hop_v4=adj.next_hop_v4,
                    adj_label=label,
                    is_overloaded=adj.is_overloaded,
                    rtt=adj.rtt,
                    timestamp=adj.timestamp,
                    weight=adj.weight,
                    other_if_name=adj.other_if_name,
                )
                for adj in db.adjacencies
            )
            return AdjacencyDatabase(
                this_node_name=db.this_node_name,
                is_overloaded=db.is_overloaded,
                adjacencies=adjs,
                node_label=db.node_label,
                area=db.area,
            )

        topo.adj_dbs["a"] = with_adj_label(topo.adj_dbs["a"], 50001)
        topo.adj_dbs["b"] = with_adj_label(topo.adj_dbs["b"], 50002)
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        r = db.mpls_routes[50001]
        (nh,) = r.nexthops
        assert nh.mpls_action.action == MplsActionCode.PHP
        assert nh.neighbor_node_name == "b"

    def test_sr_mpls_ip_to_mpls_push(self):
        topo = topologies.build_topology(
            "line",
            [("a", "b", 1), ("b", "c", 1)],
            forwarding_type=PrefixForwardingType.SR_MPLS,
        )
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        c_pfx = topo.prefix_dbs["c"].prefix_entries[0].prefix
        (nh,) = db.unicast_routes[c_pfx].nexthops
        assert nh.mpls_action.action == MplsActionCode.PUSH
        assert nh.mpls_action.push_labels == (topo.adj_dbs["c"].node_label,)
        # directly-connected destination: no label push
        b_pfx = topo.prefix_dbs["b"].prefix_entries[0].prefix
        (nhb,) = db.unicast_routes[b_pfx].nexthops
        assert nhb.mpls_action is None


class TestKsp2:
    def test_two_edge_disjoint_paths(self):
        topo = topologies.build_topology(
            "sq",
            [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)],
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            forwarding_type=PrefixForwardingType.SR_MPLS,
        )
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        d_pfx = topo.prefix_dbs["d"].prefix_entries[0].prefix
        r = db.unicast_routes[d_pfx]
        assert nh_neighbors(r) == {"b", "c"}
        for nh in r.nexthops:
            assert nh.metric == 2
            assert nh.mpls_action.action == MplsActionCode.PUSH
            assert nh.mpls_action.push_labels == (
                topo.adj_dbs["d"].node_label,
            )

    def test_second_path_longer(self):
        # a-b direct (1) plus detour a-c-b (4): KSP2 uses both
        topo = topologies.build_topology(
            "tri",
            [("a", "b", 1), ("a", "c", 2), ("c", "b", 2)],
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            forwarding_type=PrefixForwardingType.SR_MPLS,
        )
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        b_pfx = topo.prefix_dbs["b"].prefix_entries[0].prefix
        r = db.unicast_routes[b_pfx]
        by_neighbor = {nh.neighbor_node_name: nh for nh in r.nexthops}
        assert set(by_neighbor) == {"b", "c"}
        assert by_neighbor["b"].metric == 1
        assert by_neighbor["b"].mpls_action is None  # direct: PHP'd away
        assert by_neighbor["c"].metric == 4
        assert by_neighbor["c"].mpls_action.push_labels == (
            topo.adj_dbs["b"].node_label,
        )

    def test_ksp2_requires_sr_mpls(self):
        topo = topologies.build_topology(
            "sq",
            [("a", "b", 1), ("b", "d", 1)],
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            forwarding_type=PrefixForwardingType.IP,
        )
        area_ls, prefix_state = setup_network(topo)
        db = SpfSolver("a").build_route_db("a", area_ls, prefix_state)
        d_pfx = topo.prefix_dbs["d"].prefix_entries[0].prefix
        assert d_pfx not in db.unicast_routes


class TestBackendParity:
    @pytest.mark.parametrize("seed", range(3))
    def test_device_matches_host(self, seed):
        topo = topologies.random_mesh(16, degree=3, seed=seed, max_metric=10)
        if seed == 1:
            overload_node(topo, "node-3")
        area_ls, prefix_state = setup_network(topo)
        my = "node-0"
        db_dev = SpfSolver(my, backend="device").build_route_db(
            my, area_ls, prefix_state
        )
        db_host = SpfSolver(my, backend="host").build_route_db(
            my, area_ls, prefix_state
        )
        assert db_dev.to_route_db(my) == db_host.to_route_db(my)

    def test_route_db_delta(self):
        topo = topologies.build_topology(
            "line", [("a", "b", 1), ("b", "c", 1)]
        )
        area_ls, prefix_state = setup_network(topo)
        solver = SpfSolver("a")
        db1 = solver.build_route_db("a", area_ls, prefix_state)
        # metric change b->c: only c's route updates
        from openr_tpu.types import Adjacency, AdjacencyDatabase

        old = topo.adj_dbs["b"]
        new_adjs = tuple(
            Adjacency(
                other_node_name=adj.other_node_name,
                if_name=adj.if_name,
                metric=50 if adj.other_node_name == "c" else adj.metric,
                next_hop_v6=adj.next_hop_v6,
                next_hop_v4=adj.next_hop_v4,
                adj_label=adj.adj_label,
                other_if_name=adj.other_if_name,
            )
            for adj in old.adjacencies
        )
        area_ls["0"].update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="b",
                adjacencies=new_adjs,
                node_label=old.node_label,
                area=old.area,
            )
        )
        db2 = solver.build_route_db("a", area_ls, prefix_state)
        delta = db1.calculate_update(db2)
        c_pfx = topo.prefix_dbs["c"].prefix_entries[0].prefix
        assert set(delta.unicast_routes_to_update) == {c_pfx}
        assert not delta.unicast_routes_to_delete
        (nh,) = delta.unicast_routes_to_update[c_pfx].nexthops
        assert nh.metric == 51
