"""Multi-area: per-interface areas, per-area LSDBs, and cross-area
route redistribution.

Reference semantics: a border router participates in several areas (one
KvStoreDb / LinkState per area), and its PrefixManager re-originates
Decision's best routes into the areas they were not learned from, with
``area_stack`` loop suppression (openr/prefix-manager/PrefixManager.cpp,
openr/decision/Decision.h:390 per-area link states; BASELINE.json config
"Multi-area Decision with inter-area prefix redistribution").
"""

import time

import pytest

from openr_tpu.daemon import OpenrNode
from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.prefixmgr.prefix_manager import PrefixManager
from openr_tpu.spark.io_provider import MockIoProvider
from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType
from openr_tpu.types.lsdb import PrefixMetrics


def wait_until(pred, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class FakeClient:
    """Captures KvStore client calls: area -> {key: payload}."""

    def __init__(self):
        self.persisted = {}

    def persist_key(self, area, key, value):
        self.persisted.setdefault(area, {})[key] = value

    def set_key(self, area, key, value):
        self.persisted.setdefault(area, {})[key] = value

    def unset_key(self, area, key):
        self.persisted.get(area, {}).pop(key, None)

    def clear_key(self, area, key, value, ttl=None):
        self.persisted.get(area, {}).pop(key, None)


class TestRedistributionUnit:
    def make_pm(self):
        q = ReplicateQueue(name="routeUpdates")
        client = FakeClient()
        pm = PrefixManager(
            "border",
            client,
            decision_route_updates_queue=q,
            areas=["1", "2"],
        )
        pm.start()
        return pm, q, client

    def route_update(self, prefix, best_area, area_stack=()):
        update = DecisionRouteUpdate()
        update.unicast_routes_to_update[prefix] = RibUnicastEntry(
            prefix=prefix,
            best_prefix_entry=PrefixEntry(
                prefix=prefix,
                metrics=PrefixMetrics(path_preference=700),
                area_stack=area_stack,
            ),
            best_area=best_area,
        )
        return update

    def test_reoriginated_into_other_area_only(self):
        pm, q, client = self.make_pm()
        try:
            prefix = IpPrefix.from_str("fd00:a::1/128")
            q.push(self.route_update(prefix, best_area="1"))
            assert wait_until(
                lambda: any(
                    "fd00:a::1" in k for k in client.persisted.get("2", {})
                )
            )
            # never echoed back into the source area
            assert not any(
                "fd00:a::1" in k for k in client.persisted.get("1", {})
            )
            (entry, targets) = pm.get_redistributed()[prefix]
            assert entry.type == PrefixType.RIB
            assert entry.area_stack == ("1",)
            assert entry.metrics.path_preference == 700
            # the copy must always lose best-route selection to the
            # original, else two borders' identical copies oscillate
            assert entry.metrics.distance == 1
            assert targets == ("2",)
        finally:
            pm.stop()

    def test_area_stack_loop_suppression(self):
        pm, q, client = self.make_pm()
        try:
            # best route already traversed both areas: nowhere to go
            prefix = IpPrefix.from_str("fd00:b::1/128")
            q.push(self.route_update(prefix, "1", area_stack=("2",)))
            time.sleep(0.3)
            assert pm.get_redistributed() == {}
            assert not any(
                "fd00:b::1" in k
                for area in ("1", "2")
                for k in client.persisted.get(area, {})
            )
        finally:
            pm.stop()

    def test_own_prefixes_not_redistributed(self):
        pm, q, client = self.make_pm()
        try:
            prefix = IpPrefix.from_str("fd00:c::1/128")
            pm.advertise_prefixes(
                [PrefixEntry(prefix=prefix, type=PrefixType.LOOPBACK)]
            )
            q.push(self.route_update(prefix, "1"))
            time.sleep(0.3)
            assert pm.get_redistributed() == {}
        finally:
            pm.stop()

    def test_withdraw_on_route_delete(self):
        pm, q, client = self.make_pm()
        try:
            prefix = IpPrefix.from_str("fd00:d::1/128")
            q.push(self.route_update(prefix, "1"))
            assert wait_until(lambda: prefix in pm.get_redistributed())
            update = DecisionRouteUpdate()
            update.unicast_routes_to_delete.append(prefix)
            q.push(update)
            assert wait_until(lambda: pm.get_redistributed() == {})
            assert not any(
                "fd00:d::1" in k for k in client.persisted.get("2", {})
            )
        finally:
            pm.stop()


class TestAdvertisementModes:
    def test_full_db_mode_reaches_every_area(self):
        client = FakeClient()
        pm = PrefixManager(
            "n", client, areas=["1", "2"], per_prefix_keys=False
        )
        pm.start()
        try:
            pm.advertise_prefixes(
                [PrefixEntry(prefix=IpPrefix.from_str("fd00:1::/64"))]
            )
            assert wait_until(
                lambda: all(
                    client.persisted.get(a) for a in ("1", "2")
                )
            ), client.persisted
        finally:
            pm.stop()

    def test_same_prefix_two_types_advertises_best(self):
        client = FakeClient()
        pm = PrefixManager("n", client, areas=["1"])
        pm.start()
        try:
            prefix = IpPrefix.from_str("fd00:2::/64")
            pm.advertise_prefixes(
                [
                    PrefixEntry(
                        prefix=prefix,
                        type=PrefixType.BGP,
                        metrics=PrefixMetrics(path_preference=500),
                    ),
                    PrefixEntry(
                        prefix=prefix,
                        type=PrefixType.LOOPBACK,
                        metrics=PrefixMetrics(path_preference=900),
                    ),
                ]
            )
            from openr_tpu.types import PrefixDatabase
            from openr_tpu.utils import wire

            [(key, payload)] = client.persisted["1"].items()
            db = wire.loads(payload, PrefixDatabase)
            assert len(db.prefix_entries) == 1
            assert db.prefix_entries[0].type == PrefixType.LOOPBACK
            # withdrawing the winner falls back to the other type
            pm.withdraw_prefixes([])  # no-op keeps state machinery warm
        finally:
            pm.stop()

    def test_sync_by_type_applies_origination_defaults(self):
        client = FakeClient()
        pm = PrefixManager("n", client, areas=["1"])
        pm.start()
        try:
            pm.sync_prefixes_by_type(
                PrefixType.PREFIX_ALLOCATOR,
                [PrefixEntry(prefix=IpPrefix.from_str("fd00:3::/64"))],
            )
            [entry] = pm.get_prefixes()
            assert entry.metrics.path_preference == 1000
            assert entry.metrics.source_preference == 200
        finally:
            pm.stop()

    def test_daemon_rejects_unconfigured_areas(self):
        from openr_tpu.daemon import OpenrNode
        from openr_tpu.spark.io_provider import MockIoProvider

        io = MockIoProvider()
        try:
            with pytest.raises(ValueError):
                OpenrNode(
                    "x", io, areas=["1", "2"],
                    interface_areas={"eth0": "3"}, area="1",
                )
            with pytest.raises(ValueError):
                OpenrNode("y", io, areas=["1", "2"])  # default area "0"
        finally:
            io.stop()


SPARK_FAST = dict(
    hello_interval_s=0.05,
    fast_hello_interval_s=0.03,
    handshake_interval_s=0.03,
    heartbeat_interval_s=0.05,
    hold_time_s=0.6,
    graceful_restart_time_s=2.0,
)


class TestMultiAreaSystem:
    """a -(area 1)- border -(area 2)- c : end-to-end redistribution."""

    @pytest.fixture
    def net(self):
        io = MockIoProvider()
        registry = {}
        nodes = {
            "a": OpenrNode(
                "a", io, node_registry=registry, area="1",
                v6_addr="fe80::1", spark_config=SPARK_FAST,
            ),
            "border": OpenrNode(
                "border", io, node_registry=registry, area="1",
                areas=["1", "2"],
                interface_areas={"if_border_c": "2"},
                v6_addr="fe80::2", spark_config=SPARK_FAST,
            ),
            "c": OpenrNode(
                "c", io, node_registry=registry, area="2",
                v6_addr="fe80::3", spark_config=SPARK_FAST,
            ),
        }
        io.connect_pair("if_a_border", "if_border_a", 1)
        io.connect_pair("if_border_c", "if_c_border", 1)
        for n in nodes.values():
            n.start()
        nodes["a"].add_interface("if_a_border")
        nodes["border"].add_interface("if_border_a")
        nodes["border"].add_interface("if_border_c")
        nodes["c"].add_interface("if_c_border")
        yield nodes
        for n in nodes.values():
            n.stop()
        io.stop()

    def has_route(self, node, prefix):
        db = node.get_fib_routes()
        return any(r.dest == prefix for r in db.unicast_routes)

    def test_cross_area_propagation(self, net):
        a_pfx = net["a"].advertise_loopback("fd00:a::1/128")
        c_pfx = net["c"].advertise_loopback("fd00:c::1/128")

        # intra-area first
        assert wait_until(lambda: self.has_route(net["border"], a_pfx))
        assert wait_until(lambda: self.has_route(net["border"], c_pfx))
        # cross-area via the border's re-origination
        assert wait_until(lambda: self.has_route(net["c"], a_pfx))
        assert wait_until(lambda: self.has_route(net["a"], c_pfx))

        # c's route to a's loopback goes through the border
        db = net["c"].get_fib_routes()
        route = next(r for r in db.unicast_routes if r.dest == a_pfx)
        assert {nh.neighbor_node_name for nh in route.next_hops} == {"border"}

        # the redistributed advertisement carries the source area stack
        redist = net["border"].prefix_manager.get_redistributed()
        assert redist[a_pfx][0].area_stack == ("1",)
        assert redist[a_pfx][1] == ("2",)
        assert redist[c_pfx][0].area_stack == ("2",)
        assert redist[c_pfx][1] == ("1",)

        # loop prevention: a's own prefix never comes back as a route on a
        assert not self.has_route(net["a"], a_pfx)

    def test_cross_area_withdraw(self, net):
        a_pfx = net["a"].advertise_loopback("fd00:a::2/128")
        assert wait_until(lambda: self.has_route(net["c"], a_pfx))
        net["a"].prefix_manager.withdraw_prefixes([a_pfx])
        assert wait_until(lambda: not self.has_route(net["c"], a_pfx))
