"""Thrift CompactProtocol interop codec: golden byte vectors derived
by hand from the compact-protocol specification (field ids from
openr/if/KvStore.thrift), round-trip equality, and forward-compat
skipping. The goldens are INDEPENDENT of the codec: each byte is
derived in the comments, so an encoder bug cannot hide behind its own
decoder."""

import pytest

from openr_tpu.types import (
    KeyDumpParams,
    KeySetParams,
    Publication,
    TTL_INFINITY,
    Value,
)
from openr_tpu.utils import thrift_compact as tc


class TestGoldenVectors:
    def test_value_golden(self):
        v = Value(
            version=1,
            originator_id="node1",
            value=b"hi",
            ttl=TTL_INFINITY,  # -2**31
            ttl_version=0,
        )
        golden = bytes(
            [
                # field 1 (i64 version=1): delta 1 -> 0x16; zigzag(1)=2
                0x16, 0x02,
                # field 3 (string originatorId="node1"): delta 2 -> 0x28
                0x28, 0x05, 0x6E, 0x6F, 0x64, 0x65, 0x31,
                # field 2 (binary value=b"hi"): NEGATIVE delta -> long
                # form: type byte 0x08 + zigzag16(2)=4
                0x08, 0x04, 0x02, 0x68, 0x69,
                # field 4 (i64 ttl=-2**31): delta 2 -> 0x26;
                # zigzag64(-2147483648) = 0xFFFFFFFF -> 5-byte varint
                0x26, 0xFF, 0xFF, 0xFF, 0xFF, 0x0F,
                # field 5 (i64 ttlVersion=0): delta 1 -> 0x16; 0
                0x16, 0x00,
                # STOP
                0x00,
            ]
        )
        assert tc.encode_value(v) == golden
        assert tc.decode_value(golden) == v

    def test_empty_publication_golden(self):
        pub = Publication(area="0")
        golden = bytes(
            [
                0x2B, 0x00,  # field 2: empty map -> single 0x00
                0x19, 0x08,  # field 3: empty list<string>
                0x48, 0x01, 0x30,  # field 7 (delta 4): area "0"
                0x00,  # STOP
            ]
        )
        assert tc.encode_publication(pub) == golden
        assert tc.decode_publication(golden) == pub

    def test_key_set_params_golden(self):
        p = KeySetParams(
            key_vals={
                "k": Value(
                    version=2,
                    originator_id="a",
                    value=b"\x01",
                    ttl=100,
                    ttl_version=1,
                    hash=42,
                )
            },
            solicit_response=False,
            originator_id="a",
        )
        golden = bytes(
            [
                0x2B,  # field 2: map, delta 2
                0x01,  # map size 1
                0x8C,  # key type string(8) << 4 | value type struct(12)
                0x01, 0x6B,  # key "k"
                # nested Value struct:
                0x16, 0x04,  # version=2 (zigzag 4)
                0x28, 0x01, 0x61,  # originatorId "a"
                0x08, 0x04, 0x01, 0x01,  # value b"\x01" (long-form id 2)
                0x26, 0xC8, 0x01,  # ttl=100 (zigzag 200)
                0x16, 0x02,  # ttlVersion=1
                0x16, 0x54,  # hash=42 (zigzag 84)
                0x00,  # nested STOP
                0x12,  # field 3: bool FALSE in the header nibble
                0x29,  # field 5: list, delta 2
                0x18, 0x01, 0x61,  # ["a"]
                0x00,  # STOP
            ]
        )
        assert tc.encode_key_set_params(p) == golden
        assert tc.decode_key_set_params(golden) == p

    def test_bool_true_in_header(self):
        p = KeySetParams(solicit_response=True)
        data = tc.encode_key_set_params(p)
        # field 2 empty map (0x2B 0x00), then field 3 delta 1 with the
        # TRUE type nibble and NO value byte, then STOP
        assert data == bytes([0x2B, 0x00, 0x11, 0x00])
        assert tc.decode_key_set_params(data).solicit_response is True


class TestRoundTrip:
    def test_publication_full(self):
        pub = Publication(
            key_vals={
                f"adj:node-{i}": Value(
                    version=i + 1,
                    originator_id=f"node-{i}",
                    value=bytes(range(i % 7)),
                    ttl=3600_000,
                    ttl_version=i,
                    hash=(-1) ** i * i * 7919,
                )
                for i in range(20)
            },
            expired_keys=["prefix:gone", "adj:dead"],
            nodes=["a", "b", "c"],
            tobe_updated_keys=["k1"],
            flood_root_id="root-1",
            area="area-51",
        )
        assert tc.decode_publication(tc.encode_publication(pub)) == pub

    def test_key_dump_params(self):
        p = KeyDumpParams(
            prefix="adj:",
            originator_ids={"n1", "n2"},
            keys=["adj:.*", "prefix:.*"],
            key_val_hashes={
                "adj:n1": Value(
                    version=4, originator_id="n1", ttl=100, hash=123
                )
            },
        )
        assert (
            tc.decode_key_dump_params(tc.encode_key_dump_params(p)) == p
        )

    def test_large_collections_use_long_form(self):
        pub = Publication(
            expired_keys=[f"key-{i:04d}" for i in range(300)],
            area="0",
        )
        out = tc.decode_publication(tc.encode_publication(pub))
        assert out.expired_keys == pub.expired_keys

    def test_negative_and_large_ints(self):
        for version in (0, 1, 2**31, 2**62):
            for ttl in (TTL_INFINITY, -1, 0, 1, 2**40):
                v = Value(
                    version=version, originator_id="x", ttl=ttl
                )
                assert tc.decode_value(tc.encode_value(v)) == v

    def test_kvstore_request_envelope(self):
        req = {
            "cmd": tc.CMD_KEY_DUMP,
            "area": "0",
            "keyDumpParams": {
                "prefix": "",
                "originatorIds": set(),
                "ignoreTtl": True,
                "doNotPublishValue": False,
            },
        }
        data = tc.encode(tc.KV_STORE_REQUEST, req)
        back = tc.decode(tc.KV_STORE_REQUEST, data)
        assert back["cmd"] == tc.CMD_KEY_DUMP
        assert back["area"] == "0"
        assert back["keyDumpParams"]["ignoreTtl"] is True


class TestBoolCollections:
    """Collection-element bools are ONE byte each (01/02) while field
    bools ride in the header nibble — decoding or skipping with the
    wrong context desyncs the stream (code-review regression: the
    decoder returned from the field branch without consuming element
    bytes, so list<bool> corrupted every subsequent field)."""

    SCHEMA = tc.StructSchema(
        "BoolBag",
        (
            tc.Field(1, ("list", ("bool",)), "flags"),
            tc.Field(2, ("string",), "tag", optional=True),
        ),
    )

    def test_list_bool_golden_round_trip(self):
        data = {"flags": [True, False, True], "tag": "x"}
        golden = bytes(
            [
                0x19,  # field 1 delta 1, type list
                0x31,  # size 3 << 4 | elem type TRUE(0x01)
                0x01, 0x02, 0x01,  # one byte per element
                0x18, 0x01, 0x78,  # field 2: string "x"
                0x00,  # STOP
            ]
        )
        enc = tc.encode(self.SCHEMA, data)
        assert enc == golden
        assert tc.decode(self.SCHEMA, golden) == data

    def test_unknown_list_bool_field_skipped(self):
        """Forward compat: a newer peer's list<bool> field must be
        skipped byte-exactly."""
        newer = tc.StructSchema(
            "Newer",
            (
                tc.Field(1, ("list", ("bool",)), "flags"),
                tc.Field(2, ("string",), "tag", optional=True),
            ),
        )
        older = tc.StructSchema(
            "Older",
            (tc.Field(2, ("string",), "tag", optional=True),),
        )
        enc = tc.encode(
            newer, {"flags": [True, True, False], "tag": "ok"}
        )
        assert tc.decode(older, enc) == {"tag": "ok"}


class TestForwardCompat:
    def test_unknown_fields_skipped(self):
        """A newer peer's extra fields (any type, short and long form
        headers) must not break decoding."""
        w = tc._Writer()
        # field 1: i64 version = 9
        w.byte(0x16)
        w.zigzag(9, 64)
        # unknown field 2 struct (would be `value` as a WRONG type in an
        # imagined v2 schema — skipped by wire type, not schema type):
        # use a far field id instead: long form field 100, struct
        w.byte(0x0C)
        w.zigzag(100, 16)
        w.byte(0x16)  # nested field 1 i64
        w.zigzag(7, 64)
        w.byte(0x00)  # nested STOP
        # field 3 originatorId (delta from 100 is negative -> long form)
        w.byte(0x08)
        w.zigzag(3, 16)
        w.binary(b"peer")
        # field 4 ttl
        w.byte(0x16)
        w.zigzag(60_000, 64)
        w.byte(0x00)
        v = tc.decode_value(bytes(w.buf))
        assert v.version == 9
        assert v.originator_id == "peer"
        assert v.ttl == 60_000

    def test_missing_required_field_raises_on_encode(self):
        with pytest.raises(ValueError):
            tc.encode(tc.VALUE, {"version": 1})  # no originatorId

    def test_truncated_input_raises(self):
        data = tc.encode_value(
            Value(version=1, originator_id="n", ttl=5)
        )
        with pytest.raises((ValueError, IndexError)):
            tc.decode_value(data[:-3])


class TestCodecFuzz:
    """Randomized schema/value round trips: any structurally valid
    (schema, value) pair must encode+decode to itself, and skipping an
    unknown field of any shape must leave the stream in sync."""

    def _random_type(self, rng, depth):
        kinds = ["bool", "byte", "i16", "i32", "i64", "string", "binary"]
        if depth < 2:
            kinds += ["list", "set", "map", "struct"]
        kind = rng.choice(kinds)
        if kind in ("list", "set"):
            # sets need hashable (scalar) elements
            elem_depth = 2 if kind == "set" else depth + 1
            return (kind, self._random_type(rng, elem_depth))
        if kind == "map":
            return (
                "map",
                self._random_type(rng, 2),  # scalar keys
                self._random_type(rng, depth + 1),
            )
        if kind == "struct":
            return ("struct", self._random_schema(rng, depth + 1))
        return (kind,)

    def _random_schema(self, rng, depth=0):
        fields = []
        fid = 0
        for _ in range(rng.randint(1, 5)):
            fid += rng.randint(1, 40)  # exercise both header forms
            fields.append(
                tc.Field(
                    fid,
                    self._random_type(rng, depth),
                    f"f{fid}",
                    optional=rng.random() < 0.3,
                )
            )
        return tc.StructSchema(f"S{rng.randint(0, 9999)}", tuple(fields))

    def _random_value(self, rng, ftype):
        kind = ftype[0]
        if kind == "bool":
            return rng.random() < 0.5
        if kind == "byte":
            return rng.randint(-128, 127)
        if kind in ("i16", "i32", "i64"):
            bits = {"i16": 15, "i32": 31, "i64": 63}[kind]
            return rng.randint(-(2 ** bits), 2 ** bits - 1)
        if kind == "string":
            return "".join(
                rng.choice("abcdefg é中") for _ in range(rng.randint(0, 20))
            )
        if kind == "binary":
            return bytes(
                rng.randint(0, 255) for _ in range(rng.randint(0, 40))
            )
        if kind == "list":
            return [
                self._random_value(rng, ftype[1])
                for _ in range(rng.randint(0, 17))
            ]
        if kind == "set":
            return {
                self._random_value(rng, ftype[1])
                for _ in range(rng.randint(0, 17))
            }
        if kind == "map":
            return {
                self._random_value(rng, ftype[1]): self._random_value(
                    rng, ftype[2]
                )
                for _ in range(rng.randint(0, 9))
            }
        if kind == "struct":
            return self._struct_value(rng, ftype[1])
        raise AssertionError(kind)

    def _struct_value(self, rng, schema):
        out = {}
        for f in schema.fields:
            if f.optional and rng.random() < 0.4:
                continue
            out[f.name] = self._random_value(rng, f.ftype)
        return out

    def test_round_trips(self):
        import random

        rng = random.Random(0xC0DEC)
        for _ in range(200):
            schema = self._random_schema(rng)
            value = self._struct_value(rng, schema)
            data = tc.encode(schema, value)
            assert tc.decode(schema, data) == value

    def test_unknown_fields_of_every_shape_skip_cleanly(self):
        import random

        rng = random.Random(0x5EED)
        # decode with a schema that knows NONE of the fields except a
        # trailing sentinel: every unknown field must be skipped
        # byte-exactly for the sentinel to decode
        for _ in range(100):
            schema = self._random_schema(rng)
            value = self._struct_value(rng, schema)
            sentinel_id = max(f.fid for f in schema.fields) + 1
            full = tc.StructSchema(
                "full",
                schema.fields
                + (tc.Field(sentinel_id, ("i32",), "sentinel"),),
            )
            reduced = tc.StructSchema(
                "reduced",
                (tc.Field(sentinel_id, ("i32",), "sentinel"),),
            )
            value["sentinel"] = 777
            data = tc.encode(full, value)
            assert tc.decode(reduced, data) == {"sentinel": 777}
