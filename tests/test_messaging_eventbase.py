"""Runtime substrate tests (reference analogues:
openr/messaging/tests/*, openr/common/tests/*)."""

import threading
import time

import pytest

from openr_tpu.messaging.queue import (
    QueueClosedError,
    QueueTimeoutError,
    ReplicateQueue,
)
from openr_tpu.utils.eventbase import (
    AsyncDebounce,
    AsyncThrottle,
    ExponentialBackoff,
    OpenrEventBase,
)
from openr_tpu.utils.stepdetector import StepDetector, StepDetectorConfig


class TestReplicateQueue:
    def test_fanout_to_all_readers(self):
        q = ReplicateQueue(name="test")
        r1, r2 = q.get_reader(), q.get_reader()
        q.push(1)
        q.push(2)
        assert [r1.get(0.1), r1.get(0.1)] == [1, 2]
        assert [r2.get(0.1), r2.get(0.1)] == [1, 2]
        assert q.num_writes == 2

    def test_reader_after_push_misses_history(self):
        q = ReplicateQueue()
        q.push("early")
        r = q.get_reader()
        with pytest.raises(QueueTimeoutError):
            r.get(timeout=0.05)

    def test_close_unblocks_readers(self):
        q = ReplicateQueue()
        r = q.get_reader()
        results = []

        def consume():
            try:
                r.get(timeout=5)
            except QueueClosedError:
                results.append("closed")

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.05)
        q.close()
        t.join(timeout=2)
        assert results == ["closed"]

    def test_drain_before_closed_error(self):
        q = ReplicateQueue()
        r = q.get_reader()
        q.push(7)
        q.close()
        assert r.get(0.1) == 7
        with pytest.raises(QueueClosedError):
            r.get(0.1)

    def test_push_after_close_refused(self):
        q = ReplicateQueue()
        q.get_reader()
        q.close()
        assert q.push(1) is False


class TestEventBase:
    def test_run_in_event_base(self):
        evb = OpenrEventBase("t")
        evb.run_in_thread()
        hits = []
        evb.run_in_event_base(lambda: hits.append(threading.current_thread().name))
        time.sleep(0.1)
        evb.stop()
        evb.join()
        assert hits == ["t"]

    def test_call_and_wait_returns_value(self):
        evb = OpenrEventBase("t2")
        evb.run_in_thread()
        assert evb.call_and_wait(lambda: 41 + 1) == 42
        evb.stop()
        evb.join()

    def test_call_and_wait_propagates_exception(self):
        evb = OpenrEventBase("t3")
        evb.run_in_thread()

        def boom():
            raise ValueError("x")

        with pytest.raises(ValueError):
            evb.call_and_wait(boom)
        evb.stop()
        evb.join()

    def test_timers_fire_in_order(self):
        evb = OpenrEventBase("t4")
        evb.run_in_thread()
        hits = []
        evb.schedule_timeout(0.10, lambda: hits.append("b"))
        evb.schedule_timeout(0.02, lambda: hits.append("a"))
        time.sleep(0.3)
        evb.stop()
        evb.join()
        assert hits == ["a", "b"]

    def test_timer_cancel(self):
        evb = OpenrEventBase("t5")
        evb.run_in_thread()
        hits = []
        h = evb.schedule_timeout(0.05, lambda: hits.append("x"))
        h.cancel()
        time.sleep(0.15)
        evb.stop()
        evb.join()
        assert hits == []

    def test_queue_reader_delivers_on_loop_thread(self):
        evb = OpenrEventBase("t6")
        evb.run_in_thread()
        q = ReplicateQueue()
        r = q.get_reader()
        got = []
        evb.add_queue_reader(r, lambda m: got.append((m, threading.current_thread().name)))
        q.push("hello")
        time.sleep(0.3)
        evb.stop()
        evb.join()
        assert got == [("hello", "t6")]


class TestBackoffPrimitives:
    def test_exponential_backoff_doubles(self):
        b = ExponentialBackoff(0.1, 0.4)
        assert b.can_try_now()
        b.report_error()
        assert b.get_current_backoff() == pytest.approx(0.1)
        assert not b.can_try_now()
        b.report_error()
        assert b.get_current_backoff() == pytest.approx(0.2)
        b.report_error()
        b.report_error()
        assert b.get_current_backoff() == pytest.approx(0.4)
        assert b.at_max_backoff()
        b.report_success()
        assert b.can_try_now()

    def test_throttle_coalesces(self):
        evb = OpenrEventBase("th")
        evb.run_in_thread()
        hits = []
        th = AsyncThrottle(evb, 0.1, lambda: hits.append(1))
        for _ in range(20):
            th()
        time.sleep(0.3)
        assert len(hits) == 1
        evb.stop()
        evb.join()

    def test_debounce_extends_then_fires_once(self):
        evb = OpenrEventBase("db")
        evb.run_in_thread()
        hits = []
        db = AsyncDebounce(evb, 0.02, 0.2, lambda: hits.append(time.monotonic()))
        t0 = time.monotonic()
        for _ in range(5):
            db()
            time.sleep(0.005)
        time.sleep(0.6)
        assert len(hits) == 1
        # the repeated invocations should have extended beyond min backoff
        assert hits[0] - t0 > 0.02
        evb.stop()
        evb.join()

    def test_debounce_refires_after_idle(self):
        evb = OpenrEventBase("db2")
        evb.run_in_thread()
        hits = []
        db = AsyncDebounce(evb, 0.02, 0.1, lambda: hits.append(1))
        db()
        time.sleep(0.2)
        db()
        time.sleep(0.2)
        assert len(hits) == 2
        evb.stop()
        evb.join()


class TestStepDetector:
    def test_detects_step(self):
        steps = []
        sd = StepDetector(
            StepDetectorConfig(
                fast_window_size=3,
                slow_window_size=9,
                lower_threshold=2.0,
                upper_threshold=8.0,
                abs_threshold=10_000,
            ),
            steps.append,
        )
        for _ in range(20):
            sd.add_value(1000.0)
        assert steps == []
        for _ in range(20):
            sd.add_value(2000.0)
        assert len(steps) >= 1
        assert steps[0] == pytest.approx(2000.0, rel=0.05)

    def test_ignores_noise(self):
        steps = []
        sd = StepDetector(
            StepDetectorConfig(
                fast_window_size=3,
                slow_window_size=9,
                lower_threshold=2.0,
                upper_threshold=8.0,
                abs_threshold=10_000,
            ),
            steps.append,
        )
        import random

        rng = random.Random(1)
        for _ in range(100):
            sd.add_value(1000.0 + rng.uniform(-20, 20))
        assert steps == []
