"""Real rtnetlink socket tests (reference analogue:
openr/nl/tests/* and platform/tests/NetlinkFibHandlerTest.cpp — 'need a
real kernel; run on Linux CI').

Skipped when the process lacks NET_ADMIN (probed by trying to create a
dummy link)."""

import time

import pytest

from openr_tpu.messaging.queue import QueueTimeoutError, ReplicateQueue
from openr_tpu.platform.netlink import NetlinkEventType
from openr_tpu.platform.netlink_linux import (
    LinuxNetlinkProtocolSocket,
    NetlinkError,
)
from openr_tpu.types import BinaryAddress, IpPrefix, NextHop, UnicastRoute

IFACE = "oprtest0"


def _admin_socket():
    """A socket that can create links, or None. Kernels differ in which
    virtual link kinds are compiled in — try a few."""
    if not LinuxNetlinkProtocolSocket.is_available():
        return None
    nl = LinuxNetlinkProtocolSocket()
    try:
        nl.delete_link(IFACE)  # clean leftovers from a dead run
    except (NetlinkError, PermissionError, OSError):
        nl.close()
        return None
    for kind in ("dummy", "ifb"):
        try:
            nl.create_link(IFACE, kind=kind)
            return nl
        except (NetlinkError, PermissionError, OSError):
            continue
    nl.close()
    return None


# every destination this suite programs lives under one distinctive ULA
# block, and teardown deletes ONLY routes inside it — a co-resident real
# daemon's proto-99 routes must never be touched
TEST_BLOCK = "fd0a:7e57:"


@pytest.fixture
def nl():
    sock = _admin_socket()
    if sock is None:
        pytest.skip("rtnetlink link creation unavailable (no NET_ADMIN)")
    sock.set_link_up(IFACE)
    yield sock
    try:
        for route in sock.get_all_routes():
            if route.dest.to_str().startswith(TEST_BLOCK):
                sock.delete_route(route.dest)
        sock.delete_link(IFACE)
    finally:
        sock.close()


class TestLinuxNetlink:
    def test_link_dump_sees_dummy(self, nl):
        links = {l.if_name: l for l in nl.get_all_links()}
        assert IFACE in links
        assert links[IFACE].is_up
        assert "lo" in links

    def test_link_up_down(self, nl):
        nl.set_link_up(IFACE, up=False)
        links = {l.if_name: l for l in nl.get_all_links()}
        assert not links[IFACE].is_up
        nl.set_link_up(IFACE, up=True)
        links = {l.if_name: l for l in nl.get_all_links()}
        assert links[IFACE].is_up

    def test_route_add_dump_delete(self, nl):
        dest = IpPrefix.from_str("fd0a:7e57:bead::/64")
        route = UnicastRoute(
            dest=dest,
            next_hops=(
                NextHop(address=BinaryAddress(addr=b"", if_name=IFACE)),
            ),
        )
        nl.add_route(route)
        dests = [r.dest for r in nl.get_all_routes()]
        assert dest in dests
        nl.delete_route(dest)
        dests = [r.dest for r in nl.get_all_routes()]
        assert dest not in dests

    def test_route_dump_only_openr_protocol(self, nl):
        # the dump filter only returns proto-99 (openr) routes: kernel-
        # installed routes (proto boot/kernel, e.g. lo's local routes and
        # eth0's connected route) never appear, while ours do
        dest = IpPrefix.from_str("fd0a:7e57:feed::/64")
        nl.add_route(
            UnicastRoute(
                dest=dest,
                next_hops=(
                    NextHop(address=BinaryAddress(addr=b"", if_name=IFACE)),
                ),
            )
        )
        routes = nl.get_all_routes()
        # membership, not exact equality: a co-resident daemon's own
        # proto-99 routes (outside TEST_BLOCK) may legitimately appear
        assert dest in [r.dest for r in routes]
        # but kernel/boot-proto routes must not: everything dumped under
        # our test block is exactly what we programmed
        assert [
            r.dest
            for r in routes
            if r.dest.to_str().startswith(TEST_BLOCK)
        ] == [dest]
        nl.delete_route(dest)

    def test_ecmp_multipath_route(self, nl):
        # two gateways via the dummy link -> RTA_MULTIPATH group
        nl.add_ifaddress(IFACE, IpPrefix.from_str("fd0a:7e57:77::1/64"))
        dest = IpPrefix.from_str("fd0a:7e57:beef::/64")
        route = UnicastRoute(
            dest=dest,
            next_hops=(
                NextHop(
                    address=BinaryAddress.from_str(
                        "fd0a:7e57:77::2", if_name=IFACE
                    )
                ),
                NextHop(
                    address=BinaryAddress.from_str(
                        "fd0a:7e57:77::3", if_name=IFACE
                    )
                ),
            ),
        )
        nl.add_route(route)
        by_dest = {r.dest: r for r in nl.get_all_routes()}
        assert dest in by_dest
        got = by_dest[dest]
        assert len(got.next_hops) == 2
        gw = {nh.address.addr for nh in got.next_hops}
        assert gw == {
            BinaryAddress.from_str("fd0a:7e57:77::2").addr,
            BinaryAddress.from_str("fd0a:7e57:77::3").addr,
        }
        nl.delete_route(dest)

    def test_replace_route(self, nl):
        nl.add_ifaddress(IFACE, IpPrefix.from_str("fd0a:7e57:88::1/64"))
        dest = IpPrefix.from_str("fd0a:7e57:cafe::/64")
        for gw in ("fd0a:7e57:88::2", "fd0a:7e57:88::3"):
            nl.add_route(
                UnicastRoute(
                    dest=dest,
                    next_hops=(
                        NextHop(
                            address=BinaryAddress.from_str(
                                gw, if_name=IFACE
                            )
                        ),
                    ),
                )
            )
        by_dest = {r.dest: r for r in nl.get_all_routes()}
        (nh,) = by_dest[dest].next_hops
        assert nh.address.addr == BinaryAddress.from_str("fd0a:7e57:88::3").addr
        nl.delete_route(dest)

    def test_delete_missing_route_is_noop(self, nl):
        nl.delete_route(IpPrefix.from_str("fd0a:7e57:dead::/64"))  # no raise

    def test_link_event_subscription(self, nl):
        q = ReplicateQueue(name="nl-events")
        reader = q.get_reader("test")
        nl.events_queue = q
        nl.start_events()
        try:
            time.sleep(0.1)
            nl.set_link_up(IFACE, up=False)
            deadline = time.monotonic() + 5
            seen = False
            while time.monotonic() < deadline:
                try:
                    ev = reader.get(timeout=0.5)
                except QueueTimeoutError:
                    continue
                if (
                    ev.event_type == NetlinkEventType.LINK
                    and ev.link is not None
                    and ev.link.if_name == IFACE
                    and not ev.link.is_up
                ):
                    seen = True
                    break
            assert seen, "no link-down event received"
        finally:
            nl.stop_events()

    def test_fib_handler_programs_kernel(self, nl):
        """End to end: Fib module -> NetlinkFibHandler -> rtnetlink ->
        kernel FIB (reference: platform/tests/NetlinkFibHandlerTest)."""
        from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
        from openr_tpu.fib.fib import Fib
        from openr_tpu.platform.netlink_fib_handler import NetlinkFibHandler
        from openr_tpu.types import PrefixEntry

        nl.add_ifaddress(IFACE, IpPrefix.from_str("fd0a:7e57:99::1/64"))
        handler = NetlinkFibHandler(nl)
        route_q = ReplicateQueue(name="nl-e2e:routeUpdates")
        fib = Fib("nl-e2e", handler, route_q)
        fib.start()
        try:
            dest = IpPrefix.from_str("fd0a:7e57:facc::/64")
            entry = RibUnicastEntry(
                prefix=dest,
                nexthops={
                    NextHop(
                        address=BinaryAddress.from_str(
                            "fd0a:7e57:99::2", if_name=IFACE
                        ),
                        metric=10,
                    )
                },
                best_prefix_entry=PrefixEntry(prefix=dest),
                best_area="0",
            )
            route_q.push(
                DecisionRouteUpdate(unicast_routes_to_update={dest: entry})
            )
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if dest in [r.dest for r in nl.get_all_routes()]:
                    break
                time.sleep(0.05)
            assert dest in [r.dest for r in nl.get_all_routes()]
            # withdraw
            route_q.push(
                DecisionRouteUpdate(unicast_routes_to_delete=[dest])
            )
            while time.monotonic() < deadline:
                if dest not in [r.dest for r in nl.get_all_routes()]:
                    break
                time.sleep(0.05)
            assert dest not in [r.dest for r in nl.get_all_routes()]
        finally:
            fib.stop()


class TestAddressDump:
    def test_address_add_dump_delete(self, nl):
        from openr_tpu.types import IpPrefix

        target = IpPrefix.from_str("fd0a:7e57:addc::1/64")
        nl.add_ifaddress(IFACE, target)
        addrs = nl.get_ifaddresses(IFACE)
        assert target in addrs, addrs
        nl.del_ifaddress(IFACE, target)
        assert target not in nl.get_ifaddresses(IFACE)


class TestNeighborDump:
    def test_neighbor_dump_shape(self, nl):
        """The kernel neighbor table parses without error; entries are
        typed NlNeighbor with host-prefix destinations (reference:
        NetlinkProtocolSocket::getAllNeighbors,
        nl/NetlinkProtocolSocket.h:176)."""
        neighbors = nl.get_all_neighbors()
        for nbr in neighbors:
            assert nbr.destination.prefix_length in (32, 128)
            assert nbr.if_index > 0
            assert isinstance(nbr.link_address, bytes)


class TestEventSubscriptions:
    def test_address_event_published(self, nl):
        """RTMGRP_IPV*_IFADDR subscription: adding an address publishes
        an ADDRESS NetlinkEvent (reference: the reference subscribes
        the addr groups and fans out fbnl::IfAddress events)."""
        from openr_tpu.platform.netlink_linux import (
            LinuxNetlinkProtocolSocket,
        )

        queue = ReplicateQueue(name="nlev")
        sub = LinuxNetlinkProtocolSocket(events_queue=queue)
        reader = queue.get_reader()
        sub.start_events()
        try:
            time.sleep(0.1)
            target = IpPrefix.from_str("fd0a:7e57:ebd::1/64")
            nl.add_ifaddress(IFACE, target)
            deadline = time.time() + 5
            seen = False
            while time.time() < deadline:
                try:
                    ev = reader.get(timeout=0.5)
                except QueueTimeoutError:
                    continue
                if (
                    ev.event_type == NetlinkEventType.ADDRESS
                    and ev.prefix is not None
                    and ev.prefix.prefix_address
                    == target.prefix_address
                ):
                    seen = True
                    break
            assert seen, "no ADDRESS event for the added address"
            nl.del_ifaddress(IFACE, target)
        finally:
            sub.stop_events()
            sub.close()

    def test_route_event_published(self, nl):
        """RTMGRP_IPV*_ROUTE subscription: programming an openr-proto
        route publishes a ROUTE NetlinkEvent."""
        from openr_tpu.platform.netlink_linux import (
            LinuxNetlinkProtocolSocket,
        )

        queue = ReplicateQueue(name="nlev2")
        sub = LinuxNetlinkProtocolSocket(events_queue=queue)
        reader = queue.get_reader()
        sub.start_events()
        try:
            time.sleep(0.1)
            dest = IpPrefix.from_str("fd0a:7e57:ee00::/64")
            nl.add_route(
                UnicastRoute(
                    dest=dest,
                    next_hops=(
                        NextHop(
                            address=BinaryAddress(
                                addr=b"", if_name=IFACE
                            )
                        ),
                    ),
                )
            )
            deadline = time.time() + 5
            seen = False
            while time.time() < deadline:
                try:
                    ev = reader.get(timeout=0.5)
                except QueueTimeoutError:
                    continue
                if (
                    ev.event_type == NetlinkEventType.ROUTE
                    and ev.prefix == dest
                ):
                    seen = True
                    break
            assert seen, "no ROUTE event for the programmed route"
            nl.delete_route(dest)
        finally:
            sub.stop_events()
            sub.close()


class TestMplsRoutes:
    def test_mpls_add_dump_delete(self, nl):
        """AF_MPLS label routes (reference:
        nl/NetlinkProtocolSocket.h:131-196 label-route surface). Gated
        on the kernel mpls_router module."""
        from openr_tpu.platform.netlink_linux import (
            LinuxNetlinkProtocolSocket,
        )
        from openr_tpu.types import MplsAction, MplsActionCode, MplsRoute

        if not LinuxNetlinkProtocolSocket.mpls_supported():
            pytest.skip("kernel lacks MPLS modules")
        route = MplsRoute(
            top_label=10021,
            next_hops=(
                NextHop(
                    address=BinaryAddress(
                        addr=socket_inet("fe80::1"), if_name=IFACE
                    ),
                    mpls_action=MplsAction(
                        action=MplsActionCode.SWAP, swap_label=10022
                    ),
                ),
            ),
        )
        nl.add_mpls_route(route)
        try:
            dumped = {
                r.top_label: r for r in nl.get_all_mpls_routes()
            }
            assert 10021 in dumped
            got = dumped[10021]
            assert got.next_hops[0].mpls_action.swap_label == 10022
        finally:
            nl.delete_mpls_route(10021)
        assert 10021 not in {
            r.top_label for r in nl.get_all_mpls_routes()
        }


def socket_inet(addr: str) -> bytes:
    import socket as _s

    return _s.inet_pton(_s.AF_INET6, addr)
