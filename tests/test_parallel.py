"""Sharded SPF tests over the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from openr_tpu.graph.linkstate import LinkState
from openr_tpu.graph.snapshot import INF, compile_snapshot
from openr_tpu.models import topologies
from openr_tpu.ops import spf
from openr_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest should force 8 CPU devices"
    return pmesh.make_mesh()


def _snapshot(topo, n_pad):
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        ls.update_adjacency_database(topo.adj_dbs[name])
    snap = compile_snapshot(ls)
    w = np.full((n_pad, n_pad), INF, dtype=np.int32)
    w[: snap.n, : snap.n] = snap.metric[: snap.n, : snap.n]
    ov = np.zeros((n_pad,), dtype=bool)
    ov[: snap.n] = snap.overloaded[: snap.n]
    return snap, w, ov


def test_sharded_matches_single_device(mesh8):
    topo = topologies.random_mesh(40, degree=4, seed=11, max_metric=12)
    n_pad = pmesh.pad_for_mesh(40, mesh8, align=8)
    snap, w, ov = _snapshot(topo, n_pad)
    d_single = np.asarray(
        spf.all_pairs_distances(jnp.asarray(w), jnp.asarray(ov))
    )
    d_sharded = np.asarray(
        pmesh.sharded_all_sources(jnp.asarray(w), jnp.asarray(ov), mesh8)
    )
    np.testing.assert_array_equal(d_single, d_sharded)


def test_sharded_with_overloads(mesh8):
    topo = topologies.fat_tree(
        pods=3, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
    )
    n = topo.num_nodes
    ls = LinkState(area=topo.area)
    for name in sorted(topo.adj_dbs):
        db = topo.adj_dbs[name]
        if name == "fsw-0-0":
            from openr_tpu.types import AdjacencyDatabase

            db = AdjacencyDatabase(
                this_node_name=db.this_node_name,
                is_overloaded=True,
                adjacencies=db.adjacencies,
                node_label=db.node_label,
                area=db.area,
            )
        ls.update_adjacency_database(db)
    snap = compile_snapshot(ls)
    n_pad = pmesh.pad_for_mesh(snap.n, mesh8, align=16)
    w = np.full((n_pad, n_pad), INF, dtype=np.int32)
    w[: snap.n, : snap.n] = snap.metric[: snap.n, : snap.n]
    ov = np.zeros((n_pad,), dtype=bool)
    ov[: snap.n] = snap.overloaded[: snap.n]
    d_single = np.asarray(
        spf.all_pairs_distances(jnp.asarray(w), jnp.asarray(ov))
    )
    d_sharded = np.asarray(
        pmesh.sharded_all_sources(jnp.asarray(w), jnp.asarray(ov), mesh8)
    )
    np.testing.assert_array_equal(d_single, d_sharded)
    # oracle spot check on a few sources
    for src in ["rsw-0-0", "ssw-0-1", "fsw-0-0"]:
        oracle = ls.run_spf(src)
        sid = snap.node_index[src]
        for dst, res in oracle.items():
            assert d_sharded[sid, snap.node_index[dst]] == res.metric


def test_reconvergence_step_shapes(mesh8):
    topo = topologies.grid(5)
    n_pad = pmesh.pad_for_mesh(25, mesh8, align=8)
    snap, w, ov = _snapshot(topo, n_pad)
    # two prefix groups: advertised by node-0, and by {node-3, node-21}
    dest_mask = np.zeros((2, n_pad), dtype=bool)
    dest_mask[0, snap.node_index["node-0"]] = True
    dest_mask[1, snap.node_index["node-3"]] = True
    dest_mask[1, snap.node_index["node-21"]] = True
    d, best = pmesh.sharded_reconvergence_step(
        jnp.asarray(w), jnp.asarray(ov), jnp.asarray(dest_mask), mesh8
    )
    d, best = np.asarray(d), np.asarray(best)
    assert best.shape == (n_pad, 2)
    i5 = snap.node_index["node-5"]
    assert best[i5, 0] == d[i5, snap.node_index["node-0"]]
    assert best[i5, 1] == min(
        d[i5, snap.node_index["node-3"]], d[i5, snap.node_index["node-21"]]
    )
