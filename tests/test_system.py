"""Multi-node system tests: complete daemons over a simulated LAN.

The reference analogue is openr/tests/OpenrSystemTest.cpp: N full
daemons (spark + kvstore + linkmonitor + decision + fib) in one process
over MockIoProvider, asserting end-to-end route propagation.
"""

import time

import pytest

from openr_tpu.daemon import OpenrNode
from openr_tpu.spark.io_provider import MockIoProvider
from openr_tpu.types import IpPrefix


SPARK_FAST = dict(
    hello_interval_s=0.05,
    fast_hello_interval_s=0.03,
    handshake_interval_s=0.03,
    heartbeat_interval_s=0.05,
    hold_time_s=0.6,
    graceful_restart_time_s=2.0,
)


def wait_until(pred, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class Network:
    def __init__(self):
        self.io = MockIoProvider()
        self.registry = {}
        self.nodes = {}

    def add_node(self, name, idx):
        node = OpenrNode(
            name,
            self.io,
            node_registry=self.registry,
            v6_addr=f"fe80::{idx + 1}",
            spark_config=SPARK_FAST,
        )
        self.nodes[name] = node
        return node

    def link(self, a, b, latency_ms=1):
        if_ab, if_ba = f"if_{a}_{b}", f"if_{b}_{a}"
        self.io.connect_pair(if_ab, if_ba, latency_ms)
        self.nodes[a].add_interface(if_ab)
        self.nodes[b].add_interface(if_ba)

    def start(self):
        for node in self.nodes.values():
            node.start()

    def stop(self):
        for node in self.nodes.values():
            node.stop()
        self.io.stop()

    def has_route(self, node, prefix: IpPrefix) -> bool:
        db = self.nodes[node].get_fib_routes()
        return any(r.dest == prefix for r in db.unicast_routes)


@pytest.fixture
def net():
    n = Network()
    yield n
    n.stop()


class TestSystem:
    def test_line_end_to_end(self, net):
        for i, name in enumerate(["alpha", "beta", "gamma"]):
            net.add_node(name, i)
        net.start()
        net.link("alpha", "beta")
        net.link("beta", "gamma")
        prefixes = {
            name: net.nodes[name].advertise_loopback(f"fd00:{i}::1/128")
            for i, name in enumerate(["alpha", "beta", "gamma"])
        }
        # every node learns routes to every other node's loopback
        for src in net.nodes:
            for dst, prefix in prefixes.items():
                if src == dst:
                    continue
                assert wait_until(
                    lambda s=src, p=prefix: net.has_route(s, p)
                ), f"{src} has no route to {dst}"
        # transit route goes through beta
        db = net.nodes["alpha"].get_fib_routes()
        route = next(
            r for r in db.unicast_routes if r.dest == prefixes["gamma"]
        )
        assert route.next_hops[0].neighbor_node_name == "beta"
        assert route.next_hops[0].metric == 2

    def test_link_failure_reroutes(self, net):
        # square: alpha-beta-delta and alpha-gamma-delta
        for i, name in enumerate(["alpha", "beta", "gamma", "delta"]):
            net.add_node(name, i)
        net.start()
        net.link("alpha", "beta")
        net.link("beta", "delta")
        net.link("alpha", "gamma")
        net.link("gamma", "delta")
        delta_pfx = net.nodes["delta"].advertise_loopback("fd00:d::1/128")
        assert wait_until(lambda: net.has_route("alpha", delta_pfx))

        def nh_names():
            db = net.nodes["alpha"].get_fib_routes()
            for r in db.unicast_routes:
                if r.dest == delta_pfx:
                    return {nh.neighbor_node_name for nh in r.next_hops}
            return set()

        assert wait_until(lambda: nh_names() == {"beta", "gamma"})
        # cut alpha-beta: traffic must converge onto gamma only
        net.io.partition("if_beta_alpha")
        assert wait_until(lambda: nh_names() == {"gamma"}), nh_names()

    def test_monitor_event_logs_flow_on_neighbor_flap(self, net):
        """The daemon-wired Monitor receives LogSamples end to end:
        neighbor discovery pushes NEIGHBOR_UP + ADD_PEER from
        LinkMonitor and KVSTORE_FULL_SYNC from KvStore; a partition
        pushes NEIGHBOR_DOWN; route programming pushes ROUTE_CONVERGENCE
        (reference wiring: Main.cpp:269-280 logSampleQueue ->
        Monitor)."""
        for i, name in enumerate(["alpha", "beta"]):
            net.add_node(name, i)
        net.start()
        net.link("alpha", "beta")
        beta_pfx = net.nodes["beta"].advertise_loopback("fd00:b::1/128")
        assert wait_until(lambda: net.has_route("alpha", beta_pfx))

        def events(node):
            return [
                s.get("event")
                for s in net.nodes[node].monitor.get_event_logs(100)
            ]

        assert wait_until(lambda: "NEIGHBOR_UP" in events("alpha"))
        assert wait_until(lambda: "ADD_PEER" in events("alpha"))
        assert wait_until(
            lambda: "KVSTORE_FULL_SYNC" in events("alpha")
        )
        # common fields merged in by the Monitor
        up = next(
            s
            for s in net.nodes["alpha"].monitor.get_event_logs(100)
            if s.get("event") == "NEIGHBOR_UP"
        )
        assert up.get("neighbor") == "beta"
        assert up.get("node_name") == "alpha"
        # flap: partition both directions so alpha sees the hold expire
        net.io.partition("if_beta_alpha")
        net.io.partition("if_alpha_beta")
        assert wait_until(lambda: "NEIGHBOR_DOWN" in events("alpha"))
        # the ctrl surface serves the same stream (breeze monitor logs)
        logs = net.nodes["alpha"].ctrl_handler.get_event_logs(100)
        assert any('"NEIGHBOR_DOWN"' in raw for raw in logs)

    def test_node_restart_recovers(self, net):
        for i, name in enumerate(["alpha", "beta"]):
            net.add_node(name, i)
        net.start()
        net.link("alpha", "beta")
        beta_pfx = net.nodes["beta"].advertise_loopback("fd00:b::1/128")
        assert wait_until(lambda: net.has_route("alpha", beta_pfx))
        # kvstore contents converged on both sides
        a_keys = set(net.nodes["alpha"].kvstore.dump_with_filters("0").key_vals)
        b_keys = set(net.nodes["beta"].kvstore.dump_with_filters("0").key_vals)
        assert a_keys == b_keys
        assert any(k.startswith("adj:alpha") for k in a_keys)
        assert any(k.startswith("prefix:beta") for k in a_keys)


class TestThriftWirePeering:
    """Full daemons whose KvStores peer over REAL TCP speaking the
    thrift framed-CompactProtocol wire (the stock Open/R peer channel,
    KvStore.thrift:256-276), with the peer port learned from the Spark
    handshake (Spark.thrift:97 kvStoreCmdPort) — the cross-process
    deployment path of openr_tpu.main."""

    def test_route_propagation_over_thrift_tcp(self):
        from openr_tpu.kvstore.thrift_peer import (
            KvStoreThriftPeerServer,
            ThriftPeerTransport,
        )

        io = MockIoProvider()
        nodes = {}
        servers = {}

        def factory(nbr):
            if nbr.kvstore_peer_port <= 0:
                return None
            return ThriftPeerTransport(
                "127.0.0.1", nbr.kvstore_peer_port
            )

        for idx, name in enumerate(("tna", "tnb")):
            node = OpenrNode(
                name,
                io,
                node_registry={},  # isolated: force the TCP path
                v6_addr=f"fe80::{idx + 1}",
                spark_config=SPARK_FAST,
                peer_transport_factory=factory,
            )
            server = KvStoreThriftPeerServer(
                node.kvstore, host="127.0.0.1"
            )
            server.start()
            node.spark.set_kvstore_peer_port(server.port)
            nodes[name] = node
            servers[name] = server

        try:
            for node in nodes.values():
                node.start()
            if_ab, if_ba = "if_tna_tnb", "if_tnb_tna"
            io.connect_pair(if_ab, if_ba, 1)
            nodes["tna"].add_interface(if_ab)
            nodes["tnb"].add_interface(if_ba)
            pfx = nodes["tna"].advertise_loopback("fd00:aaaa::1/128")

            def has_route():
                db = nodes["tnb"].get_fib_routes()
                return any(r.dest == pfx for r in db.unicast_routes)

            assert wait_until(has_route, timeout=15.0)
            # and the adjacency DB flooded over the same wire
            adj = nodes["tnb"].kvstore.get_key_vals("0", ["adj:tna"])
            assert "adj:tna" in adj
        finally:
            for node in nodes.values():
                node.stop()
            for server in servers.values():
                server.stop()
            io.stop()


class TestMixedWireMigration:
    """The wire-migration story end to end: one daemon dials the thrift
    wire, the other dials the framework wire, BOTH serve dual-stack on
    one advertised port (the main() deployment shape) — adjacency
    forms over the mock LAN, KvStores sync over mismatched dials, and
    routes converge."""

    def test_mixed_dials_converge(self):
        from openr_tpu.kvstore.dualstack import DualStackPeerServer
        from openr_tpu.kvstore.thrift_peer import ThriftPeerTransport
        from openr_tpu.kvstore.transport import TcpPeerTransport

        io = MockIoProvider()
        nodes = {}
        servers = {}

        def mk_factory(use_thrift):
            def factory(nbr):
                if nbr.kvstore_peer_port <= 0:
                    return None
                cls = (
                    ThriftPeerTransport if use_thrift else TcpPeerTransport
                )
                return cls("127.0.0.1", nbr.kvstore_peer_port)

            return factory

        for idx, (name, use_thrift) in enumerate(
            (("mwa", True), ("mwb", False))
        ):
            node = OpenrNode(
                name,
                io,
                node_registry={},
                v6_addr=f"fe80::{idx + 1}",
                spark_config=SPARK_FAST,
                peer_transport_factory=mk_factory(use_thrift),
            )
            server = DualStackPeerServer(node.kvstore, host="127.0.0.1")
            server.start()
            node.spark.set_kvstore_peer_port(server.port)
            nodes[name] = node
            servers[name] = server

        try:
            for node in nodes.values():
                node.start()
            io.connect_pair("if_mwa_mwb", "if_mwb_mwa", 1)
            nodes["mwa"].add_interface("if_mwa_mwb")
            nodes["mwb"].add_interface("if_mwb_mwa")
            pfx = nodes["mwa"].advertise_loopback("fd00:ab::1/128")

            def has_route():
                db = nodes["mwb"].get_fib_routes()
                return any(r.dest == pfx for r in db.unicast_routes)

            assert wait_until(has_route, timeout=15.0)
            # and the reverse direction (framework-dial side originates)
            pfx_b = nodes["mwb"].advertise_loopback("fd00:ba::1/128")

            def has_route_back():
                db = nodes["mwa"].get_fib_routes()
                return any(
                    r.dest == pfx_b for r in db.unicast_routes
                )

            assert wait_until(has_route_back, timeout=15.0)
        finally:
            for node in nodes.values():
                node.stop()
            for server in servers.values():
                server.stop()
            io.stop()
