"""Deployment wrapper smoke: scripts/run_openr.sh launches the real
daemon via the reference-style env-file surface (the analogue of
/root/reference/openr/scripts/run_openr.sh + openr.service)."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "run_openr.sh")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_until(pred, timeout=30.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestRunOpenrScript:
    def test_launch_and_ctrl_roundtrip(self, tmp_path):
        port = _free_port()
        # node overrides go in the SYSCONFIG env file (the reference's
        # /etc/sysconfig/openr mechanism), not the process env
        sysconfig = tmp_path / "openr.sysconfig"
        sysconfig.write_text(
            f'NODE_NAME="smoke-node"\n'
            f'OPENR_CTRL_PORT={port}\n'
            f'CONFIG_STORE_FILEPATH="{tmp_path / "store.json"}"\n'
            f'ENABLE_NETLINK_FIB_HANDLER=false\n'
            f'ENABLE_WATCHDOG=false\n'
            f'DRYRUN=true\n'
        )
        env = dict(
            os.environ,
            SYSCONFIG=str(sysconfig),
            OPENR=f"{sys.executable} -m openr_tpu.main",
            JAX_PLATFORMS="cpu",
        )
        for knob in (
            "PALLAS_AXON_POOL_IPS",
            "PALLAS_AXON_REMOTE_COMPILE",
            "AXON_POOL_SVC_OVERRIDE",
            "AXON_LOOPBACK_RELAY",
        ):
            env.pop(knob, None)
        proc = subprocess.Popen(
            ["bash", SCRIPT],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        try:
            def ctrl_up():
                if proc.poll() is not None:
                    return True  # died: fail below with output
                try:
                    s = socket.create_connection(
                        ("127.0.0.1", port), timeout=1
                    )
                    s.close()
                    return True
                except OSError:
                    return False

            assert wait_until(ctrl_up), "ctrl port never opened"
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                pytest.fail(f"daemon exited rc={proc.returncode}:\n{out}")
            # the launched daemon answers BOTH ctrl codecs
            from openr_tpu.ctrl.server import CtrlClient
            from openr_tpu.ctrl.thrift_ctrl import ThriftCtrlClient

            client = CtrlClient("127.0.0.1", port)
            try:
                assert client.call("get_my_node_name") == "smoke-node"
            finally:
                client.close()
            tclient = ThriftCtrlClient("127.0.0.1", port)
            try:
                assert tclient.call("getMyNodeName") == "smoke-node"
            finally:
                tclient.close()
        finally:
            os.killpg(proc.pid, signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait(timeout=10)

    def test_refuses_without_node_name(self, tmp_path):
        sysconfig = tmp_path / "sc"
        sysconfig.write_text('NODE_NAME="localhost"\n')
        env = dict(os.environ, SYSCONFIG=str(sysconfig))
        proc = subprocess.run(
            ["bash", SCRIPT], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=30,
        )
        assert proc.returncode != 0
        assert b"hostname" in proc.stdout.lower()
