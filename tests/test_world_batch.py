"""Multi-tenant batched worlds (ops.world_batch): per-tenant bit
parity vs the sequential single-graph engines, compile-count flatness
as tenants join a warm shape bucket, and the residency arbiter's
evict -> warm-rehydrate round trip."""

import numpy as np
import pytest

from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import (
    SPF_COUNTERS,
    SpfSolver,
    reset_device_caches,
)
from openr_tpu.load.admission import DebounceController
from openr_tpu.models import topologies
from openr_tpu.ops.spf_sparse import (
    compile_ell,
    ell_source_batch,
    ell_view_batch_packed,
)
from openr_tpu.ops.world_batch import (
    TENANCY_COUNTERS,
    WorldManager,
    get_world_manager,
    reset_world_manager,
)
from openr_tpu.telemetry import get_registry, jax_hooks
from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry
from tests.test_sp_route_reuse import (
    _drop_adj,
    _mutate_metric,
    _restore_adj,
    _set_overload,
)
from tests.test_spf_sparse import load


def _mixed_tenants(extra_seed=0):
    """8 mixed-size worlds spanning two shape buckets."""
    topos = [
        topologies.grid(3),
        topologies.grid(4),
        topologies.grid(5),
        topologies.random_mesh(20, 3, seed=7 + extra_seed),
        topologies.random_mesh(30, 4, seed=11 + extra_seed),
        topologies.random_mesh(48, 4, seed=13 + extra_seed),
        topologies.random_mesh(64, 3, seed=17 + extra_seed),
        topologies.random_mesh(150, 3, seed=19 + extra_seed),
    ]
    lss = [load(t) for t in topos]
    roots = [sorted(ls.get_adjacency_databases())[0] for ls in lss]
    return [
        (f"t{i}", ls, root)
        for i, (ls, root) in enumerate(zip(lss, roots))
    ]


def _sequential_oracle(ls, root):
    graph = compile_ell(ls)
    srcs = ell_source_batch(graph, ls, root)
    return srcs, np.asarray(ell_view_batch_packed(graph, srcs))


def _assert_parity(mgr, items, tag=""):
    views = mgr.solve_views(items)
    for (tid, ls, root), (_graph, srcs, packed) in zip(items, views):
        ref_srcs, ref = _sequential_oracle(ls, root)
        assert srcs == ref_srcs, (tag, tid)
        assert packed.shape == ref.shape, (tag, tid)
        np.testing.assert_array_equal(packed, ref, err_msg=f"{tag}:{tid}")
    return views


class TestBatchedParity:
    def test_cold_batched_matches_sequential(self):
        items = _mixed_tenants()
        mgr = WorldManager(slots_per_bucket=8)
        _assert_parity(mgr, items, "cold")
        # one dispatch per populated bucket, not one per tenant
        assert mgr.bucket_count() >= 2
        assert mgr.resident_count() == len(items)

    def test_metric_churn_batched_matches_sequential(self):
        items = _mixed_tenants(extra_seed=100)
        mgr = WorldManager(slots_per_bucket=8)
        _assert_parity(mgr, items, "cold")
        warm0 = TENANCY_COUNTERS["warm_solves"]
        # churn a subset of tenants; the untouched ones must come back
        # bit-identical from their mirrors
        for _tid, ls, root in items[::2]:
            _mutate_metric(ls, root, 0, 55)
        _assert_parity(mgr, items, "metric-churn")
        assert TENANCY_COUNTERS["warm_solves"] - warm0 >= len(items[::2])

    def test_structural_churn_batched_matches_sequential(self):
        items = _mixed_tenants(extra_seed=200)
        mgr = WorldManager(slots_per_bucket=8)
        _assert_parity(mgr, items, "cold")
        _tid, ls, _root = items[3]
        nodes = sorted(ls.get_adjacency_databases())
        dropped = _drop_adj(ls, nodes[1], 0)
        _assert_parity(mgr, items, "link-down")
        _restore_adj(ls, nodes[1], dropped)
        _assert_parity(mgr, items, "link-up")
        _tid2, ls2, _root2 = items[4]
        nodes2 = sorted(ls2.get_adjacency_databases())
        _set_overload(ls2, nodes2[2], True)
        _assert_parity(mgr, items, "overload-on")
        _set_overload(ls2, nodes2[2], False)
        _assert_parity(mgr, items, "overload-off")

    def test_batch_composition_independence(self):
        # a tenant's rows must not depend on who shares the batch:
        # solo solve == batched-with-7-others solve, bit for bit
        items = _mixed_tenants(extra_seed=300)
        solo = WorldManager(slots_per_bucket=8)
        solo_views = solo.solve_views([items[0]])
        batched = WorldManager(slots_per_bucket=8)
        batched_views = batched.solve_views(items)
        np.testing.assert_array_equal(
            solo_views[0][2], batched_views[0][2]
        )


class TestCompileFlatness:
    def test_bucket_join_is_retrace_free(self):
        if not jax_hooks.install():
            pytest.skip("jax.monitoring unavailable")
        reg = get_registry()
        items = _mixed_tenants(extra_seed=400)
        mgr = WorldManager(slots_per_bucket=8)
        mgr.solve_views(items)  # warm every bucket shape
        # warm the resident patch-scatter executable too
        _mutate_metric(items[1][1], items[1][2], 0, 77)
        mgr.solve_views(items)
        compiles0 = reg.counter_get("jax.compile_count")
        buckets0 = TENANCY_COUNTERS["bucket_compiles"]
        # NEW tenants with the same shapes (same topologies, fresh
        # worlds, different metrics) joining the warm buckets
        join = [
            (f"j{i}", ls, root)
            for i, (_tid, ls, root) in enumerate(
                _mixed_tenants(extra_seed=400)
            )
        ]
        for _tid, ls, root in join:
            _mutate_metric(ls, root, 0, 33)
        mgr.solve_views(join)
        # churn + warm re-solve of an original tenant, still flat
        _mutate_metric(items[1][1], items[1][2], 0, 88)
        mgr.solve_views(items)
        assert reg.counter_get("jax.compile_count") == compiles0
        assert TENANCY_COUNTERS["bucket_compiles"] == buckets0


class TestResidencyArbiter:
    def test_evict_rehydrate_parity_and_warmness(self):
        # 3 same-bucket tenants in a 2-slot bucket: solving all three
        # forces an eviction; churning the evicted-but-solved tenant
        # must rehydrate it WARM (journal replay), not cold
        topos = [
            topologies.grid(3),
            topologies.grid(4),
            topologies.random_mesh(20, 3, seed=7),
        ]
        lss = [load(t) for t in topos]
        items = [
            (f"e{i}", ls, sorted(ls.get_adjacency_databases())[0])
            for i, ls in enumerate(lss)
        ]
        mgr = WorldManager(slots_per_bucket=2)
        ev0 = TENANCY_COUNTERS["evictions"]
        _assert_parity(mgr, items, "wave")
        assert TENANCY_COUNTERS["evictions"] > ev0
        assert mgr.resident_count() == 2
        evicted = [
            t
            for t in (mgr._tenants[tid] for tid, _ls, _r in items)
            if t.slot is None and t.solved
        ]
        assert evicted, "an already-solved tenant should be evicted"
        tid = evicted[0].tenant_id
        idx = [t for t, _ls, _r in items].index(tid)
        ls = items[idx][1]
        _mutate_metric(
            ls, sorted(ls.get_adjacency_databases())[0], 0, 123
        )
        r0 = TENANCY_COUNTERS["rehydrations"]
        w0 = TENANCY_COUNTERS["warm_solves"]
        c0 = TENANCY_COUNTERS["cold_solves"]
        _assert_parity(mgr, items, "rehydrate")
        assert TENANCY_COUNTERS["rehydrations"] - r0 >= 1
        assert TENANCY_COUNTERS["warm_solves"] - w0 >= 1
        assert TENANCY_COUNTERS["cold_solves"] == c0

    def test_occupancy_gauges(self):
        items = _mixed_tenants(extra_seed=500)[:3]
        mgr = WorldManager(slots_per_bucket=8)
        mgr.solve_views(items)
        assert TENANCY_COUNTERS["active"] == len(mgr._tenants)
        assert TENANCY_COUNTERS["resident"] == mgr.resident_count()
        mgr.drop(items[0][0])
        assert TENANCY_COUNTERS["active"] == len(mgr._tenants)

    def test_ls_identity_change_readmits_cold(self):
        topo = topologies.grid(3)
        ls1 = load(topo)
        root = sorted(ls1.get_adjacency_databases())[0]
        mgr = WorldManager(slots_per_bucket=4)
        _assert_parity(mgr, [("x", ls1, root)], "first")
        # same tenant id, brand-new LinkState object: must not serve
        # the old world's rows
        ls2 = load(topo)
        _mutate_metric(ls2, root, 0, 99)
        a0 = TENANCY_COUNTERS["admissions"]
        _assert_parity(mgr, [("x", ls2, root)], "readmit")
        assert TENANCY_COUNTERS["admissions"] - a0 == 1


class TestDecisionWiring:
    def _areas(self):
        return {
            f"area{i}": load(t)
            for i, t in enumerate(
                [
                    topologies.grid(3),
                    topologies.grid(4),
                    topologies.random_mesh(20, 3, seed=7),
                ]
            )
        }

    def _prefixes(self, areas):
        ps = PrefixState()
        for a, ls in areas.items():
            for node in sorted(ls.get_adjacency_databases())[:4]:
                nid = node.split("-")[-1]
                ps.update_prefix_database(
                    PrefixDatabase(
                        this_node_name=node,
                        prefix_entries=(
                            PrefixEntry(
                                prefix=IpPrefix.from_str(
                                    f"fd00:{a[-1]}:{nid}::/64"
                                )
                            ),
                        ),
                        area=a,
                    )
                )
        return ps

    def _routes(self, world_batch):
        reset_device_caches()
        areas = self._areas()
        ps = self._prefixes(areas)
        solver = SpfSolver("node-0", world_batch=world_batch)
        db1 = solver.build_route_db("node-0", areas, ps)
        _mutate_metric(areas["area1"], "node-1", 0, 44)
        db2 = solver.build_route_db("node-0", areas, ps)
        return db1, db2

    def test_multi_area_build_parity(self):
        try:
            p0 = SPF_COUNTERS["decision.world_preloads"]
            seq = self._routes(world_batch=False)
            assert SPF_COUNTERS["decision.world_preloads"] == p0
            world = self._routes(world_batch=True)
            assert SPF_COUNTERS["decision.world_preloads"] > p0
            for tag, a, b in zip(("build1", "build2"), seq, world):
                assert a.unicast_routes == b.unicast_routes, tag
                assert a.mpls_routes == b.mpls_routes, tag
        finally:
            reset_device_caches()

    def test_reset_device_caches_resets_world(self):
        mgr = get_world_manager()
        topo = topologies.grid(3)
        ls = load(topo)
        root = sorted(ls.get_adjacency_databases())[0]
        mgr.solve_views([("r", ls, root)])
        assert mgr.resident_count() == 1
        reset_device_caches()
        assert get_world_manager() is not mgr
        assert get_world_manager().resident_count() == 0
        reset_world_manager()


class TestViewCacheLru:
    def test_configurable_cap_and_eviction_counter(self):
        lss = [load(topologies.grid(3)) for _ in range(3)]
        areas = {f"a{i}": ls for i, ls in enumerate(lss)}
        solver = SpfSolver("node-0", view_cache_cap=2)
        assert solver.view_cache_cap == 2
        e0 = SPF_COUNTERS["route_engine.view_evictions"]
        for a, ls in areas.items():
            solver._view(a, ls, "node-0")
        assert len(solver._views) == 2
        assert SPF_COUNTERS["route_engine.view_evictions"] - e0 == 1

    def test_env_default(self, monkeypatch):
        import openr_tpu.decision.spf_solver as mod

        monkeypatch.setattr(mod, "VIEW_CACHE_CAP_DEFAULT", 7)
        assert SpfSolver("n").view_cache_cap == 7
        assert SpfSolver("n", view_cache_cap=3).view_cache_cap == 3


class TestDebounceSelfTune:
    def _controller(self, **kw):
        kw.setdefault("base_max_s", 0.25)
        kw.setdefault("cap_s", 2.0)
        kw.setdefault("widen_depth", 8)
        kw.setdefault("narrow_depth", 2)
        kw.setdefault("metric_prefix", f"tune{id(self)}")
        return DebounceController(**kw)

    def test_sheds_narrow_the_band(self):
        c = self._controller(tune_period=4)
        reg = get_registry()
        prefix = c._prefix
        adj0 = reg.counter_get(f"{prefix}.debounce_band_adjustments")
        for _ in range(4):
            c.observe(3)
        assert c.widen_depth == 8  # first period only samples
        reg.counter_bump(f"{prefix}.admission.sheds")
        for _ in range(4):
            c.observe(3)
        assert c.widen_depth == 7
        assert (
            reg.counter_get(f"{prefix}.debounce_band_adjustments")
            - adj0
            == 1
        )

    def test_band_floor_is_pinned_above_narrow(self):
        c = self._controller(tune_period=1, narrow_depth=2, widen_depth=4)
        reg = get_registry()
        c.observe(0)  # first sample
        for _ in range(10):
            reg.counter_bump(f"{c._prefix}.admission.sheds")
            c.observe(0)
        assert c.widen_depth == 3  # narrow_depth + 1, never lower

    def test_quiet_periods_relax_back(self):
        c = self._controller(tune_period=1)
        reg = get_registry()
        c.observe(0)  # first period only records the sample
        reg.counter_bump(f"{c._prefix}.admission.sheds")
        c.observe(0)  # shed seen: engage earlier
        assert c.widen_depth == 7
        c.observe(0)  # quiet period: relax toward configured band
        assert c.widen_depth == 8
        c.observe(0)  # never above the configured value
        assert c.widen_depth == 8

    def test_self_tune_off_keeps_fixed_band(self):
        c = self._controller(self_tune=False, tune_period=1)
        reg = get_registry()
        for _ in range(5):
            reg.counter_bump(f"{c._prefix}.admission.sheds")
            c.observe(3)
        assert c.widen_depth == 8

    def test_fsm_unchanged_by_tuning_defaults(self):
        # the original hysteresis behavior under short horizons
        c = self._controller(cap_s=1.0)
        assert c.observe(10) == DebounceController.WIDEN
        assert c.observe(10) == DebounceController.WIDEN
        assert c.observe(50) == DebounceController.STEADY
        assert c.observe(0) == DebounceController.NARROW
        assert c.observe(0) == DebounceController.NARROW
        assert c.observe(0) == DebounceController.STEADY
