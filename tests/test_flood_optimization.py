"""KvStore DUAL flood-optimization tests (reference: the
enableFloodOptimization path, KvStore.cpp:2940-2973 — flooding rides the
DUAL-computed SPT instead of every link)."""

import time

import pytest

from openr_tpu.kvstore.wrapper import KvStoreWrapper, link_bidirectional


def make_net(names, edges, root):
    stores = {
        n: KvStoreWrapper(
            n, enable_flood_optimization=True, is_flood_root=(n == root)
        )
        for n in names
    }
    for s in stores.values():
        s.start()
    for a, b in edges:
        link_bidirectional(stores[a], stores[b])
    return stores


def wait_initialized(stores, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        ok = True
        for s in stores.values():
            states = s.peer_states()
            if not states or not all(
                str(v) .endswith("INITIALIZED") or getattr(v, "name", "")
                == "INITIALIZED"
                for v in states.values()
            ):
                ok = False
        if ok:
            return
        time.sleep(0.05)
    raise AssertionError("peers never initialized")


def wait_key(store, key, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if store.get_key(key) is not None:
            return True
        time.sleep(0.02)
    return False


def stop_all(stores):
    for s in stores.values():
        s.stop()


class TestFloodOptimization:
    def test_spt_forms_and_flood_propagates(self):
        # line a-b-c-d rooted at a: SPT == the line itself, so floods
        # still reach everyone
        stores = make_net(
            ["a", "b", "c", "d"],
            [("a", "b"), ("b", "c"), ("c", "d")],
            root="a",
        )
        try:
            wait_initialized(stores)
            time.sleep(0.3)  # let DUAL converge
            dual_b = stores["b"].store._dbs["0"].dual
            root = dual_b.pick_flood_root()
            assert root == "a"
            assert dual_b.spt_peers(root) >= {"a", "c"}

            stores["a"].set_key("adj:a", b"va", version=1, originator="a")
            for n in ("b", "c", "d"):
                assert wait_key(stores[n], "adj:a"), n
            stores["d"].set_key("adj:d", b"vd", version=1, originator="d")
            for n in ("a", "b", "c"):
                assert wait_key(stores[n], "adj:d"), n
        finally:
            stop_all(stores)

    def test_triangle_prunes_redundant_link(self):
        # triangle rooted at a: the SPT uses two of the three links, so
        # SPT-constrained floods are recorded and propagation still works
        stores = make_net(
            ["a", "b", "c"],
            [("a", "b"), ("b", "c"), ("a", "c")],
            root="a",
        )
        try:
            wait_initialized(stores)
            time.sleep(0.3)
            stores["a"].set_key("prefix:a", b"pa", version=1, originator="a")
            assert wait_key(stores["b"], "prefix:a")
            assert wait_key(stores["c"], "prefix:a")
            counters = stores["a"].store.counters()
            assert counters["kvstore.spt_floods"] >= 1
            # b's SPT parent is a; c is NOT on b's SPT (root-ward) set
            dual_b = stores["b"].store._dbs["0"].dual
            root = dual_b.pick_flood_root()
            assert root == "a"
            assert "a" in dual_b.spt_peers(root)
        finally:
            stop_all(stores)

    def test_flood_falls_back_without_valid_root(self):
        # no flood root anywhere (nobody is root): full flooding still
        # delivers — correctness never depends on the optimization
        stores = make_net(
            ["a", "b", "c"],
            [("a", "b"), ("b", "c")],
            root="zz-not-a-member",
        )
        try:
            wait_initialized(stores)
            stores["a"].set_key("adj:a", b"va", version=1, originator="a")
            assert wait_key(stores["b"], "adj:a")
            assert wait_key(stores["c"], "adj:a")
        finally:
            stop_all(stores)

    def test_root_failure_reroots_via_anti_entropy(self):
        # the root dies; keys still propagate between survivors (DUAL
        # falls back / anti-entropy covers) — availability over topology
        stores = make_net(
            ["a", "b", "c"],
            [("a", "b"), ("b", "c"), ("a", "c")],
            root="a",
        )
        try:
            wait_initialized(stores)
            time.sleep(0.3)
            stores["a"].stop()
            # b and c keep exchanging through their direct link
            stores["b"].store.del_peer("0", "a")
            stores["c"].store.del_peer("0", "a")
            stores["b"].set_key("adj:b2", b"v2", version=1, originator="b")
            assert wait_key(stores["c"], "adj:b2")
        finally:
            for n in ("b", "c"):
                stores[n].stop()


class TestFloodOptimizationThriftWire:
    """DUAL over the thrift peer channel (reference: Command.DUAL on
    the same peer wire, KvStore.thrift:47-52; service methods
    OpenrCtrl.thrift:416 processKvStoreDualMessage / :424
    updateFloodTopologyChild) — and over MIXED wires, the
    mid-migration fleet the reference dual-stacks for
    (KvStore.cpp:2940-2973)."""

    @staticmethod
    def thrift_net(names, edges, root, mixed=()):
        """Line/star net where peer links ride the thrift wire, except
        links whose BOTH ends are in ``mixed`` (those use the
        framework in-process transport)."""
        from openr_tpu.kvstore.thrift_peer import (
            KvStoreThriftPeerServer,
            ThriftPeerTransport,
        )
        from openr_tpu.kvstore.store import InProcessTransport

        stores = {
            n: KvStoreWrapper(
                n, enable_flood_optimization=True,
                is_flood_root=(n == root),
            )
            for n in names
        }
        servers = {}
        for n, s in stores.items():
            s.start()
            servers[n] = KvStoreThriftPeerServer(
                s.store, host="127.0.0.1"
            )
            servers[n].start()

        def transport_to(a, b):
            if a in mixed and b in mixed:
                return InProcessTransport(stores[b].store)
            return ThriftPeerTransport("127.0.0.1", servers[b].port)

        for a, b in edges:
            stores[a].store.add_peer("0", b, transport_to(a, b))
            stores[b].store.add_peer("0", a, transport_to(b, a))
        return stores, servers

    @staticmethod
    def stop_net(stores, servers):
        for s in stores.values():
            s.stop()
        for srv in servers.values():
            srv.stop()

    def test_spt_forms_over_thrift_wire(self):
        stores, servers = self.thrift_net(
            ["a", "b", "c", "d"],
            [("a", "b"), ("b", "c"), ("c", "d")],
            root="a",
        )
        try:
            wait_initialized(stores)
            time.sleep(0.5)  # let DUAL converge over TCP
            dual_b = stores["b"].store._dbs["0"].dual
            root = dual_b.pick_flood_root()
            assert root == "a"
            assert dual_b.spt_peers(root) >= {"a", "c"}
            stores["a"].set_key("adj:a", b"va", version=1, originator="a")
            for n in ("b", "c", "d"):
                assert wait_key(stores[n], "adj:a"), n
        finally:
            self.stop_net(stores, servers)

    def test_spt_flood_counter_over_thrift_wire(self):
        stores, servers = self.thrift_net(
            ["a", "b", "c"],
            [("a", "b"), ("b", "c"), ("a", "c")],
            root="a",
        )
        try:
            wait_initialized(stores)
            time.sleep(0.5)
            stores["a"].set_key(
                "prefix:a", b"pa", version=1, originator="a"
            )
            assert wait_key(stores["b"], "prefix:a")
            assert wait_key(stores["c"], "prefix:a")
            assert (
                stores["a"].store.counters()["kvstore.spt_floods"] >= 1
            )
        finally:
            self.stop_net(stores, servers)

    def test_mixed_wire_fleet_keeps_flood_optimization(self):
        # a-b over the framework wire, b-c and c-d over thrift: the
        # mid-migration fleet keeps ONE spanning tree across both wires
        stores, servers = self.thrift_net(
            ["a", "b", "c", "d"],
            [("a", "b"), ("b", "c"), ("c", "d")],
            root="a",
            mixed={"a", "b"},
        )
        try:
            wait_initialized(stores)
            time.sleep(0.5)
            dual_d = stores["d"].store._dbs["0"].dual
            root = dual_d.pick_flood_root()
            assert root == "a"
            stores["d"].set_key("adj:d", b"vd", version=1, originator="d")
            for n in ("a", "b", "c"):
                assert wait_key(stores[n], "adj:d"), n
            stores["a"].set_key("adj:a", b"va", version=1, originator="a")
            for n in ("b", "c", "d"):
                assert wait_key(stores[n], "adj:a"), n
        finally:
            self.stop_net(stores, servers)

    def test_thrift_plus_flood_optimization_config_accepted(self):
        from openr_tpu.config.config import OpenrConfig

        cfg = OpenrConfig.from_dict(
            {
                "node_name": "x",
                "areas": [{"area_id": "0"}],
                "kvstore": {
                    "enable_kvstore_thrift": True,
                    "enable_flood_optimization": True,
                },
            }
        )
        assert cfg.kvstore.enable_kvstore_thrift
        assert cfg.kvstore.enable_flood_optimization
