"""LinkMonitor tests (reference analogue:
openr/link-monitor/tests/LinkMonitorTest.cpp, 15 cases): neighbor events
to adjacency advertisements, drain state persistence, metric overrides,
RTT metric, parallel adjacencies, and graceful-restart retention."""

import time

import pytest

from openr_tpu.config_store.persistent_store import PersistentStore
from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.kvstore.store import KvStore
from openr_tpu.linkmonitor.link_monitor import LinkMonitor
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import AdjacencyDatabase, BinaryAddress
from openr_tpu.types.spark import (
    SparkNeighbor,
    SparkNeighborEvent,
    SparkNeighborEventType,
)
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import OpenrEventBase


def neighbor(node, local_if, remote_if, area="0", rtt_us=0):
    return SparkNeighbor(
        node_name=node,
        local_if_name=local_if,
        remote_if_name=remote_if,
        transport_address_v6=BinaryAddress.from_str("fe80::2"),
        area=area,
        rtt_us=rtt_us,
    )


class Harness:
    def __init__(self, config_store=None, areas=None, **lm_kwargs):
        self.kvstore = KvStore(node_id="lm-test", areas=areas or ["0"])
        self.kvstore.start()
        self.client_evb = OpenrEventBase(name="lm-test-client")
        self.client_evb.run_in_thread()
        self.client = KvStoreClient(self.client_evb, "node-a", self.kvstore)
        self.neighbor_q = ReplicateQueue(name="lm:neighborUpdates")
        self.interface_q = ReplicateQueue(name="lm:interfaceUpdates")
        self.lm = LinkMonitor(
            "node-a",
            neighbor_updates_queue=self.neighbor_q,
            interface_updates_queue=self.interface_q,
            kvstore_client=self.client,
            kvstore=self.kvstore,
            config_store=config_store,
            areas=areas,
            **lm_kwargs,
        )
        self.lm.start()

    def emit(self, event_type, nbr):
        self.neighbor_q.push(SparkNeighborEvent(event_type, nbr))

    def adj_db(self, area="0", timeout=5.0):
        """The adj:node-a advertisement currently in the KvStore."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            key = keyutil.adj_key("node-a")
            val = self.kvstore.get_key_vals(area, [key]).get(key)
            if val is not None and val.value is not None:
                return wire.loads(val.value, AdjacencyDatabase)
            time.sleep(0.02)
        return None

    def wait_adj(self, pred, area="0", timeout=5.0):
        deadline = time.monotonic() + timeout
        db = None
        while time.monotonic() < deadline:
            db = self.adj_db(area=area, timeout=0.2)
            if db is not None and pred(db):
                return db
            time.sleep(0.02)
        raise AssertionError(f"adj db never matched; last: {db}")

    def stop(self):
        self.lm.stop()
        self.client_evb.stop()
        self.client_evb.join()
        self.kvstore.stop()


@pytest.fixture
def harness():
    h = Harness()
    yield h
    h.stop()


class TestBasicOperation:
    def test_neighbor_up_advertises_adjacency(self, harness):
        harness.emit(
            SparkNeighborEventType.NEIGHBOR_UP, neighbor("b", "if_ab", "if_ba")
        )
        db = harness.wait_adj(lambda d: len(d.adjacencies) == 1)
        (adj,) = db.adjacencies
        assert adj.other_node_name == "b"
        assert adj.if_name == "if_ab"
        assert adj.other_if_name == "if_ba"
        assert db.this_node_name == "node-a"

    def test_neighbor_down_withdraws_adjacency(self, harness):
        nbr = neighbor("b", "if_ab", "if_ba")
        harness.emit(SparkNeighborEventType.NEIGHBOR_UP, nbr)
        harness.wait_adj(lambda d: len(d.adjacencies) == 1)
        harness.emit(SparkNeighborEventType.NEIGHBOR_DOWN, nbr)
        harness.wait_adj(lambda d: len(d.adjacencies) == 0)

    def test_parallel_adjacencies_same_node(self, harness):
        # two interfaces to the same neighbor: both advertised
        # (reference: LinkMonitorTest ParallelAdj)
        harness.emit(
            SparkNeighborEventType.NEIGHBOR_UP,
            neighbor("b", "if1_ab", "if1_ba"),
        )
        harness.emit(
            SparkNeighborEventType.NEIGHBOR_UP,
            neighbor("b", "if2_ab", "if2_ba"),
        )
        db = harness.wait_adj(lambda d: len(d.adjacencies) == 2)
        assert {a.if_name for a in db.adjacencies} == {"if1_ab", "if2_ab"}

    def test_neighbor_restart_keeps_adjacency(self, harness):
        # graceful restart must not withdraw the adjacency
        # (reference: LinkMonitorTest NeighborRestart)
        nbr = neighbor("b", "if_ab", "if_ba")
        harness.emit(SparkNeighborEventType.NEIGHBOR_UP, nbr)
        harness.wait_adj(lambda d: len(d.adjacencies) == 1)
        harness.emit(SparkNeighborEventType.NEIGHBOR_RESTARTING, nbr)
        time.sleep(0.3)
        db = harness.adj_db()
        assert db is not None and len(db.adjacencies) == 1
        harness.emit(SparkNeighborEventType.NEIGHBOR_RESTARTED, nbr)
        time.sleep(0.3)
        db = harness.adj_db()
        assert db is not None and len(db.adjacencies) == 1


class TestOverloadAndMetrics:
    def test_node_overload_bit(self, harness):
        harness.emit(
            SparkNeighborEventType.NEIGHBOR_UP, neighbor("b", "if_ab", "if_ba")
        )
        harness.wait_adj(lambda d: len(d.adjacencies) == 1)
        harness.lm.set_node_overload(True)
        harness.wait_adj(lambda d: d.is_overloaded)
        harness.lm.set_node_overload(False)
        harness.wait_adj(lambda d: not d.is_overloaded)

    def test_link_overload_marks_adjacency(self, harness):
        harness.emit(
            SparkNeighborEventType.NEIGHBOR_UP, neighbor("b", "if_ab", "if_ba")
        )
        harness.wait_adj(lambda d: len(d.adjacencies) == 1)
        harness.lm.set_link_overload("if_ab", True)
        db = harness.wait_adj(lambda d: d.adjacencies[0].is_overloaded)
        assert db.adjacencies[0].is_overloaded

    def test_link_metric_override(self, harness):
        harness.emit(
            SparkNeighborEventType.NEIGHBOR_UP, neighbor("b", "if_ab", "if_ba")
        )
        harness.wait_adj(lambda d: len(d.adjacencies) == 1)
        harness.lm.set_link_metric("if_ab", "b", 777)
        harness.wait_adj(lambda d: d.adjacencies[0].metric == 777)
        harness.lm.set_link_metric("if_ab", "b", None)
        harness.wait_adj(lambda d: d.adjacencies[0].metric != 777)

    def test_rtt_metric_mode(self):
        # use_rtt_metric derives the metric from measured RTT
        # (reference: LinkMonitor metric = rtt-based when enabled)
        h = Harness(use_rtt_metric=True)
        try:
            h.emit(
                SparkNeighborEventType.NEIGHBOR_UP,
                neighbor("b", "if_ab", "if_ba", rtt_us=20000),
            )
            db = h.wait_adj(lambda d: len(d.adjacencies) == 1)
            assert db.adjacencies[0].metric > 1  # scaled from 20ms RTT
            assert db.adjacencies[0].rtt == 20000
        finally:
            h.stop()


class TestDrainPersistence:
    def test_drain_state_survives_restart(self, tmp_path):
        # reference: LinkMonitorTest DrainState — overload set, process
        # restarts, overload still set (PersistentStore-backed)
        store = PersistentStore(str(tmp_path / "lm.bin"), save_throttle_s=0.0)
        h = Harness(config_store=store)
        try:
            h.emit(
                SparkNeighborEventType.NEIGHBOR_UP,
                neighbor("b", "if_ab", "if_ba"),
            )
            h.wait_adj(lambda d: len(d.adjacencies) == 1)
            h.lm.set_node_overload(True)
            h.wait_adj(lambda d: d.is_overloaded)
        finally:
            h.stop()
            store.stop()

        store2 = PersistentStore(
            str(tmp_path / "lm.bin"), save_throttle_s=0.0
        )
        h2 = Harness(config_store=store2)
        try:
            assert h2.lm.is_overloaded
            h2.emit(
                SparkNeighborEventType.NEIGHBOR_UP,
                neighbor("b", "if_ab", "if_ba"),
            )
            db = h2.wait_adj(lambda d: len(d.adjacencies) == 1)
            assert db.is_overloaded
        finally:
            h2.stop()
            store2.stop()


class TestMultiArea:
    def test_adjacency_lands_in_interface_area(self):
        # border router: each area's adj db holds only that area's links
        # (reference: LinkMonitorTest AreaTest)
        h = Harness(areas=["0", "1"])
        try:
            h.emit(
                SparkNeighborEventType.NEIGHBOR_UP,
                neighbor("b", "if_ab", "if_ba", area="0"),
            )
            h.emit(
                SparkNeighborEventType.NEIGHBOR_UP,
                neighbor("c", "if_ac", "if_ca", area="1"),
            )
            db0 = h.wait_adj(lambda d: len(d.adjacencies) == 1, area="0")
            db1 = h.wait_adj(lambda d: len(d.adjacencies) == 1, area="1")
            assert db0.adjacencies[0].other_node_name == "b"
            assert db1.adjacencies[0].other_node_name == "c"
        finally:
            h.stop()


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestNodeLabelElection:
    """reference: LinkMonitor.cpp:171-205 — per-area SR node-label
    election over kSrGlobalRange via RangeAllocator."""

    def test_unique_labels_elected_and_advertised(self):
        from openr_tpu.linkmonitor.link_monitor import SR_GLOBAL_RANGE

        a = Harness(enable_segment_routing=True)
        # second node sharing the same KvStore graph via TCP-less
        # in-process peering is overkill here: share ONE store
        b_evb = OpenrEventBase(name="lm-test-client-b")
        b_evb.run_in_thread()
        b_client = KvStoreClient(b_evb, "node-b", a.kvstore)
        b_neighbor_q = ReplicateQueue(name="lmb:neighborUpdates")
        b_interface_q = ReplicateQueue(name="lmb:interfaceUpdates")
        b = LinkMonitor(
            "node-b",
            neighbor_updates_queue=b_neighbor_q,
            interface_updates_queue=b_interface_q,
            kvstore_client=b_client,
            kvstore=a.kvstore,
            enable_segment_routing=True,
        )
        b.start()
        try:
            assert wait_until(
                lambda: a.lm.node_label_for("0") != 0
                and b.node_label_for("0") != 0
            )
            la, lb = a.lm.node_label_for("0"), b.node_label_for("0")
            assert la != lb
            for label in (la, lb):
                assert SR_GLOBAL_RANGE[0] <= label <= SR_GLOBAL_RANGE[1]
            # the elected label rides the advertised AdjacencyDatabase
            assert a.lm._build_adj_db("0").node_label == la
        finally:
            b.stop()
            b_evb.stop()
            b_evb.join()
            a.stop()

    def test_static_label_skips_election(self):
        h = Harness(enable_segment_routing=True, node_label=777)
        try:
            time.sleep(0.3)
            assert h.lm.node_label_for("0") == 777
            assert not h.lm._label_allocators
        finally:
            h.stop()

    def test_persisted_label_reclaimed(self):
        class DictStore:
            def __init__(self):
                self.data = {}

            def store(self, key, obj):
                self.data[key] = obj

            def load(self, key, cls=None):
                return self.data.get(key)

        store = DictStore()
        h = Harness(enable_segment_routing=True, config_store=store)
        try:
            assert wait_until(lambda: h.lm.node_label_for("0") != 0)
            first = h.lm.node_label_for("0")
        finally:
            h.stop()
        h2 = Harness(enable_segment_routing=True, config_store=store)
        try:
            assert wait_until(lambda: h2.lm.node_label_for("0") == first)
        finally:
            h2.stop()
